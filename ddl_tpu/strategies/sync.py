"""Synchronous strategies: pure data-parallel and parameter-sharded (ZeRO-1).

Reference semantics being re-designed (not translated):

- ``mnist_sync``: every worker pushes its 14 grads to one PS, which sums them
  (never averaging — parameter_server.py:36-37), takes one Adam step, and
  broadcasts fresh params; workers barrier on the Bcast
  (mnist_sync/worker.py:60-72, parameter_server.py:54-69).
  TPU-native: one SPMD program per step — per-chip grads, ``psum`` over the
  ICI mesh axis (default mean; ``grad_reduction="sum"`` reproduces the
  reference's summed-LR behavior), replicated Adam. The PS process, the
  py_function grad escape hatch, and the 14 per-var round-trips all vanish
  into one compiled step.

- ``mnist_sync_sharding[_greedy]``: M PS ranks each own a block of variables
  and update only their shard (parameter_server.py:30-32,42-69); the greedy
  variant permutes variables before blocking (greedy worker.py:14-37).
  TPU-native: ZeRO-1 — flatten params into one vector in layout order,
  reduce-scatter grads so each device owns a slice, shard-local Adam (m/v
  live ONLY on the owner — the memory win), all-gather updated params.
  Layout policies: "flat" (equal chunks, bandwidth-optimal psum_scatter),
  "block"/"zigzag"/"lpt" (variable-aligned owner ranges, reproducing and
  generalizing the reference's partitioning — see ddl_tpu.parallel.layout).

Numerics: with ``grad_reduction="mean"`` and no dropout, every sync strategy
is step-equivalent to the single-chip trainer on the same global batch (the
parity tests assert this); sharded vs unsharded are equivalent for any
layout because Adam is elementwise.

The reference's sharded-PS aggregation bug (aliased buffers double-counting
workers, parameter_server.py:43-47,77-80 — SURVEY.md §3.5) is *not*
reproduced: psum/psum_scatter are correct by construction, and
``tests/test_sync_strategies.py`` pins the correct aggregation.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data import Dataset, one_hot
from ..models import cnn
from ..ops import AdamState, adam_init, adam_update
from ..parallel import collectives as coll
from ..parallel.layout import LayoutAssignment, assign_layout
from ..parallel.mesh import DP_AXIS, donation_for, make_mesh
from ..train.config import TrainConfig
from ..train.trainer import TrainResult, evaluate


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ShardedAdam:
    """Adam state over the flat param vector, sharded along the mesh axis.

    ``m``/``v`` hold only this framework's analogue of a PS shard's slots
    (reference: per-shard optimizer at
    mnist_sync_sharding/parameter_server.py:56-69): globally ``[S * max_shard]``
    with ``NamedSharding(P(DP_AXIS))``, i.e. ``max_shard`` elements resident
    per device — the ZeRO-1 memory saving.
    """

    step: jax.Array  # int32 scalar, replicated
    m: jax.Array
    v: jax.Array


def _adam_flat(p, state: ShardedAdam, g, *, lr, b1=0.9, b2=0.999, eps=1e-8):
    """TF1-semantics Adam (see ddl_tpu.ops.optimizers) on flat slices."""
    step = state.step + 1
    t = step.astype(jnp.float32)
    lr_t = lr * jnp.sqrt(1.0 - b2**t) / (1.0 - b1**t)
    m = b1 * state.m + (1.0 - b1) * g
    v = b2 * state.v + (1.0 - b2) * g * g
    return p - lr_t * m / (jnp.sqrt(v) + eps), ShardedAdam(step=step, m=m, v=v)


def _local_grads(config: TrainConfig, params, x, y, rng, axis: str):
    """Per-device loss+grads with a device-distinct dropout stream
    (reference workers use independent masks — SURVEY.md §7d)."""
    compute_dtype = jnp.bfloat16 if config.compute_dtype == "bfloat16" else None
    rng = jax.random.fold_in(rng, lax.axis_index(axis))
    loss, grads = jax.value_and_grad(cnn.loss_fn)(
        params,
        x,
        y,
        dropout_rng=rng if config.keep_prob < 1.0 else None,
        keep_prob=config.keep_prob,
        compute_dtype=compute_dtype,
    )
    return loss, grads


def make_dp_step(config: TrainConfig, mesh: Mesh) -> Callable:
    """Pure sync DP (``mnist_sync`` parity): psum grads, replicated Adam.

    Returns jitted ``step(params, opt_state, x, y, rng) -> (params, opt, loss)``
    with ``x``/``y`` batch-sharded over the mesh axis (or replicated when
    ``config.shard_data=False``, reproducing the reference's identical-batches
    behavior, mnist_sync/worker.py:27-30).
    """
    W = mesh.devices.size
    data_spec = P(DP_AXIS) if config.shard_data else P()
    mean = config.grad_reduction == "mean"

    def step(params, opt_state, x, y, rng):
        loss, grads = _local_grads(config, params, x, y, rng, DP_AXIS)
        grads = lax.psum(grads, DP_AXIS)
        loss = lax.psum(loss, DP_AXIS) / W
        if mean:
            grads = jax.tree.map(lambda g: g / W, grads)
        params, opt_state = adam_update(
            params, opt_state, grads, lr=config.learning_rate
        )
        return params, opt_state, loss

    smapped = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(), data_spec, data_spec, P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(smapped, donate_argnums=donation_for(mesh, 0, 1))


def make_sharded_step(
    config: TrainConfig,
    mesh: Mesh,
    layout: LayoutAssignment,
    shapes: Mapping[str, tuple[int, ...]] | None = None,
) -> Callable:
    """ZeRO-1 sharded sync step (``mnist_sync_sharding[_greedy]`` parity).

    Returns jitted ``step(params, sharded_opt, x, y, rng)``. Collective
    schedule per step (all along the ICI mesh axis):

      flat grads --reduce_scatter--> owner slice --local Adam-->
      updated slice --all_gather--> full flat params

    For the "flat" layout the reduce-scatter is a single fused
    ``psum_scatter`` (bandwidth-optimal); variable-aligned layouts reduce
    with ``psum`` then slice the unequal owner range (padded to max_shard).
    """
    W = mesh.devices.size
    spec = coll.FlatSpec.from_layout(layout, shapes or dict(cnn.PARAM_SPECS))
    data_spec = P(DP_AXIS) if config.shard_data else P()
    mean = config.grad_reduction == "mean"
    # The fused psum_scatter path needs one equal chunk per mesh device.
    equal_chunks = layout.policy == "flat" and layout.num_shards == W
    chunk = layout.max_shard
    reassembly = coll.reassembly_index(layout)
    starts = np.asarray(layout.shard_starts, np.int32)
    if len(starts) < W:
        # Fewer shards than devices (num_ps < num_workers): surplus devices
        # own an empty range parked at the padding tail.
        starts = np.concatenate([starts, np.full(W - len(starts), layout.total, np.int32)])
    # Enough padding that every device's (start, chunk) slice is in bounds.
    pad_len = max(W * chunk, layout.total + chunk)

    def step(params, opt: ShardedAdam, x, y, rng):
        loss, grads = _local_grads(config, params, x, y, rng, DP_AXIS)
        loss = lax.psum(loss, DP_AXIS) / W
        g_flat = coll.flatten_params(grads, spec)
        p_flat = coll.flatten_params(params, spec)

        if equal_chunks:
            g_own = coll.reduce_scatter_flat(g_flat, W, DP_AXIS, mean=mean)
            my_start = lax.axis_index(DP_AXIS) * chunk
        else:
            g_red = lax.psum(g_flat, DP_AXIS)
            if mean:
                g_red = g_red / W
            my_start = jnp.asarray(starts)[lax.axis_index(DP_AXIS)]
            g_own = lax.dynamic_slice(
                jnp.pad(g_red, (0, pad_len - layout.total)), (my_start,), (chunk,)
            )

        p_own = lax.dynamic_slice(
            jnp.pad(p_flat, (0, pad_len - layout.total)), (my_start,), (chunk,)
        )
        p_new, opt = _adam_flat(p_own, opt, g_own, lr=config.learning_rate)

        gathered = lax.all_gather(p_new, DP_AXIS, tiled=True)  # [W * chunk]
        if equal_chunks:
            full = gathered[: layout.total]
        else:
            full = gathered[jnp.asarray(reassembly)]
        return coll.unflatten_params(full, spec), opt, loss

    smapped = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), ShardedAdam(step=P(), m=P(DP_AXIS), v=P(DP_AXIS)), data_spec, data_spec, P()),
        out_specs=(P(), ShardedAdam(step=P(), m=P(DP_AXIS), v=P(DP_AXIS)), P()),
        check_vma=False,
    )
    return jax.jit(smapped, donate_argnums=donation_for(mesh, 0, 1))


def sharded_adam_init(mesh: Mesh, layout: LayoutAssignment) -> ShardedAdam:
    """Zero-initialized sharded Adam state, placed ``P(DP_AXIS)``."""
    W = mesh.devices.size
    sharding = NamedSharding(mesh, P(DP_AXIS))
    z = jnp.zeros((W * layout.max_shard,), jnp.float32)
    z = jax.device_put(z, sharding)
    return ShardedAdam(
        step=jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P())),
        m=z,
        v=jnp.copy(z),
    )


def resolve_layout(
    config: TrainConfig,
    num_devices: int,
    sizes: dict[str, int] | None = None,
) -> LayoutAssignment | None:
    """Map config topology to a layout. ``num_ps <= 1`` and layout unset
    means pure DP (no sharding); otherwise resolve the policy over the
    model's variable table (``sizes``; defaults to the flagship CNN). On TPU
    the shards co-locate with the workers (ZeRO) — there are no separate PS
    processes, so ``num_ps`` means "number of devices that own a param
    shard" and must be <= the mesh size."""
    if config.num_ps <= 1:
        return None
    if config.num_ps > num_devices:
        raise ValueError(
            f"num_ps={config.num_ps} exceeds mesh size {num_devices}: TPU "
            "shards co-locate with workers (ZeRO); use num_ps <= num_workers"
        )
    if sizes is None:
        sizes = cnn.param_sizes()
    # num_ps is honored for every policy; "flat" additionally unlocks the
    # fused psum_scatter fast path when num_ps == num_workers (full ZeRO-1).
    return assign_layout(config.layout, config.num_ps, list(sizes), sizes)


class SyncTrainer:
    """Drives any sync strategy over an epoch loop with the reference's
    eval-every-10-batches cadence (mnist_sync/worker.py:71-72)."""

    def __init__(
        self,
        config: TrainConfig,
        dataset: Dataset,
        mesh: Mesh | None = None,
        init: dict | None = None,
    ):
        self.config = config
        self.dataset = dataset
        self.mesh = mesh if mesh is not None else make_mesh(config.num_workers)
        W = self.mesh.devices.size
        if W != config.num_workers:
            raise ValueError(f"mesh has {W} devices, config.num_workers={config.num_workers}")
        key = jax.random.PRNGKey(config.seed)
        self.init_key, self.dropout_key = jax.random.split(key)
        params = init if init is not None else cnn.init_params(self.init_key)
        shapes = cnn.param_shapes(params)
        sizes = {k: int(np.prod(s)) if s else 1 for k, s in shapes.items()}
        self.layout = resolve_layout(config, W, sizes)
        self.params = jax.device_put(params, NamedSharding(self.mesh, P()))
        if self.layout is None:
            self.opt_state: Any = jax.device_put(
                adam_init(params), NamedSharding(self.mesh, P())
            )
            self._step = make_dp_step(config, self.mesh)
        else:
            self.opt_state = sharded_adam_init(self.mesh, self.layout)
            self._step = make_sharded_step(config, self.mesh, self.layout, shapes)

    def train(self, log: Callable[[str], None] = print) -> TrainResult:
        cfg = self.config
        ds = self.dataset
        x_train = np.asarray(ds.x_train)
        y_train = one_hot(ds.y_train)
        x_test = jnp.asarray(ds.x_test)
        y_test = jnp.asarray(one_hot(ds.y_test))
        data_sharding = NamedSharding(
            self.mesh, P(DP_AXIS) if cfg.shard_data else P()
        )

        params, opt_state = self.params, self.opt_state
        # Global batch per step; when data is sharded each device sees
        # batch_size/W examples (per_worker_batch validates divisibility).
        if cfg.shard_data:
            cfg.per_worker_batch()
        batch_num = ds.num_train // cfg.batch_size
        history: list[tuple[int, int, float]] = []
        images = 0
        train_time = 0.0
        start = time.perf_counter()
        seg = start
        for epoch in range(cfg.epochs):
            for cnt in range(batch_num):
                lo, hi = cfg.batch_size * cnt, cfg.batch_size * (cnt + 1)
                xb = jax.device_put(x_train[lo:hi], data_sharding)
                yb = jax.device_put(y_train[lo:hi], data_sharding)
                rng = jax.random.fold_in(self.dropout_key, epoch * batch_num + cnt)
                params, opt_state, _ = self._step(params, opt_state, xb, yb, rng)
                images += cfg.batch_size
                if cfg.eval_every and cnt % cfg.eval_every == 0:
                    jax.block_until_ready(params)
                    train_time += time.perf_counter() - seg
                    acc = evaluate(params, x_test, y_test)
                    history.append((epoch, cnt, acc))
                    log(f"epoch: {epoch} batch: {cnt} accuracy: {acc}")
                    seg = time.perf_counter()
        jax.block_until_ready(params)
        end = time.perf_counter()
        train_time += end - seg
        final_acc = evaluate(params, x_test, y_test)
        log(f"final accuracy: {final_acc}")
        self.params, self.opt_state = params, opt_state
        return TrainResult(
            params=jax.tree.map(np.asarray, params),
            final_accuracy=final_acc,
            wall_time_s=end - start,
            train_time_s=train_time,
            history=history,
            images_per_sec=images / train_time if train_time > 0 else 0.0,
        )
