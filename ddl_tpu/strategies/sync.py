"""Synchronous strategies: pure data-parallel and parameter-sharded (ZeRO-1).

Reference semantics being re-designed (not translated):

- ``mnist_sync``: every worker pushes its 14 grads to one PS, which sums them
  (never averaging — parameter_server.py:36-37), takes one Adam step, and
  broadcasts fresh params; workers barrier on the Bcast
  (mnist_sync/worker.py:60-72, parameter_server.py:54-69).
  TPU-native: one SPMD program per step — per-chip grads, ``psum`` over the
  ICI mesh axis (default mean; ``grad_reduction="sum"`` reproduces the
  reference's summed-LR behavior), replicated Adam. The PS process, the
  py_function grad escape hatch, and the 14 per-var round-trips all vanish
  into one compiled step.

- ``mnist_sync_sharding[_greedy]``: M PS ranks each own a block of variables
  and update only their shard (parameter_server.py:30-32,42-69); the greedy
  variant permutes variables before blocking (greedy worker.py:14-37).
  TPU-native: ZeRO-1 — flatten params into one vector in layout order,
  reduce-scatter grads so each device owns a slice, shard-local Adam (m/v
  live ONLY on the owner — the memory win), all-gather updated params.
  Layout policies: "flat" (equal chunks, bandwidth-optimal psum_scatter),
  "block"/"zigzag"/"lpt" (variable-aligned owner ranges, reproducing and
  generalizing the reference's partitioning — see ddl_tpu.parallel.layout).

Numerics: with ``grad_reduction="mean"`` and no dropout, every sync strategy
is step-equivalent to the single-chip trainer on the same global batch (the
parity tests assert this); sharded vs unsharded are equivalent for any
layout because Adam is elementwise.

The reference's sharded-PS aggregation bug (aliased buffers double-counting
workers, parameter_server.py:43-47,77-80 — SURVEY.md §3.5) is *not*
reproduced: psum/psum_scatter are correct by construction, and
``tests/test_sync_strategies.py`` pins the correct aggregation.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..data import Dataset, one_hot
from ..models import cnn
from ..ops import adam_init, adam_update
from ..parallel import collectives as coll
from ..parallel import multihost
from ..parallel.layout import LayoutAssignment, assign_layout, fold_shards
from ..parallel.mesh import DP_AXIS, donation_for, make_mesh, pallas_interpret_for
from ..train.config import TrainConfig
from ..train.trainer import (
    TrainResult,
    check_preempt,
    checkpoint_file,
    eval_spans,
    evaluate,
    force,
    force_within,
    guarded,
    hit_target,
    resume_plan,
    save_crossed,
    staging_dtype,
    steps_scan,
    try_resume,
)
from ..utils.checkpoint import save_checkpoint
from ..utils.metrics import StepTimer, trace


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ShardedAdam:
    """Adam state over the flat param vector, sharded along the mesh axis.

    ``m``/``v`` hold only this framework's analogue of a PS shard's slots
    (reference: per-shard optimizer at
    mnist_sync_sharding/parameter_server.py:56-69): globally ``[S * max_shard]``
    with ``NamedSharding(P(DP_AXIS))``, i.e. ``max_shard`` elements resident
    per device — the ZeRO-1 memory saving.
    """

    step: jax.Array  # int32 scalar, replicated
    m: jax.Array
    v: jax.Array


def _adam_flat(p, state: ShardedAdam, g, *, lr, b1=0.9, b2=0.999, eps=1e-8,
               fused=False, pallas_interpret=False):
    """TF1-semantics Adam (see ddl_tpu.ops.optimizers) on flat slices.

    ``fused=True`` routes through the hand-fused Pallas kernel
    (ops/pallas_adam.py, ~1-ulp-equivalent); the default is the XLA-fused
    elementwise chain. ``pallas_interpret`` selects the interpreter (the
    CPU-testable path) for the kernel."""
    step = state.step + 1
    t = step.astype(jnp.float32)
    lr_t = lr * jnp.sqrt(1.0 - b2**t) / (1.0 - b1**t)
    if fused:
        from ..ops.pallas_adam import adam_flat_fused

        p_new, m, v = adam_flat_fused(
            p, state.m, state.v, g, lr_t, b1=b1, b2=b2, eps=eps,
            interpret=pallas_interpret,
        )
        return p_new, ShardedAdam(step=step, m=m, v=v)
    m = b1 * state.m + (1.0 - b1) * g
    v = b2 * state.v + (1.0 - b2) * g * g
    return p - lr_t * m / (jnp.sqrt(v) + eps), ShardedAdam(step=step, m=m, v=v)


def _local_grads(config: TrainConfig, params, x, y, rng, axis: str):
    """Per-device loss+grads with a device-distinct dropout stream
    (reference workers use independent masks — SURVEY.md §7d). The
    compute dtype is the resolved precision policy's
    (``TrainConfig.policy()`` — ddl_tpu.precision)."""
    compute_dtype = config.policy().compute_dtype
    rng = jax.random.fold_in(rng, lax.axis_index(axis))
    loss, grads = jax.value_and_grad(cnn.loss_fn)(
        params,
        x,
        y,
        dropout_rng=rng if config.keep_prob < 1.0 else None,
        keep_prob=config.keep_prob,
        compute_dtype=compute_dtype,
        conv_matmul=config.conv_matmul_mode(),
    )
    return loss, grads


def _dp_step_body(config: TrainConfig, W: int) -> Callable:
    """Raw per-device DP step (usable inside shard_map): psum grads,
    replicated Adam."""
    mean = config.grad_reduction == "mean"

    def step(params, opt_state, x, y, rng):
        loss, grads = _local_grads(config, params, x, y, rng, DP_AXIS)
        grads = lax.psum(grads, DP_AXIS)
        loss = lax.psum(loss, DP_AXIS) / W
        if mean:
            grads = jax.tree.map(lambda g: g / W, grads)
        params, opt_state = adam_update(
            params, opt_state, grads, lr=config.learning_rate
        )
        return params, opt_state, loss

    return step


def make_dp_step(config: TrainConfig, mesh: Mesh) -> Callable:
    """Pure sync DP (``mnist_sync`` parity): psum grads, replicated Adam.

    Returns jitted ``step(params, opt_state, x, y, rng) -> (params, opt, loss)``
    with ``x``/``y`` batch-sharded over the mesh axis (or replicated when
    ``config.shard_data=False``, reproducing the reference's identical-batches
    behavior, mnist_sync/worker.py:27-30).
    """
    W = mesh.devices.size
    data_spec = P(DP_AXIS) if config.shard_data else P()
    smapped = jax.shard_map(
        _dp_step_body(config, W),
        mesh=mesh,
        in_specs=(P(), P(), data_spec, data_spec, P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(smapped, donate_argnums=donation_for(mesh, 0, 1))


def make_sharded_step(
    config: TrainConfig,
    mesh: Mesh,
    layout: LayoutAssignment,
    shapes: Mapping[str, tuple[int, ...]] | None = None,
) -> Callable:
    """ZeRO-1 sharded sync step (``mnist_sync_sharding[_greedy]`` parity).

    Returns jitted ``step(params, sharded_opt, x, y, rng)``. Collective
    schedule per step (all along the ICI mesh axis):

      flat grads --reduce_scatter--> owner slice --local Adam-->
      updated slice --all_gather--> full flat params

    Both layout families reduce-scatter with a single fused ``psum_scatter``:
    "flat" reshapes into equal contiguous rows; variable-aligned layouts
    (block/zigzag/lpt) first gather the flat grad into owner-major padded
    rows ``[W, max_shard]`` (rows may overlap for unbalanced shards) so the
    row scatter lands each device exactly its owned range.
    """
    W = mesh.devices.size
    step = _sharded_step_body(config, W, layout, shapes,
                              pallas_interpret=pallas_interpret_for(mesh))
    data_spec = P(DP_AXIS) if config.shard_data else P()
    smapped = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), ShardedAdam(step=P(), m=P(DP_AXIS), v=P(DP_AXIS)), data_spec, data_spec, P()),
        out_specs=(P(), ShardedAdam(step=P(), m=P(DP_AXIS), v=P(DP_AXIS)), P()),
        check_vma=False,
    )
    return jax.jit(smapped, donate_argnums=donation_for(mesh, 0, 1))


def _sharded_step_body(
    config: TrainConfig,
    W: int,
    layout: LayoutAssignment,
    shapes: Mapping[str, tuple[int, ...]] | None = None,
    *,
    pallas_interpret: bool = False,
) -> Callable:
    """Raw per-device ZeRO-1 step (usable inside shard_map).
    ``pallas_interpret`` runs the fused-Adam Pallas kernel (when
    ``config.fused_adam``) in interpreter mode — required off-TPU."""
    spec = coll.FlatSpec.from_layout(layout, shapes or dict(cnn.PARAM_SPECS))
    mean = config.grad_reduction == "mean"
    # The reshape-based psum_scatter path needs one equal chunk per device.
    equal_chunks = layout.policy == "flat" and layout.num_shards == W
    chunk = layout.max_shard
    reassembly = coll.reassembly_index(layout)
    sl = coll.owner_slices(layout, W)

    def step(params, opt: ShardedAdam, x, y, rng):
        loss, grads = _local_grads(config, params, x, y, rng, DP_AXIS)
        loss = lax.psum(loss, DP_AXIS) / W
        g_flat = coll.flatten_params(grads, spec)
        p_flat = coll.flatten_params(params, spec)

        if equal_chunks:
            g_own = coll.reduce_scatter_flat(
                g_flat, W, DP_AXIS, mean=mean, chunk=chunk
            )
            my_start = lax.axis_index(DP_AXIS) * chunk
        else:
            # True reduce-scatter for var-aligned layouts (round-3 verdict
            # weak #4) — see collectives.reduce_scatter_rows.
            g_own = coll.reduce_scatter_rows(
                g_flat, sl, DP_AXIS, mean=mean, num_devices=W
            )
            my_start = jnp.asarray(sl.starts)[lax.axis_index(DP_AXIS)]

        p_own = lax.dynamic_slice(
            jnp.pad(p_flat, (0, sl.pad_len - layout.total)), (my_start,), (chunk,)
        )
        p_new, opt = _adam_flat(
            p_own, opt, g_own, lr=config.learning_rate,
            fused=config.fused_adam, pallas_interpret=pallas_interpret,
        )

        gathered = lax.all_gather(p_new, DP_AXIS, tiled=True)  # [W * chunk]
        if equal_chunks:
            full = gathered[: layout.total]
        else:
            full = gathered[jnp.asarray(reassembly)]
        return coll.unflatten_params(full, spec), opt, loss

    return step


def make_sync_epoch(
    config: TrainConfig,
    mesh: Mesh,
    layout: LayoutAssignment | None,
    shapes: Mapping[str, tuple[int, ...]] | None,
    k: int,
) -> Callable:
    """Device-resident multi-step sync program: ``k`` consecutive batches in
    ONE compiled dispatch (``lax.scan`` inside the shard_map), replacing the
    reference's per-batch host round-trips (mnist_sync/worker.py:60-72).

    Returns jitted ``run(params, opt, xs, ys, first, goff, rng_base) ->
    (params, opt, mean_loss)`` where ``xs``/``ys`` hold the FULL epoch:

    - sharded data: ``[W, B, bs/W, ...]`` placed ``P(DP_AXIS)`` — worker w's
      slice of every batch lives on device w for the whole epoch;
    - replicated data (``shard_data=False`` compat): ``[B, bs, ...]``, ``P()``.

    ``first`` is the span's first batch index and ``goff`` the global step
    offset feeding the dropout stream — identical streams to the per-step
    path, so span chunking never changes the math. The scanned program and
    the per-step programs are compiled separately, so XLA fusion may
    reassociate float ops: outputs agree to ~1e-7, not bitwise
    (pinned by tests/test_sync_trainer.py).
    """
    W = mesh.devices.size
    if layout is None:
        step = _dp_step_body(config, W)
        opt_spec: Any = P()
    else:
        step = _sharded_step_body(
            config, W, layout, shapes,
            pallas_interpret=pallas_interpret_for(mesh),
        )
        opt_spec = ShardedAdam(step=P(), m=P(DP_AXIS), v=P(DP_AXIS))
    data_spec = P(DP_AXIS) if config.shard_data else P()

    def run(params, opt_state, xs, ys, first, goff, rng_base):
        def body(carry, i):
            params, opt_state = carry
            if config.shard_data:
                # Local view [1, B, bs/W, ...] -> this device's batch slice.
                x = lax.dynamic_index_in_dim(xs[0], first + i, 0, keepdims=False)
                y = lax.dynamic_index_in_dim(ys[0], first + i, 0, keepdims=False)
            else:
                x = lax.dynamic_index_in_dim(xs, first + i, 0, keepdims=False)
                y = lax.dynamic_index_in_dim(ys, first + i, 0, keepdims=False)
            rng = jax.random.fold_in(rng_base, goff + i)
            params, opt_state, loss = step(params, opt_state, x, y, rng)
            return (params, opt_state), loss

        (params, opt_state), losses = steps_scan(
            body, (params, opt_state), jnp.arange(k), k
        )
        return params, opt_state, losses.mean()

    smapped = jax.shard_map(
        run,
        mesh=mesh,
        in_specs=(P(), opt_spec, data_spec, data_spec, P(), P(), P()),
        out_specs=(P(), opt_spec, P()),
        check_vma=False,
    )
    return jax.jit(smapped, donate_argnums=donation_for(mesh, 0, 1))


def sharded_adam_init(mesh: Mesh, layout: LayoutAssignment) -> ShardedAdam:
    """Zero-initialized sharded Adam state, placed ``P(DP_AXIS)``
    (multi-host-safe: placement goes through ``multihost.put``)."""
    W = mesh.devices.size
    z = multihost.put(
        mesh, P(DP_AXIS), np.zeros((W * layout.max_shard,), np.float32)
    )
    return ShardedAdam(
        step=multihost.put(mesh, P(), np.zeros((), np.int32)),
        m=z,
        v=jnp.copy(z),
    )


def resolve_layout(
    config: TrainConfig,
    num_devices: int,
    sizes: dict[str, int] | None = None,
) -> LayoutAssignment | None:
    """Map config topology to a layout. ``num_ps <= 1`` and layout unset
    means pure DP (no sharding); otherwise resolve the policy over the
    model's variable table (``sizes``; defaults to the flagship CNN). On TPU
    the shards co-locate with the workers (ZeRO) — there are no separate PS
    processes, so ``num_ps`` means "number of parameter shards". When
    ``num_ps`` exceeds the mesh size (the reference's ``run.sh 7 2``: more
    PS processes than workers), the surplus shards fold round-robin onto the
    devices (layout.fold_shards) — any split the reference launcher accepts
    runs here too. That includes ``num_ps > num_vars`` (the reference's
    block split degenerately accepts e.g. ``run.sh 20 2`` by giving most PS
    zero variables, parameter_server.py:30-32): var-granular policies clamp
    to one shard per variable — the maximum var-aligned parallelism that
    exists — rather than reproducing empty shards."""
    if config.num_ps <= 1:
        return None
    if sizes is None:
        sizes = cnn.param_sizes()
    num_ps = config.num_ps
    if config.layout != "flat":
        # Var-granular policies cannot have more (non-empty) shards than
        # variables; the reference's degenerate empty-PS split clamps here.
        num_ps = min(num_ps, len(sizes))
    if num_ps > num_devices:
        if config.layout == "flat":
            # Element-granular equal chunks: re-splitting over the mesh size
            # is the identical ownership a fold would produce.
            return assign_layout("flat", num_devices, list(sizes), sizes)
        base = assign_layout(config.layout, num_ps, list(sizes), sizes)
        return fold_shards(base, num_devices, sizes)
    # num_ps is honored for every policy; "flat" additionally unlocks the
    # fused psum_scatter fast path when num_ps == num_workers (full ZeRO-1).
    return assign_layout(config.layout, num_ps, list(sizes), sizes)


class SyncTrainer:
    """Drives any sync strategy device-resident: the epoch's data is staged
    on the mesh once (each worker's slice of every batch resident on its
    device) and each eval span runs as one compiled multi-step program
    (``make_sync_epoch``), with the reference's eval-every-10-batches
    cadence (mnist_sync/worker.py:71-72) on the host side."""

    def __init__(
        self,
        config: TrainConfig,
        dataset: Dataset,
        mesh: Mesh | None = None,
        init: dict | None = None,
    ):
        self.config = config
        self.dataset = dataset
        self.mesh = mesh if mesh is not None else make_mesh(config.num_workers)
        W = self.mesh.devices.size
        if W != config.num_workers:
            raise ValueError(f"mesh has {W} devices, config.num_workers={config.num_workers}")
        key = jax.random.PRNGKey(config.seed)
        self.init_key, self.dropout_key = jax.random.split(key)
        params = (
            init if init is not None
            else cnn.init_params(self.init_key, specs=config.model_specs())
        )
        self._shapes = cnn.param_shapes(params)
        sizes = {k: int(np.prod(s)) if s else 1 for k, s in self._shapes.items()}
        self.layout = resolve_layout(config, W, sizes)
        self.params = multihost.put_tree(self.mesh, P(), params)
        if self.layout is None:
            self.opt_state: Any = multihost.put_tree(
                self.mesh, P(), adam_init(params)
            )
        else:
            self.opt_state = sharded_adam_init(self.mesh, self.layout)
        self._chunks: dict[int, Callable] = {}

    def _chunk_fn(self, k: int) -> Callable:
        if k not in self._chunks:
            self._chunks[k] = make_sync_epoch(
                self.config, self.mesh, self.layout, self._shapes, k
            )
        return self._chunks[k]

    def _stage_epoch(self, batch_num: int) -> tuple[jax.Array, jax.Array]:
        """Stage the epoch on the mesh: sharded -> ``[W, B, bs/W, ...]`` with
        worker w's slice of every batch on device w; replicated compat
        stream -> ``[B, bs, ...]`` everywhere."""
        cfg = self.config
        ds = self.dataset
        W = self.mesh.devices.size
        bs = cfg.batch_size
        n = batch_num * bs
        # bf16 staging when the compute dtype is bf16 (see
        # trainer.staging_dtype); labels stay fp32.
        x = np.asarray(ds.x_train)[:n].astype(staging_dtype(cfg), copy=False)
        y = one_hot(ds.y_train)[:n]
        # Explicit feature dims: batch_num may be 0 (dataset < one global
        # batch), where reshape(-1) inference fails — zero batches stages
        # empty arrays and the span loop runs zero steps.
        fx, fy = x.shape[-1], y.shape[-1]
        if cfg.shard_data:
            pb = cfg.per_worker_batch()
            xs = np.ascontiguousarray(
                x.reshape(batch_num, W, pb, fx).transpose(1, 0, 2, 3)
            )
            ys = np.ascontiguousarray(
                y.reshape(batch_num, W, pb, fy).transpose(1, 0, 2, 3)
            )
            spec = P(DP_AXIS)
        else:
            xs = x.reshape(batch_num, bs, fx)
            ys = y.reshape(batch_num, bs, fy)
            spec = P()
        return (multihost.put(self.mesh, spec, xs),
                multihost.put(self.mesh, spec, ys))

    def _ckpt_spec(self) -> coll.FlatSpec:
        return coll.FlatSpec.from_layout(self.layout, self._shapes)

    def _opt_like(self):
        """Host-shaped template for the checkpointed optimizer state:
        replicated Adam as-is (DP); ZeRO-1 m/v as PARAMS-SHAPED pytrees —
        the layout-independent form, so a checkpoint written at one
        topology resumes at any other (elastic resume: a preempted 8-chip
        flat run can continue as a 4-chip zigzag run). A flat vector would
        NOT be elastic — each layout orders variables differently."""
        if self.layout is None:
            return self.opt_state
        zeros = {n: np.zeros(s, np.float32) for n, s in self._shapes.items()}
        return ShardedAdam(
            step=np.zeros((), np.int32),
            m=zeros,
            v={n: z.copy() for n, z in zeros.items()},
        )

    def _opt_for_save(self, opt_state):
        """Checkpoint form of the optimizer state (see ``_opt_like``).
        Sharded m/v span processes in a multi-host world; replicate first
        so every process can materialize the save (no-op at one process)."""
        if self.layout is None:
            return multihost.replicate_for_host(self.mesh, opt_state)
        rep = multihost.replicate_for_host(
            self.mesh, (opt_state.m, opt_state.v)
        )
        spec = self._ckpt_spec()
        unflat = lambda padded: jax.tree.map(np.asarray, coll.unflatten_params(
            jnp.asarray(coll.to_logical(padded, self.layout)), spec
        ))
        return ShardedAdam(
            step=np.asarray(opt_state.step),
            m=unflat(rep[0]),
            v=unflat(rep[1]),
        )

    def _place_state(self, params, opt_state):
        """Re-place host (checkpoint) state onto this trainer's shardings:
        params replicated; Adam state replicated (DP) or params-shaped m/v
        re-flattened and re-sharded onto the CURRENT mesh/layout (ZeRO-1,
        elastic)."""
        params = multihost.put_tree(self.mesh, P(), params)
        if self.layout is None:
            opt_state = multihost.put_tree(self.mesh, P(), opt_state)
        else:
            spec = self._ckpt_spec()
            n = self.mesh.devices.size * self.layout.max_shard
            refit = lambda tree: multihost.put(
                self.mesh, P(DP_AXIS), coll.from_logical(
                    np.asarray(coll.flatten_params(tree, spec)),
                    self.layout, n,
                ),
            )
            opt_state = ShardedAdam(
                step=multihost.put(self.mesh, P(), np.asarray(opt_state.step)),
                m=refit(opt_state.m),
                v=refit(opt_state.v),
            )
        return params, opt_state

    def train(
        self,
        log: Callable[[str], None] = print,
        *,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 0,
        resume: bool = False,
        profile_dir: str | None = None,
        should_stop: Callable[[], bool] | None = None,
        dispatch_timeout: float = 0.0,
    ) -> TrainResult:
        cfg = self.config
        ds = self.dataset
        batch_num = ds.num_train // cfg.batch_size
        xs, ys = self._stage_epoch(batch_num)
        # Replicated placement (multi-process: a host-local jnp.asarray would
        # be device-incompatible with the global params at the first eval).
        x_test = multihost.put(self.mesh, P(), np.asarray(ds.x_test))
        y_test = multihost.put(self.mesh, P(), one_hot(ds.y_test))

        # Fresh buffers: the chunk programs donate params/opt (on TPU), which
        # must never consume arrays the caller still owns.
        params = jax.tree.map(jnp.copy, self.params)
        opt_state = jax.tree.map(jnp.copy, self.opt_state)
        ckpt = checkpoint_file(checkpoint_dir)
        tree, start_step = try_resume(
            ckpt, resume, {"params": params, "opt": self._opt_like()}, log
        )
        if tree is not None:
            params, opt_state = self._place_state(tree["params"], tree["opt"])
        # Materialize staged data + state BEFORE the clock starts: transfers
        # are async (and lazy on the tunnel backend); steady-state throughput
        # must not absorb the host->HBM upload of the train set.
        guarded(lambda: force((xs, ys, params, opt_state), all_leaves=True),
                dispatch_timeout, "train-set staging")
        spans = eval_spans(batch_num, cfg.eval_every)
        resume_epoch, resume_spans = resume_plan(
            start_step, batch_num, cfg.eval_every, spans
        )
        history: list[tuple[int, int, float]] = []
        # AOT-compile every span program outside the timed region (first TPU
        # compile is tens of seconds; steady-state throughput must not absorb
        # it). ``lower().compile()`` does not execute anything.
        t0 = time.perf_counter()
        args0 = (jnp.int32(0), jnp.int32(0), self.dropout_key)
        fns = {
            k: self._chunk_fn(k).lower(params, opt_state, xs, ys, *args0).compile()
            for k in {k for _, k, _ in spans} | {k for _, k, _ in resume_spans}
        }
        # Warm the eval program too: its first call otherwise compiles
        # INSIDE the dispatch watchdog, which a steady-state-sized
        # --dispatch-timeout would misread as accelerator death.
        if x_test.shape[0]:
            evaluate(params, x_test, y_test)
        compile_time = time.perf_counter() - t0
        timer = StepTimer()
        stopped = preempted = False
        span_idx = 0
        start = time.perf_counter()
        with trace(profile_dir):
            for epoch in range(cfg.epochs):
                for first, k, eval_after in (
                    resume_spans if epoch == resume_epoch else spans
                ):
                    gstep = epoch * batch_num + first
                    if gstep < start_step:
                        continue  # already done by the resumed run
                    span_idx += 1
                    with timer.step(images=k * cfg.batch_size):
                        params, opt_state, _ = fns[k](
                            params, opt_state, xs, ys,
                            jnp.int32(first), jnp.int32(gstep),
                            self.dropout_key,
                        )
                        # barrier: the fns[k] span dispatch
                        force_within(
                            params, dispatch_timeout,
                            f"span dispatch at global step {gstep}",
                        )
                    if eval_after:
                        cnt = first + k - 1
                        acc = guarded(
                            lambda: evaluate(params, x_test, y_test),
                            dispatch_timeout, f"eval after batch {cnt}",
                        )
                        history.append((epoch, cnt, acc))
                        log(f"epoch: {epoch} batch: {cnt} accuracy: {acc}")
                        stopped = hit_target(cfg, acc)
                    preempted = preempted or check_preempt(
                        should_stop, log, ckpt is not None, span_idx
                    )
                    if ckpt and save_crossed(
                        gstep, k, checkpoint_every,
                        first + k == batch_num or stopped or preempted,
                    ):
                        save_checkpoint(
                            ckpt,
                            {"params": params,
                             "opt": self._opt_for_save(opt_state)},
                            step=gstep + k, extra={"epoch": epoch},
                        )
                    if stopped or preempted:
                        break
                if stopped:
                    log(f"target accuracy {cfg.target_accuracy} reached")
                if stopped or preempted:
                    break
        end = time.perf_counter()
        train_time = timer.total_s
        final_acc = guarded(lambda: evaluate(params, x_test, y_test),
                            dispatch_timeout, "final eval")
        log(f"final accuracy: {final_acc}")
        self.params, self.opt_state = params, opt_state
        return TrainResult(
            params=jax.tree.map(np.asarray, params),
            final_accuracy=final_acc,
            wall_time_s=end - start,
            train_time_s=train_time,
            history=history,
            images_per_sec=timer.total_images / train_time if train_time > 0 else 0.0,
            compile_time_s=compile_time,
            step_stats=timer.stats(),
            resumed_from_step=start_step,
            preempted=preempted,
        )
