"""The pipeline train/eval step bodies — ``shard_map`` programs over the
4-D ``[dp, sp, tp, pp]`` mesh (``parallel.mesh.make_mesh_4d``).

One ``lax.scan`` over schedule TICKS executes any (GPipe or 1F1B) table
pair from ``pipeline.schedule``. Every tick, every pp position runs the
SAME masked SPMD body — one stage FORWARD slot and one stage BACKWARD
slot — and two ``ppermute``s hop the tick's products along the pp axis:
the forward slot's activation to stage ``s+1``, the backward slot's
input-cotangent to stage ``s-1``. Idle slots compute on junk and mask
the results (uniform SPMD: per-stage control flow does not exist inside
``shard_map``, and a traced ``lax.cond`` lowers to ``select`` anyway),
so wall time is proportional to TICK COUNT — which is exactly what
makes the schedule's bubble fraction measurable
(``benchmarks/pipeline_bubble.py``).

The backward is MANUAL — per-microbatch ``jax.vjp`` recompute from the
saved stage INPUT (activation-recompute pipelining: per in-flight
microbatch a stage holds one ``[mb, T, E]`` input, never the attention
residuals) — so no gradient ever rides an autodiff transpose of
``ppermute``/``psum`` whose rule varies across JAX generations
(``ddl_tpu.compat``; the same explicit-gradient discipline as
``collectives.tp_allreduce``). Megatron tensor parallelism composes
INSIDE the stage unchanged: ``jax.vjp`` honours the f/g ``custom_vjp``
pair, so tp's activation psums run in lockstep across the tp axis at
every tick.

Loss discipline matches ``strategies.seq._local_loss_fn``: each device
accumulates its own scored-token CE sum over the GLOBAL (psum'd) weight
total; every microbatch backward seeds with ``1/global_den``; gradients
stay LOCAL until ONE explicit reduction at step end — ``psum`` over
(dp, sp) for the stage-resident block stack, ``psum`` over (dp, sp, pp)
for the pp-replicated embed/head/final-LN leaves (exactly one stage
contributes nonzero; the psum doubles as the broadcast).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..models import transformer
from ..ops import adam_update
from ..parallel import collectives as coll
from ..parallel.mesh import DP_AXIS, PP_AXIS, SP_AXIS, TP_AXIS

# The data axes every pipeline loss/grad reduction runs over (sp is
# size 1 under pipeline parallelism — kept so the specs and psums stay
# word-for-word the 2-D trainer's).
AXES = (DP_AXIS, SP_AXIS)

# pp-replicated leaves of the pipeline param tree: owned by stage 0
# (embed) / the last stage (final-LN, head), zero-gradient everywhere
# else, reduced over (dp, sp, pp) instead of (dp, sp).
SHARED_LEAVES = ("embed", "head", "lnf_g", "lnf_b")


def _local_attn(config, platform):
    """Per-stage local attention: the sequence is WHOLE on every device
    under pipeline parallelism (``validate_topology`` enforces
    num_workers == 1 and scheme='full'), so this is exactly the
    scheme='full' branch of ``strategies.seq._attn_for`` — reused, not
    re-implemented, so kernel selection (xla/flash, platform gating) can
    never fork between the pipeline and the oracle it is pinned
    against. Lazy import: seq imports this package only inside methods,
    so there is no cycle either way, but keeping both directions lazy
    makes import order irrelevant."""
    from ..strategies.seq import _attn_for

    return _attn_for(config, platform)


def make_stage_fn(config, platform):
    """Build the per-stage forward closure:

    ``stage_fn(params, h_in, tokens, targets, weights, first)
    -> (h_out, ce_num)``

    ``params`` is the PIPELINE (stacked-blocks) tree; the body applies
    THIS device's local layer shard ``[L/pp, ...]`` sequentially via
    :func:`transformer.apply_block` (the oracle's exact layer unit).
    ``first`` (a traced bool — ``axis_index(PP_AXIS) == 0``) selects the
    embedding of ``tokens`` over ``h_in`` as the stage input, so the
    embed gradient is EXACTLY zero off stage 0 (the ``where`` transpose
    zeroes the unselected branch). Every stage also runs the final-LN /
    head / CE tail; only the LAST stage's ``ce_num`` is accumulated (and
    only its backward seeds it), so head/lnf grads are exactly zero off
    the last stage. One definition serves the forward slot, the
    backward slot's ``jax.vjp`` recompute, and (minus the loss tail)
    eval — the pipeline can never drift from its own backward."""
    spec = config.spec
    attn = _local_attn(config, platform)
    tp = config.tensor_parallel
    reduce_ = coll.tp_allreduce(TP_AXIS) if tp > 1 else None
    promote = coll.tp_promote(TP_AXIS) if tp > 1 else None

    def blocks_fwd(p_blocks, h, positions):
        def blk_fn(h, blk):
            return transformer.apply_block(
                h, blk, spec, attn_fn=attn, positions=positions,
                row_reduce=reduce_, col_promote=promote,
            )

        if config.remat:
            blk_fn = jax.checkpoint(blk_fn)
        l_local = jax.tree.leaves(p_blocks)[0].shape[0]
        for i in range(l_local):
            h = blk_fn(h, jax.tree.map(lambda a: a[i], p_blocks))
        return h

    def stage_fn(params, h_in, tokens, targets, weights, first):
        p = params
        if config.dtype() is not None:
            p = jax.tree.map(lambda a: a.astype(config.dtype()), dict(p))
        positions = jnp.arange(tokens.shape[1])
        h = jnp.where(first, p["embed"][tokens].astype(h_in.dtype), h_in)
        h = blocks_fwd(p["blocks"], h, positions)
        hl = transformer._layernorm(h, p["lnf_g"], p["lnf_b"])
        logits = (hl @ p["head"]).astype(jnp.float32)
        num, _ = transformer.ce_sums(logits, targets, weights)
        return h, num

    return stage_fn, blocks_fwd


def make_pipeline_step_body(config, part, tables, platform, *, lr,
                            health: bool = False, guard: bool = False):
    """One pipeline train step, already inside ``shard_map``
    (``check_vma=False``, local-grads mode):
    ``(params, opt, tokens, targets, weights) -> (params, opt, loss)``.

    ``tables`` is the ``(f_tab, b_tab)`` pair from
    ``pipeline.schedule``; the scan's per-tick carry holds three small
    activation ring buffers sized by ``schedule.buffer_slots`` —
    ``save`` (stage inputs awaiting backward: M slots under GPipe,
    min(pp, M) under 1F1B — the schedules' memory difference, realized
    as a static buffer shape), ``inbox`` (arrived activations), and
    ``ctbox`` (arrived cotangents) — plus the gradient accumulators and
    the CE-sum accumulator. Microbatch gradient accumulation feeds the
    SAME TF1-Adam update every other mode applies, on optimizer state
    placed like the pipeline params (block m/v stage-resident over pp,
    tp-sharded over tp)."""
    f_tab, b_tab = tables
    pp = part.pp
    m = int(f_tab.max()) + 1
    # Precision policy (ddl_tpu.precision): under "bf16" the step-end
    # gradient psums move bf16 bytes and the Adam boundary upcasts to
    # fp32 (master weights + m/v stay fp32); both hooks are
    # Python-level no-ops for fp32/legacy configs — the exact
    # pre-policy program.
    pol = config.policy()
    from .schedule import buffer_slots

    slots = buffer_slots(f_tab, b_tab)
    q_save, q_in, q_ct = slots["save"], slots["inbox"], slots["ctbox"]
    fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
    bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]
    stage_fn, _ = make_stage_fn(config, platform)
    act_dtype = config.dtype() or jnp.float32
    e = config.spec.d_model

    def step(params, opt_state, tokens, targets, weights):
        s_idx = lax.axis_index(PP_AXIS)
        first = s_idx == 0
        last = s_idx == pp - 1
        b_loc, t_seq = tokens.shape
        mb = b_loc // m
        xs = tokens.reshape(m, mb, t_seq)
        ys = targets.reshape(m, mb, t_seq)
        ws = weights.reshape(m, mb, t_seq)
        # Global scored-weight total: no param dependence, so dividing
        # by it keeps gradients LOCAL (the _local_loss_fn discipline).
        den = lax.psum(jnp.sum(weights.astype(jnp.float32)), AXES)
        inv_den = 1.0 / den

        buf = lambda q: jnp.zeros((q, mb, t_seq, e), act_dtype)
        carry0 = (
            buf(q_in), buf(q_save), buf(q_ct),
            jax.tree.map(jnp.zeros_like, params),
            jnp.float32(0.0),
        )

        def tick(carry, cols):
            in_buf, save_buf, ct_buf, gacc, num_acc = carry
            f_col, b_col = cols
            f_m = f_col[s_idx]
            b_m = b_col[s_idx]
            is_f = f_m >= 0
            is_b = b_m >= 0
            fi = jnp.maximum(f_m, 0)
            bi = jnp.maximum(b_m, 0)
            # Reads before writes: the B slot's saved input/cotangent
            # predate this tick by construction of the tables.
            h_in = in_buf[fi % q_in]
            h_saved = save_buf[bi % q_save]
            ct_in = ct_buf[bi % q_ct]

            # ---- forward slot (junk when idle; every result masked)
            h_out, num = stage_fn(params, h_in, xs[fi], ys[fi], ws[fi],
                                  first)
            save_buf = save_buf.at[fi % q_save].set(
                jnp.where(is_f, h_in, save_buf[fi % q_save])
            )
            num_acc = num_acc + jnp.where(is_f & last, num, 0.0)

            # ---- backward slot: vjp-recompute from the saved stage
            # input. The last stage seeds from the loss (d loss/d num =
            # 1/global_den); every other stage seeds from the arrived
            # cotangent of its stage OUTPUT.
            _, vjp_fn = jax.vjp(
                lambda p, h: stage_fn(p, h, xs[bi], ys[bi], ws[bi], first),
                params, h_saved,
            )
            ct_h = jnp.where(last, jnp.zeros_like(ct_in), ct_in)
            ct_num = jnp.where(last, inv_den, 0.0)
            d_params, d_h = vjp_fn((ct_h.astype(h_saved.dtype), ct_num))
            bmask = is_b.astype(jnp.float32)
            gacc = jax.tree.map(lambda a, g: a + bmask * g, gacc, d_params)

            # ---- stage hops: tick-end ppermutes; arrivals are stored
            # into the ring buffers for the ticks that consume them.
            # The cyclic wrap (last stage -> stage 0 forward, stage 0 ->
            # last backward) is masked out at the receiver.
            h_arr = lax.ppermute(
                jnp.where(is_f, h_out, jnp.zeros_like(h_out))
                .astype(act_dtype),
                PP_AXIS, fwd_perm,
            )
            ct_arr = lax.ppermute(
                jnp.where(is_b, d_h, jnp.zeros_like(d_h)).astype(act_dtype),
                PP_AXIS, bwd_perm,
            )
            src_f = f_col[(s_idx - 1) % pp]
            sf = jnp.maximum(src_f, 0) % q_in
            in_buf = in_buf.at[sf].set(
                jnp.where((src_f >= 0) & ~first, h_arr, in_buf[sf])
            )
            src_b = b_col[(s_idx + 1) % pp]
            sb = jnp.maximum(src_b, 0) % q_ct
            ct_buf = ct_buf.at[sb].set(
                jnp.where((src_b >= 0) & ~last, ct_arr, ct_buf[sb])
            )
            return (in_buf, save_buf, ct_buf, gacc, num_acc), None

        cols = (jnp.asarray(f_tab.T), jnp.asarray(b_tab.T))  # [T, pp]
        (_, _, _, gacc, num_acc), _ = lax.scan(tick, carry0, cols)

        loss = lax.psum(num_acc, AXES + (PP_AXIS,)) * inv_den
        gacc = pol.cast_grads(gacc)
        grads = {
            k: (lax.psum(g, AXES + (PP_AXIS,)) if k in SHARED_LEAVES
                else jax.tree.map(lambda a: lax.psum(a, AXES), g))
            for k, g in gacc.items()
        }
        grads = pol.upcast_grads(grads)
        new_params, new_opt = adam_update(params, opt_state, grads, lr=lr)
        out = ()
        if guard or health:
            # Both flags key off the same PartitionSpec-driven
            # reductions (obs.health, ISSUE 5): the stacked-block
            # leaves are stage-resident over pp (and Megatron-sharded
            # over tp), so their counts/squared sums reduce over
            # exactly the axes their PartitionSpec names; the
            # pp-replicated shared leaves are already fully reduced.
            # Python-level flags: health=False, guard=False compiles
            # the exact pre-change program.
            from ..models.partition import pipeline_param_specs
            from ..obs import health as hlt

            pspecs = pipeline_param_specs(
                config.spec, part.pp, config.tensor_parallel
            )
        if guard:
            # ISSUE 6 step guard: identity instead of the Adam update
            # when ANY stage's gradients went non-finite (the count is
            # globally reduced, so every pp/tp position selects the
            # same branch); the int32 skip flag rides as LAST output.
            from ..resilience.guard import apply_guard

            new_params, new_opt, skipped = apply_guard(
                hlt.nonfinite_count(grads, pspecs),
                params, opt_state, new_params, new_opt,
            )
            out = (skipped,)
        if health:
            h = hlt.health_signals(grads, params, new_params, pspecs)
            out = (h,) + out
        return (new_params, new_opt, loss) + out

    return step


def make_pipeline_eval_body(config, part, platform):
    """Forward-only pipeline eval, already inside ``shard_map``:
    ``(params, tokens, targets, weights) -> (num, den)`` — weighted
    top-1 hit sums (``lm_correct_sums``'s accumulator contract). The
    whole eval set flows through as ONE microbatch: ``pp - 1`` hops move
    it stage to stage (each device applies its local layers every hop —
    only the position that has the real activation computes on data),
    the last stage scores. ``num``/``den`` psum exactly like the 2-D
    trainer's eval (test data is dp-replicated, so both inflate dp-fold
    and the accuracy ratio is exact)."""
    pp = part.pp
    _, blocks_fwd = make_stage_fn(config, platform)
    act_dtype = config.dtype() or jnp.float32
    fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]

    def sums(params, tokens, targets, weights):
        s_idx = lax.axis_index(PP_AXIS)
        first = s_idx == 0
        last = s_idx == pp - 1
        p = params
        if config.dtype() is not None:
            p = jax.tree.map(lambda a: a.astype(config.dtype()), dict(p))
        positions = jnp.arange(tokens.shape[1])
        emb = p["embed"][tokens].astype(act_dtype)
        h = jnp.where(first, emb, jnp.zeros_like(emb))
        for _ in range(pp - 1):
            h = lax.ppermute(
                blocks_fwd(p["blocks"], h, positions), PP_AXIS, fwd_perm
            )
        h = blocks_fwd(p["blocks"], h, positions)
        hl = transformer._layernorm(h, p["lnf_g"], p["lnf_b"])
        logits = (hl @ p["head"]).astype(jnp.float32)
        hits = (jnp.argmax(logits, axis=-1) == targets).astype(jnp.float32)
        w = weights.astype(jnp.float32)
        num = jnp.where(last, jnp.sum(hits * w), 0.0)
        return (lax.psum(num, AXES + (PP_AXIS,)),
                lax.psum(jnp.sum(w), AXES))

    return sums
