"""Pipeline program builders — the wiring layer between the step bodies
(``pipeline.step``) and their three consumers: ``SeqTrainer``'s span
machinery (``SeqConfig.pipeline_parallel`` / ``microbatches``), the
bubble benchmark (``benchmarks/pipeline_bubble.py`` — which sweeps
``microbatches=1`` rows the trainer's topology validation deliberately
rejects), and the collective-bytes audit. One builder each, so every
consumer compiles the SAME program."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from ..models import transformer
from ..models.partition import (
    pipeline_param_specs,
    stack_blocks,
    stage_partition,
)
from ..ops import adam_init
from ..ops.optimizers import AdamState
from ..parallel import multihost
from ..parallel.mesh import (
    DP_AXIS,
    SP_AXIS,
    donation_for,
    make_mesh_4d,
)
from .schedule import schedule_tables
from .step import make_pipeline_eval_body, make_pipeline_step_body


def pipeline_shard_step(config, mesh, platform, health: bool = False,
                        guard: bool = False):
    """The ``shard_map``'d pipeline train step for this config on this
    4-D mesh: ``(params, opt, tokens, targets, weights) ->
    (params, opt, loss)`` with train batches ``P(dp, sp)`` (sp is size
    1), the stacked param tree ``P(pp, ...)``-sharded, and optimizer
    state placed like the params. ``check_vma=False`` — local-grads
    mode, every reduction explicit in the body (pipeline.step).
    ``health=True`` appends the in-graph health dict (``obs.health``)
    as a fourth, fully-reduced output; ``guard=True`` (ISSUE 6) the
    NaN-guarded update plus the int32 skip flag as LAST output."""
    part = stage_partition(config.spec, config.pipeline_parallel)
    tables = schedule_tables(
        config.pipeline_schedule, part.pp, config.microbatches
    )
    body = make_pipeline_step_body(
        config, part, tables, platform, lr=config.learning_rate,
        health=health, guard=guard,
    )
    pspecs = pipeline_param_specs(
        config.spec, part.pp, config.tensor_parallel
    )
    opt_spec = AdamState(step=P(), m=pspecs, v=pspecs)
    seq = P(DP_AXIS, SP_AXIS)
    out_specs = (pspecs, opt_spec, P())
    if health:
        from ..obs import health as hlt

        out_specs = out_specs + (hlt.health_out_specs(pspecs),)
    if guard:
        out_specs = out_specs + (P(),)
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pspecs, opt_spec, seq, seq, seq),
        out_specs=out_specs,
        check_vma=False,
    )


def pipeline_shard_eval(config, mesh, platform, data_spec):
    """The ``shard_map``'d forward-only eval: ``(params, tokens,
    targets, weights) -> (num, den)`` hit sums, test data dp-replicated
    (``data_spec`` is the trainer's ``_seq_spec``)."""
    part = stage_partition(config.spec, config.pipeline_parallel)
    body = make_pipeline_eval_body(config, part, platform)
    pspecs = pipeline_param_specs(
        config.spec, part.pp, config.tensor_parallel
    )
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pspecs, data_spec, data_spec, data_spec),
        out_specs=(P(), P()),
        check_vma=False,
    )


def make_pipeline_program(config, tokens, targets, weights,
                          health: bool = False):
    """Standalone compiled pipeline step on a FRESH ``dp x 1 x tp x pp``
    mesh — the benchmark/audit entry point (bypasses SeqTrainer, so a
    ``microbatches=1`` config — rejected by ``validate_topology`` for
    training — can still be measured as the zero-pipelining bubble
    anchor). Returns ``(fn, (params, opt, xs, ys, ws))``: placed state
    plus the jitted step; callers time ``fn(*state)`` with a host-fetch
    barrier on the loss."""
    mesh = make_mesh_4d(
        config.data_parallel, config.num_workers,
        config.tensor_parallel, config.pipeline_parallel,
    )
    platform = mesh.devices.flat[0].platform
    shard_step = pipeline_shard_step(config, mesh, platform, health=health)
    host = jax.tree.map(
        np.asarray,
        transformer.init_lm_params(
            jax.random.PRNGKey(config.seed), config.spec
        ),
    )
    stacked = stack_blocks(host)
    pspecs = pipeline_param_specs(
        config.spec, config.pipeline_parallel, config.tensor_parallel
    )
    opt_spec = AdamState(step=P(), m=pspecs, v=pspecs)
    params = multihost.put_tree(mesh, pspecs, stacked)
    opt = multihost.put_tree(mesh, opt_spec, adam_init(stacked))
    seq = P(DP_AXIS, SP_AXIS)
    put = lambda a: multihost.put(mesh, seq, np.asarray(a))
    state = (params, opt, put(tokens), put(targets), put(weights))
    fn = jax.jit(shard_step, donate_argnums=donation_for(mesh, 0, 1))
    return fn, state
