"""Microbatch schedules as static tick tables.

A pipeline step is a sequence of TICKS; at each tick every stage runs
at most one unit of work — one microbatch FORWARD or one microbatch
BACKWARD. A schedule is two ``[pp, T]`` int32 tables (``f_tab``,
``b_tab``): entry ``[s, t]`` is the microbatch index stage ``s``
forwards/backwards at tick ``t``, or :data:`IDLE`. The step program
(``pipeline.step``) executes ANY well-formed pair of tables with one
``lax.scan`` — GPipe and 1F1B are data, not code, so both schedules are
pinned against the same oracle by the same machinery.

Dependency model (what makes a table well-formed; pinned by
tests/test_pipeline.py):

- ``F(s, j)`` needs ``F(s-1, j)`` to have finished at an EARLIER tick
  (the activation ppermutes at tick end, arriving for tick t+1);
- ``B(s, j)`` needs ``B(s+1, j)`` earlier (cotangent hop), and on the
  LAST stage needs ``F(pp-1, j)`` earlier (the backward seeds from the
  loss that forward computed).

Both schedules run ``T = 2*(M + pp - 1)`` ticks — with equal-cost
slots their bubble fractions coincide at the GPipe closed form
``(pp-1)/(M + pp - 1)`` (:func:`predicted_bubble`). What 1F1B buys is
the WARMUP MEMORY: a stage's in-flight saved activations peak at
``min(pp - s, M)`` instead of GPipe's ``M`` (:func:`max_in_flight`) —
the reduced-warmup story, measurable as the ``save_buf`` slot count.
"""

from __future__ import annotations

import numpy as np

IDLE = -1

SCHEDULES = ("gpipe", "1f1b")


def gpipe_tables(pp: int, m: int) -> tuple[np.ndarray, np.ndarray]:
    """GPipe (flush) schedule: all M forwards drain through the stages,
    THEN all M backwards — closed form, no simulation. Stage ``s`` runs
    ``F_j`` at tick ``s + j``; backwards start once the last stage has
    every loss, ``B_j`` on stage ``s`` at ``(M + pp - 1) + (pp-1-s) + j``
    (the cotangent chain mirrors the forward chain, last stage first)."""
    _check(pp, m)
    t_f = m + pp - 1
    T = 2 * t_f
    f = np.full((pp, T), IDLE, np.int32)
    b = np.full((pp, T), IDLE, np.int32)
    for s in range(pp):
        for j in range(m):
            f[s, s + j] = j
            b[s, t_f + (pp - 1 - s) + j] = j
    return f, b


def one_f1b_tables(pp: int, m: int) -> tuple[np.ndarray, np.ndarray]:
    """1F1B (PipeDream-flush) schedule by greedy simulation: stage ``s``
    warms up with at most ``min(pp - s, M)`` forwards, then strictly
    alternates backward/forward (backward preferred as soon as its
    cotangent arrived), then drains the remaining backwards. The
    simulation IS the spec — the table is checked against the dependency
    model by tests, not derived twice."""
    _check(pp, m)
    fdone = [[None] * m for _ in range(pp)]  # completion tick of F(s, j)
    bdone = [[None] * m for _ in range(pp)]
    nf = [0] * pp  # next forward microbatch per stage
    nb = [0] * pp  # next backward microbatch per stage
    cols_f: list[list[int]] = []
    cols_b: list[list[int]] = []
    t = 0
    while any(n < m for n in nb):
        colf = [IDLE] * pp
        colb = [IDLE] * pp
        for s in range(pp):
            warm = min(pp - s, m)
            can_f = nf[s] < m and (
                s == 0
                or (fdone[s - 1][nf[s]] is not None
                    and fdone[s - 1][nf[s]] < t)
            )
            if s == pp - 1:
                can_b = (nb[s] < m and fdone[s][nb[s]] is not None
                         and fdone[s][nb[s]] < t)
            else:
                can_b = (nb[s] < m and bdone[s + 1][nb[s]] is not None
                         and bdone[s + 1][nb[s]] < t)
            if can_b:
                colb[s] = nb[s]
                bdone[s][nb[s]] = t
                nb[s] += 1
            elif can_f and nf[s] - nb[s] < warm:
                colf[s] = nf[s]
                fdone[s][nf[s]] = t
                nf[s] += 1
        cols_f.append(colf)
        cols_b.append(colb)
        t += 1
        if t > 4 * (m + pp) + 8:  # structurally impossible; guard a bug
            raise RuntimeError(
                f"1F1B simulation did not converge for pp={pp}, m={m}"
            )
    return (np.asarray(cols_f, np.int32).T.copy(),
            np.asarray(cols_b, np.int32).T.copy())


def schedule_tables(kind: str, pp: int, m: int):
    """``(f_tab, b_tab)`` for ``kind`` in :data:`SCHEDULES`."""
    if kind == "gpipe":
        return gpipe_tables(pp, m)
    if kind == "1f1b":
        return one_f1b_tables(pp, m)
    raise ValueError(
        f"unknown pipeline schedule {kind!r} (choices: {SCHEDULES})"
    )


def max_in_flight(f_tab: np.ndarray, b_tab: np.ndarray) -> int:
    """Peak saved-activation count over all stages: microbatches
    forwarded but not yet backwarded (each holds one stage-INPUT buffer
    for the backward's recompute). GPipe peaks at M (stage 0 forwards
    everything before any cotangent returns); 1F1B at ``min(pp, M)`` —
    THE memory difference between the schedules."""
    worst = 1
    for s in range(f_tab.shape[0]):
        live = peak = 0
        for t in range(f_tab.shape[1]):
            if f_tab[s, t] != IDLE:
                live += 1
                peak = max(peak, live)
            if b_tab[s, t] != IDLE:
                live -= 1
        worst = max(worst, peak)
    return worst


def buffer_slots(f_tab: np.ndarray, b_tab: np.ndarray) -> dict[str, int]:
    """Ring-buffer slot counts the step program needs for this table
    pair: ``save`` (stage inputs awaiting backward — the dominant term,
    = :func:`max_in_flight`), ``inbox`` (activations received from the
    previous stage but not yet consumed), ``ctbox`` (cotangents received
    from the next stage but not yet consumed). In-flight microbatch
    indices are CONSECUTIVE per buffer (forwards and backwards both
    retire in order), so indexing slot ``j % n`` is collision-free as
    long as ``n`` covers the peak — which is what these counts are."""
    pp, T = f_tab.shape

    def peak(arrive, consume):
        worst = 1
        for s in range(pp):
            ticks = sorted(
                (arr, con) for arr, con in (
                    (arrive(s, j), consume(s, j)) for j in range(_m(f_tab))
                ) if arr is not None and con is not None
            )
            live: list[int] = []
            mx = 0
            for arr, con in ticks:
                live = [c for c in live if c >= arr]
                live.append(con)
                mx = max(mx, len(live))
            worst = max(worst, mx)
        return worst

    f_tick = {(s, int(f_tab[s, t])): t
              for s in range(pp) for t in range(T) if f_tab[s, t] != IDLE}
    b_tick = {(s, int(b_tab[s, t])): t
              for s in range(pp) for t in range(T) if b_tab[s, t] != IDLE}
    inbox = peak(
        lambda s, j: f_tick.get((s - 1, j), 0) + 1 if s else None,
        lambda s, j: f_tick.get((s, j)) if s else None,
    )
    ctbox = peak(
        lambda s, j: (b_tick.get((s + 1, j), 0) + 1
                      if s < pp - 1 else None),
        lambda s, j: b_tick.get((s, j)) if s < pp - 1 else None,
    )
    return {
        "save": max_in_flight(f_tab, b_tab),
        "inbox": inbox,
        "ctbox": ctbox,
    }


def bubble_fraction(f_tab: np.ndarray, b_tab: np.ndarray) -> float:
    """Idle fraction of the schedule's (stage, tick) grid — each slot
    weighted equally, matching the step program's cost model (every tick
    executes the same masked SPMD body on every stage, so wall time is
    proportional to tick count alone)."""
    pp, T = f_tab.shape
    work = int((f_tab != IDLE).sum() + (b_tab != IDLE).sum())
    return 1.0 - work / (pp * T)


def predicted_bubble(pp: int, m: int) -> float:
    """The closed-form bubble both table families realize at equal slot
    cost: ``(pp-1)/(m+pp-1)`` (GPipe's classic expression; 1F1B's tables
    fill the same 2*(m+pp-1)-tick envelope — its win is warmup MEMORY,
    :func:`max_in_flight`). tests/test_pipeline.py pins
    :func:`bubble_fraction` of both table kinds to this value."""
    _check(pp, m)
    return (pp - 1) / (m + pp - 1)


def _m(f_tab: np.ndarray) -> int:
    return int(f_tab.max()) + 1


def _check(pp: int, m: int) -> None:
    if pp < 1 or m < 1:
        raise ValueError(f"need pp >= 1 and microbatches >= 1, "
                         f"got pp={pp}, m={m}")
