"""Pipeline parallelism: the layer stack split into contiguous stages
over the ``pp`` mesh axis (``parallel.mesh.PP_AXIS``).

The source paper shards one model's PARAMETERS across processes (PS
sharding); this package adds the classic axis that keeps per-device
memory flat as DEPTH grows (arXiv:2412.14374, arXiv:2204.06514):
stage ``s`` holds layers ``[s*L/pp, (s+1)*L/pp)``, microbatches stream
through the stages, and activations (cotangents on the backward) hop
stage-to-stage via ``lax.ppermute`` over neighbouring ICI links.

- ``schedule``: GPipe / 1F1B microbatch tick tables, the in-flight
  activation-buffer sizes they imply, and the analytic bubble model
  (``(pp-1)/(microbatches+pp-1)``) that ``benchmarks/pipeline_bubble.py``
  falsifies against measured step time.
- ``step``: the ``shard_map`` train-step body — one ``lax.scan`` over
  schedule ticks executing both schedules from their tables, with a
  MANUAL per-microbatch backward (``jax.vjp`` recompute from saved
  stage inputs, never a bare psum/ppermute transpose — the repo's
  explicit-gradient discipline, parallel/collectives.py).
- ``trainer``: program builders wiring the step into ``SeqTrainer``
  (``SeqConfig.pipeline_parallel`` / ``microbatches``) and into the
  benchmarks.
"""

from .schedule import (  # noqa: F401
    IDLE,
    buffer_slots,
    bubble_fraction,
    max_in_flight,
    predicted_bubble,
    schedule_tables,
)
from .trainer import make_pipeline_program  # noqa: F401
