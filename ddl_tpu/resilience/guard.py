"""NaN-guarded step skipping with host-side rollback escalation.

Device half (:func:`apply_guard`): inside a jitted step body, AFTER the
optimizer produced its proposed ``(new_params, new_opt)``, select the
OLD state whenever the step's fully reduced gradient contained a
non-finite element — a scalar-predicate ``jnp.where`` broadcast over
every pytree leaf. The predicate is the same ``nonfinite_grads`` count
the ISSUE-5 health tripwire computes (psum'd per each leaf's
PartitionSpec axes, so it is replicated and every device takes the SAME
branch), which means the guard adds no collective of its own beyond
that count. No host sync, no recompile: the skip happens entirely
in-graph, and ``guard=False`` is a Python-level branch in every step
body, so the default program is byte-identical to the pre-guard one
(the same discipline as ``health=False``).

Host half (:class:`GuardMonitor`): trainers fetch the span's ``[k]``
stacked skip flags on the loss barrier (a handful of int32s — no added
sync) and feed them here. The monitor counts total and CONSECUTIVE
skips; ``max_bad_steps`` consecutive skips trip ESCALATION — the
trainer rolls back to the newest valid checkpoint at or before the
streak's first bad step (``utils.checkpoint.find_latest_valid`` with
``max_step``) and re-enters its span loop there, which re-seeds the
data stream to the rolled-back step (batches are indexed by global
step, so position IS the seed). ``max_rollbacks`` bounds the retry loop
— a persistent fault (bad data, a real divergence) raises instead of
cycling forever.

Everything is observable: skips and rollbacks land on the ISSUE-5
registry (``train_skipped_steps_total``, ``train_rollbacks_total``) and
tracer (``guard_skip`` / ``guard_rollback`` events), so an incident is
auditable from the run's telemetry alone.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def rollback_state(checkpoint_dir, monitor: "GuardMonitor", like, log):
    """The trainer-agnostic half of a guard rollback (SeqTrainer and
    SingleChipTrainer share it; only array placement differs per
    trainer): locate the newest VALID checkpoint at or before the
    divergence streak's first bad step, load it in checkpoint (host)
    form, and prune every retained save NEWER than it — those describe
    the abandoned timeline and must not win a later ``--resume auto``
    race. Returns ``(host_tree, step)``; raises with a diagnosis when
    there is nothing to roll back to."""
    from ..utils.checkpoint import (
        discard_newer,
        find_latest_valid,
        load_checkpoint,
    )

    if checkpoint_dir is None:
        raise RuntimeError(
            "guard escalation tripped (max_bad_steps consecutive "
            "non-finite steps) but no checkpoint_dir is set — nothing "
            "to roll back to"
        )
    found = find_latest_valid(
        checkpoint_dir, max_step=monitor.streak_start, log=log
    )
    if found is None:
        raise RuntimeError(
            "guard escalation tripped but no valid checkpoint at or "
            f"before step {monitor.streak_start} exists under "
            f"{checkpoint_dir}"
        )
    path, _ = found
    tree, step, _ = load_checkpoint(path, like)
    step = int(step or 0)
    discard_newer(checkpoint_dir, step, log=log)
    log(f"[guard] rolled back to checkpoint step {step} ({path})")
    return tree, step


def apply_guard(nonfinite, params, opt_state, new_params, new_opt):
    """In-graph identity-on-divergence select (see module docstring).

    ``nonfinite`` is the step's REPLICATED non-finite gradient element
    count (int32 scalar). Returns ``(params', opt', skipped)`` where the
    primed trees are the proposed update when the gradients were finite
    and the UNCHANGED inputs otherwise, and ``skipped`` is an int32
    0/1 scalar (stacked per step by the span scan, fetched by the
    trainer for the escalation policy)."""
    bad = nonfinite > 0
    keep = lambda old, new: jnp.where(bad, old, new)
    return (
        jax.tree.map(keep, params, new_params),
        jax.tree.map(keep, opt_state, new_opt),
        bad.astype(jnp.int32),
    )


class GuardMonitor:
    """Host-side escalation policy over the guard's per-step skip flags.

    ``observe(skipped_stack, first_gstep)`` consumes one span's stacked
    flags and returns True when ``max_bad_steps`` CONSECUTIVE skips have
    accumulated (0 disables escalation — skip-only guard). After the
    trainer rolls back it calls :meth:`rolled_back`, which resets the
    streak and enforces ``max_rollbacks``. ``streak_start`` is the
    global step of the current streak's first skip — the rollback upper
    bound (a checkpoint saved DURING the streak embeds skipped steps
    and is not "good")."""

    def __init__(self, max_bad_steps: int = 0, *, max_rollbacks: int = 3,
                 registry=None, tracer=None):
        if max_bad_steps < 0:
            raise ValueError(
                f"max_bad_steps must be >= 0, got {max_bad_steps}"
            )
        if max_rollbacks < 1:
            raise ValueError(
                f"max_rollbacks must be >= 1, got {max_rollbacks}"
            )
        self.max_bad_steps = max_bad_steps
        self.max_rollbacks = max_rollbacks
        self.registry = registry
        self.tracer = tracer
        self.skipped_steps = 0
        self.rollbacks = 0
        self.consecutive = 0
        self.streak_start: int | None = None

    def observe(self, skipped_stack, first_gstep: int) -> bool:
        """Feed one span's ``[k]`` skip flags (host ints/array); flags
        index global steps ``first_gstep + j``. Returns True the moment
        the escalation threshold trips — the REMAINING flags of the
        span are discarded unprocessed: the trainer rolls back and
        replays everything from the streak's first bad step, so a
        trailing healthy flag belongs to an abandoned timeline and must
        not reset ``streak_start`` (the rollback's upper bound)."""
        for j, s in enumerate(np.asarray(skipped_stack).reshape(-1)):
            if int(s):
                if self.consecutive == 0:
                    self.streak_start = first_gstep + j
                self.consecutive += 1
                self.skipped_steps += 1
                if self.registry is not None:
                    self.registry.counter(
                        "train_skipped_steps_total",
                        "optimizer updates skipped by the non-finite "
                        "gradient guard",
                    ).inc()
                if self.tracer:
                    self.tracer.event("guard_skip", gstep=first_gstep + j,
                                      consecutive=self.consecutive)
                if self.max_bad_steps \
                        and self.consecutive >= self.max_bad_steps:
                    return True
            else:
                self.consecutive = 0
                self.streak_start = None
        return False

    def rolled_back(self, to_step: int) -> None:
        """Record a completed rollback; raises once ``max_rollbacks`` is
        exceeded (a persistent fault must fail loudly, not cycle)."""
        self.rollbacks += 1
        self.consecutive = 0
        self.streak_start = None
        if self.registry is not None:
            self.registry.counter(
                "train_rollbacks_total",
                "rollbacks to the last good checkpoint after "
                "max_bad_steps consecutive guarded skips",
            ).inc()
        if self.tracer:
            self.tracer.event("guard_rollback", to_step=int(to_step),
                              rollbacks=self.rollbacks)
        if self.rollbacks > self.max_rollbacks:
            raise RuntimeError(
                f"guard escalation: {self.rollbacks} rollbacks exceed "
                f"max_rollbacks={self.max_rollbacks} — the divergence "
                "recurs after restoring the last good checkpoint "
                "(persistent bad data or a real model divergence, not a "
                "transient fault); inspect train_skipped_steps_total and "
                "the guard_skip trace events"
            )
