"""Deterministic fault injection — the proof harness for every recovery
path (ISSUE 6 tentpole piece 4).

A resilience feature that has only ever run in an outage is untested
code; this module makes every failure mode a SEEDED, REPRODUCIBLE input
so the test suite (tests/test_resilience.py) and manual chaos runs
(CLI ``--inject-fault``) exercise the exact paths production will:

- ``nan_grads@K`` / ``inf_grads@K`` — poison ONE element of the staged
  loss weights (LM) or input images (CNN) for the batch at global step
  ``K`` (``@KxN`` poisons ``N`` consecutive batches). The forward then
  produces a non-finite loss and non-finite gradients NATURALLY —
  the injection exercises the real tripwire/guard path, not a mock.
  Transient by default (``once=True``): a guard rollback heals the
  data, modelling an SDC/HW blip; ``once=False`` models persistently
  bad data (the rollback bound must then trip).
- ``sigterm@K`` — deliver a REAL ``SIGTERM`` to this process once
  global step ``K`` completes (the preemption notice a TPU VM gets),
  driving the graceful drain → final checkpoint → clean exit path.
- ``corrupt_ckpt`` / ``truncate_ckpt`` — flip bytes in / truncate a
  checkpoint file (deterministic under ``seed``), the torn-write and
  bit-rot inputs ``find_latest_valid`` must survive.
- ``stall@RID`` — the serve scheduler never advances request ``RID``'s
  prefill (an upstream hang), so its deadline must evict it and release
  its pinned prefix refs.
- ``replica_crash@T:R`` — kill serve-fleet replica ``R`` at GLOBAL tick
  ``T`` (a VM preemption / device loss mid-serve): the replica's engine
  and page pool are discarded wholesale, its in-flight and queued
  requests re-queue at the front door, and the fleet controller
  (``serve.controller``) must heal — every request still completes
  exactly once. Fires once; deterministic on the tick clock.

Injection is host-side only — staged data, signals, files — so the
compiled programs under test are the production programs, bit for bit.
"""

from __future__ import annotations

import dataclasses
import os
import signal

import numpy as np

TRAIN_KINDS = ("nan_grads", "inf_grads", "sigterm")
CKPT_KINDS = ("corrupt_ckpt", "truncate_ckpt")
SERVE_KINDS = ("stall", "replica_crash")
KINDS = TRAIN_KINDS + CKPT_KINDS + SERVE_KINDS


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One deterministic fault. ``step`` is the trigger global step
    (train kinds), the target request id (``stall``), or the global
    tick (``replica_crash``); ``replica`` is the ``replica_crash``
    victim's id; ``count`` extends a grad fault over consecutive
    batches; ``once=True`` makes a grad fault transient (healed by a
    guard rollback)."""

    kind: str
    step: int = 0
    count: int = 1
    once: bool = True
    replica: int = 0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (choices: "
                f"{', '.join(KINDS)})"
            )
        if self.step < 0 or self.count < 1:
            raise ValueError(
                f"fault {self.kind}: need step >= 0 and count >= 1, got "
                f"step={self.step} count={self.count}"
            )
        if self.replica < 0:
            raise ValueError(
                f"fault {self.kind}: replica must be >= 0, got "
                f"{self.replica}"
            )


def parse_fault(text: str) -> FaultSpec:
    """CLI syntax: ``kind``, ``kind@STEP`` or ``kind@STEPxCOUNT`` —
    e.g. ``nan_grads@3``, ``nan_grads@3x2``, ``sigterm@5``,
    ``stall@7``, ``corrupt_ckpt``. ``replica_crash`` takes
    ``replica_crash@TICK:REPLICA``. A trailing ``!`` makes a grad
    fault persistent (``once=False``): ``nan_grads@3x2!``."""
    once = True
    if text.endswith("!"):
        once = False
        text = text[:-1]
    kind, at, rest = text.partition("@")
    step, count, replica = 0, 1, 0
    if kind == "replica_crash":
        head, colon, tail = rest.partition(":")
        try:
            step = int(head) if at else 0
            replica = int(tail) if colon else 0
        except ValueError:
            raise ValueError(
                f"bad fault spec {text!r}: replica_crash takes "
                "replica_crash@TICK:REPLICA with integer TICK/REPLICA"
            )
    elif at:
        head, x, tail = rest.partition("x")
        try:
            step = int(head)
            count = int(tail) if x else 1
        except ValueError:
            raise ValueError(
                f"bad fault spec {text!r}: expected kind@STEP or "
                "kind@STEPxCOUNT with integer STEP/COUNT"
            )
    return FaultSpec(kind=kind, step=step, count=count, once=once,
                     replica=replica)


class FaultInjector:
    """Stateful host-side delivery of one :class:`FaultSpec`.

    Trainers call :meth:`poison_batches` while staging data and
    :meth:`maybe_sigterm` at span boundaries; a guard rollback calls
    :meth:`heal` (True = restage clean data). The serve scheduler asks
    :meth:`stalls` per slot per tick. All decisions are pure functions
    of the spec + the healed flag — rerunning the same spec reproduces
    the same incident."""

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self.healed = False
        self._sigterm_fired = False
        self._crash_fired = False

    # -- training: data poisoning -----------------------------------------

    def poisons_data(self) -> bool:
        return self.spec.kind in ("nan_grads", "inf_grads") \
            and not self.healed

    def poison_batches(self, arr: np.ndarray, batch_num: int,
                       batch_size: int) -> np.ndarray:
        """Copy of the 2-D host array ``[N, ...]`` (rows are examples,
        sequential batching: batch ``b`` = rows ``[b*bs, (b+1)*bs)``)
        with one element of each targeted batch's first row set
        non-finite. Targets are the batch indices of global steps
        ``step .. step+count-1`` (mod ``batch_num`` — data repeats per
        epoch, so a poisoned batch is poisoned on every epoch pass
        until healed). No-op (returns ``arr``) when not armed."""
        if not self.poisons_data() or batch_num < 1:
            return arr
        value = np.nan if self.spec.kind == "nan_grads" else np.inf
        out = np.array(arr, copy=True)
        for i in range(self.spec.count):
            b = (self.spec.step + i) % batch_num
            row = b * batch_size
            if row < out.shape[0]:
                out.reshape(out.shape[0], -1)[row, 0] = value
        return out

    def heal(self) -> bool:
        """Called by the trainer after a guard rollback: a transient
        (``once=True``) data fault clears — the trainer restages clean
        data when this returns True."""
        if self.poisons_data() and self.spec.once:
            self.healed = True
            return True
        return False

    # -- training: preemption ----------------------------------------------

    def maybe_sigterm(self, completed_gstep: int) -> None:
        """Deliver one real SIGTERM to this process once training has
        completed global step ``spec.step`` (called at span boundaries —
        delivery granularity is a span, exactly like a real notice)."""
        if self.spec.kind != "sigterm" or self._sigterm_fired:
            return
        if completed_gstep > self.spec.step:
            self._sigterm_fired = True
            os.kill(os.getpid(), signal.SIGTERM)

    # -- serving -----------------------------------------------------------

    def stalls(self, request_id: int) -> bool:
        """True while request ``request_id``'s prefill must not advance
        (``stall`` faults; ``spec.step`` holds the target id)."""
        return self.spec.kind == "stall" and not self.healed \
            and request_id == self.spec.step

    @property
    def crash_pending(self) -> bool:
        """An armed replica_crash that has not fired yet — the fleet
        controller checks this at run end: a crash tick beyond the
        run's horizon must FAIL the run loudly, never report a clean
        pass that exercised nothing."""
        return self.spec.kind == "replica_crash" and not self._crash_fired

    def rearm(self) -> None:
        """Re-arm the one-shot replica_crash latch for a fresh run (the
        fleet controller's ``reset`` — a replayed scenario must crash
        again at the same tick). Trainer-side latches (sigterm, healed
        data faults) are NOT touched: their one-shot semantics span
        resume cycles by design."""
        self._crash_fired = False

    def crashes_replica(self, tick: int) -> int | None:
        """The fleet-replica id to kill once the GLOBAL clock reaches
        ``spec.step`` (``replica_crash`` faults fire ONCE), else None.
        The controller (``serve.controller``) consults this every
        global tick — delivery is deterministic on the tick clock, so
        a seeded crash scenario replays exactly."""
        if self.spec.kind != "replica_crash" or self._crash_fired:
            return None
        if tick >= self.spec.step:
            self._crash_fired = True
            return self.spec.replica
        return None


class FaultStorm:
    """Sequenced ``replica_crash`` delivery — the multi-fault injector
    behind the ``crash_storm`` scenario (``serve.scenarios``). Exposes
    the exact surface the fleet controller consumes from
    :class:`FaultInjector` (``crashes_replica`` / ``rearm`` /
    ``crash_pending`` / ``spec``), firing each spec once in ``step``
    order, at most one per tick — two crashes due the same tick deliver
    on consecutive ticks, deterministically. ``spec`` reads as the
    first unfired spec so the controller's "never fired" run-end error
    names the crash that was actually missed."""

    def __init__(self, specs):
        specs = tuple(specs)
        if not specs:
            raise ValueError("FaultStorm needs at least one FaultSpec")
        bad = sorted({s.kind for s in specs if s.kind != "replica_crash"})
        if bad:
            raise ValueError(
                f"FaultStorm sequences replica_crash faults only, got "
                f"{', '.join(bad)}"
            )
        self.specs = tuple(sorted(specs, key=lambda s: (s.step, s.replica)))
        self._fired = [False] * len(self.specs)

    @property
    def spec(self) -> FaultSpec:
        for spec, fired in zip(self.specs, self._fired):
            if not fired:
                return spec
        return self.specs[-1]

    @property
    def crash_pending(self) -> bool:
        return not all(self._fired)

    def rearm(self) -> None:
        self._fired = [False] * len(self.specs)

    def stalls(self, request_id: int) -> bool:
        return False

    def crashes_replica(self, tick: int) -> int | None:
        for i, spec in enumerate(self.specs):
            if not self._fired[i] and tick >= spec.step:
                self._fired[i] = True
                return spec.replica
        return None


def parse_fault_storm(text: str):
    """``;``-separated :func:`parse_fault` specs — one spec builds a
    plain :class:`FaultInjector`, several build a :class:`FaultStorm`:
    ``replica_crash@3:1;replica_crash@9:2``."""
    specs = [parse_fault(part) for part in text.split(";") if part]
    if not specs:
        raise ValueError(f"empty fault spec {text!r}")
    if len(specs) == 1:
        return FaultInjector(specs[0])
    return FaultStorm(specs)


# -- checkpoint chaos ---------------------------------------------------------


def corrupt_checkpoint(path: str | os.PathLike, *, seed: int = 0,
                       nbytes: int = 64) -> None:
    """Deterministically flip ``nbytes`` bytes in the middle of
    ``path`` IN PLACE (bit rot / partial overwrite). The file keeps its
    size, so only content verification — the manifest checksums, or a
    failed zip read — can catch it."""
    size = os.path.getsize(path)
    rng = np.random.default_rng(seed)
    with open(path, "r+b") as f:
        start = max(size // 2 - nbytes // 2, 0)
        f.seek(start)
        chunk = bytearray(f.read(min(nbytes, size - start)))
        for i in range(len(chunk)):
            chunk[i] ^= int(rng.integers(1, 256))
        f.seek(start)
        f.write(bytes(chunk))
        f.flush()
        os.fsync(f.fileno())


def truncate_checkpoint(path: str | os.PathLike, *, frac: float = 0.5) -> None:
    """Truncate ``path`` to ``frac`` of its size IN PLACE — the torn
    write a preemption mid-save produces on non-atomic writers (ours is
    atomic; this models an externally damaged file)."""
    if not 0 <= frac < 1:
        raise ValueError(f"frac must be in [0, 1), got {frac}")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(int(size * frac))
        f.flush()
        os.fsync(f.fileno())
