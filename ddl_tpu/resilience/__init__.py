"""Fault tolerance for training and serving (ISSUE 6).

Three layers, each provable under test via the deterministic fault
injector (``resilience.faults``):

- **In-graph step guard** (``resilience.guard``): when a step's fully
  reduced gradients contain ANY non-finite element (the ISSUE-5
  ``nonfinite_grads`` tripwire), the jitted step applies IDENTITY
  instead of the optimizer update — a ``jnp.where`` select over the
  param/opt-state pytrees, no host sync, no recompile. A host-side
  ``GuardMonitor`` escalates: K consecutive skipped steps roll the
  trainer back to the last good checkpoint and re-seed the data stream
  to the rolled-back step.
- **Checkpoint hardening** (``utils.checkpoint``): fsync'd atomic
  writes, per-array-checksum manifests, last-N retention, and
  ``find_latest_valid`` auto-resume discovery that skips corrupt or
  truncated saves (``--resume auto``).
- **Serve robustness** (``serve.scheduler``): per-request TTFT/total
  deadlines (expiry evicts the slot and releases pinned prefix refs)
  and queue-depth admission shedding, both returned as structured
  ``Completion`` statuses so overload degrades instead of collapsing.
"""

from .faults import (  # noqa: F401
    FaultInjector,
    FaultSpec,
    corrupt_checkpoint,
    parse_fault,
    truncate_checkpoint,
)
from .guard import GuardMonitor, apply_guard  # noqa: F401
