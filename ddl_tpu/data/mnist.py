"""MNIST data pipeline.

Reference parity: the reference loads ``data/mnist.pkl`` — the classic
deeplearning.net 3-way pickle ``(train, valid, test)`` with ``x`` as
``float32 [N, 784]`` in ``[0, 1]`` and integer labels — and one-hot encodes
labels with ``pd.get_dummies`` (reference: mnist_sync/model/model.py:6-14).
This module reproduces those semantics (numpy one-hot instead of pandas) and
adds a deterministic *procedural* MNIST-style dataset with identical shapes
and dtypes for hermetic environments with no network egress: glyph-rendered
digits with random shift / thickness / intensity / noise augmentation.

The procedural set is fully determined by its seed, so convergence tests and
benchmarks are reproducible bit-for-bit across runs and hosts.
"""

from __future__ import annotations

import dataclasses
import os
import pickle

import numpy as np

NUM_CLASSES = 10
IMAGE_DIM = 784  # 28 x 28

# 5x7 bitmap glyphs for digits 0-9 (classic dot-matrix font).
_GLYPHS = {
    0: ("01110", "10001", "10011", "10101", "11001", "10001", "01110"),
    1: ("00100", "01100", "00100", "00100", "00100", "00100", "01110"),
    2: ("01110", "10001", "00001", "00010", "00100", "01000", "11111"),
    3: ("11111", "00010", "00100", "00010", "00001", "10001", "01110"),
    4: ("00010", "00110", "01010", "10010", "11111", "00010", "00010"),
    5: ("11111", "10000", "11110", "00001", "00001", "10001", "01110"),
    6: ("00110", "01000", "10000", "11110", "10001", "10001", "01110"),
    7: ("11111", "00001", "00010", "00100", "01000", "01000", "01000"),
    8: ("01110", "10001", "10001", "01110", "10001", "10001", "01110"),
    9: ("01110", "10001", "10001", "01111", "00001", "00010", "01100"),
}


@dataclasses.dataclass(frozen=True)
class Dataset:
    """Train/test split with the reference's shapes.

    ``x_*``: float32 ``[N, 784]`` in [0, 1]; ``y_*``: int32 ``[N]`` labels.
    Mirrors ``Model.x_train/y_train/x_test/y_test``
    (reference: mnist_sync/model/model.py:10-14), except labels stay integer
    here and are one-hot encoded on demand.
    """

    x_train: np.ndarray
    y_train: np.ndarray
    x_test: np.ndarray
    y_test: np.ndarray

    @property
    def num_train(self) -> int:
        return self.x_train.shape[0]

    @property
    def num_test(self) -> int:
        return self.x_test.shape[0]

    def train_onehot(self) -> np.ndarray:
        return one_hot(self.y_train)

    def test_onehot(self) -> np.ndarray:
        return one_hot(self.y_test)


def one_hot(labels: np.ndarray, num_classes: int = NUM_CLASSES) -> np.ndarray:
    """Numpy equivalent of the reference's ``pd.get_dummies(y)``
    (mnist_sync/model/model.py:13-14): float32 ``[N, 10]``."""
    labels = np.asarray(labels)
    out = np.zeros((labels.shape[0], num_classes), dtype=np.float32)
    out[np.arange(labels.shape[0]), labels] = 1.0
    return out


def _blur3(img: np.ndarray) -> np.ndarray:
    """Separable 3-tap binomial blur ([1,2,1]/4 per axis) over the last two
    axes, zero-padded. Vectorized over leading axes."""
    k = np.array([0.25, 0.5, 0.25], dtype=np.float32)
    padded = np.pad(img, [(0, 0)] * (img.ndim - 2) + [(1, 1), (0, 0)])
    img = (
        k[0] * padded[..., :-2, :]
        + k[1] * padded[..., 1:-1, :]
        + k[2] * padded[..., 2:, :]
    )
    padded = np.pad(img, [(0, 0)] * (img.ndim - 2) + [(0, 0), (1, 1)])
    return (
        k[0] * padded[..., :, :-2]
        + k[1] * padded[..., :, 1:-1]
        + k[2] * padded[..., :, 2:]
    )


def _glyph_bases() -> np.ndarray:
    """Render the base bank: ``[10 digits, 2 thicknesses, 34, 34]`` floats.

    Each 5x7 glyph is upscaled 3x (15x21), optionally dilated one pixel
    (thickness variant), centered on a 28x28 canvas, blurred, then padded to
    34x34 so +/-3-pixel shifts are pure slicing.
    """
    bases = np.zeros((NUM_CLASSES, 2, 34, 34), dtype=np.float32)
    for digit, rows in _GLYPHS.items():
        glyph = np.array([[c == "1" for c in row] for row in rows], dtype=np.float32)
        big = np.kron(glyph, np.ones((3, 3), dtype=np.float32))  # 21x15
        for thick in range(2):
            g = big
            if thick:
                # 1-pixel 4-neighbour dilation for a bolder stroke.
                p = np.pad(g, 1)
                g = np.maximum.reduce(
                    [p[1:-1, 1:-1], p[:-2, 1:-1], p[2:, 1:-1], p[1:-1, :-2], p[1:-1, 2:]]
                )
            canvas = np.zeros((28, 28), dtype=np.float32)
            top, left = (28 - g.shape[0]) // 2, (28 - g.shape[1]) // 2
            canvas[top : top + g.shape[0], left : left + g.shape[1]] = g
            bases[digit, thick] = np.pad(_blur3(canvas), 3)
    return bases


def synthesize(
    num_samples: int, seed: int, *, max_shift: int = 3, noise: float = 0.08
) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic procedural MNIST-style images.

    Returns ``(x [N, 784] float32 in [0,1], y [N] int32)``. Labels cycle
    through 0-9 then are shuffled, so every class is balanced to within one
    sample. Augmentation: per-sample shift in ``[-max_shift, max_shift]^2``,
    thickness variant, intensity scale in [0.7, 1.0], additive Gaussian
    noise, clipped to [0, 1].
    """
    rng = np.random.default_rng(np.random.PCG64(seed))
    bases = _glyph_bases()

    y = np.arange(num_samples, dtype=np.int32) % NUM_CLASSES
    rng.shuffle(y)
    thick = rng.integers(0, 2, size=num_samples)
    dy = rng.integers(-max_shift, max_shift + 1, size=num_samples)
    dx = rng.integers(-max_shift, max_shift + 1, size=num_samples)

    x = np.empty((num_samples, 28, 28), dtype=np.float32)
    # Group by (dy, dx): each group is a pure slice of the padded base bank.
    span = 2 * max_shift + 1
    shift_id = (dy + max_shift) * span + (dx + max_shift)
    for sid in np.unique(shift_id):
        idx = np.nonzero(shift_id == sid)[0]
        sy, sx = divmod(int(sid), span)
        sy -= max_shift
        sx -= max_shift
        window = bases[:, :, 3 + sy : 31 + sy, 3 + sx : 31 + sx]
        x[idx] = window[y[idx], thick[idx]]

    x *= rng.uniform(0.7, 1.0, size=(num_samples, 1, 1)).astype(np.float32)
    x += rng.normal(0.0, noise, size=x.shape).astype(np.float32)
    np.clip(x, 0.0, 1.0, out=x)
    return x.reshape(num_samples, IMAGE_DIM), y


def load_mnist(
    path: str | os.PathLike | None = "data/mnist.pkl",
    *,
    synthetic_train: int = 50_000,
    synthetic_test: int = 10_000,
    seed: int = 0,
) -> Dataset:
    """Load MNIST with the reference's semantics, or synthesize it.

    If ``path`` exists it must be the 3-way pickle the reference consumes
    (mnist_sync/model/model.py:8-11): ``(train, valid, test)`` tuples of
    ``(x, y)``; like the reference, the validation split is discarded.
    Otherwise a deterministic procedural dataset of the requested size is
    generated (train seed = ``seed``, test seed = ``seed + 1``).
    """
    if path is not None and os.path.exists(path):
        with open(path, "rb") as f:
            train_set, _, test_set = pickle.load(f, encoding="latin1")
        x_train, y_train = train_set
        x_test, y_test = test_set
        return Dataset(
            x_train=np.asarray(x_train, dtype=np.float32),
            y_train=np.asarray(y_train, dtype=np.int32),
            x_test=np.asarray(x_test, dtype=np.float32),
            y_test=np.asarray(y_test, dtype=np.int32),
        )
    x_train, y_train = synthesize(synthetic_train, seed)
    x_test, y_test = synthesize(synthetic_test, seed + 1)
    return Dataset(x_train=x_train, y_train=y_train, x_test=x_test, y_test=y_test)
