from .mnist import Dataset, load_mnist, one_hot, synthesize

__all__ = ["Dataset", "load_mnist", "one_hot", "synthesize"]
