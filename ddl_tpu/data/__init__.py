from .lm import LMDataset, synthesize_copy  # noqa: F401
from .mnist import Dataset, load_mnist, one_hot, synthesize

__all__ = ["Dataset", "LMDataset", "load_mnist", "one_hot", "synthesize", "synthesize_copy"]
