from .mnist import Dataset, load_mnist, one_hot

__all__ = ["Dataset", "load_mnist", "one_hot"]
