"""Procedural language-modeling data: the copy task.

The long-context analogue of the procedural MNIST set (``data.mnist``):
fully seed-determined, no egress, and the *task itself certifies the
machinery* — each sequence is ``[BOS, prefix, prefix]`` with loss only on
the repeated half, so every scored target is a token that appeared exactly
``seq_len//2 - 2`` positions earlier. A model (or a sequence-parallel
scheme) that cannot attend that far back cannot beat chance ``1/vocab``;
reaching accuracy ~1.0 proves cross-shard attention end to end (the copy
offset spans shard boundaries whenever ``T/W < seq_len//2 - 2``), the
same way MNIST accuracy is the oracle for the CNN strategies
(reference: mnist_sync/single.py:17-21).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class LMDataset:
    """Next-token prediction triples, train/test split.

    ``tokens``: int32 ``[N, T]`` model input; ``targets``: int32 ``[N, T]``
    with ``targets[:, t] = tokens[:, t+1]`` (last position padded 0);
    ``weights``: float32 ``[N, T]``, 1.0 where the cross-entropy is scored.
    """

    tokens: np.ndarray
    targets: np.ndarray
    weights: np.ndarray
    test_tokens: np.ndarray
    test_targets: np.ndarray
    test_weights: np.ndarray

    @property
    def num_train(self) -> int:
        return self.tokens.shape[0]

    @property
    def seq_len(self) -> int:
        return self.tokens.shape[1]


def synthesize_copy(
    num_train: int = 2048,
    num_test: int = 256,
    seq_len: int = 128,
    vocab: int = 64,
    seed: int = 0,
) -> LMDataset:
    """Sequences ``[BOS, a_1..a_{h-1}, a_1..a_h]`` with ``h = seq_len//2``:
    token 0 is reserved as BOS/pad, payload tokens are uniform in
    ``[1, vocab)``. Targets shift by one; weights score exactly the
    positions whose target has APPEARED before — ``t`` in
    ``[h-1, seq_len-2)``, each a copy of the token ``h-2`` positions back.
    (The first half's targets are fresh payload, and the final target
    ``a_h`` occurs nowhere earlier — both unpredictable, weight 0.)"""
    if seq_len % 2:
        raise ValueError(f"seq_len {seq_len} must be even")
    if vocab < 3:
        raise ValueError(f"vocab {vocab} too small for payload + BOS")
    half = seq_len // 2
    rng = np.random.default_rng(seed)

    def make(n: int, r: np.random.Generator):
        payload = r.integers(1, vocab, size=(n, half), dtype=np.int32)
        tokens = np.concatenate(
            [np.zeros((n, 1), np.int32), payload[:, :-1], payload], axis=1
        )
        targets = np.concatenate(
            [tokens[:, 1:], np.zeros((n, 1), np.int32)], axis=1
        )
        weights = np.zeros((n, seq_len), np.float32)
        # target[t] = tokens[t+1] = a_{t-h+2}, previously seen at position
        # t-h+2 — except t = seq_len-2, whose target a_h has no earlier
        # occurrence (payload's last token enters only at the final slot).
        weights[:, half - 1 : seq_len - 2] = 1.0
        return tokens, targets, weights

    tr = make(num_train, rng)
    te = make(num_test, rng)
    return LMDataset(*tr, *te)


def synthesize_prompts(
    num: int = 16,
    min_len: int = 4,
    max_len: int = 48,
    vocab: int = 64,
    seed: int = 0,
) -> list[np.ndarray]:
    """Deterministic variable-length prompt set for serving tests and
    benchmarks (``ddl_tpu.serve``), so decode-parity and batching tests
    never hand-roll inputs: one seed, one prompt list, everywhere.

    Each prompt is ``[BOS, payload...]`` — token 0 reserved as BOS (the
    copy-task convention, :func:`synthesize_copy`), payload uniform in
    ``[1, vocab)``; lengths uniform in ``[min_len, max_len]``. Returns
    int32 arrays (a LIST, not a padded matrix — variable length is the
    point: the serving stack owns its own padding/bucketing)."""
    if not 1 <= min_len <= max_len:
        raise ValueError(f"need 1 <= min_len <= max_len, got "
                         f"{min_len}/{max_len}")
    if vocab < 2:
        raise ValueError(f"vocab {vocab} too small for payload + BOS")
    rng = np.random.default_rng(seed)
    lens = rng.integers(min_len, max_len + 1, size=num)
    return [
        np.concatenate([
            np.zeros(1, np.int32),
            rng.integers(1, vocab, size=int(n) - 1, dtype=np.int32),
        ])
        for n in lens
    ]


def synthesize_shared_prefix_prompts(
    n_families: int = 4,
    per_family: int = 4,
    prefix_len: int = 16,
    tail_min: int = 1,
    tail_max: int = 8,
    vocab: int = 64,
    seed: int = 0,
) -> list[np.ndarray]:
    """Deterministic SHARED-PREFIX prompt workload for the serving
    prefix cache (``ddl_tpu.serve.prefix``): ``n_families`` families of
    ``per_family`` prompts each, every prompt in a family opening with
    the same ``prefix_len``-token prefix (``[BOS, payload...]`` — the
    system-prompt / few-shot-header shape) followed by its own tail of
    uniform length in ``[tail_min, tail_max]``. Prompts return
    ROUND-ROBIN across families (family 0's first, family 1's first,
    ..., family 0's second, ...) so arrival-staggered benchmarks
    interleave families the way real traffic mixes tenants, instead of
    handing the cache one family at a time.

    Same contracts as :func:`synthesize_prompts`: one seed, one prompt
    list, everywhere; int32 arrays of VARIABLE length (the serving
    stack owns padding/bucketing); token 0 reserved as BOS, payload in
    ``[1, vocab)``. Distinct families get distinct prefixes by
    construction is NOT guaranteed for tiny vocab/prefix combinations —
    the draw is uniform — but collisions only make the workload easier
    for a prefix cache, never wrong."""
    if n_families < 1 or per_family < 1:
        raise ValueError(
            f"need n_families >= 1 and per_family >= 1, got "
            f"{n_families}/{per_family}"
        )
    if prefix_len < 2:
        raise ValueError(
            f"prefix_len {prefix_len} must be >= 2 (BOS + >=1 shared "
            f"payload token — a 1-token 'shared prefix' is just BOS)"
        )
    if not 1 <= tail_min <= tail_max:
        raise ValueError(f"need 1 <= tail_min <= tail_max, got "
                         f"{tail_min}/{tail_max}")
    if vocab < 2:
        raise ValueError(f"vocab {vocab} too small for payload + BOS")
    rng = np.random.default_rng(seed)
    prefixes = [
        np.concatenate([
            np.zeros(1, np.int32),
            rng.integers(1, vocab, size=prefix_len - 1, dtype=np.int32),
        ])
        for _ in range(n_families)
    ]
    prompts = []
    for _ in range(per_family):
        for f in range(n_families):
            tail_len = int(rng.integers(tail_min, tail_max + 1))
            prompts.append(np.concatenate([
                prefixes[f],
                rng.integers(1, vocab, size=tail_len, dtype=np.int32),
            ]))
    return prompts


@dataclasses.dataclass(frozen=True)
class MixedRequest:
    """One arrival of the mixed-traffic stream
    (:func:`synthesize_mixed_traffic`): a serve request plus its SLO
    class. ``arrival`` is a scheduler tick (the open-loop clock);
    ``family`` identifies the shared-prefix family a prompt was drawn
    from (-1 = no family — the prompt is independent), so tests can
    assert affinity without re-deriving prefixes."""

    id: int
    arrival: int
    traffic_class: str
    prompt: np.ndarray  # int32 [p], BOS-led
    max_new_tokens: int
    family: int = -1


# The canonical three-class mix (ISSUE 8 / ROADMAP item 4): short
# interactive chat with shared-prefix families (system prompts), long
# document prompts with short answers, and bulk offline generation.
# Rates are per-tick Poisson means; callers override freely.
DEFAULT_TRAFFIC_CLASSES: dict[str, dict] = {
    "chat": dict(rate=0.5, prompt_min=8, prompt_max=24, max_new_tokens=8,
                 families=4, family_prefix_len=6),
    "longdoc": dict(rate=0.1, prompt_min=48, prompt_max=96,
                    max_new_tokens=16),
    "bulk": dict(rate=0.25, prompt_min=8, prompt_max=32,
                 max_new_tokens=32),
}

_TRAFFIC_CLASS_KEYS = ("rate", "prompt_min", "prompt_max",
                       "max_new_tokens", "families", "family_prefix_len")


def synthesize_mixed_traffic(
    classes: dict[str, dict] | None = None,
    horizon: int = 64,
    vocab: int = 64,
    seed: int = 0,
    diurnal_amplitude: float = 0.0,
    diurnal_period: int = 0,
    burst: tuple | None = None,
    max_requests: int = 0,
) -> list[MixedRequest]:
    """Seeded OPEN-LOOP multi-class traffic for the multi-tenant router
    (ISSUE 8): per tick ``t`` in ``[0, horizon)`` and per class, draw
    ``k ~ Poisson(rate * diurnal(t) * burst(t))`` arrivals. Classes are
    dicts (see :data:`DEFAULT_TRAFFIC_CLASSES`): ``rate`` (per-tick
    Poisson mean, >= 0), prompt length bounds, ``max_new_tokens``, and
    optionally ``families``/``family_prefix_len`` — a family class
    draws each prompt's first ``family_prefix_len`` tokens from one of
    ``families`` fixed BOS-led prefixes (the system-prompt shape), so
    prefix affinity is measurable on the stream.

    ``diurnal_amplitude``/``diurnal_period`` ramp every class's rate by
    ``1 + A * sin(2*pi*t / period)`` (the day-night load curve);
    ``burst`` is ``(start, length, multiplier)`` or ``(start, length,
    multiplier, class_name)`` — inside the window the (one or every)
    class's rate multiplies, the overload scenario shedding is pinned
    against. ``max_requests > 0`` truncates the stream to its first N
    arrivals in (arrival, id) order — the knob the tests/test_markers.py
    token-budget audit reads, so router tests carry a statically
    visible request bound.

    Determinism: one seeded generator, classes iterated in sorted name
    order, ticks in order — one seed, one stream, everywhere. Returned
    ids are 0..n-1 in (arrival, class, draw) order. Same prompt
    contracts as :func:`synthesize_prompts` (int32, BOS-led, payload in
    ``[1, vocab)``)."""
    if classes is None:
        classes = DEFAULT_TRAFFIC_CLASSES
    if not classes:
        raise ValueError("classes must name at least one traffic class")
    if horizon < 1:
        raise ValueError(f"horizon must be >= 1, got {horizon}")
    if vocab < 2:
        raise ValueError(f"vocab {vocab} too small for payload + BOS")
    if max_requests < 0:
        raise ValueError(f"max_requests must be >= 0, got {max_requests}")
    if not 0.0 <= diurnal_amplitude < 1.0:
        # amplitude >= 1 would drive the rate negative at the trough.
        raise ValueError(
            f"diurnal_amplitude must be in [0, 1), got {diurnal_amplitude}"
        )
    if diurnal_amplitude and diurnal_period < 2:
        raise ValueError(
            f"diurnal_amplitude needs diurnal_period >= 2, got "
            f"{diurnal_period}"
        )
    if burst is not None:
        if not isinstance(burst, (tuple, list)) or not 3 <= len(burst) <= 4:
            raise ValueError(
                f"burst must be (start, length, multiplier[, class]), "
                f"got {burst!r}"
            )
        b_start, b_len, b_mult = int(burst[0]), int(burst[1]), float(burst[2])
        b_class = burst[3] if len(burst) == 4 else None
        if b_start < 0 or b_len < 1 or b_mult <= 0:
            raise ValueError(
                f"burst needs start >= 0, length >= 1, multiplier > 0, "
                f"got {burst!r}"
            )
        if b_class is not None and b_class not in classes:
            raise ValueError(
                f"burst class {b_class!r} is not a traffic class "
                f"({sorted(classes)})"
            )
    for name, spec in classes.items():
        unknown = set(spec) - set(_TRAFFIC_CLASS_KEYS)
        if unknown:
            raise ValueError(
                f"class {name!r}: unknown spec keys {sorted(unknown)} "
                f"(valid: {list(_TRAFFIC_CLASS_KEYS)})"
            )
        rate = spec.get("rate", 0.0)
        if rate < 0:
            raise ValueError(f"class {name!r}: rate must be >= 0, got {rate}")
        pmin = spec.get("prompt_min", 4)
        pmax = spec.get("prompt_max", 16)
        if not 2 <= pmin <= pmax:
            raise ValueError(
                f"class {name!r}: need 2 <= prompt_min <= prompt_max, "
                f"got {pmin}/{pmax}"
            )
        if spec.get("max_new_tokens", 8) < 1:
            raise ValueError(
                f"class {name!r}: max_new_tokens must be >= 1"
            )
        fams = spec.get("families", 0)
        if fams:
            fpl = spec.get("family_prefix_len", 0)
            if fams < 1:
                raise ValueError(f"class {name!r}: families must be >= 1")
            if not 2 <= fpl < pmin:
                raise ValueError(
                    f"class {name!r}: family_prefix_len ({fpl}) must be in "
                    f"[2, prompt_min) — a family prefix needs BOS + >= 1 "
                    "payload token and must leave >= 1 tail token"
                )
    rng = np.random.default_rng(seed)
    arrivals: list[tuple[int, str, np.ndarray, int, int]] = []
    for name in sorted(classes):
        spec = classes[name]
        rate = float(spec.get("rate", 0.0))
        pmin = int(spec.get("prompt_min", 4))
        pmax = int(spec.get("prompt_max", 16))
        max_new = int(spec.get("max_new_tokens", 8))
        fams = int(spec.get("families", 0))
        fpl = int(spec.get("family_prefix_len", 0)) if fams else 0
        prefixes = [
            np.concatenate([
                np.zeros(1, np.int32),
                rng.integers(1, vocab, size=fpl - 1, dtype=np.int32),
            ])
            for _ in range(fams)
        ]
        for t in range(horizon):
            lam = rate
            if diurnal_amplitude:
                lam *= 1.0 + diurnal_amplitude * np.sin(
                    2.0 * np.pi * t / diurnal_period
                )
            if burst is not None and b_start <= t < b_start + b_len \
                    and (b_class is None or b_class == name):
                lam *= b_mult
            for _ in range(int(rng.poisson(lam))):
                if fams:
                    fam = int(rng.integers(fams))
                    tail = int(rng.integers(pmin - fpl, pmax - fpl + 1))
                    prompt = np.concatenate([
                        prefixes[fam],
                        rng.integers(1, vocab, size=tail, dtype=np.int32),
                    ])
                else:
                    fam = -1
                    n = int(rng.integers(pmin, pmax + 1))
                    prompt = np.concatenate([
                        np.zeros(1, np.int32),
                        rng.integers(1, vocab, size=n - 1, dtype=np.int32),
                    ])
                arrivals.append((t, name, prompt, max_new, fam))
    # (arrival, class, draw) order — class order is the sorted-name
    # generation order, draw order the Poisson sequence — then ids
    # assigned sequentially so (arrival, id) sorting is stable.
    arrivals.sort(key=lambda a: a[0])  # stable: preserves class/draw order
    if max_requests:
        arrivals = arrivals[:max_requests]
    return [
        MixedRequest(id=i, arrival=t, traffic_class=name, prompt=prompt,
                     max_new_tokens=max_new, family=fam)
        for i, (t, name, prompt, max_new, fam) in enumerate(arrivals)
    ]


def synthesize_longtail_prompts(
    num_short: int = 12,
    num_long: int = 2,
    short_min: int = 4,
    short_max: int = 12,
    long_len: int = 96,
    long_prefix_len: int = 0,
    vocab: int = 64,
    seed: int = 0,
) -> list[np.ndarray]:
    """Deterministic LONG-TAIL prompt mix for the paged KV pool
    (ISSUE 7): ``num_short`` short chat-shaped prompts (lengths uniform
    in ``[short_min, short_max]``) with ``num_long`` long-document
    prompts of exactly ``long_len`` tokens spread evenly among them —
    the workload where slot-major worst-case reservation hurts most
    (every slot pays the longest request's capacity) and pooled page
    admission wins.

    The long prompts share a common ``long_prefix_len``-token prefix
    (default ``long_len // 2``; pass ``0`` for the default, ``1`` for
    fully independent longs — the leading BOS is always shared) — the
    long-context family case (one big document, many questions), which
    is what makes zero-copy page sharing measurable on this mix.

    Same contracts as :func:`synthesize_prompts`: one seed, one prompt
    list, everywhere; int32 arrays of VARIABLE length; token 0 reserved
    as BOS, payload in ``[1, vocab)``."""
    if num_short < 0 or num_long < 0 or num_short + num_long < 1:
        raise ValueError(
            f"need num_short >= 0, num_long >= 0 and at least one "
            f"prompt, got {num_short}/{num_long}"
        )
    if not 1 <= short_min <= short_max:
        raise ValueError(f"need 1 <= short_min <= short_max, got "
                         f"{short_min}/{short_max}")
    if num_long and long_len <= short_max:
        raise ValueError(
            f"long_len ({long_len}) must exceed short_max ({short_max}) "
            "— otherwise the mix has no tail"
        )
    long_prefix_len = long_prefix_len or long_len // 2
    if num_long and not 1 <= long_prefix_len <= long_len:
        raise ValueError(
            f"long_prefix_len ({long_prefix_len}) outside "
            f"[1, long_len={long_len}]"
        )
    if vocab < 2:
        raise ValueError(f"vocab {vocab} too small for payload + BOS")
    rng = np.random.default_rng(seed)
    shorts = [
        np.concatenate([
            np.zeros(1, np.int32),
            rng.integers(1, vocab, size=int(n) - 1, dtype=np.int32),
        ])
        for n in rng.integers(short_min, short_max + 1, size=num_short)
    ]
    longs = []
    if num_long:
        # Guarded: a shorts-only mix must not draw (or validate) long
        # material at all — long_len/long_prefix_len are unconstrained
        # when no long prompt will be returned.
        shared = np.concatenate([
            np.zeros(1, np.int32),
            rng.integers(1, vocab, size=long_prefix_len - 1,
                         dtype=np.int32),
        ])
        longs = [
            np.concatenate([
                shared,
                rng.integers(1, vocab, size=long_len - long_prefix_len,
                             dtype=np.int32),
            ])
            for _ in range(num_long)
        ]
    # Longs spread evenly through the shorts (a long head-of-line
    # burst would test queueing, not pooling).
    prompts = list(shorts)
    stride = max(1, (len(prompts) + 1) // (num_long + 1))
    for i, lp in enumerate(longs):
        prompts.insert(min(len(prompts), (i + 1) * stride + i), lp)
    return prompts
