"""Device-mesh construction.

Replaces the reference's MPI rank topology (PS ranks ``0..num_ps-1``, worker
ranks ``num_ps..size-1``, mnist_sync_sharding/worker.py:60-66) with a JAX
``Mesh``. On TPU the "workers" are mesh positions along a data-parallel axis
riding ICI; the "parameter servers" disappear into shardings over the same
axis (SURVEY.md §5: the PS role becomes ``NamedSharding`` placement, the
handshake becomes a static layout computed at trace time).
"""

from __future__ import annotations

import math
import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis name for the data-parallel / shard axis. One 1-D axis covers
# the whole reference feature matrix: DP replicas and parameter shards are
# both laid out along it (ZeRO-style: shard count == worker count).
DP_AXIS = "dp"


def make_mesh(
    num_devices: int | None = None, *, axis: str = DP_AXIS, devices=None
) -> Mesh:
    """A 1-D mesh over ``num_devices`` (default: all local devices).

    The device order is ``jax.devices()`` order, which on TPU follows the
    physical ICI torus so neighbouring mesh positions are ICI neighbours —
    collectives along the axis ride ICI, never DCN.
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}"
            )
        devices = devices[:num_devices]
    if jax.process_count() > 1:
        # Multi-controller world: a mesh that skips a process entirely
        # leaves that process unable to build global arrays
        # (make_array_from_process_local_data has no addressable shard) —
        # surface it here instead of a StopIteration deep in staging.
        missing = set(range(jax.process_count())) - {
            d.process_index for d in devices
        }
        if missing:
            raise ValueError(
                f"mesh over {len(devices)} devices owns no row on "
                f"process(es) {sorted(missing)}; use a worker count that "
                "spans every process (e.g. --num-workers = the global "
                "device count)"
            )
    return Mesh(np.asarray(devices), (axis,))


# Second mesh axis for 2-D (data x sequence) parallelism: batch shards
# over DP_AXIS rows, sequence over SP_AXIS columns (strategies/seq.py).
SP_AXIS = "sp"


def make_mesh_2d(
    num_dp: int,
    num_sp: int,
    *,
    axes: tuple[str, str] = (DP_AXIS, SP_AXIS),
    devices=None,
) -> Mesh:
    """A ``[num_dp, num_sp]`` mesh over the first ``num_dp * num_sp``
    devices. ``jax.devices()`` order follows the physical ICI torus, and
    the minor (sp) axis is contiguous in it, so the sequence-parallel
    ring's ppermute hops ride neighbouring ICI links; dp collectives
    stride across rows (still ICI within a slice)."""
    return _mesh_nd((num_dp, num_sp), axes, devices)


# Tensor-parallel axis: Megatron-style column/row sharded block weights
# (strategies/seq.py tensor_parallel).
TP_AXIS = "tp"


def make_mesh_3d(
    num_dp: int,
    num_sp: int,
    num_tp: int,
    *,
    axes: tuple[str, str, str] = (DP_AXIS, SP_AXIS, TP_AXIS),
    devices=None,
) -> Mesh:
    """A ``[num_dp, num_sp, num_tp]`` mesh over the first ``dp*sp*tp``
    devices. The MINOR (tp) axis is contiguous in ``jax.devices()``
    order — tensor-parallel psums are the highest-frequency collective
    (two per block per step), so they get the neighbouring ICI links;
    the sp ring's ppermute strides by ``num_tp`` (still short ICI hops
    within a slice), and dp collectives stride widest."""
    return _mesh_nd((num_dp, num_sp, num_tp), axes, devices)


# Pipeline-parallel axis: the LAYER STACK splits into contiguous stages
# over it (ddl_tpu.pipeline). Activations (and cotangents on the
# backward) hop stage-to-stage via lax.ppermute each schedule tick.
PP_AXIS = "pp"


def make_mesh_4d(
    num_dp: int,
    num_sp: int,
    num_tp: int,
    num_pp: int,
    *,
    axes: tuple[str, str, str, str] = (DP_AXIS, SP_AXIS, TP_AXIS, PP_AXIS),
    devices=None,
) -> Mesh:
    """A ``[num_dp, num_sp, num_tp, num_pp]`` mesh over the first
    ``dp*sp*tp*pp`` devices. The MINOR (pp) axis is contiguous in
    ``jax.devices()`` order, so every stage hop — one activation
    ppermute forward and one cotangent ppermute backward per schedule
    tick — rides a neighbouring ICI link; tp psums stride by ``num_pp``
    (still short hops within a slice), sp and dp stride wider. A
    ``num_pp == 1`` topology should use :func:`make_mesh_3d` /
    :func:`make_mesh_2d` instead (byte-identical programs to the
    pre-pipeline stack)."""
    return _mesh_nd((num_dp, num_sp, num_tp, num_pp), axes, devices)


def _mesh_nd(shape: tuple[int, ...], axes: tuple[str, ...], devices) -> Mesh:
    """Shared builder behind the 2-D/3-D mesh constructors: validates
    sizes, slices the leading devices, and rejects topologies that leave
    a process owning no devices (one copy of the check — the 2-D/3-D
    twins diverging here would be invisible until a multi-process run)."""
    if min(shape) < 1:
        raise ValueError(
            "mesh axes must be >= 1, got " + "x".join(map(str, shape))
        )
    if devices is None:
        devices = jax.devices()
    n = math.prod(shape)
    if n > len(devices):
        raise ValueError(
            f"requested {'x'.join(map(str, shape))} devices, "
            f"have {len(devices)}"
        )
    devices = list(devices)[:n]
    if jax.process_count() > 1:
        missing = set(range(jax.process_count())) - {
            d.process_index for d in devices
        }
        if missing:
            raise ValueError(
                f"mesh over {n} devices owns no row on process(es) "
                f"{sorted(missing)}; use a topology that spans every process"
            )
    return Mesh(np.asarray(devices).reshape(shape), axes)


def _cpu_collective_flags_supported() -> bool:
    """Whether this jaxlib's XLA knows the CPU collective-rendezvous
    timeout flags. XLA FATALLY ABORTS the whole process on any unknown
    flag in XLA_FLAGS ("Unknown flags in XLA_FLAGS", parse_flags_from
    _env.cc) — with pytest capturing output, that abort is silent — so
    on older jaxlib these flags must never be set. The flags landed
    alongside the 0.5 jaxlib line; version-gate rather than probe
    (probing would need a throwaway subprocess per import)."""
    try:
        import jaxlib

        major, minor = (int(x) for x in jaxlib.__version__.split(".")[:2])
    except Exception:
        return False
    return (major, minor) >= (0, 5)


def extend_cpu_collective_timeouts(warn_s: int = 120, kill_s: int = 900) -> None:
    """Raise XLA:CPU's in-process collective rendezvous timeouts via
    XLA_FLAGS (effective only BEFORE the CPU backend initializes).

    The CPU runtime hard-aborts the process when the devices' threads do
    not all reach a collective within ~40s of each other
    (``rendezvous.cc`` "Termination timeout ... Exiting to ensure a
    consistent program state"). On a loaded single-core host, 8 virtual
    devices each running a multi-second program segment before a
    collective can legitimately exceed that skew — a full-width W=8
    per-worker eval was measured aborting this way. Flags already present
    in XLA_FLAGS are respected. No-op on jaxlib generations whose XLA
    predates the flags (unknown XLA_FLAGS are a fatal abort there)."""
    import os

    if not _cpu_collective_flags_supported():
        return
    flags = os.environ.get("XLA_FLAGS", "")
    add = []
    if "xla_cpu_collective_call_warn_stuck_timeout_seconds" not in flags:
        add.append(
            f"--xla_cpu_collective_call_warn_stuck_timeout_seconds={warn_s}"
        )
    if "xla_cpu_collective_call_terminate_timeout_seconds" not in flags:
        add.append(
            f"--xla_cpu_collective_call_terminate_timeout_seconds={kill_s}"
        )
    if add:
        os.environ["XLA_FLAGS"] = (flags + " " + " ".join(add)).strip()


def virtual_cpu_mesh(n: int, *, probe: bool = True) -> None:
    """Point JAX at an ``n``-device virtual CPU platform — the hermetic
    surface every multi-chip strategy runs on when real chips are absent
    (tests, CI, smoke runs, the driver dryrun).

    ``probe=False`` sets the config BEFORE any backend initializes and must
    be used when CPU was explicitly requested: probing ``jax.devices()``
    first would initialize the default backend — on this host the axon TPU
    tunnel, whose remote handshake can block for minutes and is never
    needed for a CPU run. ``probe=True`` pays that init to return early
    when the active platform already has ``n`` devices, else clears the
    backends and switches.

    (The tunnel's sitecustomize forces ``jax_platforms`` programmatically,
    so plain ``JAX_PLATFORMS=cpu`` env vars cannot do this.)
    """
    import os

    import jax

    # Only effective pre-init; harmless otherwise.
    extend_cpu_collective_timeouts()
    if probe:
        if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
            # The caller's environment explicitly asked for CPU (e.g. the
            # driver dryrun); honor it over the sitecustomize override
            # BEFORE probing, or the probe itself would initialize the
            # TPU tunnel backend — a remote handshake that can block
            # indefinitely when the tunnel is down.
            jax.config.update("jax_platforms", "cpu")
        try:
            if len(jax.devices()) >= n:
                return
        except RuntimeError:
            pass
        import jax.extend.backend as jeb

        jeb.clear_backends()
    set_cpu_device_count(max(n, 8))
    jax.config.update("jax_platforms", "cpu")


def set_cpu_device_count(n: int) -> None:
    """Ask for an ``n``-device virtual CPU platform, whichever way this
    JAX generation spells it: the ``jax_num_cpu_devices`` config when it
    exists, else the ``XLA_FLAGS --xla_force_host_platform_device_count``
    env var (which the CPU client reads at creation — callers must invoke
    this BEFORE the backend initializes, exactly the contract
    ``jax_num_cpu_devices`` has anyway)."""
    import os

    import jax

    from ..compat import has_config

    if has_config("jax_num_cpu_devices"):
        jax.config.update("jax_num_cpu_devices", n)
        return
    flags = os.environ.get("XLA_FLAGS", "")
    keep = [f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f]
    keep.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(keep)


class AcceleratorTimeout(RuntimeError):
    """A watchdogged native call did not complete: the accelerator backend
    is presumed dead/unreachable. The wedged thread is STILL blocked in
    native code — after reporting, the process should exit via ``os._exit``
    (normal interpreter shutdown can re-enter the dead backend through
    atexit/PJRT destructors and hang anyway)."""


def run_within(fn, timeout_s: float, *, what: str = "operation"):
    """Run ``fn`` on a daemon watchdog thread; return its result, re-raise
    its exception, or raise :class:`AcceleratorTimeout` after ``timeout_s``
    seconds. The one shared wedged-native-call watchdog (backend probes,
    training-span fetches): native backend calls can block INDEFINITELY
    when the accelerator dies (the axon tunnel drops for hours) and cannot
    be interrupted — only abandoned. See :class:`AcceleratorTimeout` for
    the post-timeout exit contract."""
    import threading

    outcome: list[tuple[bool, object]] = []

    def run():
        try:
            outcome.append((True, fn()))
        except BaseException as e:  # surface the real error, not a timeout
            outcome.append((False, e))

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout_s)
    if not outcome:
        raise AcceleratorTimeout(
            f"{what} did not complete within {timeout_s:.0f}s"
        )
    ok, value = outcome[0]
    if not ok:
        raise value
    return value


def backend_ready(timeout_s: float = 240.0) -> bool:
    """Probe the default backend with a watchdog thread. The axon tunnel's
    remote handshake can block INDEFINITELY when the tunnel is down; a
    benchmark that hangs forever is worse than one that reports the outage.
    NB when this returns False the probe thread is stuck in native code —
    callers must exit via ``os._exit`` (after flushing stdout)."""

    def probe():
        import jax

        return len(jax.devices())

    try:
        run_within(probe, timeout_s, what="backend probe")
        return True
    except Exception:
        # Timeout OR fast failure (e.g. 'unable to initialize backend'):
        # either way the backend is not ready — callers print their error
        # JSON instead of crashing with a traceback.
        return False


def probe_backend_subprocess(timeout_s: float = 120.0) -> str:
    """Probe the default backend in a THROWAWAY subprocess.

    An in-process probe that fails leaves its thread wedged in native code
    (see :func:`backend_ready`) — it cannot be retried in the same process,
    because the second probe blocks on the same wedged backend-init lock.
    A subprocess probe is retryable forever: the wedged state dies with the
    child.

    Returns ``"tpu"`` (ready), ``"down"`` (no backend answered — hung or
    init error; worth retrying), or the answering platform name (e.g.
    ``"cpu"``) when a NON-TPU backend initialized fine — a deterministic
    condition callers must fail fast on, never retry (a silent CPU
    fallback must not count as "the accelerator is back", and a CPU-only
    host must not spin for the whole retry window)."""
    import subprocess
    import sys

    code = "import jax; print(jax.devices()[0].platform, flush=True)"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except (subprocess.TimeoutExpired, OSError):
        return "down"
    if r.returncode != 0:
        return "down"
    platform = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
    return platform or "down"


def wait_backend(
    window_s: float = 2700.0,
    *,
    probe_timeout_s: float = 120.0,
    interval_s: float = 180.0,
    log=None,
) -> bool:
    """Bounded retry window for a flaky accelerator backend (the axon TPU
    tunnel drops for minutes-to-hours at a time — round 3's driver bench
    was nulled by a single-probe exit, VERDICT r3 weak #1). Probes in
    throwaway subprocesses (:func:`probe_backend_subprocess`) every
    ``interval_s`` until one reports a TPU or ``window_s`` elapses; only
    then should the caller initialize its own backend. Returns True when
    a TPU answered; returns False IMMEDIATELY when a non-TPU backend
    answered (deterministic — retrying cannot make a TPU appear).
    ``window_s <= 0`` means a single probe."""
    import time as _time

    deadline = _time.monotonic() + max(window_s, 0.0)
    attempt = 0
    while True:
        attempt += 1
        status = probe_backend_subprocess(probe_timeout_s)
        if status == "tpu":
            if log and attempt > 1:
                log(f"backend reachable after {attempt} probes")
            return True
        if status != "down":
            if log:
                log(f"default backend is '{status}', not TPU — not "
                    "retrying (this host has no TPU to wait for)")
            return False
        now = _time.monotonic()
        if now >= deadline:
            return False
        if log:
            remaining = deadline - now
            log(
                f"backend probe {attempt} failed; retrying every "
                f"{interval_s:.0f}s for up to {remaining:.0f}s more"
            )
        _time.sleep(min(interval_s, max(deadline - _time.monotonic(), 0.0)))


def pallas_interpret_for(mesh: Mesh) -> bool:
    """Pallas kernel mode for this mesh: compiled (non-interpret) on TPU —
    the product path a real chip runs — and interpreter mode everywhere
    else (the CPU test meshes, where Mosaic cannot compile). Centralized so
    every kernel call site picks the same way and the selection is unit-
    testable without real hardware."""
    return mesh.devices.flat[0].platform != "tpu"


def donation_for(mesh: Mesh, *argnums: int) -> tuple[int, ...]:
    """Buffer-donation argnums for a jitted step on this mesh.

    On TPU, donating params/optimizer state halves peak HBM for the update.
    The in-process CPU runtime (the 8-device virtual test mesh) deadlocks in
    its AllReduce when replicated inputs are donated under shard_map, so
    donation is disabled there — correctness is identical either way.
    """
    if mesh.devices.flat[0].platform == "cpu" and mesh.devices.size > 1:
        return ()
    return argnums
