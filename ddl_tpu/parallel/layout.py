"""Parameter layout policies: which shard owns which parameter.

The reference's sharded parameter servers use the mechanism "permute the
variable list, then block-partition it by *variable count*":

- **block**: identity permutation; PS ``r`` owns the contiguous variable
  block ``[L*r, L*(r+1))`` with ``L = num_vars // num_ps`` and the last PS
  absorbing the remainder (reference:
  mnist_sync_sharding/parameter_server.py:30-32, worker routing
  ``ind = i // avg_var_size`` at mnist_sync_sharding/worker.py:33-36).
- **zigzag** ("greedy" in the reference): sort variables by element count and
  interleave smallest/largest before block-partitioning, so each block pairs
  a big tensor with small ones (reference:
  mnist_sync_sharding_greedy/worker.py:14-30).

This module reproduces both as *policies over (name, size) lists* — no MPI
ranks, no TF variables — and generalizes them:

- **lpt**: true greedy bin-packing (Longest Processing Time): place each
  variable, largest first, on the least-loaded shard. Strictly better balance
  than zigzag at any shard count (SURVEY.md §2.2 notes zigzag is *worse* than
  naive at 2 shards).
- **flat**: element-granular equal split that ignores variable boundaries —
  the TPU-native default (classic ZeRO-1): every shard gets exactly
  ``ceil(total/S)`` elements, perfect balance by construction, and the update
  maps onto ``psum_scatter``/``all_gather`` with no padding waste beyond the
  final shard.

All outputs are static Python/numpy — computed once at trace time, baked into
the compiled program (the TPU analogue of the reference's runtime metadata
handshake, mnist_sync_sharding/worker.py:72-75).
"""

from __future__ import annotations

import dataclasses

import numpy as np

Policy = str  # "block" | "zigzag" | "lpt" | "flat"

POLICIES = ("block", "zigzag", "lpt", "flat")

# TPU lane width. Per-shard slice lengths (max_shard) round up to this so
# every shard slice is tile-aligned end-to-end — reduce-scatter chunks,
# Adam state, reassembly, and the fused Pallas kernels all share the same
# aligned length and need no repacking copies. Cost: <= LANE-1 padded
# elements per shard.
LANE = 128


def align_lane(n: int) -> int:
    return -(-n // LANE) * LANE


def block_order(names: list[str], sizes: dict[str, int]) -> list[str]:
    """Identity permutation (reference creation order)."""
    return list(names)


def zigzag_order(names: list[str], sizes: dict[str, int]) -> list[str]:
    """Sort by element count (stable), then interleave smallest/largest —
    the reference's greedy ordering (mnist_sync_sharding_greedy/worker.py:14-30).
    For the 14-var CNN this yields
    [v13, v8, v1, v6, v3, v10, v5, v4, v7, v2, v11, v12, v0, v9]
    (SURVEY.md §2.2)."""
    asc = sorted(names, key=lambda n: sizes[n])
    desc = asc[::-1]
    out: list[str] = []
    for a, d in zip(asc, desc):
        out.append(a)
        out.append(d)
    return out[: len(names)]


def lpt_order(
    names: list[str], sizes: dict[str, int], num_shards: int
) -> tuple[list[str], list[int]]:
    """Longest-Processing-Time bin packing.

    Returns ``(order, shard_var_counts)`` where ``order`` lists the variables
    grouped by owning shard (shard 0's vars first) so that a contiguous
    block partition with the given per-shard counts realizes the assignment.
    """
    loads = [0] * num_shards
    bins: list[list[str]] = [[] for _ in range(num_shards)]
    for n in sorted(names, key=lambda n: -sizes[n]):
        s = int(np.argmin(loads))
        loads[s] += sizes[n]
        bins[s].append(n)
    order = [n for b in bins for n in b]
    return order, [len(b) for b in bins]


@dataclasses.dataclass(frozen=True)
class LayoutAssignment:
    """A fully-resolved layout: permutation + shard ownership.

    The flat parameter vector is the concatenation of variables in ``order``.
    Shard ``s`` owns flat elements ``[shard_starts[s], shard_starts[s] +
    shard_sizes[s])``. For var-granular policies these boundaries are
    variable-aligned; for ``flat`` they are arbitrary equal splits.
    """

    policy: Policy
    num_shards: int
    order: tuple[str, ...]  # variable names, layout order
    var_offsets: dict[str, int]  # flat offset of each var (layout order)
    shard_starts: tuple[int, ...]  # [S] flat element offsets
    shard_sizes: tuple[int, ...]  # [S] owned element counts
    var_to_shard: dict[str, int] | None  # None for "flat" (vars may span)
    total: int  # total element count (unpadded)

    @property
    def max_shard(self) -> int:
        """Per-shard slice length: the largest shard size, lane-aligned
        (see LANE above)."""
        return align_lane(max(self.shard_sizes))

    @property
    def balance(self) -> float:
        """max/mean shard load — 1.0 is perfect (true sizes, unaligned)."""
        return max(self.shard_sizes) / (self.total / self.num_shards)

    def summary(self) -> str:
        return (
            f"layout={self.policy} shards={self.num_shards} "
            f"sizes={list(self.shard_sizes)} balance={self.balance:.3f}"
        )


def _block_counts(num_vars: int, num_shards: int) -> list[int]:
    """Reference block split: ``L = num_vars // num_shards`` vars per shard,
    last shard takes the remainder (parameter_server.py:30-32)."""
    L = num_vars // num_shards
    counts = [L] * num_shards
    counts[-1] += num_vars - L * num_shards
    return counts


def _build(
    policy: Policy,
    order: list[str],
    starts: list[int],
    sz: list[int],
    var_to_shard: dict[str, int] | None,
    sizes: dict[str, int],
) -> LayoutAssignment:
    """Shared constructor tail: fill in the order-derived offsets."""
    var_offsets = {}
    off = 0
    for n in order:
        var_offsets[n] = off
        off += sizes[n]
    return LayoutAssignment(
        policy=policy,
        num_shards=len(sz),
        order=tuple(order),
        var_offsets=var_offsets,
        shard_starts=tuple(starts),
        shard_sizes=tuple(sz),
        var_to_shard=var_to_shard,
        total=sum(sizes[n] for n in order),
    )


def _var_granular(
    policy: Policy,
    order: list[str],
    counts: list[int],
    sizes: dict[str, int],
) -> LayoutAssignment:
    """Build a variable-aligned assignment from an ordered var list and
    per-shard variable counts (``order`` grouped by shard, shard 0 first)."""
    var_to_shard: dict[str, int] = {}
    starts, sz = [], []
    i = 0
    offset = 0
    for s, c in enumerate(counts):
        starts.append(offset)
        block = order[i : i + c]
        for n in block:
            var_to_shard[n] = s
        size_s = sum(sizes[n] for n in block)
        sz.append(size_s)
        offset += size_s
        i += c
    return _build(policy, order, starts, sz, var_to_shard, sizes)


def assign_layout(
    policy: Policy,
    num_shards: int,
    names: list[str],
    sizes: dict[str, int],
) -> LayoutAssignment:
    """Resolve a layout policy to a concrete shard assignment."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    total = sum(sizes[n] for n in names)

    if policy == "flat":
        # ceil then lane-align: equal padded shards whose boundaries match
        # the psum_scatter row split (collectives.reduce_scatter_flat with
        # chunk=max_shard).
        chunk = align_lane(-(-total // num_shards))
        starts = [min(s * chunk, total) for s in range(num_shards)]
        sz = [max(0, min(chunk, total - st)) for st in starts]
        return _build(policy, list(names), starts, sz, None, sizes)

    if policy == "block":
        order = block_order(names, sizes)
        counts = _block_counts(len(names), num_shards)
    elif policy == "zigzag":
        order = zigzag_order(names, sizes)
        counts = _block_counts(len(names), num_shards)
    elif policy == "lpt":
        order, counts = lpt_order(names, sizes, num_shards)
    else:
        raise ValueError(f"unknown layout policy {policy!r}; want {POLICIES}")
    if num_shards > len(names):
        raise ValueError(
            f"{policy!r} layout needs num_shards <= num_vars "
            f"({num_shards} > {len(names)}); use policy='flat'"
        )
    return _var_granular(policy, order, counts, sizes)


def fold_shards(
    base: LayoutAssignment, num_devices: int, sizes: dict[str, int]
) -> LayoutAssignment:
    """Fold an S-shard variable-granular assignment onto fewer owner devices:
    shard ``s`` lands on device ``s % num_devices``, keeping each shard's
    variable grouping intact.

    Reference parity: the launcher accepts ANY process split — ``run.sh 7 2``
    runs 7 PS processes serving 2 workers, each PS owning a block of the
    permuted variable list (mnist_sync_sharding/parameter_server.py:30-32).
    On TPU the shards co-locate with the workers (ZeRO), so when the
    requested shard count exceeds the mesh size the surplus shards wrap
    round-robin onto the devices — the balancing the policy computed over S
    bins is preserved per-bin, and the result is an ordinary
    ``num_devices``-shard assignment the step programs consume unchanged.
    ``flat`` never needs folding: re-splitting element-granular equal chunks
    over ``num_devices`` produces the identical ownership.
    """
    S, W = base.num_shards, num_devices
    if S <= W:
        return base
    if base.var_to_shard is None:
        raise ValueError("fold_shards applies to variable-granular layouts; "
                         "re-assign 'flat' over num_devices instead")
    groups: list[list[str]] = [[] for _ in range(W)]
    # base.order is grouped by shard in increasing shard index, so iterating
    # it appends each device's shards in round-robin order (d, d+W, d+2W, …)
    # with intra-shard order preserved.
    for n in base.order:
        groups[base.var_to_shard[n] % W].append(n)
    order = [n for g in groups for n in g]
    counts = [len(g) for g in groups]
    return _var_granular(base.policy, order, counts, sizes)
