"""Multi-host launch: ``jax.distributed`` over DCN.

The reference spans OS processes (and potentially nodes) with ``mpiexec``
MPMD — PS ranks then worker ranks in one MPI world
(reference: mnist_sync/run.sh:3; rank conventions at
mnist_sync_sharding/worker.py:60-66). The TPU-native equivalent is JAX's
multi-controller runtime (SURVEY.md §5 "distributed communication
backend"): every process runs the SAME SPMD program over its local chips,
``jax.distributed.initialize`` wires the processes into one global device
world over DCN, and arrays sharded over the global mesh make XLA place
collectives on ICI within a host and DCN across hosts. There are no
PS/worker *processes* — the role split stays a sharding, exactly as on one
host.

What changes per process is only the DATA: each process feeds the mesh rows
its local devices own (:func:`local_worker_rows`) and builds global arrays
with :func:`put` (``jax.make_array_from_process_local_data``). At
``process_count() == 1`` every helper degenerates to plain ``device_put``,
so the single-host path is byte-identical to not using this module — the
product trainers route all placement through :func:`put` unconditionally.

Launch (one process per host, same command everywhere):

    python -m ddl_tpu sync --multihost \\
        --coordinator host0:8476 --num-processes 2 --process-id $RANK

On a real TPU pod slice, ``initialize()`` with no arguments lets JAX pick
everything up from the TPU metadata environment.
"""

from __future__ import annotations

import socket
from typing import Any, Sequence

import numpy as np


def free_port() -> int:
    """An OS-assigned free TCP port (single-process coordinator default)."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids: Sequence[int] | None = None,
) -> None:
    """Join (or form) the multi-process JAX world.

    Must run before any other JAX call in the process (backend init
    freezes the device world). With all arguments ``None`` on a TPU pod
    slice, JAX infers everything from the TPU environment — that inference
    must NOT be pre-empted here, or every pod host would silently form its
    own 1-process world. Only the explicit ``num_processes=1`` degenerate
    case (the testable-on-one-host path) self-hosts a coordinator on a
    free local port.
    """
    import jax

    if num_processes == 1 and coordinator_address is None:
        coordinator_address = f"localhost:{free_port()}"
        process_id = 0
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


def shutdown() -> None:
    import jax

    jax.distributed.shutdown()


def process_count() -> int:
    import jax

    return jax.process_count()


def local_worker_rows(mesh) -> np.ndarray:
    """Mesh-axis positions whose device is addressable by THIS process —
    the worker rows this process must feed (the analogue of each reference
    worker slicing its own batches, mnist_sync/worker.py:27-30). The 1-D
    convenience form of :func:`_axis_positions`."""
    return _axis_positions(mesh, tuple(mesh.axis_names))


def _sharded_dims(mesh, pspec) -> list[tuple[int, tuple[str, ...], int]]:
    """``(dim, axis_names, shard_count)`` for every ARRAY dimension the
    spec genuinely shards — axes of mesh size 1 contribute nothing and a
    dim whose combined shard count is 1 is replicated in all but name
    (e.g. the batch dim of a ``[1, W]`` 2-D mesh)."""
    import math

    out = []
    for i, entry in enumerate(tuple(pspec)):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        count = math.prod(mesh.shape[a] for a in names)
        if count > 1:
            out.append((i, tuple(names), count))
    return out


def _lex_index(mesh, names: tuple[str, ...], coords: dict) -> int:
    """Lexicographic position (major-to-minor in ``names`` order) of one
    device's mesh ``coords`` along the combined axes — THE shard-order
    convention of ``NamedSharding(P(names))``, shared by every staging
    helper so it can never fork."""
    lex = 0
    for a in names:
        lex = lex * mesh.shape[a] + coords[a]
    return lex


def _local_lex_tuples(mesh, dims) -> set[tuple[int, ...]]:
    """One scan of the device array: for every device THIS process owns,
    its tuple of lex positions along each of ``dims``' combined axes."""
    import jax

    pid = jax.process_index()
    axes = list(mesh.axis_names)
    got = set()
    for idx in np.ndindex(*mesh.devices.shape):
        if mesh.devices[idx].process_index != pid:
            continue
        coords = dict(zip(axes, idx))
        got.add(tuple(_lex_index(mesh, names, coords)
                      for _, names, _ in dims))
    return got


def _axis_positions(mesh, names: tuple[str, ...]) -> np.ndarray:
    """Sorted unique lexicographic positions (major-to-minor in ``names``
    order) along the combined axes that THIS process's devices occupy —
    the n-D generalization of :func:`local_worker_rows`."""
    tuples = _local_lex_tuples(mesh, [(0, names, 0)])
    return np.asarray(sorted(t[0] for t in tuples), dtype=np.int64)


def local_slice(host_array, dim: int, num_shards: int, rows) -> np.ndarray:
    """The blocks of ``host_array`` along ``dim`` owned by mesh positions
    ``rows`` when that dim splits into ``num_shards`` equal blocks — the
    per-process data-feeding math, pure so it is unit-testable without a
    second process."""
    per = host_array.shape[dim] // num_shards
    idx = np.concatenate([np.arange(r * per, (r + 1) * per) for r in rows])
    return np.take(np.asarray(host_array), idx, axis=dim)


def _check_rectangular(mesh, dims) -> list[np.ndarray]:
    """Per-sharded-dim positions of THIS process's devices, after
    verifying they form a full cartesian product (a "rectangle") over
    the sharded dims. ``make_array_from_process_local_data`` consumes
    one contiguous block per dim, so a process whose devices cover e.g.
    positions {(0,0), (1,1)} of a 2-sharded-dim layout has no block to
    hand it — that topology needs a different process->device
    assignment, not silent mis-staging."""
    import itertools

    import jax

    # ONE device scan yields both sides of the comparison: the per-dim
    # position sets (each dim's projection of the tuples — exactly what
    # _axis_positions would report) and the actual tuple coverage.
    got = _local_lex_tuples(mesh, dims)
    per_dim = [
        np.asarray(sorted({t[i] for t in got}), dtype=np.int64)
        for i in range(len(dims))
    ]
    want = set(itertools.product(*(p.tolist() for p in per_dim)))
    if got != want:
        raise ValueError(
            f"process {jax.process_index()}'s devices cover sharded-dim "
            f"positions {sorted(got)}, not the rectangular block "
            f"{sorted(want)} that per-dim slab staging needs; choose a "
            "mesh topology whose per-process device blocks are "
            "contiguous over the sharded axes"
        )
    return per_dim


def put(mesh, pspec, host_array) -> Any:
    """Place a host array onto the global mesh with
    ``NamedSharding(mesh, pspec)``.

    Single process: plain ``device_put`` (the fast, familiar path).
    Multi-process: every process passes the FULL logical array (datasets
    here are deterministic, so each host materializes the same array);
    the blocks its devices own are extracted per sharded axis — ANY
    number of genuinely-sharded dims, which is what lets the 3-D
    ``[dp, sp, tp]`` mesh span OS processes (tp-replicated data dims
    keep each extraction an independent slab; ``_check_rectangular``
    rejects the non-slab topologies up front) — and handed to
    ``jax.make_array_from_process_local_data``, which assembles the
    global ``jax.Array`` without any cross-host transfer.
    """
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, pspec)
    if jax.process_count() == 1:
        return jax.device_put(host_array, sharding)
    dims = _sharded_dims(mesh, pspec)
    local = np.asarray(host_array)
    positions = _check_rectangular(mesh, dims)
    for (dim, _, count), rows in zip(dims, positions):
        local = local_slice(local, dim, count, rows)
    return jax.make_array_from_process_local_data(sharding, local)


def agree_flag(flag: bool) -> bool:
    """World-wide agreement (logical OR over processes) on a host-local
    flag. Preemption must stop every controller at the SAME span: if one
    process acts on its local SIGTERM while another dispatches the next
    span's training collectives, the mismatched collectives deadlock the
    world. Callers must invoke this from EVERY process at the same point
    (it is itself a collective); at ``process_count() == 1`` it is a
    no-op returning ``flag``."""
    import jax

    if jax.process_count() == 1:
        return flag
    from jax.experimental import multihost_utils

    return bool(multihost_utils.process_allgather(np.int32(flag)).max())


def replicate_for_host(mesh, tree) -> Any:
    """Make every leaf fully replicated — and therefore addressable from
    every process — before materializing to numpy (checkpoint saves, final
    param gathers). At ``process_count() == 1`` this is a no-op; in a
    multi-process world it is one cross-host reshard collective per leaf
    (``device_put`` to a replicated NamedSharding)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    if jax.process_count() == 1:
        return tree
    rep = NamedSharding(mesh, PartitionSpec())
    return jax.tree.map(lambda a: jax.device_put(a, rep), tree)


def put_tree(mesh, pspec_tree, host_tree) -> Any:
    """``put`` over a pytree: ``pspec_tree`` is either one PartitionSpec
    applied to every leaf or a matching tree of specs."""
    import jax
    from jax.sharding import PartitionSpec

    if isinstance(pspec_tree, PartitionSpec):
        return jax.tree.map(lambda a: put(mesh, pspec_tree, a), host_tree)
    return jax.tree.map(
        lambda spec, a: put(mesh, spec, a), pspec_tree, host_tree,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )
