"""Collective transport: the TPU-native replacement for the reference's
mpi4py layer (SURVEY.md §5 "distributed communication backend").

Reference wire protocol → XLA collective mapping:

- grad push + PS aggregation (``comm.Send(tag=var)`` / Recv-sum,
  mnist_sync/worker.py:22, parameter_server.py:57-61)
      → ``lax.psum`` / ``lax.psum_scatter`` over the mesh axis (ICI).
- param broadcast / sharded param pull (``comm.Bcast`` / routed ``Recv``,
  mnist_sync/parameter_server.py:68-69, mnist_sync_sharding/worker.py:89-94)
      → ``lax.all_gather`` of owner shards.
- metadata handshake (pickled dict, mnist_sync/worker.py:50-51)
      → ``FlatSpec``: static shapes/offsets resolved at trace time.

Everything here is a pure function usable inside ``shard_map``; nothing
touches the host after trace time (the reference pays a Python
``tf.py_function`` hop per tensor per step — worker.py:17-24 — which has no
TPU equivalent and is deliberately not reproduced).

Two sharded-update paths, selected by the layout policy:

- **equal-chunk ("flat")**: pad the flat vector to ``S * chunk``;
  ``psum_scatter`` gives each device its reduced chunk in one fused
  reduce-scatter (bandwidth-optimal, ~1x vector over ICI), update locally,
  ``all_gather`` back.
- **var-aligned (block/zigzag/lpt)**: shard boundaries are unequal, so
  gather the flat vector into owner-major padded rows ``[W, max_shard]``
  (a static overlap-tolerant gather, :func:`owner_slices`) and
  ``psum_scatter`` the rows (:func:`reduce_scatter_rows`) — each device
  receives only its reduced shard; update locally, ``all_gather`` +
  static-gather reassembly.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .layout import LayoutAssignment


@dataclasses.dataclass(frozen=True)
class FlatSpec:
    """Static flatten/unflatten plan for a param pytree in layout order."""

    order: tuple[str, ...]
    shapes: dict[str, tuple[int, ...]]
    offsets: dict[str, int]
    total: int

    @classmethod
    def from_layout(
        cls, layout: LayoutAssignment, shapes: Mapping[str, tuple[int, ...]]
    ) -> "FlatSpec":
        return cls(
            order=layout.order,
            shapes={n: tuple(shapes[n]) for n in layout.order},
            offsets=dict(layout.var_offsets),
            total=layout.total,
        )


def flatten_params(params: Mapping[str, jax.Array], spec: FlatSpec) -> jax.Array:
    """Concatenate params into one 1-D vector in layout order."""
    return jnp.concatenate([params[n].reshape(-1) for n in spec.order])


def unflatten_params(flat: jax.Array, spec: FlatSpec) -> dict[str, jax.Array]:
    """Inverse of :func:`flatten_params` (ignores any padding tail)."""
    out = {}
    for n in spec.order:
        off = spec.offsets[n]
        size = int(np.prod(spec.shapes[n])) if spec.shapes[n] else 1
        out[n] = lax.slice(flat, (off,), (off + size,)).reshape(spec.shapes[n])
    return out


# ---------------------------------------------------------------------------
# Tensor-parallel conjugate pair (Megatron f/g)
# ---------------------------------------------------------------------------


def tp_allreduce(axis: str | tuple[str, ...]):
    """Megatron's ``g`` operator: all-reduce FORWARD, identity BACKWARD.

    Completes a row-parallel matmul's partial sums (the ``wo``/``w2``
    outputs in strategies/seq.py's tensor parallelism). The backward is
    identity because the psum's output is consumed identically by every
    tp member — its cotangent is already tp-invariant, and re-reducing it
    would scale gradients by the tp degree. Written as a ``custom_vjp``
    (not a bare ``lax.psum``) so the gradient is EXPLICIT: JAX
    generations disagree about psum's transpose (old: psum again; vma:
    identity ``pvary``), and a step body that computes LOCAL grads inside
    ``shard_map`` (the ZeRO-1 bodies, ``check_vma=False``) must not
    inherit either rule by accident. Conjugate of :func:`tp_promote`."""

    @jax.custom_vjp
    def g(x):
        return lax.psum(x, axis)

    def fwd(x):
        return lax.psum(x, axis), None

    def bwd(_, ct):
        return (ct,)

    g.defvjp(fwd, bwd)
    return g


def tp_promote(axis: str | tuple[str, ...]):
    """Megatron's ``f`` operator: identity FORWARD, all-reduce BACKWARD.

    Marks the point where the tp-replicated residual stream enters
    column-parallel matmuls (each tp member's branch touches only its own
    head / d_ff shard): the forward is free, but the branch cotangents
    are PARTIAL sums — one per tp member — and must be psummed so
    everything upstream (LayerNorms, earlier blocks, the embedding) sees
    the full gradient. Conjugate of :func:`tp_allreduce`; together the
    pair makes the tensor-parallel forward/backward correct under ANY
    psum-transpose regime (see that function's docstring)."""

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, ct):
        return (lax.psum(ct, axis),)

    f.defvjp(fwd, bwd)
    return f


# ---------------------------------------------------------------------------
# Equal-chunk (ZeRO-1 "flat") path
# ---------------------------------------------------------------------------


def chunk_size(total: int, num_shards: int) -> int:
    return -(-total // num_shards)


def pad_to(flat: jax.Array, padded_total: int) -> jax.Array:
    return jnp.pad(flat, (0, padded_total - flat.shape[0]))


def reduce_scatter_flat(
    flat: jax.Array, num_shards: int, axis: str | tuple[str, ...], *,
    mean: bool, chunk: int | None = None
) -> jax.Array:
    """Inside shard_map: fused reduce-scatter of a (padded) flat vector.
    Returns this device's reduced chunk ``[chunk]``. Pass the layout's
    ``max_shard`` as ``chunk`` so the row split matches the flat layout's
    lane-aligned shard boundaries. ``axis`` may be a TUPLE of mesh axes
    (the 2-D ZeRO-1 path, strategies/seq.py): ``psum_scatter`` then both
    sums over all of them and splits the rows over the combined axes in
    lex order — first axis major, matching ``NamedSharding(P(axes))``
    chunk order and the caller's ``axis_index``-based owner arithmetic."""
    if chunk is None:
        chunk = chunk_size(flat.shape[0], num_shards)
    padded = pad_to(flat, chunk * num_shards)
    shard = lax.psum_scatter(
        padded.reshape(num_shards, chunk), axis, scatter_dimension=0, tiled=False
    )
    if mean:
        shard = shard / num_shards
    return shard


# ---------------------------------------------------------------------------
# Var-aligned (unequal shards) path
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class OwnerSlices:
    """Static owner-major slicing plan for a var-aligned layout on a
    ``num_devices`` mesh (the trace-time analogue of the reference PS's
    shard-bound math, mnist_sync_sharding/parameter_server.py:30-32).

    ``starts[s]`` is shard s's flat offset, padded to one entry per device
    (surplus devices own an empty range parked at the zero padding tail);
    ``pad_len`` bounds every ``(start, chunk)`` slice; ``slice_idx`` is the
    ``[W, chunk]`` gather map row s = ``flat[starts[s] : starts[s]+chunk]``
    (clipped positions land in the padding). Rows may OVERLAP for
    unbalanced layouts — a gather, not a partition — which is what lets a
    true reduce-scatter serve variable-aligned shard boundaries."""

    starts: np.ndarray  # [W] int32 flat offsets
    pad_len: int
    slice_idx: np.ndarray  # [W, chunk] int32 gather map


def owner_slices(layout: LayoutAssignment, num_devices: int) -> OwnerSlices:
    chunk = layout.max_shard
    starts = np.asarray(layout.shard_starts, np.int32)
    if len(starts) < num_devices:
        starts = np.concatenate([
            starts,
            np.full(num_devices - len(starts), layout.total, np.int32),
        ])
    pad_len = max(num_devices * chunk, layout.total + chunk)
    slice_idx = np.minimum(
        starts[:, None] + np.arange(chunk, dtype=np.int32)[None, :],
        pad_len - 1,
    )
    return OwnerSlices(starts=starts, pad_len=pad_len, slice_idx=slice_idx)


def owner_rows(flat: jax.Array, sl: OwnerSlices) -> jax.Array:
    """Gather a flat vector into owner-major padded rows ``[W, chunk]``."""
    return jnp.pad(flat, (0, sl.pad_len - flat.shape[0]))[
        jnp.asarray(sl.slice_idx)
    ]


def reduce_scatter_rows(
    flat: jax.Array, sl: OwnerSlices, axis: str, *, mean: bool,
    num_devices: int
) -> jax.Array:
    """Inside shard_map: true fused reduce-scatter for a VAR-ALIGNED layout.
    Gathers the local flat vector into owner-major rows (:func:`owner_rows`)
    and ``psum_scatter``s rows, so this device receives ONLY its reduced
    ``[chunk]`` shard (~W*chunk bytes over ICI vs a full ``psum``'s
    ~2*total; ~2x fewer reduce bytes for balanced layouts). Numerically
    identical to psum-then-slice up to reduction-order reassociation."""
    shard = lax.psum_scatter(
        owner_rows(flat, sl), axis, scatter_dimension=0, tiled=False
    )
    if mean:
        shard = shard / num_devices
    return shard


def reassembly_index(layout: LayoutAssignment) -> np.ndarray:
    """Static gather map: flat position j -> its position in the
    concatenation of per-shard padded owner slices ``[S * max_shard]``.
    Used by both the sharded sync step and the sharded async serve to
    reassemble the full vector after ``all_gather``/``all_to_all`` (the
    TPU analogue of the reference PS's shard-bound math,
    mnist_sync_sharding/parameter_server.py:30-32)."""
    idx = np.empty(layout.total, dtype=np.int32)
    m = layout.max_shard
    for s, (start, size) in enumerate(zip(layout.shard_starts, layout.shard_sizes)):
        idx[start : start + size] = s * m + np.arange(size, dtype=np.int32)
    return idx


def to_logical(padded_flat, layout: LayoutAssignment) -> np.ndarray:
    """Per-shard padded concatenation ``[>= S * max_shard]`` -> logical flat
    ``[total]`` in THIS layout's variable order (``layout.order``). NB the
    order is layout-specific — for a layout-independent form (e.g. the
    elastic checkpoint), unflatten the result into the params-shaped pytree
    with :func:`unflatten_params`."""
    return np.asarray(padded_flat)[reassembly_index(layout)]


def from_logical(logical, layout: LayoutAssignment, n: int) -> np.ndarray:
    """Inverse of :func:`to_logical`: scatter a logical flat vector (in
    THIS layout's order) into an ``[n]`` per-shard padded concatenation
    (``n = mesh_size * layout.max_shard``; padding stays zero, matching
    ``sharded_adam_init``)."""
    logical = np.asarray(logical)
    out = np.zeros(n, logical.dtype)
    out[reassembly_index(layout)] = logical
    return out
