"""Parallelism layer: mesh construction, parameter layout policies, and the
collective-communication primitives that replace the reference's mpi4py
transport (SURVEY.md §1 "transport layer", §5 "communication backend")."""

from .layout import (  # noqa: F401
    LayoutAssignment,
    assign_layout,
    block_order,
    lpt_order,
    zigzag_order,
)
from .mesh import make_mesh  # noqa: F401
from .collectives import (  # noqa: F401
    FlatSpec,
    flatten_params,
    reassembly_index,
    unflatten_params,
)
from .ring import (  # noqa: F401
    full_attention,
    make_ring_attention,
    make_ulysses_attention,
    ring_attention_shard,
    seq_sharding,
    ulysses_attention_shard,
)
