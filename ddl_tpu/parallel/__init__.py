"""Parallelism layer: mesh construction, parameter layout policies, and the
collective-communication primitives that replace the reference's mpi4py
transport (SURVEY.md §1 "transport layer", §5 "communication backend")."""

from .layout import (  # noqa: F401
    LayoutAssignment,
    assign_layout,
    block_order,
    lpt_order,
    zigzag_order,
)
from .mesh import make_mesh  # noqa: F401
from .collectives import (  # noqa: F401
    FlatSpec,
    flatten_params,
    reassembly_index,
    unflatten_params,
)
