"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Long-context support beyond the reference's feature matrix (the reference
has no attention and no sequence axis at all — fixed 784-pixel images,
mnist_sync/model/model.py:18-19; SURVEY.md §5 records sequence
parallelism as owed nothing for parity). This module adds the two
standard TPU-native sequence-parallel schemes as first-class mesh
programs, so models with a sequence dimension scale past one chip's HBM:

- **Ring attention** (:func:`ring_attention_shard`): Q stays resident;
  K/V blocks rotate around the mesh axis via ``lax.ppermute`` (ICI
  neighbour links — the mesh axis follows the physical torus, see
  ``mesh.make_mesh``). Attention is EXACT: the streaming-softmax state
  ``(m, l, acc)`` is rescaled per block (the FlashAttention/online-softmax
  recurrence), so P ring steps reproduce full softmax over the whole
  sequence while each device only ever materializes a ``[Tq_local,
  Tk_local]`` score tile. Memory per device: O(T/P) sequence, O(T/P * T/P)
  scores — the whole point of the scheme.
- **Ulysses / all-to-all** (:func:`ulysses_attention_shard`): two
  ``lax.all_to_all``s re-partition sequence-sharded activations to
  head-sharded ones and back; attention itself is an ordinary full-
  sequence computation over each device's head subset. Cheaper in
  collective count when ``num_heads >= P``; requires ``num_heads % P == 0``.

Causal ring sweeps support two position layouts: the contiguous default
(block ``i`` on device ``i`` — simple, but device P-1 computes on every
ring step) and the balanced two-ended **zigzag** layout
(:func:`zigzag_positions` / :func:`zigzag_permutation` — device ``i``
holds chunks ``i`` and ``2P-1-i`` of ``2P``, sub-tile skipping halves
the causal critical path; :func:`causal_work_profile` quantifies both).

Both are pure per-shard functions for use inside ``shard_map`` (the same
contract as ``collectives.py``), plus jitted whole-array wrappers
(:func:`make_ring_attention`, :func:`make_ulysses_attention`) that place
global ``[B, T, H, D]`` arrays sequence-sharded over the mesh axis.
Causal masking uses absolute positions (``lax.axis_index`` offsets), and
the ring starts on each device's own diagonal block so a causal sweep
never sees an all-masked first tile (the streaming state would otherwise
need NaN guards for ``exp(-inf - -inf)``).

Tests pin both schemes (fwd + grad, causal and not) against a
single-device oracle on the 8-device virtual mesh: tests/test_ring.py.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DP_AXIS

_MASKED = -1e30  # large-negative (not -inf): keeps exp(s - m) NaN-free


def full_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False,
    scale: float | None = None, q_offset: int | jax.Array = 0,
    k_offset: int | jax.Array = 0,
) -> jax.Array:
    """Plain softmax attention, ``[B, T, H, D]`` — the single-device oracle
    and the local kernel inside the Ulysses scheme. ``q_offset``/``k_offset``
    are the absolute positions of element 0 (needed when the caller holds a
    shard of the sequence), so causal masking is correct under sharding."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        s = jnp.where(kpos[None, :] <= qpos[:, None], s, _MASKED)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def zigzag_positions(
    i: int | jax.Array, axis_size: int, t_local: int
) -> jax.Array:
    """Absolute positions of shard ``i``'s rows under the two-ended
    ("zigzag") causal layout: the sequence is cut into ``2P`` equal
    chunks and device ``i`` holds chunks ``i`` and ``2P-1-i`` — one from
    each end of the causal triangle, so every device owns the same
    amount of early (cheap) and late (expensive) causal work. ``i`` may
    be a traced ``lax.axis_index``. This is the ONE definition of the
    layout — the staging permutation and the analytic work profile both
    derive from it (with ``numpy`` passed for host-side math)."""
    return _zigzag_positions(i, axis_size, t_local, jnp)


def _zigzag_positions(i, axis_size: int, t_local: int, xp):
    """Backend-generic body: ``xp`` is ``jnp`` (traced, in-shard) or
    ``numpy`` (host staging / analysis) — one source of truth for the
    chunk-pair assignment."""
    if t_local % 2:
        raise ValueError(
            f"zigzag layout needs an even per-shard length, got {t_local}"
        )
    h = t_local // 2
    lo = i * h + xp.arange(h)
    hi = (2 * axis_size - 1 - i) * h + xp.arange(h)
    return xp.concatenate([lo, hi])


def zigzag_permutation(axis_size: int, seq_len: int):
    """Host-side gather index ``perm [seq_len]`` such that contiguous
    sharding of ``x[..., perm]`` over ``axis_size`` devices lands the
    zigzag chunk pair ``(i, 2P-1-i)`` on device ``i`` — i.e. slot ``t``
    of the permuted sequence holds original position
    ``zigzag_positions(t // t_local, P, t_local)[t % t_local]`` (derived
    from that same function, so staging can never diverge from the
    in-shard position math). Pure numpy — staging-time data movement,
    not a mesh op."""
    import numpy as np

    if seq_len % (2 * axis_size):
        raise ValueError(
            f"zigzag layout needs seq_len % (2 * {axis_size}) == 0, "
            f"got {seq_len}"
        )
    t_local = seq_len // axis_size
    return np.concatenate([
        _zigzag_positions(i, axis_size, t_local, np)
        for i in range(axis_size)
    ]).astype(np.int64)


def causal_work_profile(
    axis_size: int, layout: str = "contiguous"
) -> "np.ndarray":
    """Analytic per-(device, ring step) compute for a causal ring sweep,
    in units of ONE FULL local tile — the same fully-masked-skip rule
    the runtime ``lax.cond`` applies, evaluated on the layout's position
    assignment. Returns ``work [P, P]``; ``work[i, r]`` is what device
    ``i`` computes at ring step ``r``. The wall-clock critical path of
    the lockstep ring is ``sum_r max_i work[i, r]`` (every step waits on
    its busiest device at the ppermute): contiguous = P full tiles
    (device P-1 computes every step); zigzag = (2P+1)/4 — the balanced
    layout halves the causal critical path. Used by tests and the
    balance bench row; unit-tested against the actual skip behavior."""
    import numpy as np

    P_ = axis_size
    nsub = 2 if layout == "zigzag" else 1
    t_local = 2 * nsub  # smallest even per-shard length; work is scale-free
    if layout == "zigzag":
        pos = [_zigzag_positions(i, P_, t_local, np) for i in range(P_)]
    elif layout == "contiguous":
        pos = [i * t_local + np.arange(t_local) for i in range(P_)]
    else:
        raise ValueError(f"unknown layout {layout!r}")
    ns = t_local // nsub
    work = np.zeros((P_, P_))
    for i in range(P_):
        for r in range(P_):
            j = (i - r) % P_  # origin of the K/V block held at step r
            for a in range(nsub):
                qp = pos[i][a * ns:(a + 1) * ns]
                for b in range(nsub):
                    kp = pos[j][b * ns:(b + 1) * ns]
                    if kp.min() <= qp.max():  # the runtime skip rule
                        work[i, r] += 1.0 / (nsub * nsub)
    return work


def ring_attention_shard(
    q: jax.Array, k: jax.Array, v: jax.Array, *, axis_name: str,
    axis_size: int, causal: bool = False, scale: float | None = None,
    qpos: jax.Array | None = None, kpos: jax.Array | None = None,
    vary_axes: tuple[str, ...] | None = None,
    layout: str = "contiguous", nsub: int | None = None,
) -> jax.Array:
    """Exact attention over a sequence sharded along ``axis_name``; call
    INSIDE ``shard_map``. Per-shard shapes ``[B, T/P, H, D]``.

    P ring steps; at step r this device holds the K/V block that started
    on device ``(i - r) % P`` (blocks rotate ``i -> i+1`` via
    ``ppermute`` — neighbour traffic on ICI). The online-softmax state is
    carried in fp32 regardless of input dtype; output is cast back to
    ``q.dtype``.

    ``qpos``/``kpos`` are the ABSOLUTE sequence positions of this shard's
    rows (int32 ``[Tq]`` / ``[Tk]``; default: per ``layout``). ``kpos``
    travels around the ring with its K/V block, so any assignment of
    positions to devices is supported — custom layouts just pass their
    own position arrays. ``layout`` names the built-in assignments:

    - ``"contiguous"`` (default): block ``i`` in mesh order. Simple, but
      a causal sweep leaves device P-1 computing on every ring step
      while device 0 computes once — the critical path is P full tiles.
    - ``"zigzag"``: the two-ended assignment (:func:`zigzag_positions`) —
      device ``i`` holds chunks ``i`` and ``2P-1-i`` of ``2P``. With the
      sub-tile skip below, every device computes ~2 quarter-tiles per
      ring step (3 on its diagonal step): the causal critical path drops
      to (2P+1)/4 full tiles, ~2x faster than contiguous at large P
      (:func:`causal_work_profile`). The CALLER owns the matching data
      movement: shard ``x[..., zigzag_permutation(P, T)]`` contiguously
      (strategies/seq.py stages exactly that, and feeds the same
      positions to RoPE so rotations stay absolute).

    Causal sub-tiles that are ENTIRELY masked (``min(kpos_sub) >
    max(qpos_sub)``, checked at runtime per ring step) skip their
    score/update compute via ``lax.cond``. ``nsub`` is the skip
    granularity: each local block is processed as ``nsub`` q-chunks x
    ``nsub`` travelling k-chunks (default 1; zigzag defaults to 2 —
    chunk-pair granularity, which is what makes its balance real: at
    tile granularity a zigzag tile always contains SOME unmasked work
    and nothing would skip). A skipped-from-the-start state is clean
    (the first real block's correction factor is exp(_MASKED - m_new)
    = 0), but every causal query row must attend at least one key (true
    whenever position 0 is somewhere in ``kpos``'s global set), or its
    normalization hits 0/0.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    i = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    if nsub is None:
        # Sub-tiling exists only for the causal skip: without causality
        # nothing can ever skip, so splitting would just shrink the MXU
        # tiles for zero benefit.
        nsub = 2 if (layout == "zigzag" and causal) else 1
    if qpos is None:
        qpos = (zigzag_positions(i, axis_size, Tq) if layout == "zigzag"
                else i * Tq + jnp.arange(Tq))
    if kpos is None:
        kpos = (zigzag_positions(i, axis_size, Tk) if layout == "zigzag"
                else i * Tk + jnp.arange(Tk))
    if Tq % nsub or Tk % nsub:
        raise ValueError(
            f"per-shard lengths ({Tq}, {Tk}) not divisible by nsub={nsub}"
        )

    # pcast-to-varying: the init state must carry the mesh axes in its
    # varying set, or the causal lax.cond rejects identity-vs-update
    # branches (the identity branch would return the axis-invariant init
    # while block_update's outputs vary with this device's q/k). On a
    # multi-axis mesh where q/k/v vary over MORE than the ring axis
    # (e.g. batch sharded over dp while the ring runs over sp), pass
    # ``vary_axes`` with every axis the inputs vary over.
    vary = functools.partial(
        lax.pcast, axis_name=vary_axes or axis_name, to="varying"
    )
    nq, nk = Tq // nsub, Tk // nsub
    # Per-q-chunk streaming state (python lists — nsub is static and tiny).
    qs = [q[:, a * nq:(a + 1) * nq] for a in range(nsub)]
    qps = [lax.slice(qpos, (a * nq,), ((a + 1) * nq,)) for a in range(nsub)]
    qmaxs = [qp.max() for qp in qps]
    ms = [vary(jnp.full((B, H, nq), _MASKED, dtype=jnp.float32))
          for _ in range(nsub)]
    ls = [vary(jnp.zeros((B, H, nq), dtype=jnp.float32)) for _ in range(nsub)]
    accs = [vary(jnp.zeros((B, nq, H, D), dtype=jnp.float32))
            for _ in range(nsub)]
    perm = [(s, (s + 1) % axis_size) for s in range(axis_size)]

    def block_update(m, l, acc, q, qpos, k, v, kpos):
        s_tile = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        s_tile = s_tile * scale
        if causal:
            s_tile = jnp.where(
                kpos[None, :] <= qpos[:, None], s_tile, _MASKED
            )
        m_new = jnp.maximum(m, s_tile.max(axis=-1))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(s_tile - m_new[..., None])
        l = l * correction + p.sum(axis=-1)
        acc = acc * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
        )
        return m_new, l, acc

    for r in range(axis_size):
        for b in range(nsub):
            k_sub = k[:, b * nk:(b + 1) * nk]
            v_sub = v[:, b * nk:(b + 1) * nk]
            kp_sub = lax.slice(kpos, (b * nk,), ((b + 1) * nk,))
            kmin = kp_sub.min() if causal else None
            for a in range(nsub):
                if causal:
                    # Entirely-future sub-tiles do no work (runtime check
                    # on the travelling positions — correct for ANY
                    # layout, including Tk != Tq). The saving is
                    # per-device compute; ring steps stay lockstep at the
                    # ppermute, so wall-clock balance depends on the
                    # position LAYOUT (see the docstring / zigzag).
                    ms[a], ls[a], accs[a] = lax.cond(
                        kmin > qmaxs[a],
                        lambda m, l, acc, q, qpos, k, v, kpos: (m, l, acc),
                        block_update,
                        ms[a], ls[a], accs[a], qs[a], qps[a],
                        k_sub, v_sub, kp_sub,
                    )
                else:
                    ms[a], ls[a], accs[a] = block_update(
                        ms[a], ls[a], accs[a], qs[a], qps[a],
                        k_sub, v_sub, kp_sub,
                    )
        if r != axis_size - 1:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
            if causal:
                kpos = lax.ppermute(kpos, axis_name, perm)
    acc = jnp.concatenate(accs, axis=1)
    l = jnp.concatenate(ls, axis=2)
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention_shard(
    q: jax.Array, k: jax.Array, v: jax.Array, *, axis_name: str,
    axis_size: int, causal: bool = False, scale: float | None = None,
    local_attn=None,
) -> jax.Array:
    """Ulysses sequence parallelism; call INSIDE ``shard_map``. Per-shard
    ``[B, T/P, H, D]`` with ``H % P == 0``: one ``all_to_all`` turns the
    sequence sharding into a head sharding ``[B, T, H/P, D]``, a plain
    full-sequence local kernel runs on the head subset, and a second
    ``all_to_all`` restores sequence sharding. ``local_attn`` overrides
    the kernel — a ``(q, k, v) -> out`` closure over full-sequence
    ``[B, T, H/P, D]`` with causality/scale already bound (e.g. the
    Pallas flash kernel, ops/attention.py); default
    :func:`full_attention`."""
    H = q.shape[2]
    if H % axis_size:
        raise ValueError(
            f"ulysses needs num_heads % axis_size == 0, got {H} % {axis_size}"
        )
    if local_attn is None:
        local_attn = functools.partial(
            full_attention, causal=causal, scale=scale
        )
    a2a = functools.partial(
        lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
        tiled=True,
    )
    back = functools.partial(
        lax.all_to_all, axis_name=axis_name, split_axis=1, concat_axis=2,
        tiled=True,
    )
    out = local_attn(a2a(q), a2a(k), a2a(v))
    return back(out)


def seq_sharding(mesh: Mesh, axis: str = DP_AXIS) -> NamedSharding:
    """The ``[B, T, H, D]`` sequence-sharded placement both wrappers
    expect — ``jax.device_put(x, seq_sharding(mesh))`` stages inputs
    without relying on the jit boundary to insert the transfer."""
    return NamedSharding(mesh, P(None, axis))


def _make_wrapper(shard_fn, mesh: Mesh, axis: str, causal: bool):
    P_ = mesh.shape[axis]
    spec = P(None, axis)

    @jax.jit
    def fn(q, k, v):
        return jax.shard_map(
            functools.partial(
                shard_fn, axis_name=axis, axis_size=P_, causal=causal
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            # Every spec is sharded, so the replication checker has
            # nothing to certify here — and pre-vma JAX's checker has no
            # rule for the causal sweep's lax.cond ("branches of cond
            # produced mismatched replication types"). Gradients through
            # this boundary ride ppermute/all_to_all transposes only
            # (exact on every generation), never a psum.
            check_vma=False,
        )(q, k, v)

    return fn


def make_ring_attention(
    mesh: Mesh, *, axis: str = DP_AXIS, causal: bool = False
):
    """Jitted ring attention over global ``[B, T, H, D]`` arrays sharded
    on ``T`` along ``mesh``'s ``axis`` (``T % mesh.shape[axis] == 0``).
    Use :func:`jax.device_put` with ``NamedSharding(mesh, P(None, axis))``
    to place inputs (the wrapper's jit will otherwise insert the
    placement transfer itself)."""
    return _make_wrapper(ring_attention_shard, mesh, axis, causal)


def make_ulysses_attention(
    mesh: Mesh, *, axis: str = DP_AXIS, causal: bool = False
):
    """Jitted Ulysses attention over global ``[B, T, H, D]`` arrays
    sharded on ``T`` (``T`` and ``H`` both divisible by the axis size)."""
    return _make_wrapper(ulysses_attention_shard, mesh, axis, causal)
