"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Long-context support beyond the reference's feature matrix (the reference
has no attention and no sequence axis at all — fixed 784-pixel images,
mnist_sync/model/model.py:18-19; SURVEY.md §5 records sequence
parallelism as owed nothing for parity). This module adds the two
standard TPU-native sequence-parallel schemes as first-class mesh
programs, so models with a sequence dimension scale past one chip's HBM:

- **Ring attention** (:func:`ring_attention_shard`): Q stays resident;
  K/V blocks rotate around the mesh axis via ``lax.ppermute`` (ICI
  neighbour links — the mesh axis follows the physical torus, see
  ``mesh.make_mesh``). Attention is EXACT: the streaming-softmax state
  ``(m, l, acc)`` is rescaled per block (the FlashAttention/online-softmax
  recurrence), so P ring steps reproduce full softmax over the whole
  sequence while each device only ever materializes a ``[Tq_local,
  Tk_local]`` score tile. Memory per device: O(T/P) sequence, O(T/P * T/P)
  scores — the whole point of the scheme.
- **Ulysses / all-to-all** (:func:`ulysses_attention_shard`): two
  ``lax.all_to_all``s re-partition sequence-sharded activations to
  head-sharded ones and back; attention itself is an ordinary full-
  sequence computation over each device's head subset. Cheaper in
  collective count when ``num_heads >= P``; requires ``num_heads % P == 0``.

Both are pure per-shard functions for use inside ``shard_map`` (the same
contract as ``collectives.py``), plus jitted whole-array wrappers
(:func:`make_ring_attention`, :func:`make_ulysses_attention`) that place
global ``[B, T, H, D]`` arrays sequence-sharded over the mesh axis.
Causal masking uses absolute positions (``lax.axis_index`` offsets), and
the ring starts on each device's own diagonal block so a causal sweep
never sees an all-masked first tile (the streaming state would otherwise
need NaN guards for ``exp(-inf - -inf)``).

Tests pin both schemes (fwd + grad, causal and not) against a
single-device oracle on the 8-device virtual mesh: tests/test_ring.py.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DP_AXIS

_MASKED = -1e30  # large-negative (not -inf): keeps exp(s - m) NaN-free


def full_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False,
    scale: float | None = None, q_offset: int | jax.Array = 0,
    k_offset: int | jax.Array = 0,
) -> jax.Array:
    """Plain softmax attention, ``[B, T, H, D]`` — the single-device oracle
    and the local kernel inside the Ulysses scheme. ``q_offset``/``k_offset``
    are the absolute positions of element 0 (needed when the caller holds a
    shard of the sequence), so causal masking is correct under sharding."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        s = jnp.where(kpos[None, :] <= qpos[:, None], s, _MASKED)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def ring_attention_shard(
    q: jax.Array, k: jax.Array, v: jax.Array, *, axis_name: str,
    axis_size: int, causal: bool = False, scale: float | None = None,
    qpos: jax.Array | None = None, kpos: jax.Array | None = None,
    vary_axes: tuple[str, ...] | None = None,
) -> jax.Array:
    """Exact attention over a sequence sharded along ``axis_name``; call
    INSIDE ``shard_map``. Per-shard shapes ``[B, T/P, H, D]``.

    P ring steps; at step r this device holds the K/V block that started
    on device ``(i - r) % P`` (blocks rotate ``i -> i+1`` via
    ``ppermute`` — neighbour traffic on ICI). The online-softmax state is
    carried in fp32 regardless of input dtype; output is cast back to
    ``q.dtype``.

    ``qpos``/``kpos`` are the ABSOLUTE sequence positions of this shard's
    rows (int32 ``[Tq]`` / ``[Tk]``; default: contiguous blocks in mesh
    order). ``kpos`` travels around the ring with its K/V block, so any
    assignment of positions to devices is supported — striped/two-ended
    causal layouts that spread the causal triangle's work more evenly
    just pass their own position arrays. (Tile-granularity skipping
    cannot fully balance a striped layout — that needs sub-tile updates —
    so no such layout wrapper is shipped; the capability is the explicit
    positions.) Causal tiles that are ENTIRELY masked (``min(kpos) >
    max(qpos)``, checked at runtime per ring step) skip their
    score/update compute via ``lax.cond``; a skipped-from-the-start state
    is clean (the first real block's correction factor is
    exp(_MASKED - m_new) = 0), but every causal query row must attend at
    least one key (true whenever position 0 is somewhere in ``kpos``'s
    global set), or its normalization hits 0/0.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    i = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if qpos is None:
        qpos = i * Tq + jnp.arange(Tq)
    if kpos is None:
        kpos = i * Tk + jnp.arange(Tk)
    qmax = qpos.max()

    # pcast-to-varying: the init state must carry the mesh axes in its
    # varying set, or the causal lax.cond rejects identity-vs-update
    # branches (the identity branch would return the axis-invariant init
    # while block_update's outputs vary with this device's q/k). On a
    # multi-axis mesh where q/k/v vary over MORE than the ring axis
    # (e.g. batch sharded over dp while the ring runs over sp), pass
    # ``vary_axes`` with every axis the inputs vary over.
    vary = functools.partial(
        lax.pcast, axis_name=vary_axes or axis_name, to="varying"
    )
    m = vary(jnp.full((B, H, Tq), _MASKED, dtype=jnp.float32))
    l = vary(jnp.zeros((B, H, Tq), dtype=jnp.float32))
    acc = vary(jnp.zeros((B, Tq, H, D), dtype=jnp.float32))
    perm = [(s, (s + 1) % axis_size) for s in range(axis_size)]

    def block_update(m, l, acc, k, v, kpos):
        s_tile = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        s_tile = s_tile * scale
        if causal:
            s_tile = jnp.where(
                kpos[None, :] <= qpos[:, None], s_tile, _MASKED
            )
        m_new = jnp.maximum(m, s_tile.max(axis=-1))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(s_tile - m_new[..., None])
        l = l * correction + p.sum(axis=-1)
        acc = acc * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
        )
        return m_new, l, acc

    for r in range(axis_size):
        if causal:
            # Entirely-future tiles do no work (runtime check on the
            # travelling positions — correct for ANY layout, including
            # Tk != Tq and striped assignments). The saving is per-device
            # compute; ring steps stay lockstep at the ppermute, so
            # wall-clock balance depends on the position LAYOUT — the
            # contiguous default leaves device P-1 computing every step.
            m, l, acc = lax.cond(
                kpos.min() > qmax,
                lambda m, l, acc, k, v, kpos: (m, l, acc),
                block_update,
                m, l, acc, k, v, kpos,
            )
        else:
            m, l, acc = block_update(m, l, acc, k, v, kpos)
        if r != axis_size - 1:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
            if causal:
                kpos = lax.ppermute(kpos, axis_name, perm)
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention_shard(
    q: jax.Array, k: jax.Array, v: jax.Array, *, axis_name: str,
    axis_size: int, causal: bool = False, scale: float | None = None,
    local_attn=None,
) -> jax.Array:
    """Ulysses sequence parallelism; call INSIDE ``shard_map``. Per-shard
    ``[B, T/P, H, D]`` with ``H % P == 0``: one ``all_to_all`` turns the
    sequence sharding into a head sharding ``[B, T, H/P, D]``, a plain
    full-sequence local kernel runs on the head subset, and a second
    ``all_to_all`` restores sequence sharding. ``local_attn`` overrides
    the kernel — a ``(q, k, v) -> out`` closure over full-sequence
    ``[B, T, H/P, D]`` with causality/scale already bound (e.g. the
    Pallas flash kernel, ops/attention.py); default
    :func:`full_attention`."""
    H = q.shape[2]
    if H % axis_size:
        raise ValueError(
            f"ulysses needs num_heads % axis_size == 0, got {H} % {axis_size}"
        )
    if local_attn is None:
        local_attn = functools.partial(
            full_attention, causal=causal, scale=scale
        )
    a2a = functools.partial(
        lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
        tiled=True,
    )
    back = functools.partial(
        lax.all_to_all, axis_name=axis_name, split_axis=1, concat_axis=2,
        tiled=True,
    )
    out = local_attn(a2a(q), a2a(k), a2a(v))
    return back(out)


def seq_sharding(mesh: Mesh, axis: str = DP_AXIS) -> NamedSharding:
    """The ``[B, T, H, D]`` sequence-sharded placement both wrappers
    expect — ``jax.device_put(x, seq_sharding(mesh))`` stages inputs
    without relying on the jit boundary to insert the transfer."""
    return NamedSharding(mesh, P(None, axis))


def _make_wrapper(shard_fn, mesh: Mesh, axis: str, causal: bool):
    P_ = mesh.shape[axis]
    spec = P(None, axis)

    @jax.jit
    def fn(q, k, v):
        return jax.shard_map(
            functools.partial(
                shard_fn, axis_name=axis, axis_size=P_, causal=causal
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )(q, k, v)

    return fn


def make_ring_attention(
    mesh: Mesh, *, axis: str = DP_AXIS, causal: bool = False
):
    """Jitted ring attention over global ``[B, T, H, D]`` arrays sharded
    on ``T`` along ``mesh``'s ``axis`` (``T % mesh.shape[axis] == 0``).
    Use :func:`jax.device_put` with ``NamedSharding(mesh, P(None, axis))``
    to place inputs (the wrapper's jit will otherwise insert the
    placement transfer itself)."""
    return _make_wrapper(ring_attention_shard, mesh, axis, causal)


def make_ulysses_attention(
    mesh: Mesh, *, axis: str = DP_AXIS, causal: bool = False
):
    """Jitted Ulysses attention over global ``[B, T, H, D]`` arrays
    sharded on ``T`` (``T`` and ``H`` both divisible by the axis size)."""
    return _make_wrapper(ulysses_attention_shard, mesh, axis, causal)
