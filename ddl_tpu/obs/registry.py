"""Metric registry: counters / gauges / histograms with label sets.

The machine-readable metrics surface every subsystem reports through
(ISSUE 5) — replacing the ad-hoc per-run stats dicts as the thing
benchmarks and dashboards read. Three metric kinds, Prometheus
semantics:

- **Counter** — monotonically increasing total (``inc``); negative
  increments are rejected.
- **Gauge** — last-written value (``set``).
- **Histogram** — raw observed samples per label set. Percentiles are
  computed from the RAW samples with exactly the
  ``StepStats.from_times`` definition (``stats()`` literally delegates
  to it), so a registry histogram of step durations and a
  ``StepTimer`` of the same brackets can never disagree — the parity
  is pinned in tests/test_obs.py.

Label sets: each distinct ``**labels`` dict (order-insensitive, values
stringified) is an independent series under the metric name, exactly
Prometheus's data model. Registering one name as two kinds is an error.

Two exports:

- :meth:`MetricRegistry.prometheus_text` — a text-format snapshot
  (counters/gauges verbatim; histograms as summaries with
  p50/p95/p99 quantile rows plus ``_count``/``_sum``).
- :class:`MetricsWriter` — the JSONL sink behind ``--metrics-out``:
  the FIRST record of every file is a run manifest
  (:func:`run_manifest` — jax/jaxlib versions, mesh shape, config
  dump, git sha), then one snapshot record per flush; flushes are
  rate-limited by ``interval_s`` and forced on ``close()``/exit.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

import numpy as np

from ..utils.metrics import StepStats


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(value: str) -> str:
    """Prometheus exposition-format label-value escaping: backslash,
    double-quote and newline must be escaped or a value containing any
    of them silently corrupts the scrape (ISSUE 10 satellite; pinned
    with all three characters in tests/test_obs.py). Order matters —
    backslash first, or the other escapes' backslashes double."""
    return (value.replace("\\", r"\\")
            .replace('"', r"\"")
            .replace("\n", r"\n"))


class NoSamplesError(LookupError):
    """``Histogram.percentile`` was asked about a label set that holds
    no samples — an empty registry, or a label set that was never
    observed (a typo'd label silently reading 0.0 was the bug this
    replaces; ISSUE 10 satellite)."""


class _Metric:
    kind = "?"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._series: dict[tuple, object] = {}

    def label_sets(self) -> list[dict]:
        return [dict(k) for k in self._series]


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1, **labels) -> None:
        if value < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (inc {value}); "
                "use a gauge for values that go down"
            )
        k = _label_key(labels)
        self._series[k] = self._series.get(k, 0) + value

    def value(self, **labels):
        return self._series.get(_label_key(labels), 0)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        self._series[_label_key(labels)] = value

    def value(self, **labels):
        return self._series.get(_label_key(labels))


class Histogram(_Metric):
    kind = "histogram"

    def observe(self, value: float, **labels) -> None:
        self._series.setdefault(_label_key(labels), []).append(float(value))

    def observe_many(self, values, **labels) -> None:
        self._series.setdefault(_label_key(labels), []).extend(
            float(v) for v in values
        )

    def values(self, **labels) -> list[float]:
        return list(self._series.get(_label_key(labels), ()))

    def values_since(self, start: int, **labels) -> tuple[int, list[float]]:
        """``(total_count, samples[start:])`` for one label set — the
        incremental consumer's read (obs.slo ticks every scheduler
        step; copying the WHOLE series each tick would be O(history),
        this copies only the tail)."""
        vals = self._series.get(_label_key(labels), ())
        return len(vals), list(vals[start:])

    def count(self, **labels) -> int:
        return len(self._series.get(_label_key(labels), ()))

    def percentile(self, q: float, **labels) -> float:
        """Raw-unit percentile over the observed samples —
        ``np.percentile``'s linear interpolation, the SAME definition
        ``StepStats.from_times`` uses (parity pinned in test_obs).
        Raises :class:`NoSamplesError` when the label set holds no
        samples — a percentile of nothing is a question error, not 0.0
        (``stats()`` keeps its zero-filled ``StepStats`` contract for
        aggregate reporting)."""
        vals = self._series.get(_label_key(labels))
        if not vals:
            raise NoSamplesError(
                f"histogram {self.name!r} has no samples for label set "
                f"{dict(labels)!r} (observed label sets: "
                f"{self.label_sets()!r})"
            )
        return float(np.percentile(np.asarray(vals, np.float64), q))

    def stats(self, **labels) -> StepStats:
        """The observed samples as a ``StepStats`` (ms percentiles for
        second-valued observations) — DELEGATES to
        ``StepStats.from_times`` so the two percentile surfaces are one
        computation."""
        return StepStats.from_times(self.values(**labels))


class MetricRegistry:
    """Name -> metric map with kind checking. ``counter``/``gauge``/
    ``histogram`` create on first use and return the existing instance
    after (same-name re-registration with a different kind raises)."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}

    def _get(self, cls, name: str, help: str) -> _Metric:
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(name, help)
        elif type(m) is not cls:
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {cls.kind}"
            )
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get(Histogram, name, help)

    def get(self, name: str) -> _Metric | None:
        """NON-creating lookup (ISSUE 11): read-only consumers — the
        ``/healthz`` goodput summary, probes — must never materialize
        an empty series just by asking (the create-on-first-use
        accessors above are for writers)."""
        return self._metrics.get(name)

    def metrics(self) -> list[_Metric]:
        return [self._metrics[n] for n in sorted(self._metrics)]

    # -- exports -----------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """One plain-dict record per (metric, label set). Counters and
        gauges carry ``value``; histograms carry count/sum/mean and
        raw-unit p50/p95/p99 (linear interpolation — the from_times
        definition)."""
        out = []
        for m in self.metrics():
            for lk in sorted(m._series):
                rec = {"name": m.name, "kind": m.kind, "labels": dict(lk)}
                state = m._series[lk]
                if m.kind == "histogram":
                    a = np.asarray(state, np.float64)
                    rec.update(
                        count=int(a.size),
                        sum=float(a.sum()),
                        mean=float(a.mean()) if a.size else 0.0,
                        p50=float(np.percentile(a, 50)) if a.size else 0.0,
                        p95=float(np.percentile(a, 95)) if a.size else 0.0,
                        p99=float(np.percentile(a, 99)) if a.size else 0.0,
                    )
                else:
                    rec["value"] = state
                out.append(rec)
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition of the current state (histograms
        as summaries: quantile rows + ``_sum``/``_count``)."""

        def fmt_labels(labels: dict, extra: dict | None = None) -> str:
            items = {**labels, **(extra or {})}
            if not items:
                return ""
            body = ",".join(
                f'{k}="{_escape_label_value(str(v))}"'
                for k, v in sorted(items.items())
            )
            return "{" + body + "}"

        lines = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            kind = "summary" if m.kind == "histogram" else m.kind
            lines.append(f"# TYPE {m.name} {kind}")
            for lk in sorted(m._series):
                labels = dict(lk)
                state = m._series[lk]
                if m.kind == "histogram":
                    a = np.asarray(state, np.float64)
                    for q in (0.5, 0.95, 0.99):
                        v = float(np.percentile(a, q * 100)) if a.size else 0.0
                        lines.append(
                            f"{m.name}{fmt_labels(labels, {'quantile': q})}"
                            f" {v}"
                        )
                    lines.append(
                        f"{m.name}_sum{fmt_labels(labels)} {float(a.sum())}"
                    )
                    lines.append(
                        f"{m.name}_count{fmt_labels(labels)} {int(a.size)}"
                    )
                else:
                    lines.append(f"{m.name}{fmt_labels(labels)} {state}")
        return "\n".join(lines) + "\n"


# -- run manifest ------------------------------------------------------------


def _git_sha() -> str | None:
    try:
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=repo, timeout=5,
            capture_output=True, text=True,
        )
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:  # noqa: BLE001 — no git is a fine answer
        return None


def run_manifest(config=None, mesh=None, extra: dict | None = None) -> dict:
    """Reproducibility header for a metrics file: versions, topology,
    config, git sha. Every field degrades to None instead of raising —
    a manifest must never be the thing that kills a run."""
    man: dict = {"schema": "ddl_tpu.metrics.v1"}
    try:
        import jax
        import jaxlib

        man["jax_version"] = jax.__version__
        man["jaxlib_version"] = jaxlib.__version__
        try:
            devs = jax.devices()
            man["platform"] = devs[0].platform
            man["device_count"] = len(devs)
            man["process_index"] = int(jax.process_index())
        except RuntimeError:
            man["platform"] = None
    except Exception:  # noqa: BLE001
        man["jax_version"] = None
    if mesh is not None:
        man["mesh_shape"] = {
            str(name): int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)
        }
    if config is not None:
        man["config"] = (
            dataclasses.asdict(config)
            if dataclasses.is_dataclass(config) else config
        )
    man["git_sha"] = _git_sha()
    man["pid"] = os.getpid()
    man["argv"] = list(sys.argv)
    man["python"] = sys.version.split()[0]
    man["t_wall"] = time.time()
    if extra:
        man.update(extra)
    return man


class MetricsWriter:
    """The JSONL sink behind ``--metrics-out``: manifest record first
    (``{"record": "manifest", ...}``), then one
    ``{"record": "snapshot", "t_wall", "t_mono", "metrics": [...]}``
    per flush. ``maybe_flush()`` is rate-limited by ``interval_s`` (the
    trainer/scheduler loops call it freely); ``flush(force=True)`` and
    ``close()`` always write, so the file ends with a complete final
    state on any clean exit path."""

    def __init__(self, path, registry: MetricRegistry, manifest: dict |
                 None = None, *, interval_s: float = 10.0):
        self.registry = registry
        self.interval_s = interval_s
        self._last = float("-inf")
        path = os.fspath(path)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._file = open(path, "w")
        rec = {"record": "manifest", **(manifest or run_manifest())}
        self._file.write(json.dumps(rec) + "\n")
        self._file.flush()

    def maybe_flush(self, force: bool = False) -> bool:
        if self._file is None:
            return False
        now = time.monotonic()
        if not force and now - self._last < self.interval_s:
            return False
        self._last = now
        self._file.write(json.dumps({
            "record": "snapshot",
            "t_wall": time.time(),
            "t_mono": time.perf_counter(),
            "metrics": self.registry.snapshot(),
        }) + "\n")
        self._file.flush()
        return True

    def close(self) -> None:
        if self._file is not None:
            self.maybe_flush(force=True)
            self._file.close()
            self._file = None

    def __enter__(self) -> "MetricsWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
