"""Structured span tracing: nestable host wall-clock spans as JSONL.

One record per line, so a trace survives crashes mid-run (every
completed span is already on disk) and concatenates across processes.
Each record carries BOTH clocks — ``t``/``t0`` are ``time.perf_counter``
(monotonic; all intra-run math uses these) and ``t_wall`` is
``time.time`` (correlation across hosts/files) — plus ``pid`` and the
JAX ``process_index`` so multi-process worlds merge cleanly.

Two record types::

    {"type": "span",  "name": ..., "t0": ..., "t": ..., "dur_s": ...,
     "depth": ..., "seq": ..., "pid": ..., "process_index": ...,
     "t_wall": ..., "attrs": {...}}
    {"type": "event", "name": ..., "t": ..., "depth": ..., ...}

Spans nest (``depth`` is the span's own nesting level; records are
emitted at span END, so a child's record precedes its parent's — order
by ``t0``/``seq`` to reconstruct the tree). ``event`` accepts an
explicit ``t`` so callers can stamp an event with the exact
``perf_counter`` value they used for their own derived metrics — the
serve scheduler does this, which is what makes span-derived TTFT/ITL
EXACTLY equal to ``ServeStats`` (tests/test_obs.py).

``chrome_trace_events`` converts records to the Chrome/Perfetto
``trace_event`` format; ``python -m ddl_tpu.obs.trace in.jsonl out.json``
converts a file (open the result at https://ui.perfetto.dev or
chrome://tracing). ``trace_context`` combines a host tracer with the
existing ``jax.profiler`` trace (utils.metrics.trace), so a single
``--trace-dir`` run captures the host span timeline AND the XLA device
timeline side by side.

``NULL_TRACER`` is the disabled instance: same API, no records, and
FALSY — call sites guard clock reads with ``if tracer:`` so a disabled
run does not even pay the ``perf_counter`` calls (the off-path-unchanged
acceptance bar).
"""

from __future__ import annotations

import contextlib
import json
import os
import time


def _process_index() -> int:
    """JAX process index, 0 when no backend is reachable. Called lazily
    at first emit / context entry — never at import — so constructing a
    tracer can never initialize a backend before the CLI configures the
    platform."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — no backend is a fine answer
        return 0


class Tracer:
    """JSONL span/event emitter. ``path=None`` keeps records in memory
    only (``self.records`` — the test/derivation surface); with a path,
    records stream to disk and are ALSO kept when ``keep=True``."""

    def __init__(self, path: str | os.PathLike | None = None, *,
                 keep: bool | None = None):
        self._path = os.fspath(path) if path is not None else None
        self._file = None
        self._keep = keep if keep is not None else self._path is None
        self.records: list[dict] = []
        self._depth = 0
        self._seq = 0
        self._pid = os.getpid()
        self._pindex: int | None = None

    def __bool__(self) -> bool:
        return True

    # -- emission ----------------------------------------------------------

    def _emit(self, rec: dict) -> None:
        if self._pindex is None:
            self._pindex = _process_index()
        rec["seq"] = self._seq
        self._seq += 1
        rec["pid"] = self._pid
        rec["process_index"] = self._pindex
        rec["t_wall"] = time.time()
        if self._keep:
            self.records.append(rec)
        if self._path is not None:
            if self._file is None:
                parent = os.path.dirname(self._path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                # "w", matching MetricsWriter: a rerun into the same
                # --trace-dir replaces the old trace — appending would
                # interleave two runs' unrelated monotonic clocks in
                # the Chrome conversion. Crash-safety is unaffected
                # (records still stream line by line).
                self._file = open(self._path, "w")
            self._file.write(json.dumps(rec) + "\n")

    def event(self, name: str, t: float | None = None, **attrs) -> None:
        """Instant event. ``t`` (``perf_counter`` seconds) defaults to
        now; pass it explicitly to stamp the event with a timestamp you
        also used elsewhere (exact-derivation contract, module doc)."""
        self._emit({
            "type": "event", "name": name,
            "t": time.perf_counter() if t is None else t,
            "depth": self._depth, "attrs": attrs,
        })

    def complete(self, name: str, t0: float, t1: float, **attrs) -> None:
        """A finished span with caller-supplied bracket timestamps."""
        self._emit({
            "type": "span", "name": name, "t0": t0, "t": t1,
            "dur_s": t1 - t0, "depth": self._depth, "attrs": attrs,
        })

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Nestable wall-clock span; the record is emitted at exit (so
        an exception inside still leaves the span on disk)."""
        t0 = time.perf_counter()
        self._depth += 1
        try:
            yield self
        finally:
            self._depth -= 1
            self.complete(name, t0, time.perf_counter(), **attrs)

    # -- lifecycle ---------------------------------------------------------

    def flush(self) -> None:
        if self._file is not None:
            self._file.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "Tracer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class NullTracer:
    """Disabled tracer: same API, records nothing, and FALSY so call
    sites can skip even their clock reads (``if tracer: ...``)."""

    records: tuple = ()

    def __bool__(self) -> bool:
        return False

    def event(self, name: str, t: float | None = None, **attrs) -> None:
        pass

    def complete(self, name: str, t0: float, t1: float, **attrs) -> None:
        pass

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        yield self

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


NULL_TRACER = NullTracer()


def host_trace_file(trace_dir: str | os.PathLike) -> str:
    """The per-process host-span JSONL path inside ``trace_dir``
    (created): ``host_trace_p<process_index>.jsonl`` — one file per
    controller, mergeable by concatenation."""
    trace_dir = os.fspath(trace_dir)
    os.makedirs(trace_dir, exist_ok=True)
    return os.path.join(trace_dir, f"host_trace_p{_process_index()}.jsonl")


@contextlib.contextmanager
def trace_context(trace_dir: str | os.PathLike | None):
    """Host tracer + ``jax.profiler`` trace in one directory (None =
    disabled: yields ``NULL_TRACER``, starts nothing). The host spans
    land in ``host_trace_p<process_index>.jsonl`` next to the XLA
    profile, so one ``--trace-dir`` run captures both timelines."""
    if trace_dir is None:
        yield NULL_TRACER
        return
    trace_dir = os.fspath(trace_dir)
    tracer = Tracer(host_trace_file(trace_dir))
    from ..utils.metrics import trace as profiler_trace

    try:
        with profiler_trace(trace_dir):
            yield tracer
    finally:
        tracer.close()


# -- Chrome/Perfetto conversion ---------------------------------------------

# Instant events that mark attribution incidents (ISSUE 11 satellite):
# rendered GLOBALLY scoped (a full-height line on the timeline, not a
# thread-local tick) under a dedicated category, and chained into flow
# arrows so the timeline shows WHERE the incident's time went — a
# guard_skip flows to its guard_rollback, a shed to the request's
# completion record, consecutive anomalies of one signal to each other.
# Fleet incidents (ISSUE 13): scale/drain/crash render full-height; a
# preempt flows to its resume (both carry req) and on to the request's
# completion record — the timeline shows the hand-off. ONE definition:
# the analyze report's fleet-incident table reads this same tuple, so
# the two surfaces cannot drift.
FLEET_EVENTS = ("scale_out", "scale_in", "drain", "preempt", "resume",
                "preempt_move", "replica_crash", "requeue", "handoff")

INCIDENT_EVENTS = frozenset({
    "anomaly", "guard_skip", "guard_rollback", "shed", "router_shed",
    "deadline_exceeded", "slo_alert",
    *FLEET_EVENTS,
})


def _flow_key(name: str, attrs: dict):
    """The identity a flow chain follows: the request for lifecycle
    incidents (a preempt chains to its resume to the completion), the
    signal for anomalies, the rule for SLO alerts, the replica for
    fleet scale/drain/crash events (a drain flows into the scale_in
    that removes the replica), one shared chain for the trainer guard
    (its skips flow into the rollback that resolves them)."""
    if "req" in attrs:
        return ("req", attrs["req"])
    if "signal" in attrs:
        return ("signal", attrs["signal"])
    if "rule" in attrs:
        return ("rule", attrs["rule"])
    if "replica" in attrs:
        return ("replica", attrs["replica"])
    if name.startswith("guard_"):
        return ("guard", "train")
    return None


def chrome_trace_events(records) -> list[dict]:
    """Tracer records -> Chrome ``trace_event`` list (``ph``="X"
    complete events for spans, "i" instants for events; timestamps in
    microseconds of the monotonic clock). Incident instants
    (:data:`INCIDENT_EVENTS`) carry ``cat="incident"``, global scope,
    and flow (``s``/``t``/``f``) chains as above. Wrap in
    ``{"traceEvents": [...]}`` or pass through :func:`convert`."""
    out = []
    chains: dict[tuple, list[dict]] = {}
    for r in records:
        base = {
            "name": r["name"],
            "pid": r.get("pid", 0),
            "tid": r.get("process_index", 0),
            "args": r.get("attrs", {}),
        }
        if r.get("type") == "span":
            out.append({**base, "ph": "X", "ts": r["t0"] * 1e6,
                        "dur": r["dur_s"] * 1e6})
            continue
        inst = {**base, "ph": "i", "ts": r["t"] * 1e6, "s": "t"}
        attrs = r.get("attrs", {})
        name = r["name"]
        incident = name in INCIDENT_EVENTS
        if incident:
            inst["s"] = "g"
            inst["cat"] = "incident"
        out.append(inst)
        # Flow chains: every incident joins its key's chain; a
        # request's `complete` instant terminates that request's chain
        # (so shed/deadline incidents point at the completion record)
        # without itself opening one.
        key = _flow_key(name, attrs)
        if key is not None and (incident or (name == "complete"
                                             and key in chains)):
            chains.setdefault(key, []).append(inst)
    for flow_id, key in enumerate(sorted(chains, key=str), start=1):
        chain = chains[key]
        if len(chain) < 2:
            continue
        for i, inst in enumerate(chain):
            ph = "s" if i == 0 else ("f" if i == len(chain) - 1 else "t")
            flow = {
                "name": f"incident:{key[0]}={key[1]}",
                "cat": "incident_flow", "ph": ph, "id": flow_id,
                "ts": inst["ts"], "pid": inst["pid"], "tid": inst["tid"],
            }
            if ph == "f":
                flow["bp"] = "e"  # bind to the enclosing slice's end
            out.append(flow)
    return sorted(out, key=lambda e: (e["ts"], e["name"], e["ph"]))


def read_jsonl(path) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def convert(src, dst) -> int:
    """JSONL trace file -> Chrome ``trace_event`` JSON file; returns the
    event count."""
    events = chrome_trace_events(read_jsonl(src))
    with open(dst, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Convert a ddl_tpu host-trace JSONL file to a "
                    "Chrome/Perfetto trace_event JSON file "
                    "(open at https://ui.perfetto.dev)"
    )
    ap.add_argument("src", help="host_trace_p*.jsonl input")
    ap.add_argument("dst", help="trace_event JSON output")
    args = ap.parse_args(argv)
    n = convert(args.src, args.dst)
    # sys.stdout.write, not print: library code routes through the
    # tracer/registry — tests/test_no_stray_prints.py enforces it, and
    # this one-line converter report is not worth an exemption.
    import sys

    sys.stdout.write(f"[obs.trace] wrote {n} trace events to {args.dst}\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
