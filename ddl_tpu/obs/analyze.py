"""Offline trace/metrics analysis CLI (ISSUE 11 tentpole piece 3)::

    python -m ddl_tpu.obs.analyze report  TRACE.jsonl   [--json] [--top N]
    python -m ddl_tpu.obs.analyze comms   ARTIFACT      [--json]
    python -m ddl_tpu.obs.analyze compare OLD NEW [--threshold F]
                                          [--keys SUBSTR ...]
                                          [--ignore SUBSTR ...] [--json]

``report`` reads a host-trace JSONL file (``--trace-dir``'s
``host_trace_p*.jsonl``) and produces the run's time-attribution story
offline:

- **Goodput**: per-span-name wall-time totals mapped onto the
  obs.goodput phase taxonomy (``prefill_chunk`` -> prefill,
  ``decode_tick`` -> decode, ``train/span`` -> compute, ...), with the
  trace-side goodput fraction. This is the offline twin of the live
  ``time_in_seconds{phase=}`` gauges — the trace carries only closed
  spans, so host/idle residuals (live-only knowledge) are absent by
  construction.
- **Per-request critical path**: ``submit -> eligible -> admit ->
  prefill -> first_token -> complete`` per request, grouped per traffic
  class (the router's ``route`` events; ``default`` without one). TTFT
  and ITL are computed by :func:`serve.scheduler.request_slo_samples` /
  :func:`derive_request_slo` themselves — one definition, so the
  report can never disagree with the live SLO surfaces (pinned in
  tests/test_analyze.py).
- **Stragglers & anomalies**: the slowest-TTFT requests with their
  breakdowns, every ``anomaly`` event (signal, tick, z), and incident
  counts (guard skips/rollbacks, sheds, deadline evictions, SLO
  alerts).

``comms`` (ISSUE 20) renders the communication story of either artifact
shape: a ``benchmarks/collective_bytes.py`` JSON artifact (per-topology
collective schedules, the two-roofline fit against measured step times,
the fp32/bf16 gradient-collective byte ratio from precision-twin rows)
or a ``--metrics-out`` JSONL (the live per-program collective ledger,
per-mesh-axis bytes, roofline gauges and ``handoff_bytes_total`` paths
from the LAST snapshot). Always exits 0 on well-formed input — the
regression gating over these numbers is ``compare``'s job (CI runs both
over the committed artifact).

``compare`` diffs two metrics artifacts — ``--metrics-out`` JSONL files
(the LAST snapshot record) or plain-JSON benchmark artifacts
(``benchmarks/results_cpu/*.json``), flattened to dotted numeric
leaves — and **exits nonzero when any shared numeric key moved by more
than ``--threshold``** (relative). That exit code is the regression
gate CI runs over the committed artifacts (ISSUE 11 satellite); an
identical pair always exits 0.

Exit codes: 0 clean, 1 regressions found (compare only), 2 usage/input
errors.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import math
import sys

from .trace import FLEET_EVENTS, read_jsonl

# Span-name -> goodput phase for the trace-side attribution (the
# live-gauge taxonomy of obs.goodput, minus the residual-only phases).
SPAN_PHASE = {
    "prefill_chunk": "prefill",
    "decode_tick": "decode",
    "prefix_copy": "prefix_copy",
    "prefix_map": "prefix_copy",
    "compile": "compile",
    "train/span": "compute",
    "train/eval": "eval",
}
GOODPUT_SPAN_PHASES = ("prefill", "decode", "compute")

# Fleet-incident table rows (ISSUE 13 satellite): every scale / drain /
# preempt / crash event, in trace order, with its tick and actors —
# the SAME tuple the Chrome converter renders under cat=incident
# (obs.trace.FLEET_EVENTS), so the two surfaces cannot drift.
_FLEET_NAMES = FLEET_EVENTS

_INCIDENT_NAMES = ("guard_skip", "guard_rollback", "shed", "router_shed",
                   "deadline_exceeded", "slo_alert", "anomaly",
                   *_FLEET_NAMES)


def _emit(line: str = "") -> None:
    # sys.stdout.write, not print — tests/test_no_stray_prints.py bans
    # print() in library code, and this module is importable library
    # code first, CLI second.
    sys.stdout.write(line + "\n")


# -- report -------------------------------------------------------------------


def _span_totals(records) -> dict[str, dict]:
    out: dict[str, dict] = {}
    for r in records:
        if r.get("type") != "span":
            continue
        row = out.setdefault(r["name"], {"count": 0, "total_s": 0.0})
        row["count"] += 1
        row["total_s"] += float(r.get("dur_s", 0.0))
    return out


def _class_of(records) -> dict[int, str]:
    """request id -> traffic class from the router's ``route`` events
    (every request without one is ``default`` — the single-engine
    path)."""
    out: dict[int, str] = {}
    for r in records:
        # router_shed carries the class too: a door-shed request never
        # gets a route event (it never reached a replica).
        if r.get("name") in ("route", "router_shed"):
            attrs = r.get("attrs", {})
            out[int(attrs["req"])] = str(attrs.get("cls", "default"))
    return out


def _request_paths(records) -> dict[int, dict]:
    """Per-request critical-path stamps from the lifecycle events."""
    paths: dict[int, dict] = {}

    def at(rid):
        return paths.setdefault(int(rid), {})

    for r in records:
        name = r.get("name")
        attrs = r.get("attrs", {})
        if name in ("submit", "eligible", "admit", "first_token"):
            at(attrs["req"]).setdefault(name, r["t"])
        elif name == "complete":
            p = at(attrs["req"])
            p.setdefault("complete", r["t"])
            p["tokens"] = attrs.get("tokens")
            p["status"] = attrs.get("status", "ok")
        elif name in ("shed", "router_shed", "deadline_exceeded") \
                and "req" in attrs:
            at(attrs["req"]).setdefault(
                "status", "shed" if name == "router_shed" else name
            )
    return paths


def _breakdown(p: dict) -> dict:
    """The critical-path segment durations one request's stamps allow
    (absent stamps -> absent segments; a shed request has no path)."""
    out = {}

    def seg(name, a, b):
        if a in p and b in p:
            out[name] = p[b] - p[a]

    seg("queue_wait_s", "eligible", "admit")
    seg("prefill_s", "admit", "first_token")
    seg("decode_s", "first_token", "complete")
    seg("total_s", "submit", "complete")
    return out


def build_report(records, top: int = 5) -> dict:
    """The full report dict from tracer records (list of dicts — a
    ``Tracer.records`` slice or a read-back JSONL file)."""
    from ..serve.scheduler import derive_request_slo, request_slo_samples

    spans = _span_totals(records)
    phases: dict[str, float] = {}
    other_s = 0.0
    for name, row in spans.items():
        phase = SPAN_PHASE.get(name)
        if phase is None:
            other_s += row["total_s"]
        else:
            phases[phase] = phases.get(phase, 0.0) + row["total_s"]
    if other_s:
        phases["other"] = other_s
    observed = sum(phases.values())
    goodput = sum(phases.get(p, 0.0) for p in GOODPUT_SPAN_PHASES)

    cls_of = _class_of(records)
    samples = request_slo_samples(records)
    grouped = derive_request_slo(
        records, group_by=lambda rid: cls_of.get(rid, "default")
    )
    paths = _request_paths(records)
    per_class: dict[str, dict] = {}
    for rid, p in paths.items():
        cls = cls_of.get(rid, "default")
        row = per_class.setdefault(cls, {
            "requests": 0, "served": 0, "shed": 0, "deadline_exceeded": 0,
            "_sums": {}, "_served": 0,
        })
        row["requests"] += 1
        status = p.get("status", "ok")
        if status in ("shed", "deadline_exceeded"):
            row[status] += 1
        if rid in samples:
            row["served"] += 1
        bd = _breakdown(p)
        if bd:
            row["_served"] += 1
            for k, v in bd.items():
                row["_sums"][k] = row["_sums"].get(k, 0.0) + v
    for cls, row in per_class.items():
        n = row.pop("_served")
        sums = row.pop("_sums")
        row["mean_breakdown_s"] = (
            {k: v / n for k, v in sums.items()} if n else {}
        )
        if cls in grouped:
            ttft, itl = grouped[cls]
            row["ttft_ms"] = {"p50": ttft.p50_ms, "p95": ttft.p95_ms,
                              "p99": ttft.p99_ms}
            row["itl_ms"] = {"p50": itl.p50_ms, "p95": itl.p95_ms,
                             "p99": itl.p99_ms}

    stragglers = sorted(
        ({"req": rid, "class": cls_of.get(rid, "default"),
          "ttft_s": samples[rid][0], **_breakdown(paths.get(rid, {}))}
         for rid in samples),
        key=lambda row: -row["ttft_s"],
    )[:top]

    anomalies = [
        {"signal": r["attrs"].get("signal"), "tick": r["attrs"].get("tick"),
         "value": r["attrs"].get("value"), "z": r["attrs"].get("z")}
        for r in records if r.get("name") == "anomaly"
    ]
    incidents = {
        name: sum(1 for r in records if r.get("name") == name)
        for name in _INCIDENT_NAMES
    }
    fleet = [
        {"kind": r["name"],
         "tick": r["attrs"].get("tick", r["attrs"].get("step")),
         **{k: r["attrs"][k]
            for k in ("replica", "req", "src", "dst", "reason", "pages")
            if k in r["attrs"]}}
        for r in records if r.get("name") in _FLEET_NAMES
    ]
    return {
        "spans": {n: spans[n] for n in sorted(spans)},
        "goodput": {
            "phases_s": {k: phases[k] for k in sorted(phases)},
            "observed_s": observed,
            "goodput_fraction": goodput / observed if observed else 0.0,
        },
        "requests": {
            "count": len(paths),
            "served": len(samples),
            "per_class": {c: per_class[c] for c in sorted(per_class)},
        },
        "stragglers": stragglers,
        "anomalies": anomalies,
        "incidents": incidents,
        "fleet_incidents": fleet,
    }


def _print_report(rep: dict) -> None:
    g = rep["goodput"]
    _emit(f"goodput: {g['goodput_fraction']:.1%} of "
          f"{g['observed_s']:.3f}s traced span time")
    for phase, s in g["phases_s"].items():
        frac = s / g["observed_s"] if g["observed_s"] else 0.0
        _emit(f"  {phase:<12} {s:>10.3f}s  {frac:>6.1%}")
    req = rep["requests"]
    if req["count"]:
        _emit(f"requests: {req['count']} total, {req['served']} served")
        for cls, row in req["per_class"].items():
            ttft = row.get("ttft_ms", {})
            _emit(f"  class {cls}: {row['requests']} requests "
                  f"(shed {row['shed']}, deadline "
                  f"{row['deadline_exceeded']}) ttft p95 "
                  f"{ttft.get('p95', 0.0):.1f}ms")
            for k, v in row["mean_breakdown_s"].items():
                _emit(f"    mean {k:<13} {v * 1e3:>8.1f}ms")
        if rep["stragglers"]:
            _emit("stragglers (by ttft):")
            for s in rep["stragglers"]:
                _emit(f"  req {s['req']} [{s['class']}] ttft "
                      f"{s['ttft_s'] * 1e3:.1f}ms total "
                      f"{s.get('total_s', 0.0) * 1e3:.1f}ms")
    if rep["anomalies"]:
        _emit("anomalies:")
        for a in rep["anomalies"]:
            _emit(f"  tick {a['tick']}: {a['signal']} value {a['value']} "
                  f"z {a['z']:.1f}")
    if rep.get("fleet_incidents"):
        _emit("fleet incidents:")
        for f in rep["fleet_incidents"]:
            who = " ".join(f"{k}={f[k]}"
                           for k in ("replica", "req", "src", "dst",
                                     "reason", "pages") if k in f)
            _emit(f"  tick {f.get('tick')}: {f['kind']:<14} {who}")
    hits = {k: v for k, v in rep["incidents"].items() if v}
    if hits:
        _emit("incidents: " + ", ".join(f"{k}={v}"
                                        for k, v in sorted(hits.items())))


# -- comms --------------------------------------------------------------------


def _load_comms_doc(path: str):
    """``("bench", doc)`` for a ``collective_bytes.py`` JSON artifact
    (recognized by its ``lm`` row list), else ``("metrics",
    metrics_list)`` for a ``--metrics-out`` JSONL's LAST snapshot."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict) and isinstance(doc.get("lm"), list):
        return "bench", doc
    if isinstance(doc, dict) and doc.get("record") in ("manifest",
                                                       "snapshot"):
        doc = None  # single-line JSONL — fall through to line scan
    if doc is not None:
        raise ValueError(
            f"{path}: JSON document without an 'lm' benchmark section "
            "(not a collective_bytes.py artifact)"
        )
    snapshot = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        rec = json.loads(line)
        if rec.get("record") == "snapshot":
            snapshot = rec
    if snapshot is None:
        raise ValueError(
            f"{path}: neither a collective_bytes.py artifact nor a "
            "metrics JSONL with snapshot records"
        )
    return "metrics", snapshot["metrics"]


def _bench_comms_report(doc: dict) -> dict:
    """The comms story of a benchmark artifact: per-topology collective
    schedules, the two-roofline fit (recomputed through
    :func:`obs.comms.fit_roofline` when the artifact predates the
    stored fit), and the fp32/bf16 gradient-collective byte ratio of
    every precision-twin pair (same mode, same mesh)."""
    from .comms import fit_roofline

    rows = []
    for r in doc.get("lm", []):
        by_kind: dict[str, int] = {}
        for o in r.get("collectives", []):
            by_kind[o["op"]] = by_kind.get(o["op"], 0) + o["bytes"]
        rows.append({
            "mode": r.get("mode"), "mesh": r.get("mesh"),
            "precision": r.get("precision", "fp32"),
            "devices": r.get("devices"),
            "by_kind": {k: by_kind[k] for k in sorted(by_kind)},
            "reduce_bytes": r.get("reduce_bytes"),
            "wire_reduce_bytes": r.get("wire_reduce_bytes"),
            "comms_bytes_per_step": r.get("comms_bytes_per_step"),
            "flops_per_step": r.get("flops_per_step"),
            "measured_step_s": r.get("measured_step_s"),
        })
    fit = doc.get("roofline_fit")
    if fit is None:
        fit = fit_roofline([
            {"flops": r["flops_per_step"],
             "bytes": r["comms_bytes_per_step"],
             "measured_s": r["measured_step_s"]}
            for r in rows
        ])
    if fit is not None:
        for i, r in enumerate(rows):
            if i < len(fit.get("model_s", [])):
                r["model_s"] = fit["model_s"][i]
                r["rel_err"] = fit["rel_err"][i]
                comms_s = ((r.get("comms_bytes_per_step") or 0)
                           * fit["inv_bw_s_per_byte"])
                compute_s = ((r.get("flops_per_step") or 0)
                             * fit["inv_peak_s_per_flop"])
                r["bound"] = "comms" if comms_s > compute_s else "compute"
    twins = {}
    for r in rows:
        twins.setdefault((r["mode"], r["mesh"]), {})[r["precision"]] = r
    ratios = []
    for (mode, mesh), by_prec in sorted(twins.items()):
        if "fp32" in by_prec and "bf16" in by_prec:
            # Wire bytes (the as-written schedule) when the artifact
            # carries them: the backend that compiled the artifact may
            # fold bf16 collectives back to f32 (CPU does), so only the
            # pre-optimization schedule can show the policy's ratio.
            def _rb(row):
                wb = row.get("wire_reduce_bytes")
                return wb if wb is not None else row["reduce_bytes"]

            a, b = _rb(by_prec["fp32"]), _rb(by_prec["bf16"])
            ratios.append({
                "mode": mode, "mesh": mesh,
                "fp32_reduce_bytes": a,
                "bf16_reduce_bytes": b,
                "ratio": a / b if b else math.inf,
            })
    return {"source": "bench", "devices": doc.get("devices"),
            "rows": rows, "roofline_fit": fit,
            "precision_ratios": ratios}


def _metrics_comms_report(metrics: list[dict]) -> dict:
    """The comms story of a live-run snapshot: the per-program ledger
    (``collective_bytes{kind=,program=}`` and friends), the roofline
    gauges, and the host byte plane (``handoff_bytes_total{path=}``)."""
    programs: dict[str, dict] = {}

    def prog(labels):
        return programs.setdefault(labels.get("program", "?"), {
            "total_bytes": None, "by_kind": {}, "by_axis": {}, "ops": {},
        })

    roofline: dict[str, float] = {}
    handoff: dict[str, float] = {}
    for m in metrics:
        name, labels = m["name"], m.get("labels", {})
        value = m.get("value")
        if name == "collective_bytes_total":
            prog(labels)["total_bytes"] = value
        elif name == "collective_bytes":
            prog(labels)["by_kind"][labels.get("kind", "?")] = value
        elif name == "collective_axis_bytes":
            prog(labels)["by_axis"][labels.get("axis", "?")] = value
        elif name == "collective_ops_total":
            prog(labels)["ops"][labels.get("kind", "?")] = value
        elif name == "handoff_bytes_total":
            handoff[labels.get("path", "?")] = value
        elif name in ("comms_bytes_per_step", "comms_time_model_s",
                      "compute_time_model_s", "step_time_model_s",
                      "comms_fraction"):
            roofline[name] = value
        elif name == "step_bound" and value:
            roofline["bound"] = labels.get("bound", "?")
    return {"source": "metrics",
            "programs": {p: programs[p] for p in sorted(programs)},
            "roofline": roofline, "handoff_bytes": handoff}


def _print_comms_report(rep: dict) -> None:
    if rep["source"] == "bench":
        fit = rep.get("roofline_fit")
        if fit:
            _emit(f"roofline fit: peak {fit['fitted_peak_flops']:.3g} "
                  f"FLOP/s, bw {fit['fitted_bw_bytes_per_s']:.3g} B/s, "
                  f"max rel err {fit['max_rel_err']:.2f}")
        for r in rep["rows"]:
            head = (f"[{r['mode']} {r['mesh']} {r['precision']}] "
                    f"{r['comms_bytes_per_step'] or 0} B/step")
            if "model_s" in r:
                head += (f"  measured {r['measured_step_s'] * 1e3:.1f}ms "
                         f"model {r['model_s'] * 1e3:.1f}ms "
                         f"(err {r['rel_err']:+.0%}, {r['bound']}-bound)")
            _emit(head)
            for k, b in r["by_kind"].items():
                _emit(f"    {k:<18} {b} B")
        for p in rep["precision_ratios"]:
            _emit(f"precision twin [{p['mode']} {p['mesh']}]: "
                  f"fp32/bf16 gradient-collective bytes "
                  f"{p['fp32_reduce_bytes']}/{p['bf16_reduce_bytes']} "
                  f"= {p['ratio']:.2f}x")
        return
    for name, row in rep["programs"].items():
        _emit(f"program {name}: {row['total_bytes']} B")
        for k, b in sorted(row["by_kind"].items()):
            n = row["ops"].get(k)
            _emit(f"    {k:<18} {b} B" + (f"  ({n:.0f} ops)"
                                          if n is not None else ""))
        axes = {a: b for a, b in sorted(row["by_axis"].items()) if b}
        if axes:
            _emit("    axes: " + ", ".join(f"{a}={b} B"
                                           for a, b in axes.items()))
    rl = rep["roofline"]
    if rl:
        parts = [f"{k}={rl[k]:.3g}" for k in
                 ("comms_bytes_per_step", "compute_time_model_s",
                  "comms_time_model_s", "step_time_model_s",
                  "comms_fraction") if k in rl]
        if "bound" in rl:
            parts.append(f"bound={rl['bound']}")
        _emit("roofline gauges: " + " ".join(parts))
    if rep["handoff_bytes"]:
        _emit("handoff bytes: " + ", ".join(
            f"{path}={v:.0f}" for path, v in
            sorted(rep["handoff_bytes"].items())))


# -- compare ------------------------------------------------------------------


def _flatten(obj, prefix: str, out: dict) -> None:
    if isinstance(obj, bool) or obj is None or isinstance(obj, str):
        return
    if isinstance(obj, (int, float)):
        if not (isinstance(obj, float) and math.isnan(obj)):
            out[prefix] = float(obj)
        return
    if isinstance(obj, dict):
        for k in obj:
            _flatten(obj[k], f"{prefix}.{k}" if prefix else str(k), out)
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _flatten(v, f"{prefix}[{i}]", out)


def _snapshot_flat(metrics: list[dict]) -> dict[str, float]:
    """One registry snapshot record's ``metrics`` list -> flat
    ``{name{labels}[:field]: value}`` (histograms expand to
    count/mean/p50/p95/p99)."""
    out: dict[str, float] = {}
    for m in metrics:
        labels = m.get("labels", {})
        base = m["name"]
        if labels:
            body = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            base += "{" + body + "}"
        if m.get("kind") == "histogram":
            for field in ("count", "mean", "p50", "p95", "p99"):
                _flatten(m.get(field), f"{base}:{field}", out)
        else:
            _flatten(m.get("value"), base, out)
    return out


def load_metrics_flat(path: str) -> dict[str, float]:
    """Load either artifact shape into a flat numeric dict: a
    ``--metrics-out`` JSONL file uses its LAST snapshot record (the
    final state a clean exit always forces); anything else is treated
    as a plain JSON document and flattened to dotted leaves."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict) and doc.get("record") in ("manifest",
                                                       "snapshot"):
        # A SINGLE-line metrics JSONL (e.g. a run that died before its
        # first snapshot flush leaves only the manifest) parses as one
        # JSON document — without this check it would be flattened as
        # a bench artifact and compare would diff manifest leaves
        # (pid, t_wall) as "regressions". Route it to the JSONL
        # handling below instead, where a snapshot-less file is the
        # documented input error.
        doc = None
    if isinstance(doc, (dict, list)):
        out: dict[str, float] = {}
        _flatten(doc, "", out)
        return out
    snapshot = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        rec = json.loads(line)
        if rec.get("record") == "snapshot":
            snapshot = rec
    if snapshot is None:
        raise ValueError(
            f"{path}: neither a JSON document nor a metrics JSONL with "
            "snapshot records"
        )
    return _snapshot_flat(snapshot["metrics"])


def compare_metrics(old: dict[str, float], new: dict[str, float],
                    threshold: float, keys=(), ignore=()) -> list[dict]:
    """Relative deltas of the SHARED numeric keys exceeding
    ``threshold`` (sorted worst first). ``keys``/``ignore`` are
    substring-or-glob selectors applied to the flattened key names."""

    def selected(key: str) -> bool:
        if keys and not any(s in key or fnmatch.fnmatch(key, s)
                            for s in keys):
            return False
        return not any(s in key or fnmatch.fnmatch(key, s) for s in ignore)

    out = []
    for key in sorted(set(old) & set(new)):
        if not selected(key):
            continue
        a, b = old[key], new[key]
        if a == b:
            continue
        rel = (b - a) / abs(a) if a != 0 else math.inf
        if abs(rel) > threshold:
            out.append({"key": key, "old": a, "new": b, "rel": rel})
    out.sort(key=lambda r: -abs(r["rel"]))
    return out


# -- CLI ----------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ddl_tpu.obs.analyze", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("report", help="goodput / critical-path / anomaly "
                                       "report from a host-trace JSONL")
    rp.add_argument("trace", help="host_trace_p*.jsonl input")
    rp.add_argument("--top", type=int, default=5,
                    help="straggler rows to show (default 5)")
    rp.add_argument("--json", action="store_true")
    mp = sub.add_parser("comms", help="communication story of a "
                                      "collective_bytes.py artifact or a "
                                      "--metrics-out JSONL")
    mp.add_argument("artifact")
    mp.add_argument("--json", action="store_true")
    cp = sub.add_parser("compare", help="diff two metrics artifacts; exit 1 "
                                        "past --threshold")
    cp.add_argument("old")
    cp.add_argument("new")
    cp.add_argument("--threshold", type=float, default=0.1,
                    help="relative-change gate (default 0.1 = 10%%)")
    cp.add_argument("--keys", nargs="*", default=[],
                    help="only keys containing/matching any of these")
    cp.add_argument("--ignore", nargs="*", default=[],
                    help="skip keys containing/matching any of these")
    cp.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.cmd == "report":
        try:
            records = read_jsonl(args.trace)
            rep = build_report(records, top=args.top)
            if args.json:
                _emit(json.dumps(rep))
            else:
                _print_report(rep)
        except (OSError, json.JSONDecodeError, KeyError, TypeError,
                ValueError) as e:
            # Exit-code contract (module docstring): malformed input —
            # unreadable file OR schema-broken records (a lifecycle
            # event missing its req, a span without a name) — is a
            # usage/input error (2), never a traceback.
            _emit(f"[obs.analyze] cannot analyze trace {args.trace}: "
                  f"{type(e).__name__}: {e}")
            return 2
        return 0

    if args.cmd == "comms":
        try:
            kind, payload = _load_comms_doc(args.artifact)
            rep = (_bench_comms_report(payload) if kind == "bench"
                   else _metrics_comms_report(payload))
        except (OSError, json.JSONDecodeError, KeyError, TypeError,
                ValueError) as e:
            _emit(f"[obs.analyze] cannot analyze comms artifact "
                  f"{args.artifact}: {type(e).__name__}: {e}")
            return 2
        if args.json:
            _emit(json.dumps(rep))
        else:
            _print_comms_report(rep)
        return 0

    if args.threshold <= 0:
        _emit("[obs.analyze] --threshold must be > 0")
        return 2
    try:
        old = load_metrics_flat(args.old)
        new = load_metrics_flat(args.new)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        _emit(f"[obs.analyze] cannot load metrics: {e}")
        return 2
    regressions = compare_metrics(old, new, args.threshold,
                                  keys=args.keys, ignore=args.ignore)
    shared = len(set(old) & set(new))
    if args.json:
        # rel can be math.inf (old == 0, new != 0); json.dumps would
        # emit the bare token `Infinity`, which is not legal JSON —
        # strict consumers (jq, JSON.parse) must keep parsing exactly
        # when a 0-to-nonzero regression was found.
        _emit(json.dumps({"shared_keys": shared,
                          "threshold": args.threshold,
                          "regressions": [
                              {**r, "rel": ("inf" if math.isinf(r["rel"])
                                            else r["rel"])}
                              for r in regressions
                          ]}))
    else:
        _emit(f"[obs.analyze] {shared} shared keys, threshold "
              f"{args.threshold:.0%}: {len(regressions)} past it")
        for r in regressions[:20]:
            rel = ("inf" if math.isinf(r["rel"])
                   else f"{r['rel']:+.1%}")
            _emit(f"  {r['key']}: {r['old']:.6g} -> {r['new']:.6g} ({rel})")
        if len(regressions) > 20:
            _emit(f"  ... and {len(regressions) - 20} more")
    return 1 if regressions else 0


if __name__ == "__main__":
    raise SystemExit(main())
