"""Streaming anomaly detection: rolling median/MAD baselines on the
deterministic tick clock (ISSUE 11 tentpole piece 2).

The SLO monitor (obs.slo) answers "is the error budget burning?" — a
contract question. This module answers the incident question one layer
down: "does this signal look NOTHING like its own recent past?", with
no target to configure, over whatever per-tick signals the run loops
feed it — step time, ITL, MFU, pages-free, queue depth, backlog.

Detection is the standard robust-z test:

- A rolling window of the last ``window`` samples per signal is the
  baseline; the current sample is scored BEFORE it enters the window
  (evaluate-then-insert, so a spike cannot vouch for itself).
- ``z = (x - median) / max(1.4826 * MAD, min_scale)`` — median/MAD, not
  mean/stddev, so a handful of prior outliers cannot drag the
  baseline; the ``1.4826`` factor makes MAD sigma-consistent. A
  CONSTANT baseline (integer host-state signals: pages free, active
  slots) has MAD 0 — ``min_scale`` floors the scale so any deviation
  from a flat baseline scores decisively instead of dividing by zero.
- ``direction`` gates which tail alarms: ``high`` (latency-like),
  ``low`` (capacity-like: pages free, active slots), ``both``.

Firing is EDGE-triggered exactly like the SLO monitor: entry into the
anomalous state increments ``anomaly_total{signal=}``, stamps
``anomaly_last_tick{signal=}``, and traces an ``anomaly`` event carrying
the tick, value, baseline and z; ``anomaly_z{signal=}`` gauges update
every scored tick regardless. The tick clock is the DETERMINISTIC
scheduler/router/trainer tick, and the host-state signals (queue depth,
active slots, pages free, backlog) are deterministic functions of it —
so the seeded stall-injection and bulk-burst scenarios fire their
anomalies at IDENTICAL ticks across fresh runs (pinned in
tests/test_goodput.py). Wall-clock signals (step time, ITL, MFU) ride
the same machinery for live operation but are host-noise-dependent; the
determinism pins use only the host-state signals.

Off path: a scheduler/router/trainer constructed without a detector
makes no ``anomaly_*`` metrics and pays no extra clock reads — the
PR 5 discipline.
"""

from __future__ import annotations

import collections
import dataclasses
import statistics

from .registry import MetricRegistry
from .trace import NULL_TRACER

_DIRECTIONS = ("high", "low", "both")


@dataclasses.dataclass(frozen=True)
class AnomalyRule:
    """One monitored signal (module docstring). ``signal`` names the
    per-tick value the feeding loop publishes (see the loop's
    docstring for its vocabulary); ``window`` bounds the baseline,
    ``min_history`` is how many baseline samples must exist before
    anything can fire (a cold baseline flags nothing), ``threshold``
    the robust-z magnitude that alarms, ``min_scale`` the MAD floor."""

    signal: str
    window: int = 32
    min_history: int = 8
    threshold: float = 6.0
    direction: str = "both"
    min_scale: float = 1e-9

    def __post_init__(self):
        if not self.signal:
            raise ValueError("AnomalyRule needs a non-empty signal name")
        if self.window < 2:
            raise ValueError(
                f"signal {self.signal!r}: window must be >= 2, got "
                f"{self.window}"
            )
        if not 1 <= self.min_history <= self.window:
            raise ValueError(
                f"signal {self.signal!r}: need 1 <= min_history <= "
                f"window, got {self.min_history}/{self.window}"
            )
        if self.threshold <= 0:
            raise ValueError(
                f"signal {self.signal!r}: threshold must be > 0, got "
                f"{self.threshold}"
            )
        if self.direction not in _DIRECTIONS:
            raise ValueError(
                f"signal {self.signal!r}: direction must be one of "
                f"{_DIRECTIONS}, got {self.direction!r}"
            )
        if self.min_scale <= 0:
            raise ValueError(
                f"signal {self.signal!r}: min_scale must be > 0, got "
                f"{self.min_scale}"
            )


class _SignalState:
    def __init__(self, window: int):
        self.history: collections.deque = collections.deque(maxlen=window)
        self.firing = False
        self.alerts = 0
        self.fired_ticks: list[int] = []
        self.last_z = 0.0


class AnomalyDetector:
    """Scores ``rules`` against the per-tick ``values`` dict the owning
    loop passes to :meth:`tick` — one call per scheduler/router/trainer
    tick, the deterministic clock. A declared signal absent from a
    tick's values is simply not scored that tick (ITL does not exist on
    an idle tick). Emits into (and is validated against) the SAME
    registry the loop publishes its other metrics to; ``tracer`` is a
    plain attribute so the CLI can attach the run-scoped tracer after
    construction."""

    def __init__(self, rules, registry: MetricRegistry, tracer=None):
        rules = tuple(rules)
        if not rules:
            raise ValueError("AnomalyDetector needs at least one rule")
        names = [r.signal for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate anomaly signal names in {names}")
        if registry is None:
            raise ValueError(
                "AnomalyDetector needs the MetricRegistry it emits "
                "anomaly_* metrics into"
            )
        self.rules = rules
        self.registry = registry
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.ticks = 0
        self._state = {r.signal: _SignalState(r.window) for r in rules}

    def tick(self, values: dict) -> list[str]:
        """Score one tick's signals; returns the signals that ENTERED
        the anomalous state this tick."""
        self.ticks += 1
        entered: list[str] = []
        z_gauge = None
        for rule in self.rules:
            if rule.signal not in values:
                continue
            x = float(values[rule.signal])
            st = self._state[rule.signal]
            fire = False
            z = 0.0
            if len(st.history) >= rule.min_history:
                med = statistics.median(st.history)
                mad = statistics.median(abs(h - med) for h in st.history)
                scale = max(1.4826 * mad, rule.min_scale)
                z = (x - med) / scale
                dev = (z if rule.direction == "high"
                       else -z if rule.direction == "low" else abs(z))
                fire = dev >= rule.threshold
                st.last_z = z
                if z_gauge is None:
                    z_gauge = self.registry.gauge(
                        "anomaly_z",
                        "robust z-score of the last scored sample per "
                        "signal",
                    )
                z_gauge.set(z, signal=rule.signal)
                if fire and not st.firing:
                    st.alerts += 1
                    st.fired_ticks.append(self.ticks)
                    entered.append(rule.signal)
                    self.registry.counter(
                        "anomaly_total",
                        "entries into the anomalous state per signal",
                    ).inc(signal=rule.signal)
                    self.registry.gauge(
                        "anomaly_last_tick",
                        "detector tick of the most recent anomaly entry "
                        "per signal",
                    ).set(self.ticks, signal=rule.signal)
                    if self.tracer:
                        self.tracer.event(
                            "anomaly", signal=rule.signal, tick=self.ticks,
                            value=x, median=float(med), mad=float(mad),
                            z=float(z),
                        )
                st.firing = fire
            # Evaluate-then-insert: the sample joins the baseline only
            # after it was scored against it.
            st.history.append(x)
        return entered

    # -- introspection ------------------------------------------------------

    def alerts(self, signal: str) -> int:
        return self._st(signal).alerts

    def fired_ticks(self, signal: str) -> list[int]:
        """Detector tick indices at which ``signal`` entered the
        anomalous state — the determinism pin compares these across
        fresh runs."""
        return list(self._st(signal).fired_ticks)

    def baseline(self, signal: str) -> tuple[float, float]:
        """Current ``(median, mad)`` of the signal's rolling window
        (``(0.0, 0.0)`` before any history)."""
        hist = self._st(signal).history
        if not hist:
            return 0.0, 0.0
        med = statistics.median(hist)
        return float(med), float(statistics.median(
            abs(h - med) for h in hist
        ))

    @property
    def anomalous(self) -> set[str]:
        return {n for n, st in self._state.items() if st.firing}

    def summary(self) -> dict:
        """JSON-able digest (the CLI surface): per-signal alert counts,
        fired ticks and the last z."""
        return {
            r.signal: {
                "alerts": self._state[r.signal].alerts,
                "fired_ticks": list(self._state[r.signal].fired_ticks),
                "last_z": self._state[r.signal].last_z,
            }
            for r in self.rules
        }

    def _st(self, signal: str) -> _SignalState:
        try:
            return self._state[signal]
        except KeyError:
            raise KeyError(
                f"no anomaly rule for signal {signal!r} "
                f"(rules: {[r.signal for r in self.rules]})"
            ) from None


# -- CLI spec grammar ---------------------------------------------------------

_RULE_KEYS = ("window", "min", "threshold", "direction", "scale")


def parse_anomaly_rules(spec: str) -> tuple[AnomalyRule, ...]:
    """``--anomaly-rules`` grammar -> :class:`AnomalyRule` tuple.
    Segments are ``;``-separated ``SIGNAL[:key=val,...]`` with keys
    ``window``, ``min`` (min_history), ``threshold``, ``direction``
    (high/low/both) and ``scale`` (min_scale). The signal names are the
    feeding loop's per-tick vocabulary — serve: ``step_time``, ``itl``,
    ``mfu``, ``queue_depth``, ``active_slots``, ``occupied_slots``,
    ``pages_free`` (paged only); router: ``backlog``, ``shed_rate``;
    trainers: ``step_time``, ``mfu``. Example::

        itl:window=32,threshold=8,direction=high;pages_free:direction=low
    """
    rules = []
    for seg in spec.split(";"):
        seg = seg.strip()
        if not seg:
            continue
        name, _, body = seg.partition(":")
        name = name.strip()
        kw: dict = {"signal": name}
        for part in body.split(",") if body else []:
            part = part.strip()
            if not part:
                continue
            key, eq, val = part.partition("=")
            key = key.strip()
            if not eq:
                raise ValueError(
                    f"signal {name!r}: bad key {part!r} (expected key=val)"
                )
            if key == "window":
                kw["window"] = int(val)
            elif key == "min":
                kw["min_history"] = int(val)
            elif key == "threshold":
                kw["threshold"] = float(val)
            elif key == "direction":
                kw["direction"] = val.strip()
            elif key == "scale":
                kw["min_scale"] = float(val)
            else:
                raise ValueError(
                    f"signal {name!r}: unknown key {key!r} (valid: "
                    f"{list(_RULE_KEYS)})"
                )
        rules.append(AnomalyRule(**kw))
    if not rules:
        raise ValueError(f"--anomaly-rules spec {spec!r} declares no rules")
    names = [r.signal for r in rules]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate anomaly signal names in {names}")
    return tuple(rules)


__all__ = [
    "AnomalyRule",
    "AnomalyDetector",
    "parse_anomaly_rules",
]
