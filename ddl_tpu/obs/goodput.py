"""Goodput & wall-clock time attribution (ISSUE 11 tentpole piece 1).

The repo can say *how fast* a run was (``train_mfu``/``serve_mfu``,
ISSUE 10) but not *where the time went* — the source paper's entire
contribution is exactly that decomposition for sync/async PS training,
and the TPUv4 LM-scaling work (PAPERS.md 2204.06514) reports its
compute/comm/stall split as headline methodology. This module is the
live attribution plane: every second the run loop observes is assigned
to exactly ONE phase, and the assignment is published as gauges next to
the MFU story:

- ``time_in_seconds{phase=}`` — cumulative seconds per phase,
- ``time_observed_seconds`` — total bracketed wall time,
- ``goodput_fraction`` — goodput phases over observed time.

**The identity**: phase times SUM to the observed wall time (pinned in
tests/test_goodput.py at 1e-9 relative — float re-association is the
only slack). It holds by construction: trainer brackets are attributed
whole (a guarded span splits ``span_s`` into ``compute`` +
``stall`` shares that sum back exactly), and a serve tick's residual —
tick wall time minus its measured sub-brackets — lands in ``host``
(bookkeeping overhead) or ``idle`` (no device work this tick), never
on the floor.

Phase taxonomy (one vocabulary per kind, validated at ``add``):

- ``train``: ``compute`` (span dispatch — the goodput), ``staging``
  (host->device upload of the train set), ``compile`` (program
  builds), ``eval`` (test-set accuracy), ``checkpoint_io`` (save
  brackets), ``stall`` (guard-skipped step share + rollback
  restore — the fault-tolerance tax, ISSUE 6).
- ``serve``: ``prefill`` + ``decode`` (the goodput — device token
  work), ``prefix_copy`` (cache reuse copies), ``shed`` (shed/
  deadline-eviction sweeps), ``handoff`` (disaggregated
  prefill->decode page transfers, ISSUE 15 — attributed to the SOURCE
  replica's tracker by the fleet coordinator, outside any tick
  bracket), ``idle`` (ticks with no device work), ``host`` (non-idle
  tick residual: admission, telemetry, Python).

Everything here is host arithmetic on brackets the loops ALREADY close
(the ``StepTimer`` values, the compile/save brackets) — no new device
syncs, and with no registry no tracker exists at all (compiled programs
untouched by construction; the PR 5 off-path bar).
"""

from __future__ import annotations

import time

TRAIN_PHASES = ("compute", "staging", "compile", "eval", "checkpoint_io",
                "stall")
SERVE_PHASES = ("prefill", "decode", "prefix_copy", "shed", "handoff",
                "idle", "host")

# The phases that count as goodput — useful device work — per kind.
GOODPUT_PHASES = {
    "train": ("compute",),
    "serve": ("prefill", "decode"),
}

_PHASES = {"train": TRAIN_PHASES, "serve": SERVE_PHASES}


class GoodputTracker:
    """Accumulates the per-phase wall-clock decomposition of one run
    loop and publishes it as live gauges (module docstring).

    Two usage shapes, matching the two loop styles:

    - **Trainers** call :meth:`add` with whole brackets they already
      measure (span seconds, compile seconds, ...); the observed total
      is the sum of everything added.
    - **The serve scheduler** wraps each tick in :meth:`begin_tick` /
      :meth:`end_tick` and ``add``\\ s sub-brackets inside; ``end_tick``
      measures the tick wall time and files the residual under
      ``host`` (device work happened) or ``idle`` (it did not — only
      ``add(..., work=True)`` marks device work).
    """

    def __init__(self, registry, kind: str):
        if kind not in _PHASES:
            raise ValueError(
                f"kind must be one of {sorted(_PHASES)}, got {kind!r}"
            )
        if registry is None:
            raise ValueError(
                "GoodputTracker needs the MetricRegistry it publishes "
                "into (no registry -> no tracker: the off path makes no "
                "goodput gauges)"
            )
        self.kind = kind
        self.registry = registry
        self.phases: dict[str, float] = dict.fromkeys(_PHASES[kind], 0.0)
        self.observed_s = 0.0
        self._tick_t0: float | None = None
        self._tick_sub = 0.0
        self._tick_work = False

    # -- accumulation -------------------------------------------------------

    def add(self, phase: str, seconds: float, *, work: bool = True) -> None:
        """Attribute ``seconds`` to ``phase``. Inside a tick bracket the
        amount also counts toward the tick's measured sub-total (so the
        residual excludes it); ``work=False`` attributes time without
        marking the tick as having done device work (the shed sweep is
        bookkeeping, not goodput-adjacent activity)."""
        if phase not in self.phases:
            raise ValueError(
                f"unknown {self.kind} phase {phase!r} "
                f"(valid: {list(self.phases)})"
            )
        if seconds < 0:
            seconds = 0.0
        self.phases[phase] += seconds
        if self._tick_t0 is not None:
            self._tick_sub += seconds
            self._tick_work = self._tick_work or work
        else:
            # Outside a tick bracket (the trainer shape) every add IS
            # observed time — the identity's other half.
            self.observed_s += seconds

    def begin_tick(self) -> None:
        """Open the serve tick bracket (one ``perf_counter`` read)."""
        self._tick_sub = 0.0
        self._tick_work = False
        self._tick_t0 = time.perf_counter()

    def end_tick(self, publish: bool = True) -> float:
        """Close the tick bracket: measure the tick's wall time, file
        the residual (tick minus sub-brackets) under ``host``/``idle``,
        and publish the gauges. Returns the tick wall seconds."""
        if self._tick_t0 is None:
            raise RuntimeError("end_tick without begin_tick")
        t = time.perf_counter() - self._tick_t0
        self._tick_t0 = None
        resid = t - self._tick_sub
        if resid < 0:
            # Sub-brackets and the tick bracket read the same monotonic
            # clock in nested order, so a negative residual is float
            # noise at most — clamp, and keep the identity by observing
            # exactly what the phases hold.
            resid = 0.0
        self.phases["host" if self._tick_work else "idle"] += resid
        self.observed_s += self._tick_sub + resid
        if publish:
            self.publish()
        return t

    # -- the derived quantities ---------------------------------------------

    @property
    def total_s(self) -> float:
        """Sum of the phase times — equals :attr:`observed_s` up to
        float re-association (the pinned identity)."""
        return sum(self.phases.values())

    @property
    def goodput_s(self) -> float:
        return sum(self.phases[p] for p in GOODPUT_PHASES[self.kind])

    @property
    def goodput_fraction(self) -> float:
        tot = self.observed_s
        return self.goodput_s / tot if tot > 0 else 0.0

    def publish(self) -> None:
        """Set the three gauge surfaces from the current totals."""
        g = self.registry.gauge(
            "time_in_seconds",
            "cumulative observed wall seconds per attribution phase",
        )
        for phase, s in self.phases.items():
            g.set(s, phase=phase)
        self.registry.gauge(
            "time_observed_seconds",
            "total bracketed wall seconds the attribution covers",
        ).set(self.observed_s)
        self.registry.gauge(
            "goodput_fraction",
            "goodput phase seconds over observed seconds",
        ).set(self.goodput_fraction)

    def summary(self) -> dict:
        """JSON-able digest (the CLI / bench surface)."""
        return {
            "kind": self.kind,
            "observed_s": self.observed_s,
            "goodput_fraction": self.goodput_fraction,
            "phases_s": dict(self.phases),
        }


def attribute_train_span(tracker: GoodputTracker, span_s: float,
                         compile_in_span: float, n_skip: int,
                         k: int) -> None:
    """File one dispatched train span's bracket — the ONE copy of the
    split both span trainers share (a one-trainer edit must not let
    the other's pinned identity silently diverge). Any compile that
    ran INSIDE the bracket (a guard-rollback realignment build) was
    already attributed under ``compile`` and is carved out; the
    remaining work splits into ``compute`` plus the guard-skipped
    share as ``stall``. The shares sum back EXACTLY
    (``a + (b - a) == b``) — the pinned identity — and in the
    AOT-precompiled steady state ``compile_in_span`` is 0.0, so
    ``compute`` equals the StepTimer bracket to the float."""
    span_compile = min(max(compile_in_span, 0.0), span_s)
    work_s = span_s - span_compile
    stall_s = work_s * (n_skip / k) if n_skip else 0.0
    tracker.add("stall", stall_s)
    tracker.add("compute", work_s - stall_s)
    tracker.publish()


def goodput_summary(registry) -> dict:
    """Compact probe digest read NON-CREATINGLY from a registry (the
    ``/healthz`` surface, ISSUE 11 satellite): current
    ``goodput_fraction``, the last anomaly tick (max over
    ``anomaly_last_tick{signal=}``), cumulative anomaly count, and the
    last SLO alert tick when present. Missing metrics are simply
    absent — a train run without a detector reports only its fraction,
    and reading never mutates the registry (``MetricRegistry.get``)."""
    out: dict = {}
    g = registry.get("goodput_fraction")
    if g is not None and g.kind == "gauge":
        v = g.value()
        if v is not None:
            out["goodput_fraction"] = v
    last = registry.get("anomaly_last_tick")
    if last is not None and last.kind == "gauge":
        ticks = [last.value(**ls) for ls in last.label_sets()]
        ticks = [t for t in ticks if t is not None]
        if ticks:
            out["last_anomaly_tick"] = int(max(ticks))
    tot = registry.get("anomaly_total")
    if tot is not None and tot.kind == "counter":
        out["anomalies_total"] = int(sum(
            tot.value(**ls) for ls in tot.label_sets()
        ))
    alert = registry.get("slo_last_alert_tick")
    if alert is not None and alert.kind == "gauge":
        ticks = [alert.value(**ls) for ls in alert.label_sets()]
        ticks = [t for t in ticks if t is not None]
        if ticks:
            out["last_slo_alert_tick"] = int(max(ticks))
    return out


def fleet_summary(registry) -> dict:
    """Compact fleet digest read NON-CREATINGLY from a registry (the
    ``/healthz`` surface, ISSUE 13 satellite — same
    ``MetricRegistry.get`` pattern as :func:`goodput_summary`):
    replicas active/draining, the last scale-event tick, and the
    cumulative preemption count. Missing metrics are simply absent — a
    run without a fleet controller reports nothing here, and reading
    never mutates the registry."""
    out: dict = {}
    for key, name in (("replicas_active", "fleet_replicas_active"),
                      ("replicas_draining", "fleet_replicas_draining"),
                      ("last_scale_tick", "fleet_last_scale_tick")):
        g = registry.get(name)
        if g is not None and g.kind == "gauge":
            v = g.value()
            if v is not None:
                out[key] = int(v)
    g = registry.get("fleet_replicas_active")
    if g is not None and g.kind == "gauge":
        # Per-role replica counts (ISSUE 15): the disagg coordinator /
        # controller publish `fleet_replicas_active{role=}` next to the
        # unlabeled total, so a role-starved fleet (prefill replicas
        # with no decode replica to hand to) is visible at a glance.
        by_role = {
            ls["role"]: int(g.value(**ls))
            for ls in g.label_sets()
            if "role" in ls and g.value(**ls) is not None
        }
        if by_role:
            out["replicas_by_role"] = by_role
    for name, key in (("preemptions_total", "preemptions_total"),
                      ("handoff_total", "handoffs_total")):
        c = registry.get(name)
        if c is not None and c.kind == "counter":
            out[key] = int(sum(
                c.value(**ls) for ls in c.label_sets()
            ))
    g = registry.get("fleet_engine_sim")
    if g is not None and g.kind == "gauge":
        # Twin transparency (ISSUE 18): the router stamps this gauge at
        # construction, so /healthz and every fleet digest says whether
        # the numbers came from real engines or the cost-model twin — a
        # sim run can never masquerade as measured.
        v = g.value()
        if v is not None:
            out["engine_kind"] = "sim" if v else "real"
    return out


# Per-phase cost fitting (ISSUE 18): phase name -> (fitted key, the
# denominator metric that normalizes it, that metric's kind). The
# denominators are the exact unit each cost-model charge uses:
# prefill charges per PROMPT TOKEN, decode per BATCHED STEP (one
# histogram sample per decode call), hand-off per MOVED PAGE.
_PHASE_FIT = {
    "prefill": ("prefill_s_per_token", "serve_prefill_tokens_total",
                "counter"),
    "decode": ("decode_s_per_tick", "serve_decode_step_seconds",
               "histogram"),
    "handoff": ("handoff_s_per_page", "handoff_pages_total", "counter"),
}


def _last_snapshot(path) -> list[dict]:
    import json

    last = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("record") == "snapshot":
                last = rec
    if last is None:
        raise ValueError(
            f"{path}: no snapshot records — not a MetricsWriter JSONL "
            "(or the run never flushed one)"
        )
    return last["metrics"]


def phase_cost_fit(source, *, phases=("prefill", "decode")) -> dict:
    """Fit per-phase virtual-time costs from a MEASURED run — the
    digital twin's cost table (``serve.sim.CostModel.from_phase_fit``),
    normalized per unit of work:

    - ``prefill_s_per_token`` = ``time_in_seconds{phase=prefill}`` /
      ``serve_prefill_tokens_total``
    - ``decode_s_per_tick``   = ``time_in_seconds{phase=decode}`` /
      ``serve_decode_step_seconds`` sample count (batched steps)
    - ``handoff_s_per_page``  = ``time_in_seconds{phase=handoff}`` /
      ``handoff_pages_total``

    ``source`` is a live :class:`~ddl_tpu.obs.registry.MetricRegistry`
    (a replica registry — that is where the serve-side attribution
    lands) or a path to a ``MetricsWriter`` JSONL (the LAST snapshot
    wins — costs are cumulative ratios). Any requested phase whose
    numerator or denominator is missing/zero is a LOUD error naming the
    phase and the absent metric — a fit from a run that never decoded
    must fail, not silently return a zero cost. Fit ``handoff`` only
    from disaggregated runs (default phases omit it)."""
    bad = [p for p in phases if p not in _PHASE_FIT]
    if bad:
        raise ValueError(
            f"unknown fit phase(s) {', '.join(map(repr, bad))} "
            f"(fittable: {', '.join(_PHASE_FIT)})"
        )
    if hasattr(source, "get") and not isinstance(source, (str, bytes)) \
            and not hasattr(source, "__fspath__"):
        def num_of(phase):
            g = source.get("time_in_seconds")
            if g is None or g.kind != "gauge":
                return None
            return g.value(phase=phase)

        def den_of(name, kind):
            m = source.get(name)
            if m is None or m.kind != kind:
                return None
            if kind == "histogram":
                return sum(m.count(**ls) for ls in m.label_sets())
            return sum(m.value(**ls) for ls in m.label_sets())
    else:
        metrics = _last_snapshot(source)

        def num_of(phase):
            for e in metrics:
                if e["name"] == "time_in_seconds" \
                        and e.get("labels", {}).get("phase") == phase:
                    return e.get("value")
            return None

        def den_of(name, kind):
            got = [e for e in metrics
                   if e["name"] == name and e.get("kind") == kind]
            if not got:
                return None
            key = "count" if kind == "histogram" else "value"
            return sum(e.get(key, 0) for e in got)

    out: dict = {}
    problems = []
    for phase in phases:
        key, den_name, den_kind = _PHASE_FIT[phase]
        num = num_of(phase)
        den = den_of(den_name, den_kind)
        if num is None or num <= 0:
            problems.append(
                f"{phase} (time_in_seconds{{phase={phase}}} absent or 0 "
                "— the run never attributed that phase)"
            )
        elif not den:
            problems.append(
                f"{phase} ({den_name} absent or 0 — no work units to "
                "normalize by)"
            )
        else:
            out[key] = float(num) / float(den)
    if problems:
        raise ValueError(
            "phase_cost_fit: cannot fit " + "; ".join(problems)
        )
    return out


__all__ = [
    "GoodputTracker",
    "attribute_train_span",
    "fleet_summary",
    "goodput_summary",
    "phase_cost_fit",
    "TRAIN_PHASES",
    "SERVE_PHASES",
    "GOODPUT_PHASES",
]
