"""In-graph training health signals (ISSUE 5 tentpole piece 3).

Computed INSIDE the jitted step bodies, right after each body's own
explicit gradient reduction, and returned as a small dict of scalars —
an aux output the trainer fetches BATCHED (a whole span's worth at
once, only on spans crossing ``metrics_interval``), so enabling health
never adds a per-step device sync and disabling it leaves the compiled
step byte-identical (the flag is a Python-level branch).

Signals:

- ``grad_norm`` — global L2 norm of the fully-reduced gradient: the
  same tensor a single-device ``jax.grad`` of the global weighted-mean
  loss would produce (pinned against that oracle on the dp2 x tp2 mesh
  in tests/test_obs.py).
- ``nonfinite_grads`` — count of non-finite gradient ELEMENTS (int32):
  the divergence tripwire; 0 on every healthy step.
- ``param_norm`` / ``update_norm`` — global L2 norms of the params and
  of this step's applied update (new - old), plus one
  ``param_norm/<subtree>`` / ``update_norm/<subtree>`` pair per
  top-level param subtree (LM: embed / blocks / lnf_g / lnf_b / head;
  CNN: the per-variable names) — the update/param ratio per subtree is
  the classic learning-rate health read.

Cross-device correctness is PartitionSpec-driven: each leaf's local
squared sum is ``psum``'d over exactly the mesh axes its spec names
(tp-sharded Megatron leaves over tp, stage-resident pipeline stacks
over pp, replicated leaves over nothing). Callers pass the same spec
tree they place the params with, so the health math can never disagree
with the placement. The ZeRO-1 flat-chunk paths use
:func:`flat_grad_sq_nonfinite` instead — chunks are disjoint across
the (dp, sp) devices, so one psum of the local chunk's squared sum IS
the global value (padding contributes zero).

The dict's key set is a static function of the param template
(:func:`health_keys`), so ``shard_map`` out_specs and scan carries are
knowable without tracing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _spec_axes(spec) -> tuple:
    """Every mesh axis named anywhere in ``spec`` (deduped, stable)."""
    if not isinstance(spec, P):
        return ()
    axes = []
    for part in spec:
        if part is None:
            continue
        parts = part if isinstance(part, (tuple, list)) else (part,)
        axes.extend(a for a in parts if a is not None)
    return tuple(dict.fromkeys(axes))


def _top_key(path) -> str:
    """Top-level subtree label of a ``tree_leaves_with_path`` path."""
    k = path[0]
    for attr in ("key", "idx", "name"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def _leaves_with_specs(tree, pspecs):
    """``[(subtree, leaf, spec_axes)]`` with the spec tree flattened in
    the SAME leaf order as the value tree. ``pspecs`` may be a single
    ``P()`` / None (the tp=1 broadcast form) — every leaf then shares
    it."""
    named = jax.tree_util.tree_leaves_with_path(tree)
    if pspecs is None or isinstance(pspecs, P):
        spec = pspecs if isinstance(pspecs, P) else P()
        specs = [spec] * len(named)
    else:
        specs = jax.tree.flatten(
            pspecs, is_leaf=lambda s: isinstance(s, P)
        )[0]
        if len(specs) != len(named):
            raise ValueError(
                f"param/spec tree mismatch: {len(named)} leaves vs "
                f"{len(specs)} specs"
            )
    return [
        (_top_key(path), leaf, _spec_axes(spec))
        for (path, leaf), spec in zip(named, specs)
    ]


def _grouped_sq(entries) -> dict[str, jax.Array]:
    """Per-subtree global sum of squares: local sums grouped by
    (subtree, psum axes) so each group pays ONE scalar psum, not one
    per leaf."""
    local: dict[tuple[str, tuple], jax.Array] = {}
    for key, leaf, axes in entries:
        sq = jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        gk = (key, axes)
        local[gk] = local.get(gk, jnp.float32(0.0)) + sq
    out: dict[str, jax.Array] = {}
    for (key, axes), sq in local.items():
        if axes:
            sq = lax.psum(sq, axes)
        out[key] = out.get(key, jnp.float32(0.0)) + sq
    return out


def subtree_keys(template) -> list[str]:
    """Sorted top-level subtree labels of a param tree (static — works
    on shapes-only templates AND on PartitionSpec trees: a P is a tuple
    subclass, so it must be treated as a leaf, not flattened into)."""
    return sorted({
        _top_key(path)
        for path, _ in jax.tree_util.tree_leaves_with_path(
            template, is_leaf=lambda x: isinstance(x, P)
        )
    })


def health_keys(template) -> list[str]:
    """The static key set of :func:`health_signals` for this param
    template — what shard_map out_specs / scan carries are built from."""
    keys = ["grad_norm", "nonfinite_grads", "param_norm", "update_norm"]
    for k in subtree_keys(template):
        keys.append(f"param_norm/{k}")
        keys.append(f"update_norm/{k}")
    return keys


def health_out_specs(template) -> dict:
    """``shard_map`` out_specs for the health dict: every signal is
    fully reduced (replicated) by construction."""
    return {k: P() for k in health_keys(template)}


def nonfinite_count(grads, pspecs) -> jax.Array:
    """Global non-finite ELEMENT count (int32, replicated) of a gradient
    tree whose leaves are complete up to the sharding ``pspecs``
    describes — the divergence-tripwire scalar, exposed standalone so
    the ISSUE-6 step guard can compute ONLY it (no norm FLOPs) when
    metrics are off."""
    nf_local: dict[tuple, jax.Array] = {}
    for _, leaf, axes in _leaves_with_specs(grads, pspecs):
        n = jnp.sum(~jnp.isfinite(leaf.astype(jnp.float32))).astype(jnp.int32)
        nf_local[axes] = nf_local.get(axes, jnp.int32(0)) + n
    nf = jnp.int32(0)
    for axes, n in nf_local.items():
        nf = nf + (lax.psum(n, axes) if axes else n)
    return nf


def grad_signals(grads, pspecs) -> dict[str, jax.Array]:
    """``grad_norm`` + ``nonfinite_grads`` from a FULL gradient tree
    whose leaves are complete up to the sharding ``pspecs`` describes
    (i.e. after the step body's explicit data-axis reduction)."""
    entries = _leaves_with_specs(grads, pspecs)
    total = jnp.float32(0.0)
    for sq in _grouped_sq(entries).values():
        total = total + sq
    return {"grad_norm": jnp.sqrt(total),
            "nonfinite_grads": nonfinite_count(grads, pspecs)}


def norm_signals(params, new_params, pspecs) -> dict[str, jax.Array]:
    """Global + per-subtree param and update (new - old) L2 norms.
    Subtree keys are emitted in sorted order so the dict structure is
    identical across step-body modes (scan/stacking relies on it)."""
    updates = jax.tree.map(
        lambda a, b: b.astype(jnp.float32) - a.astype(jnp.float32),
        params, new_params,
    )
    p_sub = _grouped_sq(_leaves_with_specs(params, pspecs))
    u_sub = _grouped_sq(_leaves_with_specs(updates, pspecs))
    out = {
        "param_norm": jnp.sqrt(sum(p_sub.values(), jnp.float32(0.0))),
        "update_norm": jnp.sqrt(sum(u_sub.values(), jnp.float32(0.0))),
    }
    for k in sorted(p_sub):
        out[f"param_norm/{k}"] = jnp.sqrt(p_sub[k])
        out[f"update_norm/{k}"] = jnp.sqrt(u_sub[k])
    return out


def health_signals(grads, params, new_params, pspecs) -> dict[str, jax.Array]:
    """The full signal dict (see module docstring); key set ==
    :func:`health_keys` of the param template."""
    out = grad_signals(grads, pspecs)
    out.update(norm_signals(params, new_params, pspecs))
    return {k: out[k] for k in health_keys(params)}


def record_health(registry, hstack, *, prefix: str = "train",
                  include_nonfinite: bool = True) -> None:
    """Record a fetched ``[k]``-stacked health dict (one span's steps)
    into the registry: the LAST step's values as gauges (per-subtree
    norms as ``subtree``-labelled series of the same metric name; the
    unlabelled series is the global norm) and the span's total
    non-finite element count onto ``<prefix>_nonfinite_grads_total``.
    ``include_nonfinite=False`` skips the counter — for trainers that
    feed it separately from EVERY span (the tripwire must never skip a
    step, while the norm gauges are interval-sampled)."""
    import numpy as np

    hs = {k: np.asarray(v) for k, v in hstack.items()}
    nf = hs.pop("nonfinite_grads")
    if include_nonfinite:
        record_nonfinite(registry, nf, prefix=prefix)
    for key, arr in hs.items():
        v = float(arr[-1])
        if "/" in key:
            base, sub = key.split("/", 1)
            registry.gauge(f"{prefix}_{base}").set(v, subtree=sub)
        else:
            registry.gauge(f"{prefix}_{key}").set(v)


def record_nonfinite(registry, nf_stack, *, prefix: str = "train") -> None:
    """Add one span's ``[k]``-stacked non-finite element counts to the
    divergence-tripwire counter. Trainers call this for EVERY span (the
    array is a handful of int32s riding the already-synced span
    boundary), so a NaN burst can never fall between metrics
    intervals."""
    import numpy as np

    registry.counter(
        f"{prefix}_nonfinite_grads_total",
        "non-finite gradient elements seen (divergence tripwire)",
    ).inc(int(np.asarray(nf_stack).sum()))


def flat_grad_sq_nonfinite(g_own, axes) -> tuple[jax.Array, jax.Array]:
    """(global squared sum, global non-finite count) of a ZeRO-1 flat
    gradient CHUNK: chunks are disjoint across the devices of ``axes``
    and cover the whole gradient (padding is zeros), so one psum of
    the local values is the global answer."""
    g = g_own.astype(jnp.float32)
    sq = lax.psum(jnp.sum(jnp.square(g)), axes)
    nf = lax.psum(jnp.sum(~jnp.isfinite(g)).astype(jnp.int32), axes)
    return sq, nf
