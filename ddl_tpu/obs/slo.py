"""Streaming SLO evaluation: multi-window burn-rate monitors over the
metric registry (ISSUE 10 tentpole piece 1).

PR 8's per-class SLO accounting is post-hoc — ``RouterStats`` derives
attainment after the run ends. An autoscaler (ROADMAP item 4) needs the
same signal LIVE: "is this class burning its error budget faster than
it can afford, right now?". This module is that signal plane, built as
the Google-SRE multi-window burn-rate alert:

- An :class:`SloRule` names a **bad-event stream** read from the
  existing registry, in one of two shapes:

  - **histogram mode** (``target_s`` set): the metric is a latency
    histogram (seconds); a sample above ``target_s`` is a miss. The
    monitor consumes NEW samples incrementally per tick (the series is
    append-only), so evaluation cost per tick is O(new samples), never
    O(history).
  - **counter mode** (``total_metric`` set): the metric is a counter of
    bad events (e.g. ``router_shed_total{class="bulk"}``) and
    ``total_metric`` the matching attempt counter
    (``router_requests_total{class="bulk"}``) — the live shed-fraction
    signal the burst scenario alerts on.

- **Burn rate** over a window of W ticks: ``(misses in window / events
  in window) / (1 - objective)`` — the rate the error budget is being
  spent at. 1.0 = exactly on budget; an all-miss window with
  ``objective=0.9`` burns 10x. A window with zero events burns 0.0
  (no evidence is not an incident).
- An alert FIRES when the **fast** and **slow** windows both reach
  ``threshold`` (the standard two-window guard: the slow window stops
  one blip from paging, the fast window stops a resolved incident from
  paging forever). Firing is edge-triggered: ``slo_alerts_total{rule=}``
  counts ENTRIES into the alerting state, and each entry traces an
  ``slo_alert`` event; ``slo_burn_rate{rule=,window=}`` gauges update
  every tick regardless.

The window math is pinned against a brute-force recompute over the raw
sample log (tests/test_slo.py), and — on a live serve run — the
monitor's cumulative miss count is pinned equal to counting over
``serve.request_slo_samples`` of the same run's trace, so the streaming
evaluator and the post-hoc derivation can never disagree.

Off path: a scheduler/router constructed without a monitor makes no
``slo_*`` metrics and no extra registry reads — the PR 5 discipline.
"""

from __future__ import annotations

import collections
import dataclasses

from .registry import MetricRegistry
from .trace import NULL_TRACER


@dataclasses.dataclass(frozen=True)
class SloRule:
    """One burn-rate rule (module docstring). Exactly one of
    ``target_s`` (histogram mode) and ``total_metric`` (counter mode)
    must be set. ``labels`` selects ONE series of the metric (and of
    ``total_metric`` in counter mode) — a dict is accepted and
    normalized to a sorted tuple so rules stay hashable."""

    name: str
    metric: str
    target_s: float | None = None
    total_metric: str | None = None
    objective: float = 0.9
    fast_window: int = 8
    slow_window: int = 32
    threshold: float = 1.0
    labels: tuple[tuple[str, str], ...] = ()

    def __post_init__(self):
        if isinstance(self.labels, dict):
            object.__setattr__(self, "labels", tuple(
                sorted((str(k), str(v)) for k, v in self.labels.items())
            ))
        if not self.name:
            raise ValueError("SloRule needs a non-empty name")
        if (self.target_s is None) == (self.total_metric is None):
            raise ValueError(
                f"rule {self.name!r}: set exactly one of target_s "
                "(histogram mode: latency samples above the target are "
                "misses) and total_metric (counter mode: metric counts "
                "bad events, total_metric the attempts)"
            )
        if self.target_s is not None and self.target_s <= 0:
            raise ValueError(
                f"rule {self.name!r}: target_s must be > 0 seconds, got "
                f"{self.target_s}"
            )
        if not 0.0 <= self.objective < 1.0:
            raise ValueError(
                f"rule {self.name!r}: objective must be in [0, 1), got "
                f"{self.objective} (1.0 leaves a zero error budget — "
                "every miss would burn infinitely)"
            )
        if not 1 <= self.fast_window < self.slow_window:
            raise ValueError(
                f"rule {self.name!r}: need 1 <= fast_window < "
                f"slow_window, got {self.fast_window}/{self.slow_window}"
            )
        if self.threshold <= 0:
            raise ValueError(
                f"rule {self.name!r}: threshold must be > 0, got "
                f"{self.threshold}"
            )

    @property
    def label_dict(self) -> dict:
        return dict(self.labels)

    @property
    def budget(self) -> float:
        """The error budget: the miss fraction the objective allows."""
        return 1.0 - self.objective


class _RuleState:
    """Streaming state of one rule: the bounded history of cumulative
    ``(misses, total)`` pairs (one per tick, plus the attach-time
    baseline at index 0 — window deltas subtract pairs, so only
    ``slow_window + 1`` entries ever matter), the histogram scan
    position, and the edge-trigger latch."""

    def __init__(self, slow_window: int):
        self.history: collections.deque = collections.deque(
            maxlen=slow_window + 1
        )
        self.seen = 0  # histogram samples already classified
        self.misses = 0  # cumulative histogram misses
        self.firing = False
        self.alerts = 0
        self.fired_ticks: list[int] = []


class SloMonitor:
    """Evaluates ``rules`` against ``registry`` once per
    :meth:`tick` — the scheduler/router call it at their own tick
    boundary, so a "window" is a window of scheduler ticks (the
    deterministic clock that makes the burst-alert scenario replayable;
    wall-clock windows would make alerts host-noise-dependent).

    Emits into the SAME registry it reads: ``slo_burn_rate{rule=,
    window=fast|slow}`` gauges every tick, ``slo_alerts_total{rule=}``
    on each entry into the alerting state, plus an ``slo_alert`` tracer
    event. ``tracer`` is a plain attribute so the serve CLI can attach
    the run-scoped tracer after construction."""

    def __init__(self, rules, registry: MetricRegistry, tracer=None):
        rules = tuple(rules)
        if not rules:
            raise ValueError("SloMonitor needs at least one rule")
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO rule names in {names}")
        if registry is None:
            raise ValueError(
                "SloMonitor needs the MetricRegistry it evaluates "
                "against (and emits slo_* metrics into)"
            )
        self.rules = rules
        self.registry = registry
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.ticks = 0
        self._state = {r.name: _RuleState(r.slow_window) for r in rules}
        for rule in rules:
            st = self._state[rule.name]
            # Attach-time baseline: events that happened before the
            # monitor existed are history, not budget burn.
            st.history.append(self._read(rule, st))

    # -- reading the registry ----------------------------------------------

    def _read(self, rule: SloRule, st: _RuleState) -> tuple[int, int]:
        """Current cumulative ``(misses, total)`` for one rule. The
        registry's create-on-first-use semantics make a not-yet-touched
        metric an empty series (0, 0) — and a NAME collision with the
        wrong kind a loud ValueError at the first tick."""
        labels = rule.label_dict
        if rule.target_s is not None:
            h = self.registry.histogram(rule.metric)
            total, new = h.values_since(st.seen, **labels)
            st.seen = total
            st.misses += sum(1 for v in new if v > rule.target_s)
            return st.misses, total
        bad = self.registry.counter(rule.metric).value(**labels)
        total = self.registry.counter(rule.total_metric).value(**labels)
        return int(bad), int(total)

    @staticmethod
    def _window_burn(rule: SloRule, history, window: int) -> float:
        """Burn rate over the last ``window`` ticks of ``history``
        (cumulative pairs; earlier-than-recorded clamps to the
        baseline). Zero events in the window burns 0.0."""
        i = max(0, len(history) - 1 - window)
        m0, t0 = history[i]
        m1, t1 = history[-1]
        total = t1 - t0
        if total <= 0:
            return 0.0
        return ((m1 - m0) / total) / rule.budget

    # -- the tick ----------------------------------------------------------

    def tick(self) -> list[str]:
        """Advance every rule one window step; returns the rules that
        ENTERED the alerting state this tick."""
        self.ticks += 1
        burn_g = self.registry.gauge(
            "slo_burn_rate",
            "error-budget burn rate per rule and window (1.0 = on "
            "budget)",
        )
        entered = []
        for rule in self.rules:
            st = self._state[rule.name]
            st.history.append(self._read(rule, st))
            fast = self._window_burn(rule, st.history, rule.fast_window)
            slow = self._window_burn(rule, st.history, rule.slow_window)
            burn_g.set(fast, rule=rule.name, window="fast")
            burn_g.set(slow, rule=rule.name, window="slow")
            firing = fast >= rule.threshold and slow >= rule.threshold
            if firing and not st.firing:
                st.alerts += 1
                st.fired_ticks.append(self.ticks)
                entered.append(rule.name)
                self.registry.counter(
                    "slo_alerts_total",
                    "entries into the alerting state per rule",
                ).inc(rule=rule.name)
                # The /healthz goodput summary (ISSUE 11 satellite)
                # reads this non-creatingly — probes see "when did an
                # alert last fire" without scraping /metrics.
                self.registry.gauge(
                    "slo_last_alert_tick",
                    "monitor tick of the most recent alert entry per "
                    "rule",
                ).set(self.ticks, rule=rule.name)
                if self.tracer:
                    self.tracer.event(
                        "slo_alert", rule=rule.name, tick=self.ticks,
                        fast_burn=fast, slow_burn=slow,
                    )
            st.firing = firing
        return entered

    # -- introspection ------------------------------------------------------

    def burn_rate(self, name: str, window: str = "fast") -> float:
        if window not in ("fast", "slow"):
            raise ValueError(
                f"window must be 'fast' or 'slow', got {window!r}"
            )
        rule = self._rule(name)
        w = rule.fast_window if window == "fast" else rule.slow_window
        return self._window_burn(rule, self._state[name].history, w)

    def cumulative(self, name: str) -> tuple[int, int]:
        """Cumulative ``(misses, total)`` as of the last tick — the
        quantity the brute-force ``request_slo_samples`` pin recounts."""
        return self._state[name].history[-1]

    def alerts(self, name: str) -> int:
        return self._state[name].alerts

    def fired_ticks(self, name: str) -> list[int]:
        """Monitor tick indices at which ``name`` entered the alerting
        state — the determinism pin compares these across runs."""
        return list(self._state[name].fired_ticks)

    @property
    def alerting(self) -> set[str]:
        return {n for n, st in self._state.items() if st.firing}

    def _rule(self, name: str) -> SloRule:
        for r in self.rules:
            if r.name == name:
                return r
        raise KeyError(f"no SLO rule named {name!r} "
                       f"(rules: {[r.name for r in self.rules]})")


# -- CLI spec grammar ---------------------------------------------------------


_RULE_KEYS = ("metric", "target", "total", "objective", "fast", "slow",
              "threshold")


def parse_slo_rules(spec: str) -> tuple[SloRule, ...]:
    """``--slo-rules`` grammar -> :class:`SloRule` tuple. Segments are
    ``;``-separated ``NAME:key=val,...`` with keys ``metric``
    (required), ``target`` (seconds — histogram mode), ``total``
    (counter mode denominator), ``objective``, ``fast``/``slow``
    (window ticks), ``threshold``, and ``label.K=V`` (repeatable)
    series selectors. The rules read the registry the monitor is built
    on: single-engine serve publishes the ``serve_*`` histograms there,
    while under ``--replicas`` those land in per-replica registries —
    router-mode histogram rules must target
    ``router_ttft_seconds`` + ``label.class=...`` (observed live per
    global tick) and counter rules the ``router_*_total`` counters.
    Example::

        bulk_shed:metric=router_shed_total,total=router_requests_total,\
label.class=bulk,objective=0.5,fast=4,slow=8;\
chat_ttft:metric=router_ttft_seconds,label.class=chat,target=0.5
    """
    rules = []
    for seg in spec.split(";"):
        seg = seg.strip()
        if not seg:
            continue
        name, colon, body = seg.partition(":")
        name = name.strip()
        if not colon or not body:
            raise ValueError(
                f"slo rule segment {seg!r} needs NAME:key=val[,...]"
            )
        kw: dict = {"name": name}
        labels: dict = {}
        for part in body.split(","):
            part = part.strip()
            if not part:
                continue
            key, eq, val = part.partition("=")
            key = key.strip()
            if not eq:
                raise ValueError(
                    f"rule {name!r}: bad key {part!r} (expected key=val)"
                )
            if key.startswith("label."):
                labels[key[len("label."):]] = val.strip()
            elif key == "metric":
                kw["metric"] = val.strip()
            elif key == "target":
                kw["target_s"] = float(val)
            elif key == "total":
                kw["total_metric"] = val.strip()
            elif key == "objective":
                kw["objective"] = float(val)
            elif key == "fast":
                kw["fast_window"] = int(val)
            elif key == "slow":
                kw["slow_window"] = int(val)
            elif key == "threshold":
                kw["threshold"] = float(val)
            else:
                raise ValueError(
                    f"rule {name!r}: unknown key {key!r} (valid: "
                    f"{list(_RULE_KEYS)} and label.K)"
                )
        if "metric" not in kw:
            raise ValueError(f"rule {name!r}: metric= is required")
        if labels:
            kw["labels"] = labels
        rules.append(SloRule(**kw))
    if not rules:
        raise ValueError(f"--slo-rules spec {spec!r} declares no rules")
    names = [r.name for r in rules]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate SLO rule names in {names}")
    return tuple(rules)


__all__ = [
    "SloRule",
    "SloMonitor",
    "parse_slo_rules",
]
