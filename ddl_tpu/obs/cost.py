"""Analytic FLOPs accounting and MFU (ISSUE 10 tentpole piece 2).

Model-FLOPs utilization — achieved FLOP/s over the hardware's peak — is
the efficiency headline of the pjit/TPUv4 LM-scaling work (PAPERS.md
2204.06514 reports MFU, not tok/s, precisely because it composes across
model sizes and chip generations). This module makes the numerator
EXACT and ANALYTIC: closed-form matmul FLOPs per train step / serve
token, parameterized on the same config dataclasses the programs
compile from, so the ``train_mfu`` gauge is a derived quantity of
(config, measured span time, device peak) and nothing else.

Accounting conventions (the standard ones, stated so the hand-computed
test oracle and this module can only disagree by a real bug):

- A matmul ``[m, k] @ [k, n]`` costs ``2*m*k*n`` FLOPs (multiply +
  accumulate). Only matmul-shaped work is counted — layernorms,
  softmax, bias adds, pooling and activations are O(elements) noise
  next to the contractions on both model families here.
- Attention computes the FULL ``T x T`` score matrix (that is what the
  einsum kernels here materialize — causal masking discards half the
  result but not the work), so forward attention per layer is
  ``4*B*T*T*e`` (QK^T plus AV).
- Backward is the standard 2x forward (each matmul re-appears as a
  dL/dx and a dL/dW matmul); a train step is ``3x`` forward.
  ``remat=True`` recomputes each block's forward in the backward pass:
  ``+1x`` the BLOCK forward (head/embed are not rematerialized).
- **Mode-awareness** (pp/tp/zero1): the parallel modes re-shard the
  SAME math — total model FLOPs per step are topology-invariant
  (tensor parallelism splits the contractions, pipelining splits the
  layers, ZeRO shards the optimizer; none adds or removes a matmul).
  What changes is the denominator: :func:`mfu` divides by
  ``n_devices * peak``, and the trainers pass their mesh size, so a
  pp=2 run at the same step time reports half the MFU of a 1-chip run
  — the bubble made visible, not hidden.
- Serving is accounted PER TOKEN, and **paged-aware**: decode attention
  cost is ``4*e*W`` per layer where ``W`` is the attended width — the
  page-count-bucket residency (``pages * page_size``) on the paged
  layout, the fixed ``capacity`` on the contiguous ring. That asymmetry
  IS the paged layout's perf story, so the gauge must show it.

Peak FLOP/s come from :data:`PEAK_FLOPS_BY_KIND` (per-chip dense
**bf16** marketing peaks, matched on the JAX ``device_kind`` string)
with a ``--peak-flops`` override; unknown kinds (including CPU) fall
back to :data:`CPU_NOMINAL_PEAK_FLOPS` so CPU runs still produce a
number — an order-of-magnitude anchor, clearly not a measured roofline
(override it for real CPU studies).

**Precision-aware denominator** (ISSUE 19): the table rows are bf16
peaks, but an fp32 run's matmuls cannot reach them — TPU MXUs run fp32
at half the bf16 rate, so scoring an fp32 run against the bf16 peak
flatters its MFU ~2x. ``peak_flops_per_device(precision=)`` takes the
active precision policy's matmul row (``PrecisionPolicy.mfu_kind`` —
"bf16" or "fp32") and halves the TPU table entry for fp32
(:data:`FP32_PEAK_FRACTION`). The CPU nominal is NOT halved — it is an
fp32-ish anchor already, so every committed CPU artifact is unchanged.
The trainers and the serve scheduler plumb their resolved policy in;
the default keeps the historical bf16 anchoring for direct callers.
"""

from __future__ import annotations

# Per-chip peak dense FLOP/s by device-kind substring (lowercase), most
# specific first. TPU entries are the published bf16 peaks per chip.
PEAK_FLOPS_BY_KIND: tuple[tuple[str, float], ...] = (
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

# Nominal single-CPU-core fp32 peak (~a few 10s of GFLOP/s with vector
# units): the documented fallback that keeps MFU defined on CPU smoke
# runs. It is an anchor, not a measurement — pass --peak-flops to pin
# a real number.
CPU_NOMINAL_PEAK_FLOPS = 5e10

# TPU MXU fp32 throughput as a fraction of the bf16 peak: fp32 matmuls
# run the same systolic array at half rate on every generation in the
# table above, so an fp32-policy run divides the bf16 row by 2.
FP32_PEAK_FRACTION = 0.5


_warned_kinds: set = set()


def peak_flops_per_device(device=None, override: float | None = None,
                          precision: str = "bf16") -> float:
    """Peak FLOP/s for one device at the given matmul ``precision``
    ("bf16" or "fp32" — the resolved policy's ``mfu_kind``):
    ``override`` wins (taken as the peak at the ACTIVE precision — the
    operator pinning a roofline pins the one their run can reach); else
    the ``device_kind`` table (bf16 rows, halved for fp32 per
    :data:`FP32_PEAK_FRACTION`); else the CPU nominal fallback
    (precision-independent — it is an fp32-ish anchor). An ACCELERATOR
    kind the table doesn't know (a new TPU generation, a GPU) warns
    once per kind — silently anchoring its MFU to the CPU nominal
    would report utilizations orders of magnitude above 1.0 as if they
    were real."""
    if precision not in ("bf16", "fp32"):
        raise ValueError(
            f"unknown peak precision {precision!r} (bf16 or fp32)"
        )
    if override is not None:
        if override <= 0:
            raise ValueError(f"peak flops override must be > 0, got "
                             f"{override}")
        return float(override)
    kind = ""
    if device is not None:
        kind = str(getattr(device, "device_kind", "")).lower()
    for key, peak in PEAK_FLOPS_BY_KIND:
        if key in kind:
            return peak * (FP32_PEAK_FRACTION if precision == "fp32"
                           else 1.0)
    platform = str(getattr(device, "platform", "cpu")).lower()
    if platform != "cpu" and kind not in _warned_kinds:
        import warnings

        _warned_kinds.add(kind)
        warnings.warn(
            f"unknown accelerator device_kind {kind!r}: MFU gauges will "
            f"use the CPU nominal anchor ({CPU_NOMINAL_PEAK_FLOPS:.0e} "
            "FLOP/s) and read far above 1.0 — pass --peak-flops (or "
            "peak_flops=) with the chip's real peak",
            stacklevel=2,
        )
    return CPU_NOMINAL_PEAK_FLOPS


def mfu(flops: float, seconds: float, n_devices: int,
        peak_per_device: float) -> float:
    """Model-FLOPs utilization: analytic FLOPs executed over the window
    divided by what ``n_devices`` could have executed at peak."""
    if seconds <= 0 or n_devices < 1 or peak_per_device <= 0:
        return 0.0
    return flops / (seconds * n_devices * peak_per_device)


# -- LM transformer -----------------------------------------------------------


def _lm_block_forward_flops(spec, tokens: int, attend_width: int) -> int:
    """Forward matmul FLOPs of ONE transformer block over ``tokens``
    query rows attending ``attend_width`` key rows: QKV+O projections
    (``8*t*e^2``), attention (``4*t*W*e`` — QK^T + AV), MLP
    (``4*t*e*f``)."""
    e, f = spec.d_model, spec.d_ff
    return (8 * tokens * e * e
            + 4 * tokens * attend_width * e
            + 4 * tokens * e * f)


def lm_forward_flops(spec, batch: int, seq_len: int) -> int:
    """Forward FLOPs of one full-sequence pass: ``num_layers`` blocks
    (full ``T x T`` attention) plus the untied head projection
    (``2*B*T*e*vocab``; the embedding lookup is a gather — no
    matmul)."""
    t = batch * seq_len
    # Per sequence, every one of its T query rows attends its own T key
    # rows: the block helper with tokens=T, width=T, scaled by batch.
    block = batch * _lm_block_forward_flops(spec, seq_len, seq_len)
    return spec.num_layers * block + 2 * t * spec.d_model * spec.vocab


def lm_train_step_flops(spec, batch: int, seq_len: int, *,
                        remat: bool = False) -> int:
    """Forward + backward FLOPs of one LM train step (global batch).
    Backward is 2x forward; ``remat`` adds one extra BLOCK forward per
    layer (the head is not rematerialized). Topology-invariant — see
    the module docstring's mode-awareness note."""
    fwd = lm_forward_flops(spec, batch, seq_len)
    total = 3 * fwd
    if remat:
        total += (spec.num_layers * batch
                  * _lm_block_forward_flops(spec, seq_len, seq_len))
    return total


# -- CNN ----------------------------------------------------------------------

# SAME 5x5 convs at stride 1 keep spatial dims; the 2x2 pool halves them
# (28 -> 14 -> 7 -> 4 -> 2), so each conv stage's output spatial extent
# equals its INPUT extent. The FC input is the 2x2 pooled final stage.
_CNN_SPATIAL = (28, 14, 7, 4)
_CNN_KERNEL = 5 * 5


def cnn_forward_flops(conv_channels=(32, 64, 128, 256),
                      fc_sizes=(1024, 512), num_classes: int = 10,
                      batch: int = 1) -> int:
    """Forward matmul FLOPs of the 4-conv/3-FC MNIST family per
    ``batch`` images: each SAME conv is ``2 * H*W * cout * (25*cin)``
    (identical whether lowered as a conv or a patches-matmul — the
    contraction is the same, which is why ``conv_matmul`` modes need no
    separate accounting), plus the three FC matmuls."""
    cins = (1,) + tuple(conv_channels[:3])
    flops = 0
    for s, cin, cout in zip(_CNN_SPATIAL, cins, conv_channels):
        flops += 2 * s * s * cout * (_CNN_KERNEL * cin)
    f1, f2 = fc_sizes
    flops += 2 * (2 * 2 * conv_channels[3]) * f1
    flops += 2 * f1 * f2
    flops += 2 * f2 * num_classes
    return batch * flops


def cnn_train_step_flops(batch: int, conv_channels=(32, 64, 128, 256),
                         fc_sizes=(1024, 512),
                         num_classes: int = 10) -> int:
    """Forward + backward (2x forward) FLOPs of one CNN train step."""
    return 3 * cnn_forward_flops(conv_channels, fc_sizes, num_classes,
                                 batch)


# -- serving ------------------------------------------------------------------


def serve_decode_flops_per_token(spec, attend_width: int) -> int:
    """Decode FLOPs for ONE token of one slot attending ``attend_width``
    resident rows — the paged-aware width: ``pages * page_size`` of the
    decode bucket on the paged layout, ``capacity`` on the contiguous
    ring (serve/engine.py sets ``last_attend_width`` accordingly)."""
    return (spec.num_layers
            * _lm_block_forward_flops(spec, 1, attend_width)
            + 2 * spec.d_model * spec.vocab)


def serve_speculate_verify_flops(spec, fed_rows: int,
                                 attend_width: int) -> int:
    """One speculative verify call (ISSUE 15): ``fed_rows`` decode-
    shaped rows — the real active slots PLUS every draft lane — each
    attending ``attend_width`` resident rows. The verify is literally
    the decode program with lanes riding in free slots, so its cost is
    per-token decode cost times the rows actually computed; emitted
    tokens can be fewer (rejected lanes) or more (a fully-accepted
    block's bonus token) — the asymmetry IS the speculation trade, so
    the accounting must price rows, not tokens."""
    return fed_rows * serve_decode_flops_per_token(spec, attend_width)


def serve_prefill_flops(spec, tokens: int, attend_width: int) -> int:
    """Prefill FLOPs for a ``tokens``-row block whose attention spans
    ``attend_width`` rows (the compiled bucket width — padding computes
    too; honesty about the bucket is the point)."""
    return (spec.num_layers
            * _lm_block_forward_flops(spec, tokens, attend_width)
            + 2 * tokens * spec.d_model * spec.vocab)
