"""Device memory watermarks + compile-activity counters (ISSUE 10
tentpole piece 3).

Two resource signals the live control plane needs that nothing
published before:

- **Memory watermarks**: ``device.memory_stats()`` (the PJRT allocator
  counters — ``bytes_in_use``, ``peak_bytes_in_use``, ...) sampled into
  ``device_memory_bytes_in_use{device=}`` / ``device_memory_peak_bytes
  {device=}`` gauges. The call is a HOST query of allocator state — no
  device sync, no dispatch — but not every backend implements it (this
  container's XLA:CPU returns ``None``), so :class:`MemorySampler`
  probes once and disables itself on unsupported backends: after the
  first empty probe a sample is one attribute check. Trainers sample on
  the existing ``--metrics-interval`` span boundary, the serve
  scheduler on its tick (already host-paced) — the hot path gains zero
  new device syncs either way.
- **Compile activity**: every DISTINCT program build — a trainer span
  program's ``lower().compile()``, an engine prefill/decode bucket, a
  prefix-copy program — increments ``xla_compiles_total{kind=}`` and
  traces a ``compile`` record. A mid-run recompile (a guard rollback
  realigning spans, a decode bucket the warmup ladder missed) is
  exactly the latency incident this makes auditable. Engine programs
  are counted at BUILD time (each cached program serves exactly one
  shape signature, so builds and XLA compiles are 1:1); trainer builds
  carry the real compile bracket as a span.
"""

from __future__ import annotations


def device_memory_stats(device) -> dict | None:
    """``device.memory_stats()`` guarded for backends that lack it or
    return None/empty (XLA:CPU here) — any failure is 'no data', never
    an exception on the metrics path."""
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — unsupported backend is a fine answer
        return None
    return dict(stats) if stats else None


class MemorySampler:
    """Samples memory watermark gauges for ``devices`` into
    ``registry``. The first sample that finds NO device reporting stats
    latches the sampler off (``supported = False``), so unsupported
    backends pay one probe total."""

    def __init__(self, registry, devices):
        self.registry = registry
        self.devices = list(devices)
        self.supported: bool | None = None  # None = not yet probed

    def sample(self) -> bool:
        """Record current watermarks; returns True when any device
        reported. No-op (False) once latched unsupported."""
        if self.supported is False:
            return False
        any_stats = False
        for i, dev in enumerate(self.devices):
            stats = device_memory_stats(dev)
            if stats is None:
                continue
            any_stats = True
            in_use = stats.get("bytes_in_use")
            if in_use is not None:
                self.registry.gauge(
                    "device_memory_bytes_in_use",
                    "live allocator bytes per device",
                ).set(int(in_use), device=i)
            peak = stats.get("peak_bytes_in_use")
            if peak is not None:
                self.registry.gauge(
                    "device_memory_peak_bytes",
                    "high-watermark allocator bytes per device",
                ).set(int(peak), device=i)
            limit = stats.get("bytes_limit")
            if limit is not None:
                self.registry.gauge(
                    "device_memory_bytes_limit",
                    "allocator capacity per device",
                ).set(int(limit), device=i)
        if self.supported is None:
            self.supported = any_stats
        return any_stats


def record_compile(registry, tracer, kind: str, *,
                   t0: float | None = None, t1: float | None = None,
                   **attrs) -> None:
    """Count one program build (``xla_compiles_total{kind=}``) and
    trace it — a real ``compile`` span when the caller measured the
    bracket (trainer AOT builds), an instant event otherwise (engine
    lazy builds, whose XLA compile happens inside the first dispatch).
    ``registry``/``tracer`` may each be None/falsy — partial telemetry
    records what it can."""
    if registry is not None:
        registry.counter(
            "xla_compiles_total",
            "distinct compiled programs built, by kind",
        ).inc(kind=kind)
        if t0 is not None and t1 is not None:
            registry.histogram(
                "xla_compile_seconds",
                "wall seconds per measured program build",
            ).observe(t1 - t0, kind=kind)
    if tracer:
        if t0 is not None and t1 is not None:
            tracer.complete("compile", t0, t1, kind=kind, **attrs)
        else:
            tracer.event("compile", kind=kind, **attrs)
