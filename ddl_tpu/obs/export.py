"""HTTP pull endpoint for the metric registry (ISSUE 10 tentpole piece
4): ``GET /metrics`` serves ``MetricRegistry.prometheus_text()`` and
``GET /healthz`` a liveness JSON — since ISSUE 11 carrying the compact
goodput digest (current ``goodput_fraction``, last anomaly/SLO-alert
tick, cumulative anomaly count; see ``obs.goodput.goodput_summary``) so
a probe sees degradation without parsing the full exposition — from a
stdlib ``ThreadingHTTPServer`` in a daemon thread — no dependencies,
CLI flag ``--prom-port``.

The JSONL ``MetricsWriter`` is a push artifact read after the run; the
pull endpoint is what a live scraper (Prometheus, the PR-11 autoscaler,
an operator's ``curl``) reads DURING the run. The body is byte-for-byte
the in-process ``prometheus_text()`` (pinned mid-run in
tests/test_slo.py) — the endpoint adds transport, never a second
formatting path.

Threading: the handler thread reads registry state the run loop
mutates. Python-level dict/list operations are GIL-atomic, but
ITERATING a dict while the run loop inserts a new series raises
``RuntimeError: dictionary changed size`` — the handler retries the
snapshot a few times (new-series insertion is rare after startup) and
degrades to 503 rather than ever crashing the serving thread. Port 0
binds an ephemeral port (the tests' race-free choice); the bound port
is exposed as ``.port``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .registry import MetricRegistry

_SNAPSHOT_RETRIES = 5
_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsExporter:
    """Serve ``registry`` at ``http://{host}:{port}``. ``start()``
    launches the daemon thread and returns self; ``close()`` shuts the
    server down (idempotent). Context-manager friendly."""

    def __init__(self, registry: MetricRegistry, port: int = 0,
                 host: str = "127.0.0.1"):
        if registry is None:
            raise ValueError("MetricsExporter needs a MetricRegistry")
        self.registry = registry
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: D102 — silence
                pass  # no stray stdout from the handler thread

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    for attempt in range(_SNAPSHOT_RETRIES):
                        try:
                            body = exporter.registry.prometheus_text() \
                                .encode("utf-8")
                            break
                        except RuntimeError:
                            # The run loop inserted a series mid-walk;
                            # re-snapshot (module docstring).
                            if attempt == _SNAPSHOT_RETRIES - 1:
                                self._send(
                                    503,
                                    b"snapshot raced registry mutation\n",
                                    "text/plain",
                                )
                                return
                    self._send(200, body, _CONTENT_TYPE)
                elif path == "/healthz":
                    # Compact goodput/degradation digest (ISSUE 11
                    # satellite) + the fleet digest (ISSUE 13: replicas
                    # active/draining, last scale tick, preemptions):
                    # probes see degradation AND fleet churn without
                    # scraping /metrics. Read NON-creatingly
                    # (registry.get) with the same mutation-race
                    # retry discipline as /metrics.
                    from .goodput import fleet_summary, goodput_summary

                    body = {"status": "ok"}
                    for attempt in range(_SNAPSHOT_RETRIES):
                        try:
                            body.update(goodput_summary(exporter.registry))
                            body.update(fleet_summary(exporter.registry))
                            break
                        except RuntimeError:
                            if attempt == _SNAPSHOT_RETRIES - 1:
                                body = {"status": "degraded",
                                        "error": "snapshot raced registry "
                                                 "mutation"}
                    self._send(
                        200,
                        json.dumps(body).encode() + b"\n",
                        "application/json",
                    )
                else:
                    self._send(404, b"not found\n", "text/plain")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = int(self._server.server_port)
        self._thread: threading.Thread | None = None

    def start(self) -> "MetricsExporter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="ddl-tpu-metrics-exporter", daemon=True,
            )
            self._thread.start()
        return self

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._server.server_close()

    def __enter__(self) -> "MetricsExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
