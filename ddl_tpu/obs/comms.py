"""Live communication plane (ISSUE 20): the collective ledger, the ICI
roofline, and comms-vs-compute attribution.

The source paper's sync-vs-async question is a communication story, and
the grounding papers judge their systems by exactly these ledgers —
2004.13336's weight-update rewrite by bytes-per-step, 2204.06514's
pjit/TPUv4 scaling by compute-vs-ICI roofline attribution. Until now the
repo's only byte evidence was the OFFLINE audit in
``benchmarks/collective_bytes.py``; this module makes the same parser a
library surface and feeds it from the points where programs are already
built, so the byte story is live telemetry, not a separate tool run:

- :func:`collective_ops` — THE collective-op HLO parser (the benchmark
  now imports it; one parser, no drift), extended with replica-group /
  source-target-pair recovery so bytes can be attributed to MESH AXES.
- :func:`program_text` — the optimized-HLO fetch, module-level and
  monkeypatchable ON PURPOSE: ``as_text()`` costs real milliseconds per
  program, so every caller gates it behind a live registry exactly like
  the falsy-tracer clock reads, and the off-path pin installs a bomb
  here to prove registry-less runs never fetch (tests/test_comms.py).
- :func:`publish_program_ledger` — one static ledger per DISTINCT
  compiled program: ``collective_bytes{kind=,program=}`` /
  ``collective_axis_bytes{axis=,program=}`` gauges and
  ``collective_ops_total{kind=,program=}`` counters, plus a
  ``collective_bytes_total{program=}`` sum that exists even at 0 so a
  collective-free program still proves it published.
- :data:`ICI_BW_BY_KIND` / :func:`ici_bw_per_device` — the comms twin
  of ``obs.cost.PEAK_FLOPS_BY_KIND``: per-device-kind nominal link
  bandwidth with a CPU fallback and an ``--ici-bw`` override.
- :func:`roofline` / :func:`fit_roofline` — the two-roofline step-time
  model ``t = max(flops/peak, bytes/bw)``: the live gauges publish the
  model next to ``train_mfu`` every span, and the fit falsifies it
  against measured step times across topologies
  (``benchmarks/collective_bytes.py`` rows, ``analyze comms``) the way
  ``pipeline_bubble.py`` falsified the bubble model.

Wiring (all gated on a live registry — no registry, no HLO fetch, no
parsing, no gauges, compiled programs unchanged by construction):

- trainers (``strategies/seq.py``, ``train/trainer.py``): the span/eval
  compiles where ``record_compile`` already fires publish the ledger,
  and the per-span metrics block publishes ``comms_bytes_per_step``,
  ``comms_time_model_s`` / ``compute_time_model_s`` /
  ``step_time_model_s``, ``comms_fraction`` and
  ``step_bound{bound=compute|comms}`` next to ``train_mfu``.
- serve (``serve/engine.py`` + ``serve/scheduler.py``): the scheduler
  attaches ``engine.ledger_hook`` beside the existing ``compile_hook``;
  each cached program then AOT-compiles at its first real call,
  publishes its ledger once, and runs the ``Compiled`` executable from
  then on (engine ``_LedgeredProgram`` docstring for why this is the
  only order that avoids compiling twice).
- host-side byte plane: ``handoff_bytes_total{path=preempt|requeue|
  disagg}`` counters on the scheduler/router registries, priced by the
  ``serve.cache.kv_row_bytes`` oracle (``engine.handoff_bytes``).
"""

from __future__ import annotations

import itertools
import re

import numpy as np

# -- the parser (lifted from benchmarks/collective_bytes.py) ------------------

_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "f64": 8,
                "pred": 1, "s8": 1, "u8": 1}

_COLLECTIVES = ("all-reduce", "reduce-scatter", "all-gather", "all-to-all",
                "collective-permute")

_OP_PAT = re.compile(r"=\s*(.*?)\s(" + "|".join(_COLLECTIVES) + r")\(")
_SHAPE_PAT = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
# replica_groups={{0,2},{1,3}} — the explicit form this backend emits.
_GROUPS_PAT = re.compile(r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")
# replica_groups=[2,2]<=[4] (iota form, optionally [2,2]<=[2,2]T(1,0)):
# arange over the source dims, transposed, reshaped to [groups, size].
_IOTA_PAT = re.compile(
    r"replica_groups=\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?"
)
# collective-permute carries source_target_pairs instead of groups.
_PAIRS_PAT = re.compile(r"source_target_pairs=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")


def _parse_groups(line: str):
    """Device groups of one HLO collective line: a list of id lists, or
    ``None`` when the line carries no group attribute (HLO semantics:
    one group of every participant — the caller resolves "every" from
    its mesh). ``collective-permute`` pairs are unioned into their
    connected components (a ring permute over an axis connects exactly
    that axis's members, so the component set matches the axis
    partition the same way a replica-group set does)."""
    m = _IOTA_PAT.search(line)
    if m:
        dims = [int(d) for d in m.group(1).split(",")]
        src = [int(d) for d in m.group(2).split(",")]
        ids = np.arange(int(np.prod(src)), dtype=np.int64).reshape(src)
        if m.group(3):
            ids = ids.transpose([int(d) for d in m.group(3).split(",")])
        return [list(map(int, row)) for row in ids.reshape(dims)]
    m = _GROUPS_PAT.search(line)
    if m:
        return [[int(x) for x in g.split(",") if x.strip() != ""]
                for g in re.findall(r"\{([^}]*)\}", m.group(1))]
    m = _PAIRS_PAT.search(line)
    if m:
        pairs = [tuple(int(x) for x in g.split(","))
                 for g in re.findall(r"\{([^}]*)\}", m.group(1))]
        parent: dict[int, int] = {}

        def find(a: int) -> int:
            parent.setdefault(a, a)
            while parent[a] != a:
                parent[a] = parent[parent[a]]
                a = parent[a]
            return a

        for a, b in pairs:
            parent[find(a)] = find(b)
        comps: dict[int, list[int]] = {}
        for a in parent:
            comps.setdefault(find(a), []).append(a)
        return [sorted(v) for v in comps.values()]
    return None


def collective_ops(hlo_text: str) -> list[dict]:
    """Parse collective ops + result shapes out of optimized HLO text.

    Handles tuple-shaped (fused) results — ``= (f32[5882], f32[])
    all-reduce(...)`` counts EVERY member shape, so a fused full-vector
    all-reduce can never hide behind a scalar sibling (the audit's whole
    point is catching exactly that regression). Each row also carries
    ``groups`` — the op's device groups (replica_groups, iota or
    permute pairs; ``None`` when the line names no groups) — the raw
    material :func:`publish_program_ledger` turns into per-mesh-axis
    attribution."""
    out = []
    for line in hlo_text.splitlines():
        m = _OP_PAT.search(line)
        if not m:
            continue
        result_txt, op = m.group(1), m.group(2)
        shapes = []
        total_bytes = 0
        for dtype, dims in _SHAPE_PAT.findall(result_txt):
            shape = [int(d) for d in dims.split(",") if d] if dims else []
            elems = 1
            for d in shape:
                elems *= d
            shapes.append({"dtype": dtype, "shape": shape,
                           "elems": elems})
            total_bytes += elems * _DTYPE_BYTES.get(dtype, 4)
        out.append({
            "op": op,
            "dtype": shapes[0]["dtype"] if shapes else "?",
            "shape": [s["shape"] for s in shapes] if len(shapes) > 1
                     else (shapes[0]["shape"] if shapes else []),
            "max_elems": max((s["elems"] for s in shapes), default=0),
            "bytes": total_bytes,
            "groups": _parse_groups(line),
        })
    return out


def program_text(compiled) -> str:
    """Optimized-HLO text of an AOT-``Compiled`` program. The ONE
    fetch every ledger goes through — module-level so the off-path pin
    can monkeypatch a bomb here and prove registry-less runs never pay
    the (real, milliseconds-per-program) ``as_text()`` cost."""
    return compiled.as_text()


# -- mesh-axis attribution ----------------------------------------------------


def mesh_axis_partitions(mesh) -> dict:
    """``{frozenset-of-frozenset device groups: axis label}`` for every
    nonempty subset of ``mesh``'s axes: the subset's groups are the
    partition of global device ids that agree on every OTHER axis's
    coordinate — exactly the replica_groups a collective over those
    axes names. Labels join axis names with ``x`` in mesh order;
    size-1-axis collisions keep the SMALLEST subset's label (an op
    over ``(dp,)`` on a ``dp=2, tp=1`` mesh is a dp op)."""
    ids = np.vectorize(lambda d: d.id)(np.asarray(mesh.devices))
    names = tuple(mesh.axis_names)
    n = ids.ndim
    out: dict = {}
    for r in range(1, n + 1):
        for subset in itertools.combinations(range(n), r):
            other = [a for a in range(n) if a not in subset]
            flat = ids.transpose([*other, *subset]).reshape(
                -1, int(np.prod([ids.shape[a] for a in subset],
                                dtype=np.int64))
            )
            part = frozenset(frozenset(int(x) for x in row) for row in flat)
            out.setdefault(part, "x".join(names[a] for a in subset))
    return out


def _axis_of(groups, partitions: dict, all_ids: frozenset | None) -> str:
    """Axis label of one op's device groups (``unknown`` when the
    group set matches no axis subset of the mesh — or when no mesh was
    given). A group-less op (``groups=None``) spans every participant:
    resolved as the full-device partition."""
    if not partitions:
        return "unknown"
    if groups is None:
        if all_ids is None:
            return "unknown"
        part = frozenset((all_ids,))
    else:
        part = frozenset(frozenset(g) for g in groups)
    return partitions.get(part, "unknown")


# -- the ledger ---------------------------------------------------------------


def publish_program_ledger(registry, hlo_text: str, *, program: str,
                           mesh=None) -> dict:
    """Publish ONE compiled program's static collective ledger on
    ``registry`` and return its summary. Gauges, not counters, for the
    byte surfaces — the ledger is a property of the program, set once
    at build (re-publishing the same program is idempotent by
    construction); ``collective_ops_total`` counts ops per (collective
    kind, program) so re-compiles of the same program label are visible
    as increments, exactly like ``xla_compiles_total``.

    ``program`` is the ``kind[key]`` label the compile-activity hook
    already uses (``train_span[3]``, ``prefill[16]``, ``decode[2]``...)
    so the two surfaces join on it. ``mesh`` (optional) turns each op's
    recovered device groups into a mesh-axis label
    (:func:`mesh_axis_partitions`); without it — or when the groups
    match no axis subset — bytes land under ``axis="unknown"``."""
    ops = collective_ops(hlo_text)
    partitions = mesh_axis_partitions(mesh) if mesh is not None else {}
    all_ids = None
    if mesh is not None:
        all_ids = frozenset(
            int(d.id) for d in np.asarray(mesh.devices).flat
        )
    by_kind: dict[str, int] = {}
    by_axis: dict[str, int] = {}
    for o in ops:
        by_kind[o["op"]] = by_kind.get(o["op"], 0) + o["bytes"]
        axis = _axis_of(o["groups"], partitions, all_ids)
        by_axis[axis] = by_axis.get(axis, 0) + o["bytes"]
        registry.counter(
            "collective_ops_total",
            "collective ops per compiled program (kind=collective op)",
        ).inc(1, kind=o["op"], program=program)
    g = registry.gauge(
        "collective_bytes",
        "static per-program collective result bytes by collective kind",
    )
    for k, b in sorted(by_kind.items()):
        g.set(b, kind=k, program=program)
    ga = registry.gauge(
        "collective_axis_bytes",
        "static per-program collective bytes by mesh axis",
    )
    for a, b in sorted(by_axis.items()):
        ga.set(b, axis=a, program=program)
    total = sum(by_kind.values())
    # Present even at 0: a collective-free program (a single-device
    # span, a page write) still proves its ledger published.
    registry.gauge(
        "collective_bytes_total",
        "static per-program collective result bytes, all kinds",
    ).set(total, program=program)
    return {"program": program, "total_bytes": total, "ops": len(ops),
            "by_kind": by_kind, "by_axis": by_axis}


# -- ICI bandwidth table (the comms twin of cost.PEAK_FLOPS_BY_KIND) ----------

# Nominal per-chip aggregate ICI bandwidth (bytes/s) by device-kind
# substring (lowercase), most specific first — vendor-published
# interconnect figures converted to bytes/s. Anchors for the roofline
# model, not measurements: --ici-bw pins a real number (the fitted
# value `fit_roofline` recovers from measured rows is the honest one).
ICI_BW_BY_KIND: tuple[tuple[str, float], ...] = (
    ("v5p", 6.0e11),
    ("v5e", 2.0e11),
    ("v5litepod", 2.0e11),
    ("v4", 3.0e11),
    ("v3", 1.4e11),
    ("v2", 1.0e11),
)

# Nominal host fallback (~10 GB/s, memcpy-through-shared-memory order):
# keeps the comms roofline defined on CPU smoke runs. An anchor, not a
# measurement — pass --ici-bw to pin a real number.
CPU_NOMINAL_ICI_BW = 1e10


_warned_kinds: set = set()


def ici_bw_per_device(device=None, override: float | None = None) -> float:
    """Nominal interconnect bytes/s for one device: ``override`` wins;
    else the ``device_kind`` table; else the CPU nominal fallback. An
    ACCELERATOR kind the table doesn't know warns once per kind —
    silently anchoring its comms roofline to the CPU nominal would
    model every step as hopelessly comms-bound (the exact failure mode
    ``cost.peak_flops_per_device`` guards for MFU)."""
    if override is not None:
        if override <= 0:
            raise ValueError(
                f"ici bw override must be > 0, got {override}"
            )
        return float(override)
    kind = ""
    if device is not None:
        kind = str(getattr(device, "device_kind", "")).lower()
    for key, bw in ICI_BW_BY_KIND:
        if key in kind:
            return bw
    platform = str(getattr(device, "platform", "cpu")).lower()
    if platform != "cpu" and kind not in _warned_kinds:
        import warnings

        _warned_kinds.add(kind)
        warnings.warn(
            f"unknown accelerator device_kind {kind!r}: comms roofline "
            f"gauges will use the CPU nominal anchor "
            f"({CPU_NOMINAL_ICI_BW:.0e} B/s) and read absurdly "
            "comms-bound — pass --ici-bw (or ici_bw=) with the chip's "
            "real link bandwidth",
            stacklevel=2,
        )
    return CPU_NOMINAL_ICI_BW


# -- the two-roofline step-time model -----------------------------------------


def roofline(flops: float, comm_bytes: float, n_devices: int,
             peak_per_device: float, bw_per_device: float) -> dict:
    """The two-roofline step-time model of one step:
    ``compute = flops / (n_devices * peak)``, ``comms = bytes / bw``
    (the parser's bytes are already per-device result bytes — each
    device's share of the program's collective traffic), and the
    modeled step is their MAX (perfect-overlap assumption — the
    falsifiable claim :func:`fit_roofline` tests). ``comms_fraction``
    is the no-overlap share ``comms / (compute + comms)`` — a live
    dial, not the binding verdict; ``bound`` is the verdict."""
    compute_s = (flops / (n_devices * peak_per_device)
                 if n_devices >= 1 and peak_per_device > 0 else 0.0)
    comms_s = comm_bytes / bw_per_device if bw_per_device > 0 else 0.0
    denom = compute_s + comms_s
    return {
        "compute_time_model_s": compute_s,
        "comms_time_model_s": comms_s,
        "step_time_model_s": max(compute_s, comms_s),
        "comms_fraction": comms_s / denom if denom > 0 else 0.0,
        "bound": "comms" if comms_s > compute_s else "compute",
    }


def fit_roofline(rows, iters: int = 25) -> dict | None:
    """Fit the two parameters of ``t = max(f * inv_peak, b * inv_bw)``
    to measured rows ``{"flops": f, "bytes": b, "measured_s": t}`` —
    the falsification harness: if the two-roofline model is right, ONE
    (inv_peak, inv_bw) pair must explain every topology's measured step
    time at once (the way ``pipeline_bubble.py``'s one alpha had to
    explain every (pp, M) cell).

    Alternating assignment + per-side least squares: classify each row
    by which term currently binds, refit that side's slope on its rows,
    repeat to a fixed point. Returns the fitted peaks, per-row model
    times and relative errors, and ``max_rel_err`` — the headline
    number ``analyze comms`` prints. ``None`` with fewer than 2 usable
    rows (a 1-row fit is unfalsifiable)."""
    rows = [r for r in rows
            if r.get("measured_s") and r["measured_s"] > 0
            and r.get("flops") and r["flops"] > 0]
    if len(rows) < 2:
        return None
    f = np.array([float(r["flops"]) for r in rows])
    b = np.array([float(r.get("bytes") or 0.0) for r in rows])
    t = np.array([float(r["measured_s"]) for r in rows])
    inv_peak = float(np.median(t / f))
    with np.errstate(divide="ignore"):
        ratios = np.where(b > 0, t / np.where(b > 0, b, 1.0), np.inf)
    finite = ratios[np.isfinite(ratios)]
    inv_bw = float(np.median(finite)) if finite.size else 0.0
    for _ in range(iters):
        comp = f * inv_peak >= b * inv_bw
        new_peak, new_bw = inv_peak, inv_bw
        if comp.any():
            new_peak = float((t[comp] * f[comp]).sum()
                             / (f[comp] * f[comp]).sum())
        comms = ~comp & (b > 0)
        if comms.any():
            new_bw = float((t[comms] * b[comms]).sum()
                           / (b[comms] * b[comms]).sum())
        if new_peak == inv_peak and new_bw == inv_bw:
            break
        inv_peak, inv_bw = new_peak, new_bw
    model = np.maximum(f * inv_peak, b * inv_bw)
    rel = np.abs(model - t) / t
    return {
        "inv_peak_s_per_flop": inv_peak,
        "inv_bw_s_per_byte": inv_bw,
        "fitted_peak_flops": 1.0 / inv_peak if inv_peak > 0 else 0.0,
        "fitted_bw_bytes_per_s": 1.0 / inv_bw if inv_bw > 0 else 0.0,
        "model_s": [float(x) for x in model],
        "rel_err": [float(x) for x in rel],
        "max_rel_err": float(rel.max()),
    }


__all__ = [
    "CPU_NOMINAL_ICI_BW",
    "ICI_BW_BY_KIND",
    "collective_ops",
    "fit_roofline",
    "ici_bw_per_device",
    "mesh_axis_partitions",
    "program_text",
    "publish_program_ledger",
    "roofline",
]
