"""Unified telemetry (ISSUE 5): the observability layer every subsystem
reports through.

The reference's only observability is rank/epoch ``print``s and a
``time.clock()`` wall bracket (SURVEY.md §5); production pjit/TPU stacks
treat step-time breakdowns and per-request traces as first class
(arXiv:2204.06514 §5; the serving comparisons of arXiv:2605.25645 are
built entirely on such telemetry). Three pieces, one package:

- :mod:`ddl_tpu.obs.trace` — nestable host wall-clock spans + instant
  events, emitted as JSONL and convertible to a Chrome/Perfetto
  ``trace_event`` file; ``trace_context`` wraps the existing
  ``jax.profiler`` trace so one ``--trace-dir`` run captures both the
  host span timeline and the XLA device timeline.
- :mod:`ddl_tpu.obs.registry` — counters / gauges / histograms with
  label sets, a JSONL snapshot writer (manifest-first), and a
  Prometheus-text export. Replaces the ad-hoc per-subsystem stats
  dicts as the machine-readable surface (``ServeStats`` et al. remain
  as typed in-process views).
- :mod:`ddl_tpu.obs.health` — in-graph training health signals
  (global grad norm, per-subtree param/update norms, non-finite
  gradient counts) computed INSIDE the jitted step bodies as an aux
  output and fetched batched, so the hot path never gains a device
  sync.

The live SLO control plane (ISSUE 10) adds four more:

- :mod:`ddl_tpu.obs.slo` — streaming multi-window burn-rate monitors
  (``SloRule``/``SloMonitor``) evaluated per scheduler/router tick
  against the registry, emitting ``slo_burn_rate`` gauges,
  ``slo_alerts_total`` counters and ``slo_alert`` trace events.
- :mod:`ddl_tpu.obs.cost` — exact analytic FLOPs for the LM/CNN train
  steps and per-token serve work (paged-aware), the device peak-FLOPs
  table, and the ``mfu()`` division behind the ``train_mfu`` /
  ``serve_mfu`` gauges.
- :mod:`ddl_tpu.obs.memory` — device memory watermark gauges (guarded
  ``memory_stats()``) and the ``xla_compiles_total`` compile-activity
  counter every trainer/engine program build feeds.
- :mod:`ddl_tpu.obs.export` — the stdlib-threaded ``/metrics`` +
  ``/healthz`` HTTP pull endpoint behind CLI ``--prom-port``.

The goodput & time-attribution plane (ISSUE 11) adds three more:

- :mod:`ddl_tpu.obs.goodput` — per-span/per-tick wall-clock phase
  attribution (``GoodputTracker``): every observed second lands in
  exactly one phase, published as ``time_in_seconds{phase=}`` +
  ``goodput_fraction`` gauges next to the MFU story, with the pinned
  identity that phases sum to the observed wall time.
- :mod:`ddl_tpu.obs.anomaly` — streaming robust baselines
  (``AnomalyDetector``): rolling median/MAD per signal on the
  deterministic tick clock, edge-triggered ``anomaly`` trace events
  and ``anomaly_total{signal=}`` counters.
- :mod:`ddl_tpu.obs.analyze` — the offline CLI
  (``python -m ddl_tpu.obs.analyze``): goodput report, per-request
  critical-path breakdown and straggler/anomaly tables from a trace
  JSONL, plus a ``compare`` regression gate over two metrics
  artifacts (exit nonzero past a threshold).

The communication plane (ISSUE 20) adds one more:

- :mod:`ddl_tpu.obs.comms` — the collective-op HLO parser as a library
  surface (``benchmarks/collective_bytes.py`` now imports it), the
  per-program static collective ledger (``collective_bytes{kind=,
  program=}`` / ``collective_axis_bytes{axis=}`` /
  ``collective_ops_total``) published at the same build points
  ``xla_compiles_total`` counts, the per-device-kind ICI bandwidth
  table behind ``--ici-bw``, the two-roofline step-time model
  (``comms_time_model_s`` / ``comms_fraction`` /
  ``step_bound{bound=}`` next to ``train_mfu``) with its
  ``fit_roofline`` falsification harness, and the host byte plane
  (``handoff_bytes_total{path=}`` priced by ``serve.cache.
  kv_row_bytes``). ``analyze comms`` renders either a metrics JSONL or
  the bench artifact (README "Communication accounting").

Everything is surfaced by ``cli.py`` via ``--metrics-out``,
``--metrics-interval``, ``--trace-dir``, ``--prom-port``,
``--peak-flops``, ``--ici-bw``, ``--slo-rules`` and
``--anomaly-rules`` (README "Observability").
"""

from .registry import (  # noqa: F401
    MetricRegistry,
    MetricsWriter,
    NoSamplesError,
    run_manifest,
)
from .trace import NULL_TRACER, Tracer, trace_context  # noqa: F401
