"""Unified telemetry (ISSUE 5): the observability layer every subsystem
reports through.

The reference's only observability is rank/epoch ``print``s and a
``time.clock()`` wall bracket (SURVEY.md §5); production pjit/TPU stacks
treat step-time breakdowns and per-request traces as first class
(arXiv:2204.06514 §5; the serving comparisons of arXiv:2605.25645 are
built entirely on such telemetry). Three pieces, one package:

- :mod:`ddl_tpu.obs.trace` — nestable host wall-clock spans + instant
  events, emitted as JSONL and convertible to a Chrome/Perfetto
  ``trace_event`` file; ``trace_context`` wraps the existing
  ``jax.profiler`` trace so one ``--trace-dir`` run captures both the
  host span timeline and the XLA device timeline.
- :mod:`ddl_tpu.obs.registry` — counters / gauges / histograms with
  label sets, a JSONL snapshot writer (manifest-first), and a
  Prometheus-text export. Replaces the ad-hoc per-subsystem stats
  dicts as the machine-readable surface (``ServeStats`` et al. remain
  as typed in-process views).
- :mod:`ddl_tpu.obs.health` — in-graph training health signals
  (global grad norm, per-subtree param/update norms, non-finite
  gradient counts) computed INSIDE the jitted step bodies as an aux
  output and fetched batched, so the hot path never gains a device
  sync.

Everything is surfaced by ``cli.py`` via ``--metrics-out``,
``--metrics-interval`` and ``--trace-dir`` (README "Observability").
"""

from .registry import MetricRegistry, MetricsWriter, run_manifest  # noqa: F401
from .trace import NULL_TRACER, Tracer, trace_context  # noqa: F401
