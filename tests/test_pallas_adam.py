"""Fused Pallas Adam kernel (ops/pallas_adam.py): equivalence with the
XLA-fused update at ~1-ulp tolerance (exact bit-equality across separately
compiled programs is not guaranteed — fusion may reassociate the
multiply-adds), padding correctness at awkward sizes, and the
config.fused_adam product path end-to-end on the 8-device mesh.

On the CPU test platform the kernel runs in Pallas interpreter mode (the
trainers select this automatically from the mesh platform).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ddl_tpu.models import cnn
from ddl_tpu.ops.pallas_adam import adam_flat_fused
from ddl_tpu.parallel.mesh import DP_AXIS, make_mesh
from ddl_tpu.strategies.sync import (
    SyncTrainer,
    make_sharded_step,
    resolve_layout,
    sharded_adam_init,
)
from ddl_tpu.train import TrainConfig


def _oracle(p, m, v, g, lr_t, b1=0.9, b2=0.999, eps=1e-8):
    m2 = b1 * m + (1.0 - b1) * g
    v2 = b2 * v + (1.0 - b2) * g * g
    return p - lr_t * m2 / (jnp.sqrt(v2) + eps), m2, v2


@pytest.mark.parametrize("n", [5, 1024, 512 * 128, 512 * 128 + 17])
def test_fused_matches_xla_chain(n, rng):
    """Sizes cover sub-tile, single-tile, exact-grid, and padded-grid."""
    p, m, g = (jnp.asarray(rng.normal(size=n), jnp.float32) for _ in range(3))
    v = jnp.abs(jnp.asarray(rng.normal(size=n), jnp.float32))
    lr_t = jnp.float32(3e-4)
    p_r, m_r, v_r = _oracle(p, m, v, g, lr_t)
    p_f, m_f, v_f = adam_flat_fused(p, m, v, g, lr_t, interpret=True)
    np.testing.assert_allclose(np.asarray(p_f), np.asarray(p_r), atol=2e-7)
    np.testing.assert_allclose(np.asarray(m_f), np.asarray(m_r), atol=2e-7)
    np.testing.assert_allclose(np.asarray(v_f), np.asarray(v_r), atol=2e-7)


def test_padding_tail_not_leaked(rng):
    """Values past n must never contaminate results for any block layout."""
    n = 300  # well inside one (512, 128) block
    args = [jnp.asarray(rng.normal(size=n), jnp.float32) for _ in range(3)]
    v = jnp.abs(args.pop())
    p, m = args
    g = jnp.asarray(rng.normal(size=n), jnp.float32)
    small = adam_flat_fused(p, m, v, g, jnp.float32(1e-3), block_rows=8,
                            interpret=True)
    big = adam_flat_fused(p, m, v, g, jnp.float32(1e-3), interpret=True)
    for a, b in zip(small, big):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-7)
        assert a.shape == (n,)


def test_sharded_step_fused_matches_default(small_params, small_dataset):
    """The product path: make_sharded_step with config.fused_adam on the
    8-device mesh ≡ the XLA-fused default, for a variable-aligned layout
    (padding exercised via max_shard)."""
    W = 8
    mesh = make_mesh(W)
    shapes = cnn.param_shapes(small_params)
    sizes = {k: int(np.prod(s)) if s else 1 for k, s in shapes.items()}
    base = dict(num_workers=W, num_ps=4, layout="zigzag", batch_size=32,
                keep_prob=1.0, seed=0)
    x = jnp.asarray(np.asarray(small_dataset.x_train[:32]))
    y = jnp.asarray(
        np.eye(10, dtype=np.float32)[np.asarray(small_dataset.y_train[:32])]
    )
    data_sh = NamedSharding(mesh, P(DP_AXIS))
    x, y = jax.device_put(x, data_sh), jax.device_put(y, data_sh)
    params0 = jax.device_put(small_params, NamedSharding(mesh, P()))
    rng_key = jax.random.PRNGKey(7)

    results = {}
    for fused in (False, True):
        cfg = TrainConfig(fused_adam=fused, **base)
        layout = resolve_layout(cfg, W, sizes)
        step = make_sharded_step(cfg, mesh, layout, shapes)
        opt = sharded_adam_init(mesh, layout)
        p, opt, loss = step(params0, opt, x, y, rng_key)
        p, opt, loss = step(p, opt, x, y, jax.random.fold_in(rng_key, 1))
        results[fused] = (p, opt, float(loss))

    (p0, o0, l0), (p1, o1, l1) = results[False], results[True]
    # Step 2's loss is computed from step-1 params, which may already
    # differ ~1 ulp between the paths — tolerance, not bit-equality.
    np.testing.assert_allclose(l0, l1, rtol=1e-5)
    for k in p0:
        np.testing.assert_allclose(
            np.asarray(p0[k]), np.asarray(p1[k]), atol=1e-6, err_msg=k
        )
    np.testing.assert_allclose(np.asarray(o0.m), np.asarray(o1.m), atol=1e-6)
    np.testing.assert_allclose(np.asarray(o0.v), np.asarray(o1.v), atol=1e-6)
    assert int(o1.step) == 2


def test_sync_trainer_fused_end_to_end(small_dataset, small_params):
    """SyncTrainer with fused_adam trains and stays close to the default
    path over a short run (divergence bounded by ulp-level update noise)."""
    kw = dict(num_workers=8, num_ps=8, layout="flat", batch_size=256,
              epochs=1, eval_every=0, seed=3)
    r0 = SyncTrainer(
        TrainConfig(**kw), small_dataset, init=small_params
    ).train(log=lambda s: None)
    r1 = SyncTrainer(
        TrainConfig(fused_adam=True, **kw), small_dataset, init=small_params
    ).train(log=lambda s: None)
    for k in r0.params:
        np.testing.assert_allclose(r0.params[k], r1.params[k], atol=1e-5,
                                   err_msg=k)
