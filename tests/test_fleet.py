"""Self-healing serve fleet (ddl_tpu/serve/controller.py, ISSUE 13).

The acceptance chain: a preempted-and-resumed request's tokens are
BIT-IDENTICAL to the same request served unpreempted — pinned via
per-step decode logits at tp=1 AND tp=2 (the KV hand-off moves pages as
bits; sampling keys fold in only (seed, request_id, token_index)); a
seeded ``replica_crash`` mid-decode heals with every in-flight request
completing exactly ONCE (status accounting pinned, tokens identical to
a crash-free run); and the seeded bulk-burst that fires the
``bulk_shed`` alert on a static fleet instead triggers scale-out — the
alert never fires, chat burn stays 0.0 through a full drain cycle, and
two fresh runs replay the controller's event timeline tick-identically.

Budget discipline: the burst arms live in a helper (the test_slo
pattern); the tier-1 tests stay within the tests/test_markers.py audit
bounds — ``max_replicas=`` literals now count into the topology budget
exactly like ``replicas=``.

The seeded bulk-burst and replica-crash specs themselves now live in
``ddl_tpu.serve.scenarios`` (ISSUE 18 dedupe): the pinned tests build
their runs from the SAME named scenarios the ``ddl_tpu sim`` CLI and
the twin bench replay, so the pins and the product scenario library
cannot drift.
"""

import json
import urllib.request

import numpy as np
import pytest

from ddl_tpu.models.transformer import TINY_SPEC
from ddl_tpu.obs import MetricRegistry, Tracer
from ddl_tpu.obs.export import MetricsExporter
from ddl_tpu.obs.goodput import fleet_summary
from ddl_tpu.obs.slo import SloMonitor
from ddl_tpu.resilience.faults import FaultInjector, FaultSpec, parse_fault
from ddl_tpu.serve import (
    AutoscaleConfig,
    ClassSpec,
    FleetController,
    InferenceEngine,
    Request,
    Router,
    RouterConfig,
    Scheduler,
    ServeConfig,
    parse_autoscale_spec,
)
from ddl_tpu.serve.scenarios import BULK_BURST, REPLICA_CRASH

SPEC = TINY_SPEC


def _prompt(n, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(1, SPEC.vocab, size=n, dtype=np.int32)


def _record_decodes(eng, log):
    d0 = eng.decode

    def dec(*a, **k):
        nxt, lg = d0(*a, **k)
        log.append(np.asarray(lg).copy())
        return nxt, lg

    eng.decode = dec


@pytest.mark.parametrize("tp", [1, 2])
def test_preempt_resume_bit_identical(tp):
    """THE hand-off pin: a request preempted mid-decode (pages
    serialized host-side off scheduler A) and resumed on scheduler B
    produces the SAME tokens — and the SAME per-step decode logits,
    bitwise — as the oracle run that never moved, at tp=1 AND tp=2.
    Both pools read byte-whole (reservations included) afterwards."""
    cfg = ServeConfig(spec=SPEC, slots=1, capacity=32, page_size=8,
                      num_pages=8, tensor_parallel=tp)
    req = Request(id=0, prompt=_prompt(6, 3), max_new_tokens=6)
    eng_o = InferenceEngine(cfg)
    logits_o = []
    _record_decodes(eng_o, logits_o)
    done_o, _ = Scheduler(eng_o).run([req])

    eng_a, eng_b = InferenceEngine(cfg), InferenceEngine(cfg)
    logits_ab = []
    _record_decodes(eng_a, logits_ab)
    _record_decodes(eng_b, logits_ab)
    tr = Tracer()
    sa, sb = Scheduler(eng_a, tracer=tr), Scheduler(eng_b, tracer=tr)
    sa.begin()
    sb.begin()
    sa.submit(req)
    for _ in range(3):
        sa.tick()
    pre = sa.preempt(0)
    assert len(pre.generated) == 4  # mid-decode: prefill tick made 2
    assert pre.k.shape[1] == pre.pos.shape[0]  # pages, table order
    sb.adopt(pre)
    while not sb.idle:
        sb.tick()
    done_a, _ = sa.collect()
    done_b, _ = sb.collect()
    sa.release()
    sb.release()
    # Completes exactly once, on the adopting scheduler.
    assert done_a == {} and done_b[0].status == "ok"
    assert done_b[0].tokens == done_o[0].tokens
    # Per-step decode logits: the full device-call sequence across the
    # move equals the oracle's, bitwise.
    assert len(logits_ab) == len(logits_o)
    for got, want in zip(logits_ab, logits_o):
        np.testing.assert_array_equal(got, want)
    # The preempt/resume lifecycle is in the trace, chained by req.
    names = [r["name"] for r in tr.records]
    assert names.index("preempt") < names.index("resume") \
        < names.index("complete")
    # Pools byte-whole: pages freed AND reservations cancelled.
    for eng in (eng_a, eng_b):
        assert eng.pages.free == eng.num_pages
        assert eng.pages.reserved == 0


def test_fleet_preemption_policy_bit_identical():
    """Full-stack preemption: a chat request queued behind a long bulk
    occupant (equal page reservations tie it to replica 0) is unblocked
    when the controller moves the bulk to the replica that freed up —
    chat admits EARLIER than the no-controller oracle, every token of
    every request is bit-identical, and the placement ledger shows the
    move."""
    cfg = ServeConfig(spec=SPEC, slots=1, capacity=32, page_size=8,
                      num_pages=8)
    classes = (ClassSpec("chat", priority=0), ClassSpec("bulk", priority=2))
    reqs = [
        Request(id=0, prompt=_prompt(6, 0), max_new_tokens=16,
                arrival=0, traffic_class="bulk"),
        Request(id=1, prompt=_prompt(6, 1), max_new_tokens=12,
                arrival=0, traffic_class="bulk"),
        Request(id=2, prompt=_prompt(6, 2), max_new_tokens=2,
                arrival=2, traffic_class="chat"),
    ]
    router = Router(RouterConfig(serve=cfg, replicas=2, classes=classes))
    done_o, stats_o = router.run(reqs)
    assert stats_o.placements[2] == 0  # chat queued behind the long bulk

    ctrl = FleetController(AutoscaleConfig(max_replicas=2, min_replicas=2,
                                           preempt_wait_ticks=2))
    reg = MetricRegistry()
    router.registry = reg
    router.controller = ctrl
    ctrl.bind(router)
    router.reset()
    done_p, stats_p = router.run(reqs)
    assert ctrl.preemptions == 1
    assert int(reg.counter("preemptions_total").value()) == 1
    # The move is in the ledger: bulk 0 now lives on replica 1.
    assert stats_p.placements[0] == 1
    assert stats_p.fleet["preemptions"] == 1
    # Chat admitted strictly earlier than the oracle run.
    assert done_p[2].admitted_step < done_o[2].admitted_step
    # Every request's tokens bit-identical to the unpreempted run.
    assert {i: done_p[i].tokens for i in done_p} == \
        {i: done_o[i].tokens for i in done_o}
    assert all(done_p[i].status == "ok" for i in done_p)
    names = [r["name"] for r in router.tracer.records]
    assert "preempt" in names and "resume" in names \
        and "preempt_move" in names


def test_replica_crash_heals_and_completes_exactly_once():
    """THE crash pin: a seeded replica_crash mid-decode kills replica 1
    wholesale; its in-flight and queued requests requeue at the door
    (trace + counters), the fleet heals (min_replicas), and EVERY
    request completes exactly once with status "ok" and tokens
    identical to a crash-free run — the "requeued" placeholder is
    overwritten exactly once, router_requests_total counts each arrival
    once, and the crashed replica's stats slot reads None.

    The whole run — seeded requests, topology, fault schedule,
    autoscale policy — is built from the named REPLICA_CRASH scenario
    (serve.scenarios), the same definition the sim CLI and twin bench
    replay."""
    reqs = REPLICA_CRASH.build_traffic(SPEC.vocab)
    router = Router(REPLICA_CRASH.router_config(SPEC))
    done_o, stats_o = router.run(reqs)

    ctrl = REPLICA_CRASH.make_controller()
    reg = MetricRegistry()
    router.registry = reg
    router.controller = ctrl
    ctrl.bind(router)
    router.reset()
    done_c, stats_c = router.run(reqs)
    assert ctrl.crashes == 1 and ctrl.requeues >= 1
    crash = [r for r in router.tracer.records
             if r["name"] == "replica_crash"]
    assert len(crash) == 1 and crash[0]["attrs"]["replica"] == 1
    # Mid-decode: the crash caught at least one in-flight occupant.
    assert crash[0]["attrs"]["inflight"] >= 1
    assert [r["name"] for r in router.tracer.records].count("requeue") \
        == ctrl.requeues
    # Exactly-once accounting: every id present once, final status ok,
    # tokens identical to the crash-free oracle (sampling keys ignore
    # replicas and arrival), no "requeued" placeholder left behind.
    assert sorted(done_c) == sorted(done_o)
    for i in done_c:
        assert done_c[i].status == "ok", (i, done_c[i].status)
        assert done_c[i].tokens == done_o[i].tokens, i
    # Per-class tallies count each request once (no double count).
    assert sum(r.requests for r in stats_c.per_class.values()) == len(reqs)
    # SLO samples derive from each request's FINAL serve only: the
    # crashed attempt's token emissions are not folded in, so the
    # per-class ITL sample count equals the crash-free run's (same
    # tokens -> same gap count) instead of gaining duplicated prefix
    # samples plus a crash-spanning gap.
    assert stats_c.per_class["bulk"].itl.steps == \
        stats_o.per_class["bulk"].itl.steps
    # The live router_ttft_seconds histogram holds ONE sample per
    # request — a crash re-serve never observes a second TTFT.
    assert reg.histogram("router_ttft_seconds").count(
        **{"class": "bulk"}
    ) == len(reqs)
    assert int(reg.counter("router_requests_total").value(
        **{"class": "bulk"})) == len(reqs)
    assert int(reg.counter("fleet_crashes_total").value()) == 1
    # The crashed replica's device-side stats died with it; the healed
    # replacement (id 2) collected normally.
    assert stats_c.replica[1] is None
    assert stats_c.replica[0] is not None
    assert stats_c.fleet["crashes"] == 1

    # A crash tick beyond the run's horizon must FAIL loudly at run
    # end (a chaos run that exercised nothing must not pass clean).
    # ctrl's config is reused verbatim (only the injector differs) —
    # which also keeps the test inside the markers-audit cap ledger.
    late = FleetController(
        ctrl.config,
        injector=FaultInjector(FaultSpec(kind="replica_crash",
                                         step=999, replica=0)),
    )
    router.controller = late
    late.bind(router)
    router.reset()
    with pytest.raises(RuntimeError, match="never fired"):
        router.run(reqs[:1])


def _burst_arm(autoscale: bool):
    """The ISSUE 10 seeded bulk-burst scenario (test_slo._burst_run's
    traffic spec, verbatim — now the named BULK_BURST scenario in
    serve.scenarios) with the fleet controller as the only delta: the
    static arm sheds and alerts; the autoscale arm scales out instead.
    Returns (monitor, controller, router stats, done, tracer)."""
    traffic = BULK_BURST.build_traffic(SPEC.vocab)
    reg, tr = MetricRegistry(), Tracer()
    mon = SloMonitor(BULK_BURST.slo_rules(), reg, tracer=tr)
    ctrl = BULK_BURST.make_controller() if autoscale else None
    router = Router(BULK_BURST.router_config(SPEC), registry=reg,
                    tracer=tr, slo_monitor=mon, controller=ctrl)
    done, rstats = router.run(traffic)
    return mon, ctrl, rstats, done, tr


def test_burst_scale_out_instead_of_shed_tick_reproducible():
    """THE scenario pin (ISSUE 13 satellite): the same seeded traffic
    spec that fires the bulk_shed alert on the static fleet instead
    triggers SCALE-OUT — the alert never fires, the door sheds nothing
    (the deferral), total bulk sheds drop, chat burn stays 0.0 through
    a FULL drain cycle (scale_out -> drain -> scale_in all happen), and
    two fresh runs replay the controller's event timeline and every
    token tick-identically."""
    s_mon, _, s_stats, _, _ = _burst_arm(autoscale=False)
    assert s_mon.alerts("bulk_shed") >= 1  # the static arm DOES alert
    assert s_stats.per_class["bulk"].shed > 0

    mon, ctrl, rstats, done, tr = _burst_arm(autoscale=True)
    assert ctrl.scale_outs >= 1 and ctrl.drains >= 1 \
        and ctrl.scale_ins >= 1  # the full cycle
    assert mon.alerts("bulk_shed") == 0  # scale-out replaced the alert
    # The door deferred while the fleet could grow; at max scale it is
    # the backstop again — strictly fewer door sheds AND fewer total
    # bulk sheds than the static arm.
    assert rstats.router_sheds < s_stats.router_sheds
    assert rstats.per_class["bulk"].shed < s_stats.per_class["bulk"].shed
    # Chat stayed green the whole run.
    assert mon.alerts("chat_shed") == 0
    assert mon.burn_rate("chat_shed", "fast") == 0.0
    assert mon.burn_rate("chat_shed", "slow") == 0.0
    assert rstats.per_class["chat"].shed == 0
    kinds = [r["name"] for r in tr.records
             if r["name"] in ("scale_out", "drain", "scale_in")]
    assert kinds and kinds[0] == "scale_out"

    mon2, ctrl2, rstats2, done2, _ = _burst_arm(autoscale=True)
    assert ctrl2.events == ctrl.events  # tick-identical timeline
    assert {i: done2[i].tokens for i in done2} == \
        {i: done[i].tokens for i in done}
    assert {i: done2[i].status for i in done2} == \
        {i: done[i].status for i in done}
    for name in ("bulk_shed", "chat_shed"):
        assert mon2.cumulative(name) == mon.cumulative(name)


def test_drain_stops_routing_then_removes():
    """Drain semantics: once a replica begins draining it receives NO
    routed arrivals (placement skips it) while its occupants finish;
    only then is it collected and removed — its ServeStats survive in
    the stats list and later arrivals all land on the survivor."""
    cfg = ServeConfig(spec=SPEC, slots=1, capacity=32, page_size=8,
                      num_pages=8)
    classes = (ClassSpec("bulk", priority=1),)
    # Two early co-arrivals spread over both replicas; replica 1 then
    # idles past idle_ticks while late arrivals keep replica 0 busy.
    reqs = [
        Request(id=0, prompt=_prompt(6, 20), max_new_tokens=12,
                arrival=0, traffic_class="bulk"),
        Request(id=1, prompt=_prompt(6, 21), max_new_tokens=2,
                arrival=0, traffic_class="bulk"),
        Request(id=2, prompt=_prompt(6, 22), max_new_tokens=2,
                arrival=8, traffic_class="bulk"),
    ]
    ctrl = FleetController(AutoscaleConfig(max_replicas=2, min_replicas=1,
                                           idle_ticks=3, preempt=False,
                                           backlog_per_replica=10.0))
    router = Router(RouterConfig(serve=cfg, replicas=2, classes=classes),
                    controller=ctrl)
    done, stats = router.run(reqs)
    assert all(done[i].status == "ok" for i in done)
    drains = [r for r in router.tracer.records if r["name"] == "drain"]
    assert drains, "replica 1 should have drained mid-run"
    drain_tick = drains[0]["attrs"]["tick"]
    assert drains[0]["attrs"]["replica"] == 1
    # No arrival routed to the draining replica after the drain began.
    for r in router.tracer.records:
        if r["name"] == "route" and r["attrs"]["tick"] >= drain_tick:
            assert r["attrs"]["replica"] != 1
    # Removed from the fleet, stats collected, not crashed.
    assert router.scheds[1] is None
    assert stats.replica[1] is not None
    assert ctrl.scale_ins >= 1


def test_autoscale_spec_and_validation():
    """Loud-config discipline: the --autoscale grammar round-trips, bad
    keys/values and invalid configs are named errors, and a controller
    refuses to bind a router already above its cap."""
    acfg = parse_autoscale_spec(
        "max=4,min=2,backlog=3.5,sustain=3,idle=6,preempt=0,wait=4,"
        "gap=2,burn=bulk_shed|chat_shed,defer=0"
    )
    assert acfg.max_replicas == 4 and acfg.min_replicas == 2
    assert acfg.backlog_per_replica == 3.5 and acfg.sustain_ticks == 3
    assert acfg.idle_ticks == 6 and acfg.preempt is False
    assert acfg.preempt_wait_ticks == 4 and acfg.preempt_priority_gap == 2
    assert acfg.burn_rules == ("bulk_shed", "chat_shed")
    assert acfg.defer_door_shed is False  # the conservative opt-out
    # --max-replicas overrides the spec's max; min defaults to the
    # seed replica count capped at max.
    over = parse_autoscale_spec("max=4", max_replicas=2, replicas=3)
    assert over.max_replicas == 2 and over.min_replicas == 2
    with pytest.raises(ValueError, match="fleet cap"):
        parse_autoscale_spec("backlog=2")
    with pytest.raises(ValueError, match="unknown autoscale key"):
        parse_autoscale_spec("max=2,frob=1")
    with pytest.raises(ValueError, match="bad value"):
        parse_autoscale_spec("max=two")
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscaleConfig(max_replicas=1, min_replicas=2)
    with pytest.raises(ValueError, match="backlog_per_replica"):
        AutoscaleConfig(max_replicas=2, backlog_per_replica=0)
    with pytest.raises(ValueError, match="sustain_ticks"):
        AutoscaleConfig(max_replicas=2, sustain_ticks=0)
    with pytest.raises(ValueError, match="above max_replicas"):
        Router(RouterConfig(serve=ServeConfig(spec=SPEC, slots=1,
                                              capacity=16),
                            replicas=2,
                            classes=(ClassSpec("chat"),)),
               controller=FleetController(AutoscaleConfig(max_replicas=1)))
    assert parse_fault("replica_crash@7:2") == FaultSpec(
        kind="replica_crash", step=7, replica=2
    )
    with pytest.raises(ValueError, match="replica_crash takes"):
        parse_fault("replica_crash@x:y")
    with pytest.raises(ValueError, match="replica"):
        FaultSpec(kind="replica_crash", step=1, replica=-1)


def test_healthz_fleet_digest_and_summary():
    """ISSUE 13 satellite: /healthz carries the fleet digest (replicas
    active/draining, last scale tick, preemptions) via the non-creating
    MetricRegistry.get pattern — present when the controller published,
    absent on a fleet-less registry, and reading creates nothing."""
    reg = MetricRegistry()
    assert fleet_summary(reg) == {}
    assert not [m.name for m in reg.metrics()]  # get created nothing
    reg.gauge("fleet_replicas_active").set(3)
    reg.gauge("fleet_replicas_draining").set(1)
    reg.gauge("fleet_last_scale_tick").set(17)
    reg.counter("preemptions_total").inc(2)
    digest = fleet_summary(reg)
    assert digest == {"replicas_active": 3, "replicas_draining": 1,
                      "last_scale_tick": 17, "preemptions_total": 2}
    with MetricsExporter(reg, 0) as exp:
        health = json.loads(urllib.request.urlopen(
            exp.url("/healthz")
        ).read())
    assert health["status"] == "ok"
    for key, want in digest.items():
        assert health[key] == want


def test_fleet_incident_report_and_chrome_flows():
    """ISSUE 13 satellite: the analyze report renders the fleet-incident
    table from the trace, and the Chrome converter emits the fleet
    events under cat=incident with a preempt -> resume -> complete flow
    chain (keyed by req) and a drain -> scale_in chain (keyed by
    replica)."""
    from ddl_tpu.obs.analyze import build_report
    from ddl_tpu.obs.trace import chrome_trace_events

    tr = Tracer()
    tr.event("scale_out", tick=3, replica=1, reason="pressure")
    tr.event("preempt", req=7, slot=0, step=5, tokens=3)
    tr.event("resume", req=7, slot=0, step=2, tokens=3)
    tr.event("complete", req=7, slot=0, step=9, tokens=6, status="ok")
    tr.event("drain", tick=11, replica=1)
    tr.event("scale_in", tick=12, replica=1)
    rep = build_report(tr.records)
    kinds = [f["kind"] for f in rep["fleet_incidents"]]
    assert kinds == ["scale_out", "preempt", "resume", "drain", "scale_in"]
    assert rep["fleet_incidents"][0] == {"kind": "scale_out", "tick": 3,
                                         "replica": 1,
                                         "reason": "pressure"}
    assert rep["incidents"]["preempt"] == 1
    assert rep["incidents"]["scale_in"] == 1

    events = chrome_trace_events(tr.records)
    incidents = [e for e in events if e.get("cat") == "incident"]
    assert {e["name"] for e in incidents} == {
        "scale_out", "preempt", "resume", "drain", "scale_in"
    }
    assert all(e["s"] == "g" for e in incidents)
    flows = [e for e in events if e.get("cat") == "incident_flow"]
    req_chain = [e for e in flows if e["name"] == "incident:req=7"]
    # s (preempt) -> t (resume) -> f (complete): the hand-off rendered.
    assert [e["ph"] for e in req_chain] == ["s", "t", "f"]
    rep_chain = [e for e in flows if e["name"] == "incident:replica=1"]
    assert [e["ph"] for e in rep_chain] == ["s", "t", "f"]
