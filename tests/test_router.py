"""Multi-tenant router (ddl_tpu/serve/router.py, ISSUE 8).

The acceptance chain: a 1-replica router run is BIT-IDENTICAL (tokens
AND per-device-call logits) to driving the bare ``Scheduler`` on the
same request stream — the router adds policy, never numerics; an
N=2-replica mixed-traffic run is seed-deterministic (tokens and routing
decisions replay exactly); and under a seeded burst, prefix affinity
measurably lifts the chat-class hit rate while BULK (not chat) absorbs
the overload as router sheds — all pinned via trace events, registry
counters and the ``RouterStats``/``ServeStats`` product surfaces, never
private scheduler state.

Budget discipline: the wide burst A/B (two 2-replica routers = four
compiled engines) is ``slow``; the tier-1 pins stay within the
tests/test_markers.py audit bounds (<= 64 est. tokens, <= 2 replicas).
"""

import dataclasses

import numpy as np
import pytest

from ddl_tpu.data.lm import synthesize_mixed_traffic, synthesize_prompts
from ddl_tpu.models.transformer import TINY_SPEC
from ddl_tpu.obs import MetricRegistry
from ddl_tpu.serve import (
    ClassSpec,
    InferenceEngine,
    Request,
    Router,
    RouterConfig,
    Scheduler,
    ServeConfig,
    parse_slo_spec,
    parse_traffic_spec,
)

SPEC = TINY_SPEC


def _record_device_calls(eng, log):
    """Wrap an engine's prefill/decode so every device call's logits
    land in ``log`` — the bit-identity pin compares the full call
    sequence, not just final tokens."""
    d0, p0 = eng.decode, eng.prefill

    def dec(*a, **k):
        nxt, lg = d0(*a, **k)
        log.append(("decode", np.asarray(lg).copy()))
        return nxt, lg

    def pre(*a, **k):
        nxt, lg = p0(*a, **k)
        log.append(("prefill", np.asarray(lg).copy()))
        return nxt, lg

    eng.decode, eng.prefill = dec, pre


@pytest.mark.parametrize("tp", [1, 2])
def test_router_single_replica_bit_identical_to_bare_scheduler(tp):
    """THE transparency pin: one replica behind the router ≡ the bare
    Scheduler on the same staggered stream — same tokens, same
    admitted steps, and the SAME device-call sequence with bitwise-
    equal logits (idle router ticks make no device calls), at tp=1 AND
    tp=2."""
    cfg = ServeConfig(spec=SPEC, slots=2, capacity=32, tensor_parallel=tp)
    prompts = synthesize_prompts(num=5, min_len=3, max_len=9,
                                 vocab=SPEC.vocab, seed=6)
    arrivals = [0, 0, 1, 3, 7]  # co-arrivals AND an idle gap before 7
    reqs = [Request(id=i, prompt=p, max_new_tokens=4, arrival=arrivals[i],
                    traffic_class="chat")
            for i, p in enumerate(prompts)]
    bare_eng = InferenceEngine(cfg)
    bare_log = []
    _record_device_calls(bare_eng, bare_log)
    bare_done, _ = Scheduler(bare_eng).run(reqs)

    router = Router(RouterConfig(serve=cfg, replicas=1,
                                 classes=(ClassSpec("chat"),)))
    router_log = []
    _record_device_calls(router.engines[0], router_log)
    router_done, stats = router.run(reqs)

    assert sorted(router_done) == sorted(bare_done)
    for i in bare_done:
        assert router_done[i].tokens == bare_done[i].tokens, (tp, i)
        assert router_done[i].admitted_step == bare_done[i].admitted_step
    assert len(router_log) == len(bare_log)
    for (kind_a, lg_a), (kind_b, lg_b) in zip(bare_log, router_log):
        assert kind_a == kind_b
        np.testing.assert_array_equal(lg_a, lg_b)
    assert stats.per_class["chat"].ok == 5
    assert sum(stats.per_class["chat"].ttft.steps for _ in [0]) == 5


def test_router_two_replica_mixed_traffic_seed_deterministic():
    """Two runs of the same seeded mixed-traffic stream through one
    2-replica router (reset between) produce identical per-request
    tokens AND identical routing decisions — placement reads only
    deterministic host state (pressure counts, pure prefix probes, the
    sticky family map)."""
    traffic = synthesize_mixed_traffic(
        classes={"chat": dict(rate=0.8, prompt_min=6, prompt_max=10,
                              max_new_tokens=2, families=2,
                              family_prefix_len=4),
                 "bulk": dict(rate=0.4, prompt_min=6, prompt_max=10,
                              max_new_tokens=2)},
        horizon=10, vocab=SPEC.vocab, seed=7, max_requests=12,
    )
    router = Router(RouterConfig(
        serve=ServeConfig(spec=SPEC, slots=2, capacity=32, prefix_slots=2),
        replicas=2,
        classes=(ClassSpec("chat"), ClassSpec("bulk", priority=2)),
        shed_threshold=8,
    ))
    d1, s1 = router.run(traffic)
    router.reset()
    d2, s2 = router.run(traffic)
    assert {i: d1[i].tokens for i in d1} == {i: d2[i].tokens for i in d2}
    assert {i: d1[i].status for i in d1} == {i: d2[i].status for i in d2}
    assert s1.placements == s2.placements
    assert s1.router_sheds == s2.router_sheds
    # Both replicas actually served traffic (the spread is the point).
    assert len(set(s1.placements.values())) == 2
    # Per-class accounting covers every request exactly once.
    assert sum(r.requests for r in s1.per_class.values()) == len(traffic)
    # The SECOND run's SLO stats derive from ITS OWN trace slice only:
    # one TTFT sample per served request, never the previous run's
    # records folded in (a repeated id would pair run 1's `eligible`
    # with run 2's `first_token` — a TTFT spanning the inter-run gap).
    for name, rep in s2.per_class.items():
        assert rep.ttft.steps == rep.ok, (name, rep)


def test_router_affinity_routes_family_to_same_replica():
    """A shared-prefix family lands on ONE replica: the first member
    places by load and seeds the sticky map; staggered siblings follow
    via the live prefix probe (registration landed) or the sticky key
    (co-arrival), so the family never splits — pinned via the route
    trace events and the placement ledger."""
    base = synthesize_prompts(num=1, min_len=9, max_len=9,
                              vocab=SPEC.vocab, seed=11)[0]
    rng = np.random.default_rng(12)
    fam = [np.concatenate([base[:6],
                           rng.integers(1, SPEC.vocab, size=3,
                                        dtype=np.int32)])
           for _ in range(4)]
    reqs = [Request(id=i, prompt=p, max_new_tokens=2, arrival=2 * i,
                    traffic_class="chat")
            for i, p in enumerate(fam)]
    router = Router(RouterConfig(
        serve=ServeConfig(spec=SPEC, slots=2, capacity=32, prefix_slots=2),
        replicas=2, classes=(ClassSpec("chat"),), affinity_window=6,
    ))
    done, stats = router.run(reqs)
    assert all(done[i].status == "ok" for i in range(4))
    replicas = {stats.placements[i] for i in range(4)}
    assert len(replicas) == 1, stats.placements
    assert stats.affinity_placements >= 3  # all but the seeding member
    routes = [r for r in router.tracer.records if r["name"] == "route"]
    assert [r["attrs"]["reason"] for r in routes].count("affinity") >= 3
    # The replica that served the family actually HIT its prefix cache
    # (ServeStats is the replica's product surface).
    k = replicas.pop()
    assert stats.replica[k].prefix_hits >= 1


def test_router_load_balances_without_affinity_signal():
    """Unrelated prompts spread by least backlog: with affinity finding
    nothing (distinct prompts, no families), co-arriving requests split
    across replicas instead of piling onto replica 0."""
    prompts = synthesize_prompts(num=4, min_len=4, max_len=8,
                                 vocab=SPEC.vocab, seed=13)
    reqs = [Request(id=i, prompt=p, max_new_tokens=2,
                    traffic_class="bulk")
            for i, p in enumerate(prompts)]
    router = Router(RouterConfig(
        serve=ServeConfig(spec=SPEC, slots=1, capacity=32),
        replicas=2, classes=(ClassSpec("bulk"),), prefix_affinity=False,
    ))
    done, stats = router.run(reqs)
    assert all(done[i].status == "ok" for i in range(4))
    counts = [sum(1 for v in stats.placements.values() if v == k)
              for k in range(2)]
    assert counts == [2, 2], stats.placements
    assert stats.affinity_placements == 0


def test_router_fully_shed_class_reports_zero_attainment():
    """A class whose every request was shed attained NOTHING: both
    ttft and itl attainment read 0.0 (the vacuous-1.0 ITL escape is
    reserved for classes that actually completed 1-token answers)."""
    chat = Request(id=0, prompt=np.zeros(6, np.int32), max_new_tokens=4,
                   arrival=0, traffic_class="chat")
    bulk = Request(id=1, prompt=np.zeros(6, np.int32), max_new_tokens=4,
                   arrival=1, traffic_class="bulk")
    router = Router(RouterConfig(
        serve=ServeConfig(spec=SPEC, slots=1, capacity=16),
        replicas=1,
        classes=(ClassSpec("chat", priority=0),
                 ClassSpec("bulk", itl_slo_s=1.0, shed_margin=1)),
        shed_threshold=2,
    ))
    done, stats = router.run([chat, bulk])
    assert done[1].status == "shed" and done[0].status == "ok"
    bulk_rep = stats.per_class["bulk"]
    assert bulk_rep.shed == 1 and bulk_rep.ok == 0
    assert bulk_rep.ttft_slo_attained == 0.0
    assert bulk_rep.itl_slo_attained == 0.0
    # The served class keeps its earned attainment.
    assert stats.per_class["chat"].ttft_slo_attained == 1.0


def test_router_validation_and_spec_parsers():
    """Loud-ctor discipline: malformed router configs and spec strings
    are config errors naming the fix, never mid-run surprises."""
    cfg = ServeConfig(spec=SPEC, slots=1, capacity=16)
    with pytest.raises(ValueError, match="replicas"):
        Router(RouterConfig(serve=cfg, replicas=0))
    with pytest.raises(ValueError, match="duplicate traffic class"):
        Router(RouterConfig(serve=cfg, replicas=1,
                            classes=(ClassSpec("a"), ClassSpec("a"))))
    with pytest.raises(ValueError, match="affinity_window"):
        Router(RouterConfig(serve=cfg, replicas=1, affinity_window=1))
    with pytest.raises(ValueError, match="headroom"):
        Router(RouterConfig(serve=cfg, replicas=1,
                            classes=(ClassSpec("bulk", shed_margin=3),),
                            shed_threshold=3))
    router = Router(RouterConfig(serve=cfg, replicas=1,
                                 classes=(ClassSpec("chat"),)))
    with pytest.raises(ValueError, match="unknown traffic_class"):
        router.run([Request(id=0, prompt=np.zeros(4, np.int32),
                            max_new_tokens=1, traffic_class="bulk")])
    with pytest.raises(ValueError, match="duplicate request ids"):
        router.run([
            Request(id=1, prompt=np.zeros(4, np.int32), max_new_tokens=1,
                    traffic_class="chat"),
            Request(id=1, prompt=np.zeros(4, np.int32), max_new_tokens=1,
                    traffic_class="chat"),
        ])

    kw = parse_traffic_spec(
        "horizon=48;seed=3;max_requests=9;burst=10:4:6.5:bulk;"
        "diurnal=0.5:24;"
        "chat:rate=0.6,pmin=8,pmax=24,new=8,families=4,fprefix=6;"
        "bulk:rate=0.3,pmin=8,pmax=32,new=16"
    )
    assert kw["horizon"] == 48 and kw["seed"] == 3
    assert kw["max_requests"] == 9
    assert kw["burst"] == (10, 4, 6.5, "bulk")
    assert kw["diurnal_amplitude"] == 0.5 and kw["diurnal_period"] == 24
    assert kw["classes"]["chat"] == dict(
        rate=0.6, prompt_min=8, prompt_max=24, max_new_tokens=8,
        families=4, family_prefix_len=6,
    )
    with pytest.raises(ValueError, match="unknown traffic key"):
        parse_traffic_spec("bogus=1;chat:rate=1")
    with pytest.raises(ValueError, match="bad key"):
        parse_traffic_spec("chat:rate=1,nope=2")
    with pytest.raises(ValueError, match="no traffic classes"):
        parse_traffic_spec("horizon=8")
    with pytest.raises(ValueError, match="burst takes"):
        parse_traffic_spec("burst=1:2;chat:rate=1")

    specs = parse_slo_spec("chat:ttft=0.5,itl=0.05,priority=0;"
                           "bulk:ttft=60,priority=2,margin=3",
                           {"chat", "bulk", "longdoc"})
    by = {c.name: c for c in specs}
    assert by["chat"].ttft_slo_s == 0.5 and by["chat"].itl_slo_s == 0.05
    assert by["bulk"].priority == 2 and by["bulk"].margin == 3
    assert by["longdoc"].priority == 1  # DEFAULT_CLASS_SPECS fallback
    with pytest.raises(ValueError, match="unknown class"):
        parse_slo_spec("nope:ttft=1", {"chat"})
    with pytest.raises(ValueError, match="bad slo key"):
        parse_slo_spec("chat:frob=1", {"chat"})


@pytest.mark.slow
def test_router_burst_affinity_and_priority_shedding_slow():
    """THE ISSUE 8 scenario pin: a seeded burst overloads a 2-replica
    router. With prefix affinity ON, the chat-class hit rate measurably
    beats affinity OFF (same stream, same replicas), and the overload
    is absorbed by BULK-class router sheds — chat requests all complete
    "ok" — pinned via registry counters ({class=...} labels), trace
    events and the per-replica serve_* registries, not private
    state."""
    traffic = synthesize_mixed_traffic(
        classes={"chat": dict(rate=0.7, prompt_min=8, prompt_max=12,
                              max_new_tokens=2, families=3,
                              family_prefix_len=6),
                 "bulk": dict(rate=0.6, prompt_min=8, prompt_max=12,
                              max_new_tokens=2)},
        horizon=24, vocab=SPEC.vocab, seed=9, burst=(4, 8, 4.0),
        max_requests=28,
    )
    base = RouterConfig(
        serve=ServeConfig(spec=SPEC, slots=2, capacity=32,
                          prefix_slots=3),
        replicas=2,
        classes=(ClassSpec("chat", ttft_slo_s=30.0, priority=0),
                 ClassSpec("bulk", ttft_slo_s=60.0, priority=2)),
        shed_threshold=5,
    )
    hit_rates = {}
    sheds = {}
    for affinity in (True, False):
        reg = MetricRegistry()
        router = Router(dataclasses.replace(base,
                                            prefix_affinity=affinity),
                        registry=reg)
        done, stats = router.run(traffic)
        hits = sum(int(r.counter("serve_prefix_hits_total").value())
                   for r in router.replica_registries)
        lookups = sum(int(r.counter("serve_prefix_lookups_total").value())
                      for r in router.replica_registries)
        hit_rates[affinity] = hits / lookups if lookups else 0.0
        sheds[affinity] = {
            cls: int(reg.counter("router_shed_total").value(
                **{"class": cls}))
            for cls in ("chat", "bulk")
        }
        # Chat absorbed nothing: every chat request completed ok.
        chat = stats.per_class["chat"]
        assert chat.shed == 0 and chat.ok == chat.requests, chat
        assert sheds[affinity]["chat"] == 0
        # The burst DID overload the pool: bulk paid, visibly, both in
        # the class report and the labeled registry counter.
        bulk = stats.per_class["bulk"]
        assert bulk.shed > 0 and sheds[affinity]["bulk"] == bulk.shed
        shed_events = [r for r in router.tracer.records
                       if r["name"] == "router_shed"]
        assert shed_events and all(
            e["attrs"]["cls"] == "bulk" for e in shed_events
        )
        # Per-class SLO accounting spans both classes from ONE trace.
        assert chat.ttft.steps == chat.ok
    # Affinity ON beats OFF on hit rate — the placement policy, not
    # the cache, is what moved (same engines, same stream).
    assert hit_rates[True] > hit_rates[False], hit_rates
