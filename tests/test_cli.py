"""CLI argument → config mapping (the run.sh replacement, SURVEY.md §1
launcher layer). Pure parsing — no training, no device use."""

from ddl_tpu.cli import build_parser, config_from_args


def _cfg(argv):
    return config_from_args(build_parser().parse_args(argv))


def test_sharding_variant_maps_num_ps():
    # reference: run.sh $1=num_ps $2=num_workers (mnist_sync_sharding/run.sh)
    cfg = _cfg(["sync_sharding", "--num-ps", "4", "--num-workers", "8"])
    assert cfg.num_ps == 4
    assert cfg.num_workers == 8
    assert cfg.layout == "block"


def test_greedy_variant_defaults_zigzag():
    cfg = _cfg(["async_sharding_greedy", "--num-ps", "2", "--num-workers", "4"])
    assert cfg.layout == "zigzag"
    assert cfg.num_ps == 2


def test_unsharded_variant_forces_single_ps():
    cfg = _cfg(["sync", "--num-ps", "5", "--num-workers", "4"])
    assert cfg.num_ps == 1  # unsharded variants ignore --num-ps


def test_reference_compat_flags():
    cfg = _cfg(["sync", "--num-workers", "2", "--reference-compat"])
    assert cfg.grad_reduction == "sum"
    assert cfg.shard_data is False
    default = _cfg(["sync", "--num-workers", "2"])
    assert default.grad_reduction == "mean"
    assert default.shard_data is True


def test_reference_hyperparameter_defaults():
    # epoch=1, batch=100, lr=1e-4, keep_prob=0.5, eval every 10
    # (reference worker.py:41-42, model.py:93, worker.py:30,71).
    cfg = _cfg(["single"])
    assert cfg.epochs == 1
    assert cfg.batch_size == 100
    assert cfg.learning_rate == 1e-4
    assert cfg.keep_prob == 0.5
    assert cfg.eval_every == 10
    assert cfg.num_workers == 1


def test_bf16_flag():
    assert _cfg(["single", "--bf16"]).compute_dtype == "bfloat16"
    assert _cfg(["single"]).compute_dtype is None


def test_default_batch_rounds_to_worker_multiple():
    # ADVICE r1: `sync --num-workers 8` must not crash on 100 % 8 != 0.
    cfg = _cfg(["sync", "--num-workers", "8"])
    assert cfg.batch_size == 104
    assert cfg.per_worker_batch() == 13
    # Explicit divisible batch is honored verbatim.
    assert _cfg(["sync", "--num-workers", "8", "--batch-size", "200"]).batch_size == 200
    # Compat stream replicates data — the reference batch stays exactly 100.
    assert _cfg(["sync", "--num-workers", "8", "--reference-compat"]).batch_size == 100


def test_explicit_indivisible_batch_fails_fast():
    import pytest

    with pytest.raises(SystemExit, match="not divisible"):
        _cfg(["sync", "--num-workers", "8", "--batch-size", "100"])
