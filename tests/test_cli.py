"""CLI layer (the run.sh replacement, SURVEY.md §1 launcher layer):
argument → config mapping, plus end-to-end drives of ``main()`` — every
variant trains a tiny run to completion through the real entry point on
the virtual 8-device mesh."""

import json
import subprocess
import sys

import numpy as np
import pytest

from ddl_tpu.cli import build_parser, config_from_args, main


def _cfg(argv):
    return config_from_args(build_parser().parse_args(argv))


def test_sharding_variant_maps_num_ps():
    # reference: run.sh $1=num_ps $2=num_workers (mnist_sync_sharding/run.sh)
    cfg = _cfg(["sync_sharding", "--num-ps", "4", "--num-workers", "8"])
    assert cfg.num_ps == 4
    assert cfg.num_workers == 8
    assert cfg.layout == "block"


def test_greedy_variant_defaults_zigzag():
    cfg = _cfg(["async_sharding_greedy", "--num-ps", "2", "--num-workers", "4"])
    assert cfg.layout == "zigzag"
    assert cfg.num_ps == 2


def test_unsharded_variant_forces_single_ps():
    cfg = _cfg(["sync", "--num-ps", "5", "--num-workers", "4"])
    assert cfg.num_ps == 1  # unsharded variants ignore --num-ps


def test_reference_compat_flags():
    cfg = _cfg(["sync", "--num-workers", "2", "--reference-compat"])
    assert cfg.grad_reduction == "sum"
    assert cfg.shard_data is False
    default = _cfg(["sync", "--num-workers", "2"])
    assert default.grad_reduction == "mean"
    assert default.shard_data is True


def test_reference_hyperparameter_defaults():
    # epoch=1, batch=100, lr=1e-4, keep_prob=0.5, eval every 10
    # (reference worker.py:41-42, model.py:93, worker.py:30,71).
    cfg = _cfg(["single"])
    assert cfg.epochs == 1
    assert cfg.batch_size == 100
    assert cfg.learning_rate == 1e-4
    assert cfg.keep_prob == 0.5
    assert cfg.eval_every == 10
    assert cfg.num_workers == 1


def test_bf16_flag():
    assert _cfg(["single", "--bf16"]).compute_dtype == "bfloat16"
    # Off-TPU (this CPU test host) the auto default is fp32; on a TPU
    # platform it would be bf16 (--fp32 to override) — cli._resolve_dtype.
    assert _cfg(["single"]).compute_dtype is None
    assert _cfg(["single", "--fp32"]).compute_dtype is None
    import pytest

    with pytest.raises(SystemExit, match="mutually exclusive"):
        _cfg(["single", "--bf16", "--fp32"])


def test_default_batch_rounds_to_worker_multiple():
    # ADVICE r1: `sync --num-workers 8` must not crash on 100 % 8 != 0.
    cfg = _cfg(["sync", "--num-workers", "8"])
    assert cfg.batch_size == 104
    assert cfg.per_worker_batch() == 13
    # Explicit divisible batch is honored verbatim.
    assert _cfg(["sync", "--num-workers", "8", "--batch-size", "200"]).batch_size == 200
    # Compat stream replicates data — the reference batch stays exactly 100.
    assert _cfg(["sync", "--num-workers", "8", "--reference-compat"]).batch_size == 100


def test_explicit_indivisible_batch_fails_fast():
    with pytest.raises(SystemExit, match="not divisible"):
        _cfg(["sync", "--num-workers", "8", "--batch-size", "100"])


# ---------------------------------------------------------------------------
# End-to-end: main() trains every variant on the 8-device mesh (VERDICT r2
# task 7). --tiny narrow model + small procedural data keep each run to a
# few seconds; the JSON line is the machine-readable contract.

_E2E = [
    "--tiny", "--batch-size", "16", "--synthetic-train", "512",
    "--synthetic-test", "64", "--eval-every", "4", "--json",
]


def _run_main(argv, capsys, *, expect_steps=True):
    assert main(argv) == 0
    out = capsys.readouterr().out
    payload = json.loads(out.strip().splitlines()[-1])
    assert 0.0 <= payload["final_accuracy"] <= 1.0
    if expect_steps:
        assert payload["step_stats"]["steps"] > 0
        assert payload["images_per_sec"] > 0
    return payload


@pytest.mark.parametrize("variant", [
    "single", "sync", "async", "sync_sharding", "async_sharding",
    "sync_sharding_greedy", "async_sharding_greedy",
])
def test_main_end_to_end(variant, capsys):
    argv = [variant] + _E2E
    if variant != "single":
        argv += ["--num-workers", "8"]
    if "sharding" in variant:
        argv += ["--num-ps", "4"]
    payload = _run_main(argv, capsys)
    assert payload["variant"] == variant
    assert payload["config"]["conv_channels"] == [4, 8, 8, 8]


def test_main_lm_end_to_end(capsys):
    """The lm variant (sequence-parallel decoder LM, strategies/seq.py)
    trains end-to-end through main() on the 8-device mesh: ring attention
    over the copy task, JSON contract with tokens_per_sec."""
    payload = _run_main([
        "lm", "--num-workers", "8", "--seq-len", "32", "--vocab", "16",
        "--d-model", "32", "--heads", "2", "--layers", "2", "--d-ff", "64",
        "--train-seqs", "64", "--test-seqs", "16", "--batch-size", "16",
        "--eval-every", "2", "--json",
    ], capsys, expect_steps=False)
    assert payload["variant"] == "lm"
    assert payload["config"]["scheme"] == "ring"
    assert payload["tokens_per_sec"] > 0
    assert np.isfinite(payload["final_loss"])


def test_main_lm_rejects_mnist_only_flags(capsys):
    with pytest.raises(SystemExit, match="--tiny"):
        main(["lm", "--tiny"])
    with pytest.raises(SystemExit, match="--fused-adam"):
        main(["lm", "--fused-adam"])


def test_main_reference_compat_end_to_end(capsys):
    payload = _run_main(
        ["sync", "--num-workers", "8", "--reference-compat"] + _E2E, capsys
    )
    assert payload["config"]["grad_reduction"] == "sum"
    assert payload["config"]["shard_data"] is False


def test_main_conv1_matmul_end_to_end(capsys):
    """--conv1-matmul (patches-matmul first conv) trains end-to-end through
    the DP collective path; model-level numerics parity is pinned by
    tests/test_model.py::test_first_conv_matmul_matches_conv."""
    payload = _run_main(
        ["sync", "--num-workers", "8", "--conv1-matmul"] + _E2E, capsys
    )
    assert payload["config"]["conv1_matmul"] is True


def test_main_checkpoint_resume_roundtrip(tmp_path, capsys):
    d = str(tmp_path / "ckpt")
    args = ["sync_sharding", "--num-workers", "8", "--num-ps", "8",
            "--layout", "flat", "--checkpoint-dir", d] + _E2E
    _run_main(args, capsys)
    # All 32 batches were done by run 1, so the resumed run replays nothing
    # (zero spans dispatched — expect_steps off).
    resumed = _run_main(args + ["--resume"], capsys, expect_steps=False)
    assert resumed["resumed_from_step"] == 32


def test_cli_subprocess_smoke():
    """The real process path: python -m ddl_tpu with an explicit --platform
    (the tunnel sitecustomize override) in a fresh interpreter."""
    proc = subprocess.run(
        [sys.executable, "-m", "ddl_tpu", "sync_sharding_greedy",
         "--platform", "cpu", "--num-workers", "8", "--num-ps", "4"] + _E2E,
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["variant"] == "sync_sharding_greedy"
    assert payload["config"]["layout"] == "zigzag"


def test_cli_sigterm_checkpoints_and_resumes(tmp_path):
    """The real preemption path: SIGTERM to a running `python -m ddl_tpu`
    makes it checkpoint, report preempted=true, and exit 0; a --resume
    invocation finishes the job."""
    import os
    import signal as sig

    d = str(tmp_path / "ck")
    args = [sys.executable, "-m", "ddl_tpu", "single", "--platform", "cpu",
            "--tiny", "--synthetic-train", "512", "--synthetic-test", "64",
            "--batch-size", "64", "--eval-every", "2", "--epochs", "200",
            "--checkpoint-dir", d, "--json"]
    env = dict(os.environ, PYTHONUNBUFFERED="1")
    proc = subprocess.Popen(args, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, env=env)
    try:
        # Wait for training to actually progress, then deliver SIGTERM.
        for line in proc.stdout:
            if line.startswith("epoch:"):
                proc.send_signal(sig.SIGTERM)
                break
        out, err = proc.communicate(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, err[-2000:]
    payload = json.loads(out.strip().splitlines()[-1])
    assert payload["preempted"] is True
    assert os.path.exists(os.path.join(d, "ckpt.npz"))

    resumed = subprocess.run(
        args[:-1] + ["--resume", "--epochs", "1", "--json"],
        capture_output=True, text=True, timeout=240,
    )
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    rp = json.loads(resumed.stdout.strip().splitlines()[-1])
    assert rp["preempted"] is False
    assert rp["resumed_from_step"] > 0


def test_main_serve_prefix_cache_and_chunked_prefill(capsys):
    """The serve variant end-to-end through main() with the ISSUE 4
    flags: a prefix-cache pool plus chunked prefill under a tick
    budget, JSON contract carrying the new SLO fields (ttft/itl) and
    the prefix ledger. Tiny model + 4 tokens/request keeps this inside
    the tier-1 budget."""
    assert main([
        "serve", "--slots", "2", "--capacity", "64", "--max-new-tokens",
        "4", "--num-prompts", "3", "--prompt-min", "6", "--prompt-max",
        "12", "--vocab", "16", "--d-model", "32", "--heads", "2",
        "--layers", "2", "--d-ff", "64", "--prefix-cache", "2",
        "--prefill-chunk", "8", "--prefill-budget", "8", "--json",
    ]) == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["variant"] == "serve"
    assert payload["config"]["prefix_slots"] == 2
    assert payload["config"]["prefill_chunk"] == 8
    assert payload["prefix_lookups"] == 3
    assert payload["ttft_ms"]["p95"] > 0
    assert len(payload["completions"]) == 3
    # ISSUE 8 satellite: the single-engine path tallies one "default"
    # class — same JSON shape the router path fills with real classes.
    assert payload["per_class"] == {
        "default": {"total": 3, "ok": 3, "shed": 0,
                    "deadline_exceeded": 0}
    }
    assert all(len(c["tokens"]) == 4
               for c in payload["completions"].values())


def test_main_serve_paged_pool_end_to_end(capsys):
    """ISSUE 7 CLI surface: ``--page-size``/``--num-pages`` serve the
    same workload on the paged pool (prefix sharing + chunking on — the
    full composition), with the JSON contract carrying the page fields
    and the warmup having compiled the page-count ladders (any jit
    inside the run would still pass, but the run exercises the paged
    warmup path end to end)."""
    assert main([
        "serve", "--slots", "2", "--capacity", "64", "--max-new-tokens",
        "4", "--num-prompts", "3", "--prompt-min", "6", "--prompt-max",
        "12", "--vocab", "16", "--d-model", "32", "--heads", "2",
        "--layers", "2", "--d-ff", "64", "--prefix-cache", "2",
        "--prefill-chunk", "8", "--page-size", "8", "--num-pages", "12",
        "--json",
    ]) == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["variant"] == "serve"
    assert payload["config"]["page_size"] == 8
    assert payload["config"]["num_pages"] == 12
    assert payload["kv_pages_free"] >= 0
    assert len(payload["completions"]) == 3
    assert all(c["status"] == "ok" and len(c["tokens"]) == 4
               for c in payload["completions"].values())


def test_main_serve_slo_rules_and_prom_port(capsys):
    """ISSUE 10 CLI surface: ``--slo-rules`` arms the streaming
    burn-rate monitor on the single-engine serve path (every TTFT
    misses the 1ns target, so the rule alerts) and ``--prom-port 0``
    stands up the /metrics endpoint for the run (ephemeral port,
    printed). The JSON contract carries the per-rule burn/alert
    digest; flag hygiene rejects the flag off the serve variant."""
    assert main([
        "serve", "--slots", "2", "--capacity", "64", "--max-new-tokens",
        "4", "--num-prompts", "3", "--prompt-min", "6", "--prompt-max",
        "12", "--vocab", "16", "--d-model", "32", "--heads", "2",
        "--layers", "2", "--d-ff", "64", "--prom-port", "0",
        "--slo-rules",
        "ttft:metric=serve_ttft_seconds,target=0.000000001,fast=2,slow=4,"
        "objective=0.5",
        "--json",
    ]) == 0
    out = capsys.readouterr().out
    assert "metrics endpoint: http://127.0.0.1:" in out
    payload = json.loads(out.strip().splitlines()[-1])
    row = payload["slo_rules"]["ttft"]
    assert row["alerts"] >= 1 and row["fired_ticks"]
    assert row["slow_burn"] > 1.0
    with pytest.raises(SystemExit, match="--slo-rules does not apply"):
        main(["lm", "--platform", "cpu", "--slo-rules",
              "r:metric=m,target=1"])
    with pytest.raises(SystemExit, match="--slo-rules"):
        main(["serve", "--platform", "cpu", "--slo-rules", "bogus"])


def test_main_serve_router_end_to_end_from_checkpoint(tmp_path, capsys):
    """ISSUE 8 CLI surface: a tiny lm training run leaves a checkpoint;
    ``serve --replicas 2 --traffic ... --slo ...`` serves a mixed
    two-class stream from it through the router — the JSON contract
    carries per-class completion/status tallies (the chaos-chain
    assertion surface), the router summary with per-replica placements,
    and per-completion traffic classes."""
    d = str(tmp_path / "ck")
    model = ["--vocab", "16", "--d-model", "32", "--heads", "2",
             "--layers", "2", "--d-ff", "64"]
    assert main(["lm", "--num-workers", "1", "--seq-scheme", "full",
                 "--seq-len", "16", "--train-seqs", "32", "--test-seqs",
                 "8", "--batch-size", "16", "--eval-every", "2",
                 "--checkpoint-dir", d] + model) == 0
    capsys.readouterr()
    assert main([
        "serve", "--replicas", "2", "--checkpoint-dir", d, "--slots", "2",
        "--capacity", "64", "--prefix-cache", "2", "--shed-threshold", "4",
        "--traffic",
        "horizon=8;max_requests=8;seed=5;"
        "chat:rate=0.9,pmin=6,pmax=10,new=2,families=2,fprefix=4;"
        "bulk:rate=0.5,pmin=6,pmax=10,new=2",
        "--slo", "chat:ttft=30,priority=0;bulk:ttft=60,priority=2",
        "--json"] + model) == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["variant"] == "serve" and payload["replicas"] == 2
    assert len(payload["completions"]) == 8
    classes = {c["traffic_class"] for c in payload["completions"].values()}
    assert classes <= {"chat", "bulk"} and len(classes) == 2
    tallies = payload["per_class"]
    assert sum(row["total"] for row in tallies.values()) == 8
    for row in tallies.values():
        assert row["total"] == row["ok"] + row["shed"] \
            + row["deadline_exceeded"]
    router = payload["router"]
    assert len(router["per_replica_requests"]) == 2
    assert sum(router["per_replica_requests"]) + router["router_sheds"] == 8
    assert set(router["per_class"]) == classes
    for row in router["per_class"].values():
        assert 0.0 <= row["ttft_slo_attained"] <= 1.0


def test_main_serve_router_flag_hygiene():
    """Router flag hygiene both directions: --traffic/--slo without
    --replicas fail loudly, router flags fail on training variants,
    bare-prompt-set flags fail under --replicas, and malformed specs
    are config errors."""
    with pytest.raises(SystemExit, match="--traffic requires --replicas"):
        main(["serve", "--platform", "cpu", "--traffic", "chat:rate=1"])
    with pytest.raises(SystemExit, match="--slo requires --replicas"):
        main(["serve", "--platform", "cpu", "--slo", "chat:ttft=1"])
    with pytest.raises(SystemExit, match="--replicas"):
        main(["lm", "--replicas", "2"])
    with pytest.raises(SystemExit, match="--num-prompts does not apply"):
        main(["serve", "--platform", "cpu", "--replicas", "2",
              "--num-prompts", "5"])
    with pytest.raises(SystemExit, match="serve config error"):
        main(["serve", "--platform", "cpu", "--replicas", "2",
              "--traffic", "chat:rate=1,nope=3"])
    with pytest.raises(SystemExit, match="serve config error"):
        main(["serve", "--platform", "cpu", "--replicas", "2",
              "--traffic", "chat:rate=1,pmin=8,pmax=300,new=8"])
    with pytest.raises(SystemExit, match="serve config error"):
        main(["serve", "--platform", "cpu", "--replicas", "2",
              "--slo", "nope:ttft=1"])
    with pytest.raises(SystemExit, match="--replicas must be >= 1"):
        main(["serve", "--platform", "cpu", "--replicas", "0"])


def test_main_serve_autoscale_end_to_end(capsys):
    """ISSUE 13 CLI surface: ``--autoscale`` + ``--max-replicas`` on a
    bursty stream scales the fleet out and back in; the JSON contract
    carries the controller digest (scale events, drains, the event
    ledger) under router.fleet, and every request resolves to a final
    status."""
    model = ["--vocab", "16", "--d-model", "32", "--heads", "2",
             "--layers", "2", "--d-ff", "64"]
    assert main([
        "serve", "--platform", "cpu", "--replicas", "1", "--slots", "1",
        "--capacity", "64", "--shed-threshold", "2",
        "--autoscale", "backlog=2,sustain=2,idle=4", "--max-replicas", "2",
        "--slo", "bulk:priority=1,margin=1",
        "--traffic",
        "horizon=12;seed=0;max_requests=10;burst=3:4:5.0:bulk;"
        "chat:rate=0.3,pmin=4,pmax=8,new=2;"
        "bulk:rate=0.4,pmin=4,pmax=8,new=2",
        "--json"] + model) == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    fleet = payload["router"]["fleet"]
    assert fleet["max_replicas"] == 2
    assert fleet["scale_outs"] >= 1 and fleet["scale_ins"] >= 1
    assert fleet["crashes"] == 0
    kinds = [e["kind"] for e in fleet["events"]]
    assert "scale_out" in kinds and "drain" in kinds
    for row in payload["per_class"].values():
        assert row["total"] == row["ok"] + row["shed"] \
            + row["deadline_exceeded"]


def test_main_serve_autoscale_flag_hygiene():
    """Fleet flag hygiene: --autoscale needs --replicas, --max-replicas
    needs --autoscale, replica_crash needs the controller, and
    malformed autoscale specs are named config errors."""
    with pytest.raises(SystemExit, match="--autoscale requires --replicas"):
        main(["serve", "--platform", "cpu", "--autoscale", "backlog=2"])
    with pytest.raises(SystemExit,
                       match="--max-replicas requires --autoscale"):
        main(["serve", "--platform", "cpu", "--max-replicas", "2"])
    with pytest.raises(SystemExit, match="--autoscale"):
        main(["lm", "--autoscale", "backlog=2"])
    with pytest.raises(SystemExit, match="fleet cap"):
        main(["serve", "--platform", "cpu", "--replicas", "1",
              "--autoscale", "backlog=2"])
    with pytest.raises(SystemExit, match="unknown autoscale key"):
        main(["serve", "--platform", "cpu", "--replicas", "1",
              "--autoscale", "frob=1", "--max-replicas", "2"])
    with pytest.raises(SystemExit, match="replica_crash needs --autoscale"):
        main(["serve", "--platform", "cpu", "--replicas", "2",
              "--inject-fault", "replica_crash@3:1"])
    with pytest.raises(SystemExit, match="applies to the serve variant"):
        main(["lm", "--inject-fault", "replica_crash@3:1"])


def test_main_serve_rejects_bad_prefix_chunk_flags():
    """Flag hygiene both ways: serve-only prefix/chunk flags fail
    loudly on training variants, and invalid combinations fail as
    config errors, not deep tracebacks."""
    with pytest.raises(SystemExit, match="--prefix-cache"):
        main(["lm", "--prefix-cache", "2"])
    with pytest.raises(SystemExit, match="--prefill-chunk"):
        main(["sync", "--prefill-chunk", "8"])
    with pytest.raises(SystemExit, match="serve config error"):
        main(["serve", "--platform", "cpu", "--prefill-chunk", "12"])
    with pytest.raises(SystemExit, match="serve config error"):
        main(["serve", "--platform", "cpu", "--prefill-budget", "16"])
    # Paged flag hygiene (ISSUE 7), both directions: geometry errors
    # are loud config errors; --num-pages without --page-size too.
    with pytest.raises(SystemExit, match="serve config error"):
        main(["serve", "--platform", "cpu", "--page-size", "12"])
    with pytest.raises(SystemExit, match="serve config error"):
        main(["serve", "--platform", "cpu", "--num-pages", "8"])
    with pytest.raises(SystemExit, match="serve config error"):
        main(["serve", "--platform", "cpu", "--page-size", "8",
              "--num-pages", "2"])  # below --slots (default 4)


def test_main_serve_disagg_speculate_end_to_end(capsys):
    """ISSUE 15 CLI surface: ``--roles`` + ``--speculate`` on a paged
    router fleet serves the stream disaggregated AND speculative — the
    JSON contract carries the disagg digest (role split, hand-off
    ledger) and the speculation acceptance digest, and every request
    resolves ok."""
    model = ["--vocab", "16", "--d-model", "32", "--heads", "2",
             "--layers", "2", "--d-ff", "64"]
    assert main([
        "serve", "--platform", "cpu", "--replicas", "2", "--slots", "2",
        "--capacity", "64", "--page-size", "8",
        "--roles", "prefill=1,decode=1", "--speculate", "2",
        "--traffic",
        "horizon=8;seed=0;max_requests=6;"
        "chat:rate=0.6,pmin=4,pmax=8,new=6",
        "--metrics-out", "/dev/null", "--json"] + model) == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    disagg = payload["router"]["disagg"]
    assert disagg["roles"] == {"prefill": 1, "decode": 1}
    assert disagg["handoffs"] >= 1
    assert disagg["handoff_pages"] >= disagg["handoffs"]
    spec = payload["speculate"]
    assert spec["k"] == 2 and spec["method"] == "ngram"
    assert 0 <= spec["accepted"] <= spec["proposed"]
    for row in payload["per_class"].values():
        assert row["total"] == row["ok"]


def test_main_serve_disagg_speculate_flag_hygiene():
    """ISSUE 15 flag hygiene BOTH WAYS: --roles/--speculate without
    --replicas or on contiguous engines reject loudly with the
    offending combination named; malformed specs are named errors; the
    flags fail on training variants."""
    with pytest.raises(SystemExit, match="--roles .* requires --replicas"):
        main(["serve", "--platform", "cpu",
              "--roles", "prefill=1,decode=1"])
    with pytest.raises(SystemExit,
                       match="--roles .* requires --page-size"):
        main(["serve", "--platform", "cpu", "--replicas", "2",
              "--roles", "prefill=1,decode=1"])
    with pytest.raises(SystemExit,
                       match="--speculate 4 requires --replicas"):
        main(["serve", "--platform", "cpu", "--speculate", "4"])
    with pytest.raises(SystemExit,
                       match="--speculate 4 requires --page-size"):
        main(["serve", "--platform", "cpu", "--replicas", "2",
              "--speculate", "4"])
    with pytest.raises(SystemExit, match="sum to it"):
        main(["serve", "--platform", "cpu", "--replicas", "2",
              "--page-size", "8", "--roles", "prefill=1,decode=2"])
    with pytest.raises(SystemExit, match="no decode-"):
        main(["serve", "--platform", "cpu", "--replicas", "2",
              "--page-size", "8", "--roles", "prefill=2"])
    with pytest.raises(SystemExit, match="draft length"):
        main(["serve", "--platform", "cpu", "--replicas", "2",
              "--page-size", "8", "--speculate", "zero"])
    with pytest.raises(SystemExit, match="unknown method"):
        main(["serve", "--platform", "cpu", "--replicas", "2",
              "--page-size", "8", "--speculate", "4,beam"])
    with pytest.raises(SystemExit, match="--roles"):
        main(["lm", "--roles", "prefill=1,decode=1"])
    with pytest.raises(SystemExit, match="--speculate"):
        main(["lm", "--speculate", "4"])
    # Deep engine validation still surfaces as a config error: greedy
    # is required for greedy-accept.
    with pytest.raises(SystemExit, match="serve config error"):
        main(["serve", "--platform", "cpu", "--replicas", "2",
              "--page-size", "8", "--speculate", "2",
              "--temperature", "0.8"])
