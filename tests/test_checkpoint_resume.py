"""Product-reachable checkpoint / resume / profiling (SURVEY.md §5 gap-fill).

The reference has NO persistence — params die with the TF session
(mnist_sync/model/model.py:109-112) and training is restart-from-scratch.
These tests pin the recovery story the rebuild adds: a run killed
mid-training and resumed from its rolling checkpoint reproduces the
uninterrupted run's params bit-for-bit, for every trainer family.
"""

import os

import numpy as np
import pytest

from ddl_tpu.strategies.async_ps import AsyncTrainer
from ddl_tpu.strategies.sync import SyncTrainer
from ddl_tpu.train import SingleChipTrainer, TrainConfig


class Killer:
    """Log callback that raises after the Nth training-progress line,
    simulating a mid-run crash (the reference would hang forever on a dead
    rank, SURVEY.md §5 'failure detection: none')."""

    def __init__(self, after: int):
        self.after = after
        self.seen = 0

    def __call__(self, msg: str) -> None:
        if msg.startswith("epoch:"):
            self.seen += 1
            if self.seen >= self.after:
                raise KeyboardInterrupt(f"killed at: {msg}")


def _assert_same_params(a: dict, b: dict) -> None:
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_single_killed_and_resumed_mid_epoch(small_dataset, small_params, tmp_path):
    # batch_num=8, eval spans (0,1)(1,2)(3,2)(5,2)(7,1); checkpoint_every=3
    # saves at steps 3 and 7 plus the epoch end. Killing at the 4th eval
    # (batch 6, before the step-7 save) leaves step 3 as the last durable
    # state — a genuinely mid-epoch resume point.
    cfg = TrainConfig(epochs=1, batch_size=256, eval_every=2, seed=5)
    ref = SingleChipTrainer(cfg, small_dataset, init=small_params).train(
        log=lambda s: None
    )

    d = str(tmp_path / "ckpt")
    with pytest.raises(KeyboardInterrupt):
        SingleChipTrainer(cfg, small_dataset, init=small_params).train(
            log=Killer(4), checkpoint_dir=d, checkpoint_every=3
        )
    assert os.path.exists(os.path.join(d, "ckpt.npz"))

    resumed = SingleChipTrainer(cfg, small_dataset, init=small_params).train(
        log=lambda s: None, checkpoint_dir=d, resume=True
    )
    assert resumed.resumed_from_step == 3
    _assert_same_params(ref.params, resumed.params)
    assert resumed.final_accuracy == ref.final_accuracy


def test_sync_sharded_resume_across_epochs(small_dataset, small_params, tmp_path):
    # Epoch-boundary kill: run 1 of 2 epochs with checkpointing, then a
    # fresh trainer resumes epoch 2. Exercises ShardedAdam (ZeRO-1 m/v)
    # round-tripping through the host checkpoint and back onto P(DP_AXIS).
    kw = dict(num_workers=8, num_ps=4, layout="zigzag", batch_size=256,
              eval_every=0, seed=2)
    ref = SyncTrainer(
        TrainConfig(epochs=2, **kw), small_dataset, init=small_params
    ).train(log=lambda s: None)

    d = str(tmp_path / "sync")
    SyncTrainer(
        TrainConfig(epochs=1, **kw), small_dataset, init=small_params
    ).train(log=lambda s: None, checkpoint_dir=d)
    resumed = SyncTrainer(
        TrainConfig(epochs=2, **kw), small_dataset, init=small_params
    ).train(log=lambda s: None, checkpoint_dir=d, resume=True)
    assert resumed.resumed_from_step == 8  # batch_num = 2048/256
    _assert_same_params(ref.params, resumed.params)


def test_async_sharded_resume_across_epochs(small_dataset, small_params, tmp_path):
    kw = dict(num_workers=8, num_ps=8, layout="block", batch_size=64,
              eval_every=0, seed=4)
    ref = AsyncTrainer(
        TrainConfig(epochs=2, **kw), small_dataset, init=small_params
    ).train(log=lambda s: None)

    d = str(tmp_path / "async")
    AsyncTrainer(
        TrainConfig(epochs=1, **kw), small_dataset, init=small_params
    ).train(log=lambda s: None, checkpoint_dir=d)
    resumed = AsyncTrainer(
        TrainConfig(epochs=2, **kw), small_dataset, init=small_params
    ).train(log=lambda s: None, checkpoint_dir=d, resume=True)
    assert resumed.resumed_from_step == 4  # rounds = 2048/(64*8)
    _assert_same_params(ref.params, resumed.params)


def test_resume_without_checkpoint_starts_fresh(small_dataset, small_params, tmp_path):
    cfg = TrainConfig(epochs=1, batch_size=512, eval_every=0, seed=0)
    d = str(tmp_path / "none")
    r = SingleChipTrainer(cfg, small_dataset, init=small_params).train(
        log=lambda s: None, checkpoint_dir=d, resume=True
    )
    assert r.resumed_from_step == 0
    assert os.path.exists(os.path.join(d, "ckpt.npz"))  # saved at epoch end


def test_profile_emits_trace(small_dataset, small_params, tmp_path):
    cfg = TrainConfig(epochs=1, batch_size=512, eval_every=0, seed=0)
    d = str(tmp_path / "trace")
    r = SingleChipTrainer(cfg, small_dataset, init=small_params).train(
        log=lambda s: None, profile_dir=d
    )
    files = [os.path.join(dp, f) for dp, _, fs in os.walk(d) for f in fs]
    assert files, "profiler produced no trace files"
    # Step stats ride along in every result.
    assert r.step_stats is not None and r.step_stats.steps > 0
    assert r.step_stats.images_per_sec > 0


class StopAfter:
    """should_stop callable flipping true at the Nth poll — the
    deterministic stand-in for a SIGTERM flag (polled once per span)."""

    def __init__(self, after: int):
        self.after = after
        self.polls = 0

    def __call__(self) -> bool:
        self.polls += 1
        return self.polls >= self.after


def test_preempted_run_saves_and_resumes(small_dataset, small_params, tmp_path):
    """Graceful preemption: should_stop (the CLI's SIGTERM flag) stops the
    run after the current span WITH a checkpoint, and a --resume run
    finishes the job bit-identically to an uninterrupted one."""
    cfg = TrainConfig(epochs=1, batch_size=256, eval_every=2, seed=5)
    ref = SingleChipTrainer(cfg, small_dataset, init=small_params).train(
        log=lambda s: None
    )

    d = str(tmp_path / "preempt")
    # batch_num=8, spans (0,1)(1,2)(3,2)(5,2)(7,1): stop at the 3rd poll ->
    # 5 of 8 batches done, mid-epoch.
    pre = SingleChipTrainer(cfg, small_dataset, init=small_params).train(
        log=lambda s: None, checkpoint_dir=d, should_stop=StopAfter(3)
    )
    assert pre.preempted
    assert os.path.exists(os.path.join(d, "ckpt.npz"))

    resumed = SingleChipTrainer(cfg, small_dataset, init=small_params).train(
        log=lambda s: None, checkpoint_dir=d, resume=True
    )
    assert resumed.resumed_from_step == 5
    assert not resumed.preempted
    _assert_same_params(ref.params, resumed.params)
    assert resumed.final_accuracy == ref.final_accuracy


def test_preempted_sync_sharded_run_saves_and_resumes(
    small_dataset, small_params, tmp_path
):
    kw = dict(num_workers=8, num_ps=4, layout="block", batch_size=256,
              eval_every=2, seed=2)
    ref = SyncTrainer(
        TrainConfig(epochs=1, **kw), small_dataset, init=small_params
    ).train(log=lambda s: None)

    d = str(tmp_path / "sync-preempt")
    pre = SyncTrainer(
        TrainConfig(epochs=1, **kw), small_dataset, init=small_params
    ).train(log=lambda s: None, checkpoint_dir=d, should_stop=StopAfter(3))
    assert pre.preempted
    resumed = SyncTrainer(
        TrainConfig(epochs=1, **kw), small_dataset, init=small_params
    ).train(log=lambda s: None, checkpoint_dir=d, resume=True)
    assert resumed.resumed_from_step == 5
    _assert_same_params(ref.params, resumed.params)


def test_elastic_resume_across_topologies(small_dataset, small_params, tmp_path):
    """ZeRO-1 optimizer state is checkpointed in LOGICAL (layout-free)
    order, so a run preempted at one topology resumes at another: epoch 1
    on 8 workers / 8 flat shards, epoch 2 on 4 workers / 3 zigzag shards.
    keep_prob=1 + mean reduction make every sync topology step-equivalent,
    so the stitched run must match a single-chip 2-epoch oracle."""
    base = dict(batch_size=256, eval_every=0, keep_prob=1.0, seed=2)
    ref = SingleChipTrainer(
        TrainConfig(epochs=2, **base), small_dataset, init=small_params
    ).train(log=lambda s: None)

    d = str(tmp_path / "elastic")
    SyncTrainer(
        TrainConfig(epochs=1, num_workers=8, num_ps=8, layout="flat", **base),
        small_dataset, init=small_params,
    ).train(log=lambda s: None, checkpoint_dir=d)
    resumed = SyncTrainer(
        TrainConfig(epochs=2, num_workers=4, num_ps=3, layout="zigzag", **base),
        small_dataset, init=small_params,
    ).train(log=lambda s: None, checkpoint_dir=d, resume=True)
    assert resumed.resumed_from_step == 8  # batch_num = 2048/256
    for k in ref.params:
        np.testing.assert_allclose(
            ref.params[k], resumed.params[k], atol=2e-5, err_msg=k
        )


def test_cross_strategy_resume_single_to_sharded(
    small_dataset, small_params, tmp_path
):
    """The elastic checkpoint format (params-shaped m/v) is shared by the
    replicated AdamState and ZeRO-1 ShardedAdam, so resume even crosses
    strategy families: epoch 1 on the single-chip trainer, epoch 2 on the
    8-worker sharded sync trainer, matching the uninterrupted oracle."""
    base = dict(batch_size=256, eval_every=0, keep_prob=1.0, seed=2)
    ref = SingleChipTrainer(
        TrainConfig(epochs=2, **base), small_dataset, init=small_params
    ).train(log=lambda s: None)

    d = str(tmp_path / "cross")
    SingleChipTrainer(
        TrainConfig(epochs=1, **base), small_dataset, init=small_params
    ).train(log=lambda s: None, checkpoint_dir=d)
    resumed = SyncTrainer(
        TrainConfig(epochs=2, num_workers=8, num_ps=4, layout="flat", **base),
        small_dataset, init=small_params,
    ).train(log=lambda s: None, checkpoint_dir=d, resume=True)
    assert resumed.resumed_from_step == 8
    for k in ref.params:
        np.testing.assert_allclose(
            ref.params[k], resumed.params[k], atol=2e-5, err_msg=k
        )


def test_incompatible_checkpoint_is_diagnosed(small_dataset, small_params, tmp_path):
    """Resuming a checkpoint into a DIFFERENT model width fails with a
    diagnosed RuntimeError, not a raw shape ValueError."""
    base = dict(batch_size=512, eval_every=0, seed=0)
    d = str(tmp_path / "mismatch")
    SingleChipTrainer(
        TrainConfig(epochs=1, **base), small_dataset, init=small_params
    ).train(log=lambda s: None, checkpoint_dir=d)
    with pytest.raises(RuntimeError, match="incompatible"):
        SingleChipTrainer(
            TrainConfig(epochs=1, conv_channels=(2, 4, 4, 4),
                        fc_sizes=(16, 8), **base),
            small_dataset,
        ).train(log=lambda s: None, checkpoint_dir=d, resume=True)


def test_cross_cadence_resume_trains_every_batch(
    small_dataset, small_params, tmp_path
):
    """Elastic resume with a DIFFERENT eval cadence than the saving run
    (round-3 advisor, medium): the checkpoint's start_step lands mid-span
    of the resumed run's grid; the resume epoch's spans must realign to
    start exactly there — skipping the whole span would silently drop up
    to eval_every-1 batches while reporting them done. The Adam step
    counter is the no-batch-left-behind oracle: it counts every applied
    update."""
    # Saving run: eval_every=2, checkpoint_every=3 over batch_num=8
    # -> last durable save before the kill is step 3.
    cfg_a = TrainConfig(epochs=1, batch_size=256, eval_every=2, seed=5)
    d = str(tmp_path / "xc")
    with pytest.raises(KeyboardInterrupt):
        SingleChipTrainer(cfg_a, small_dataset, init=small_params).train(
            log=Killer(4), checkpoint_dir=d, checkpoint_every=3
        )

    # Resumed run: eval_every=5 -> fresh spans (0,1)(1..5)(6..7); step 3
    # is mid-span of (1..5). The realigned resume spans are (3..5)(6..7).
    cfg_b = TrainConfig(epochs=1, batch_size=256, eval_every=5, seed=5)
    trainer = SingleChipTrainer(cfg_b, small_dataset, init=small_params)
    resumed = trainer.train(log=lambda s: None, checkpoint_dir=d, resume=True)
    assert resumed.resumed_from_step == 3
    # Every batch trained exactly once: 3 before the kill + 5 after.
    assert int(trainer.opt_state.step) == 8
    # And the result matches an uninterrupted run (span chunking may
    # reassociate float ops across differently-compiled scans: ~1e-6).
    ref = SingleChipTrainer(cfg_a, small_dataset, init=small_params).train(
        log=lambda s: None
    )
    for k in ref.params:
        np.testing.assert_allclose(
            resumed.params[k], ref.params[k], atol=2e-6, err_msg=k
        )


def test_cross_cadence_resume_async_rounds(
    small_dataset, small_params, tmp_path
):
    """Async analogue: the saving run's checkpoint can land mid-chunk of
    the resumed run's round grid; chunks realign so every remaining round
    (and its W pushes) runs. The global push counter t is the oracle."""
    kw = dict(num_workers=8, num_ps=8, layout="block", batch_size=64, seed=4)
    d = str(tmp_path / "xca")
    # Saving run: eval_every=3 -> chunks (0,3)(3,4); checkpoint_every=2
    # saves at round 3 (after the first chunk's eval). Kill at the SECOND
    # eval line — the round-3 save is durable, the epoch-end one never
    # happens.
    with pytest.raises(KeyboardInterrupt):
        AsyncTrainer(
            TrainConfig(epochs=1, eval_every=3, **kw),
            small_dataset, init=small_params,
        ).train(log=Killer(2), checkpoint_dir=d, checkpoint_every=2)

    # Resumed run: eval_every=2 -> fresh chunks (0,2)(2,4); round 3 is
    # mid-chunk of (2,4); realigned resume chunks are (3,4).
    trainer = AsyncTrainer(
        TrainConfig(epochs=1, eval_every=2, **kw),
        small_dataset, init=small_params,
    )
    resumed = trainer.train(log=lambda s: None, checkpoint_dir=d, resume=True)
    assert resumed.resumed_from_step == 3
    # 4 rounds x 8 pushes, every round served exactly once.
    assert int(np.asarray(trainer.state.t)) == 32
