"""Transformer LM + sequence-parallel trainer (models/transformer.py,
strategies/seq.py, data/lm.py).

The oracle chain: ``apply_lm`` with ``full_attention`` on one device is the
reference numerics; the ring/ulysses sharded trainers must reproduce its
losses and gradients on the 8-device virtual mesh, and the copy task —
solvable only by attending ``seq_len//2 - 2`` positions back, across shard
boundaries — certifies cross-shard attention end to end.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.data.lm import synthesize_copy
from ddl_tpu.models import transformer
from ddl_tpu.models.transformer import LMSpec, TINY_SPEC
from ddl_tpu.parallel import ring
from ddl_tpu.strategies.seq import LMResult, SeqConfig, SeqTrainer

SPEC = TINY_SPEC
T = 32  # divisible by the 8-device mesh
B = 4


def _batch(seed=0, n=B, seq_len=T, vocab=SPEC.vocab):
    ds = synthesize_copy(
        num_train=n, num_test=n, seq_len=seq_len, vocab=vocab, seed=seed
    )
    return (
        jnp.asarray(ds.tokens),
        jnp.asarray(ds.targets),
        jnp.asarray(ds.weights),
    )


def _oracle_attn():
    return functools.partial(ring.full_attention, causal=True)


def test_param_count_matches_spec():
    params = transformer.init_lm_params(jax.random.PRNGKey(0), SPEC)
    n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert n == SPEC.num_params()


def test_copy_dataset_shapes_and_mask():
    ds = synthesize_copy(num_train=8, num_test=4, seq_len=16, vocab=16, seed=1)
    assert ds.tokens.shape == (8, 16) and ds.test_tokens.shape == (4, 16)
    # Next-token alignment and the scored window [half-1, T-2).
    np.testing.assert_array_equal(ds.targets[:, :-1], ds.tokens[:, 1:])
    assert ds.weights[:, :7].sum() == 0 and ds.weights[:, 14:].sum() == 0
    np.testing.assert_array_equal(ds.weights[:, 7:14], 1.0)
    # Every scored target is a copy of the token half-2 = 6 positions back.
    t = np.arange(7, 14)
    np.testing.assert_array_equal(ds.targets[:, t], ds.tokens[:, t - 6])
    assert ds.tokens[:, 0].max() == 0  # BOS


def test_rope_offset_consistency():
    """RoPE on a shard with absolute positions == the shard's slice of
    RoPE on the full sequence — the property sequence sharding relies on."""
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 2, 8))
    full = transformer.rope(x, jnp.arange(16), 10000.0)
    shard = transformer.rope(x[:, 8:], 8 + jnp.arange(8), 10000.0)
    np.testing.assert_allclose(
        np.asarray(full[:, 8:]), np.asarray(shard), atol=1e-6
    )


def test_lm_loss_matches_manual_ce():
    tokens, targets, weights = _batch()
    params = transformer.init_lm_params(jax.random.PRNGKey(1), SPEC)
    num, den = transformer.lm_loss_sums(
        params, tokens, targets, weights, SPEC, attn_fn=_oracle_attn()
    )
    logits = transformer.apply_lm(
        params, tokens, SPEC, attn_fn=_oracle_attn()
    )
    lp = jax.nn.log_softmax(logits)
    ce = -np.take_along_axis(
        np.asarray(lp), np.asarray(targets)[..., None], axis=-1
    )[..., 0]
    expect = (ce * np.asarray(weights)).sum()
    np.testing.assert_allclose(float(num), expect, rtol=1e-5)
    assert float(den) == float(np.asarray(weights).sum())


@pytest.mark.parametrize(
    "scheme,workers", [("ring", 8), ("ulysses", 2)]
)
def test_sharded_loss_and_grads_match_oracle(scheme, workers):
    """The trainer's sharded loss program (psum-normalized, shard-offset
    RoPE, cross-shard attention) == single-device full-attention oracle,
    for both the value and the replicated-param gradients. (Ulysses shards
    heads, so its width is capped by TINY_SPEC's 2 heads.)"""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddl_tpu.parallel.mesh import make_mesh_2d
    from ddl_tpu.strategies.seq import _shard_sums

    tokens, targets, weights = _batch(seed=3)
    params = transformer.init_lm_params(jax.random.PRNGKey(4), SPEC)

    def oracle_loss(p):
        num, den = transformer.lm_loss_sums(
            p, tokens, targets, weights, SPEC, attn_fn=_oracle_attn()
        )
        return num / den

    cfg = SeqConfig(num_workers=workers, scheme=scheme, spec=SPEC)
    mesh = make_mesh_2d(1, workers)  # the trainer's [dp, sp] mesh shape
    sums = _shard_sums(cfg, transformer.lm_loss_sums)

    # The trainer's OWN gradient pattern (_step_body / _local_loss_fn):
    # local grads of [this shard's CE sum / psum'd weight total], ONE
    # explicit psum over the mesh axes. No gradient rides a bare
    # psum transpose, so the pattern is exact on every JAX generation
    # (compat.py) — the value check still goes through _shard_sums'
    # psum-normalized program.
    from ddl_tpu.strategies.seq import AXES, _attn_for, _local_loss_fn
    from jax import lax

    def body(p, tk, tg, w):
        local_loss = _local_loss_fn(cfg, _attn_for(cfg), tk, tg, w)
        l_local, grads = jax.value_and_grad(local_loss)(p)
        num, den = sums(p, tk, tg, w)
        return (num / den, lax.psum(l_local, AXES),
                jax.tree.map(lambda g: lax.psum(g, AXES), grads))

    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=(P(), P(), P()),
        check_vma=False,  # local-grads mode: the explicit psum owns it
    )
    seq = NamedSharding(mesh, P(None, "sp"))
    rep = NamedSharding(mesh, P())
    loss_sums, loss, grads = fn(
        jax.device_put(params, rep),
        jax.device_put(tokens, seq),
        jax.device_put(targets, seq),
        jax.device_put(weights, seq),
    )
    l0, g0 = jax.value_and_grad(oracle_loss)(params)
    np.testing.assert_allclose(float(loss_sums), float(l0), rtol=1e-4)
    np.testing.assert_allclose(float(loss), float(l0), rtol=1e-4)
    flat, flat0 = jax.tree.leaves(grads), jax.tree.leaves(g0)
    for a, b in zip(flat, flat0):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-3
        )


def test_seq_trainer_rejects_bad_configs():
    ds = synthesize_copy(num_train=8, num_test=4, seq_len=20, vocab=16, seed=0)
    with pytest.raises(ValueError, match="not divisible"):
        SeqTrainer(SeqConfig(num_workers=8, spec=SPEC), ds)  # 20 % 8 != 0
    ds = synthesize_copy(num_train=8, num_test=4, seq_len=32, vocab=16, seed=0)
    with pytest.raises(ValueError, match="ulysses"):
        SeqTrainer(
            SeqConfig(num_workers=8, scheme="ulysses", spec=SPEC), ds
        )  # 2 heads on 8 devices
    with pytest.raises(ValueError, match="full"):
        SeqTrainer(SeqConfig(num_workers=8, scheme="full", spec=SPEC), ds)
    big = synthesize_copy(num_train=8, num_test=4, seq_len=32, vocab=64, seed=0)
    with pytest.raises(ValueError, match="vocab"):
        SeqTrainer(SeqConfig(num_workers=1, scheme="full", spec=SPEC), big)


def test_seq_trainer_learns_copy_task_ring():
    """End to end on the 8-device mesh: the copy task is unlearnable
    without cross-shard attention (scored targets live half a sequence
    away), so accuracy >> chance certifies the whole sequence-parallel
    training path — sharded loss, ring grads, Adam, eval program."""
    ds = synthesize_copy(
        num_train=256, num_test=64, seq_len=T, vocab=SPEC.vocab, seed=5
    )
    cfg = SeqConfig(
        # 10 epochs, not 6: the copy task's phase transition lands
        # between 6 and 10 depending on the init draw, and the random
        # STREAM behind a given seed differs across JAX generations
        # (jax_threefry_partitionable flipped defaults) — 10 clears the
        # transition on both (measured: 0.13 at 6 vs 0.998 at 10 on the
        # 0.4 line, same exact numerics as W=1).
        epochs=10, batch_size=32, learning_rate=3e-3, eval_every=0,
        num_workers=8, scheme="ring", spec=SPEC, seed=1,
    )
    result = SeqTrainer(cfg, ds).train(log=lambda s: None)
    assert isinstance(result, LMResult)
    chance = 1.0 / (SPEC.vocab - 1)
    assert result.final_accuracy > 10 * chance, (
        result.final_accuracy, result.history
    )
    assert np.isfinite(result.final_loss)
    assert result.tokens_per_sec > 0
    # Deterministic: same config + data => same result.
    again = SeqTrainer(cfg, ds).train(log=lambda s: None)
    assert again.final_accuracy == result.final_accuracy


def test_seq_trainer_schemes_agree():
    """ring (W=8), ulysses (W=2, head-divisible), and full (W=1) are the
    same math: short identical trainings land within fp tolerance of each
    other in final loss."""
    ds = synthesize_copy(
        num_train=64, num_test=32, seq_len=T, vocab=SPEC.vocab, seed=6
    )
    results = {}
    for scheme, w in (("full", 1), ("ring", 8), ("ulysses", 2)):
        cfg = SeqConfig(
            epochs=1, batch_size=16, learning_rate=1e-3, eval_every=0,
            num_workers=w, scheme=scheme, spec=SPEC, seed=2,
        )
        results[scheme] = SeqTrainer(cfg, ds).train(log=lambda s: None)
    losses = {k: r.final_loss for k, r in results.items()}
    assert np.isclose(losses["ring"], losses["full"], rtol=1e-3), losses
    assert np.isclose(losses["ulysses"], losses["full"], rtol=1e-3), losses
    accs = {k: r.final_accuracy for k, r in results.items()}
    assert max(accs.values()) - min(accs.values()) < 0.02, accs


def test_seq_trainer_bf16_and_target_accuracy():
    """The MXU-dtype path trains, and --target-accuracy stops early at an
    eval boundary (trivial target: any accuracy >= 0)."""
    ds = synthesize_copy(
        num_train=64, num_test=32, seq_len=T, vocab=SPEC.vocab, seed=7
    )
    cfg = SeqConfig(
        epochs=2, batch_size=16, eval_every=2, num_workers=8, scheme="ring",
        spec=SPEC, compute_dtype="bfloat16", target_accuracy=0.0,
    )
    result = SeqTrainer(cfg, ds).train(log=lambda s: None)
    assert np.isfinite(result.final_loss)
    # Early stop: hit at the FIRST eval point (batch index 1 of 4).
    assert result.history[-1][1] <= 2


def test_seq_trainer_checkpoint_resume(tmp_path):
    """Kill-and-resume ≡ uninterrupted: bit-for-bit when the resumed run
    keeps the saving run's cadence (the LM step has no RNG, and identical
    span lengths compile identical programs), and ~fp-identical across a
    DIFFERENT eval cadence (the elastic resume_plan realignment — span
    regrouping reassociates XLA fusion at the 1e-7 level, the same
    envelope the CNN span-parity tests pin)."""
    ds = synthesize_copy(
        num_train=64, num_test=16, seq_len=T, vocab=SPEC.vocab, seed=8
    )
    base = dict(batch_size=16, learning_rate=1e-3, num_workers=8,
                scheme="ring", spec=SPEC, seed=3)
    golden = SeqTrainer(
        SeqConfig(epochs=2, eval_every=0, **base), ds
    ).train(log=lambda s: None)

    # Stop after epoch 0 (epoch-end checkpoint), resume with the SAME
    # cadence: bit-equal.
    ckdir = str(tmp_path / "ck_same")
    SeqTrainer(SeqConfig(epochs=1, eval_every=0, **base), ds).train(
        log=lambda s: None, checkpoint_dir=ckdir
    )
    resumed = SeqTrainer(SeqConfig(epochs=2, eval_every=0, **base), ds).train(
        log=lambda s: None, checkpoint_dir=ckdir, resume=True
    )
    assert resumed.resumed_from_step == 4  # 4 batches = epoch 0
    for a, b in zip(jax.tree.leaves(golden.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert resumed.final_accuracy == golden.final_accuracy

    # Resume under a DIFFERENT cadence (eval every batch): every batch
    # still trains; params agree to span-reassociation tolerance.
    ckdir = str(tmp_path / "ck_cross")
    SeqTrainer(SeqConfig(epochs=1, eval_every=0, **base), ds).train(
        log=lambda s: None, checkpoint_dir=ckdir
    )
    crossed = SeqTrainer(SeqConfig(epochs=2, eval_every=1, **base), ds).train(
        log=lambda s: None, checkpoint_dir=ckdir, resume=True
    )
    assert crossed.resumed_from_step == 4
    assert len(crossed.history) == 4  # one eval per remaining batch
    for a, b in zip(jax.tree.leaves(golden.params),
                    jax.tree.leaves(crossed.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        )


def test_seq_trainer_preemption_saves_and_stops(tmp_path):
    """should_stop flips true after the first span -> trainer saves the
    rolling checkpoint and returns preempted=True without finishing."""
    ds = synthesize_copy(
        num_train=64, num_test=16, seq_len=T, vocab=SPEC.vocab, seed=9
    )
    ckdir = str(tmp_path / "ck")
    calls = {"n": 0}

    def stop():
        calls["n"] += 1
        return calls["n"] > 1

    result = SeqTrainer(
        SeqConfig(epochs=4, batch_size=16, eval_every=2, num_workers=8,
                  scheme="ring", spec=SPEC),
        ds,
    ).train(log=lambda s: None, checkpoint_dir=ckdir, should_stop=stop)
    assert result.preempted
    import os

    assert os.path.exists(os.path.join(ckdir, "ckpt.npz"))


def test_seq_trainer_zero1_matches_replicated():
    """zero1 (reduce-scatter + chunk Adam + all_gather) is the same math
    as the replicated update: identical short trainings agree in final
    params to flatten-reassociation tolerance, and the optimizer state
    actually lives sharded (each device holds total/W + padding m/v
    elements — the ZeRO-1 memory claim)."""
    ds = synthesize_copy(
        num_train=64, num_test=32, seq_len=T, vocab=SPEC.vocab, seed=10
    )
    base = dict(epochs=1, batch_size=16, learning_rate=1e-3, eval_every=0,
                num_workers=8, scheme="ring", spec=SPEC, seed=4)
    rep = SeqTrainer(SeqConfig(**base), ds)
    z1 = SeqTrainer(SeqConfig(zero1=True, **base), ds)
    # Shard-resident m/v: one device's addressable shard is the chunk.
    total = z1._plan.total
    per_dev = z1.opt_state.m.addressable_shards[0].data.size
    assert per_dev == -(-total // 8), (per_dev, total)
    r_rep = rep.train(log=lambda s: None)
    r_z1 = z1.train(log=lambda s: None)
    assert np.isclose(r_z1.final_loss, r_rep.final_loss, rtol=1e-4), (
        r_z1.final_loss, r_rep.final_loss
    )
    for a, b in zip(jax.tree.leaves(r_rep.params),
                    jax.tree.leaves(r_z1.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        )


def test_seq_trainer_zero1_checkpoint_cross_strategy(tmp_path):
    """Elastic across the update strategy: a replicated run's epoch-end
    checkpoint resumes under zero1 (params-shaped m/v in the checkpoint),
    and the final params match continuing the replicated run."""
    ds = synthesize_copy(
        num_train=64, num_test=16, seq_len=T, vocab=SPEC.vocab, seed=11
    )
    base = dict(batch_size=16, learning_rate=1e-3, eval_every=0,
                num_workers=8, scheme="ring", spec=SPEC, seed=5)
    golden = SeqTrainer(SeqConfig(epochs=2, **base), ds).train(
        log=lambda s: None
    )
    ckdir = str(tmp_path / "ck")
    SeqTrainer(SeqConfig(epochs=1, **base), ds).train(
        log=lambda s: None, checkpoint_dir=ckdir
    )
    crossed = SeqTrainer(SeqConfig(epochs=2, zero1=True, **base), ds).train(
        log=lambda s: None, checkpoint_dir=ckdir, resume=True
    )
    assert crossed.resumed_from_step == 4
    for a, b in zip(jax.tree.leaves(golden.params),
                    jax.tree.leaves(crossed.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        )


def test_seq_trainer_2d_mesh_matches_1d():
    """data_parallel x sequence-parallel (2x4 over 8 devices) is the same
    math as pure sequence parallel (1x8): identical trainings agree in
    final loss/accuracy (batch halves shard over dp rows; grads pick up
    the dp psum through shard_map's transpose)."""
    ds = synthesize_copy(
        num_train=64, num_test=32, seq_len=T, vocab=SPEC.vocab, seed=12
    )
    base = dict(epochs=2, batch_size=16, learning_rate=1e-3, eval_every=0,
                scheme="ring", spec=SPEC, seed=6)
    r1 = SeqTrainer(
        SeqConfig(num_workers=8, data_parallel=1, **base), ds
    ).train(log=lambda s: None)
    r2 = SeqTrainer(
        SeqConfig(num_workers=4, data_parallel=2, **base), ds
    ).train(log=lambda s: None)
    assert np.isclose(r2.final_loss, r1.final_loss, rtol=1e-3), (
        r1.final_loss, r2.final_loss
    )
    for a, b in zip(jax.tree.leaves(r1.params), jax.tree.leaves(r2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=1e-3
        )


def test_seq_trainer_2d_zero1_matches_replicated():
    """The full composition — dp x sp x ZeRO-1: the combined-axes
    psum_scatter/all_gather update on the 2x4 mesh equals the replicated
    2x4 update, and m/v shards live at total/(dp*sp) per device."""
    ds = synthesize_copy(
        num_train=64, num_test=32, seq_len=T, vocab=SPEC.vocab, seed=13
    )
    base = dict(epochs=1, batch_size=16, learning_rate=1e-3, eval_every=0,
                num_workers=4, data_parallel=2, scheme="ring", spec=SPEC,
                seed=7)
    rep = SeqTrainer(SeqConfig(**base), ds)
    z1 = SeqTrainer(SeqConfig(zero1=True, **base), ds)
    total = z1._plan.total
    assert z1.opt_state.m.addressable_shards[0].data.size == -(-total // 8)
    r_rep = rep.train(log=lambda s: None)
    r_z1 = z1.train(log=lambda s: None)
    assert np.isclose(r_z1.final_loss, r_rep.final_loss, rtol=1e-4)
    for a, b in zip(jax.tree.leaves(r_rep.params),
                    jax.tree.leaves(r_z1.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        )


def test_seq_trainer_2d_rejects_indivisible_batch():
    ds = synthesize_copy(num_train=8, num_test=4, seq_len=32, vocab=16,
                         seed=0)
    with pytest.raises(ValueError, match="data_parallel"):
        SeqTrainer(
            SeqConfig(batch_size=5, num_workers=4, data_parallel=2,
                      spec=SPEC), ds
        )


def test_seq_trainer_zigzag_matches_contiguous():
    """seq_layout='zigzag' is the same computation re-placed: identical
    trainings (ring, W=8) agree with the contiguous layout in final
    loss/params to attention-reassociation tolerance, and the copy task
    still trains (the permuted loss mask follows its tokens). Also
    composes with zero1."""
    ds = synthesize_copy(
        num_train=64, num_test=32, seq_len=T, vocab=SPEC.vocab, seed=16
    )
    base = dict(epochs=2, batch_size=16, learning_rate=1e-3, eval_every=0,
                num_workers=8, scheme="ring", spec=SPEC, seed=9)
    cont = SeqTrainer(SeqConfig(**base), ds).train(log=lambda s: None)
    zz = SeqTrainer(
        SeqConfig(seq_layout="zigzag", **base), ds
    ).train(log=lambda s: None)
    assert np.isclose(zz.final_loss, cont.final_loss, rtol=1e-3), (
        zz.final_loss, cont.final_loss
    )
    assert abs(zz.final_accuracy - cont.final_accuracy) < 0.02
    for a, b in zip(jax.tree.leaves(cont.params), jax.tree.leaves(zz.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=1e-3
        )
    zz1 = SeqTrainer(
        SeqConfig(seq_layout="zigzag", zero1=True, **base), ds
    ).train(log=lambda s: None)
    assert np.isclose(zz1.final_loss, cont.final_loss, rtol=1e-3)


def test_seq_trainer_zigzag_rejects_bad_configs():
    ds = synthesize_copy(num_train=8, num_test=4, seq_len=32, vocab=16,
                         seed=0)
    with pytest.raises(ValueError, match="ring"):
        SeqTrainer(
            SeqConfig(num_workers=2, scheme="ulysses", seq_layout="zigzag",
                      spec=SPEC), ds
        )
    ds24 = synthesize_copy(num_train=8, num_test=4, seq_len=24, vocab=16,
                           seed=0)
    with pytest.raises(ValueError, match="zigzag"):
        SeqTrainer(
            SeqConfig(num_workers=8, scheme="ring", seq_layout="zigzag",
                      spec=SPEC), ds24
        )  # 24 % 8 == 0 but 24 % 16 != 0 — only zigzag rejects
    big_test = synthesize_copy(num_train=8, num_test=4, seq_len=32, vocab=16,
                               seed=0)
    # Test-split vocab overflow is caught too (JAX clamps gathers
    # silently — round-4 advisor): corrupt ONLY the test tokens.
    big_test.test_tokens[0, 0] = SPEC.vocab
    with pytest.raises(ValueError, match="test vocab"):
        SeqTrainer(SeqConfig(num_workers=8, spec=SPEC), big_test)
    with pytest.raises(ValueError, match="exceeds"):
        SeqTrainer(SeqConfig(num_workers=8, batch_size=64, spec=SPEC), ds)


def test_seq_trainer_tensor_parallel_matches_1d():
    """Megatron tp is the same math re-placed: tp=2 trainings (pure tp;
    tp x ring sp; the full dp x sp x tp cube; tp + remat) match the
    single-device oracle's losses/params, and the block weights actually
    live sharded (each device holds H/tp heads' worth of wq)."""
    ds = synthesize_copy(
        num_train=32, num_test=16, seq_len=T, vocab=SPEC.vocab, seed=20
    )
    base = dict(epochs=2, batch_size=16, learning_rate=1e-3, eval_every=0,
                spec=SPEC, seed=11)
    oracle = SeqTrainer(
        SeqConfig(num_workers=1, scheme="full", **base), ds
    ).train(log=lambda s: None)
    configs = {
        "full_tp2": SeqConfig(num_workers=1, scheme="full",
                              tensor_parallel=2, **base),
        "ring2_tp2": SeqConfig(num_workers=2, scheme="ring",
                               tensor_parallel=2, **base),
        "dp2_ring2_tp2": SeqConfig(num_workers=2, data_parallel=2,
                                   tensor_parallel=2, scheme="ring",
                                   **base),
        "ring2_tp2_remat": SeqConfig(num_workers=2, scheme="ring",
                                     tensor_parallel=2, remat=True,
                                     **base),
    }
    for tag, cfg in configs.items():
        tr = SeqTrainer(cfg, ds)
        wq = tr.params["blocks"][0]["wq"]
        e = SPEC.d_model
        assert wq.addressable_shards[0].data.shape == (e, e // 2), tag
        r = tr.train(log=lambda s: None)
        assert np.isclose(r.final_loss, oracle.final_loss, rtol=1e-3), (
            tag, r.final_loss, oracle.final_loss
        )
        for a, b in zip(jax.tree.leaves(oracle.params),
                        jax.tree.leaves(r.params)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5, rtol=1e-3,
                err_msg=tag,
            )


def test_seq_trainer_tp_checkpoint_elastic(tmp_path):
    """Checkpoints are tp-topology-free in BOTH directions: a tp=1 save
    resumes under tp=2 (weights re-shard on load), a tp=2 save — whose
    m/v and block weights live tp-sharded — gathers to the params-shaped
    host form and resumes under tp=1; both match the uninterrupted tp=1
    golden run."""
    ds = synthesize_copy(
        num_train=32, num_test=16, seq_len=T, vocab=SPEC.vocab, seed=21
    )
    base = dict(batch_size=16, learning_rate=1e-3, eval_every=0,
                num_workers=2, scheme="ring", spec=SPEC, seed=12)
    golden = SeqTrainer(SeqConfig(epochs=2, **base), ds).train(
        log=lambda s: None
    )
    for save_tp, resume_tp in ((1, 2), (2, 1)):
        ckdir = str(tmp_path / f"ck_{save_tp}to{resume_tp}")
        SeqTrainer(
            SeqConfig(epochs=1, tensor_parallel=save_tp, **base), ds
        ).train(log=lambda s: None, checkpoint_dir=ckdir)
        crossed = SeqTrainer(
            SeqConfig(epochs=2, tensor_parallel=resume_tp, **base), ds
        ).train(log=lambda s: None, checkpoint_dir=ckdir, resume=True)
        assert crossed.resumed_from_step == 2, (save_tp, resume_tp)
        for a, b in zip(jax.tree.leaves(golden.params),
                        jax.tree.leaves(crossed.params)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4,
                err_msg=f"tp {save_tp}->{resume_tp}",
            )


def test_seq_trainer_tp_rejects_bad_configs():
    ds = synthesize_copy(num_train=8, num_test=4, seq_len=32, vocab=16,
                         seed=0)
    with pytest.raises(ValueError, match="num_heads"):
        SeqTrainer(
            SeqConfig(num_workers=1, scheme="full", tensor_parallel=3,
                      spec=SPEC), ds
        )  # 2 heads % 3
    with pytest.raises(ValueError, match="d_ff"):
        spec5 = LMSpec(vocab=32, d_model=32, num_heads=2, num_layers=1,
                       d_ff=65)
        SeqTrainer(
            SeqConfig(num_workers=1, scheme="full", tensor_parallel=2,
                      spec=spec5), ds
        )
    # zero1 x tensor_parallel is a SUPPORTED composition (the hybrid
    # sharded optimizer) — constructing it must NOT raise.
    SeqTrainer(
        SeqConfig(num_workers=2, scheme="ring", tensor_parallel=2,
                  zero1=True, spec=SPEC), ds
    )


def test_seq_trainer_zero1_tp_matches_replicated_tp_on_cube():
    """The tentpole composition: zero1 x tensor_parallel on the 2x2x2
    dp x sp x tp cube. The hybrid sharded optimizer (tp-sharded weights
    keep tp-local Adam; the replicated subtree's Adam lives as flat
    chunks over the combined dp x sp axes) is the same math as the
    replicated-Adam tp run — identical trainings agree in final
    loss/params — and the state actually lives sharded: the replicated
    subtree's m/v hold rep_total/(dp*sp) elements per device (the
    ~(dp*sp)x optimizer-memory claim) and each tp leaf's m/v mirrors its
    weight shard."""
    ds = synthesize_copy(
        num_train=64, num_test=32, seq_len=T, vocab=SPEC.vocab, seed=23
    )
    base = dict(epochs=2, batch_size=16, learning_rate=1e-3, eval_every=0,
                num_workers=2, data_parallel=2, tensor_parallel=2,
                scheme="ring", spec=SPEC, seed=13)
    rep = SeqTrainer(SeqConfig(**base), ds)
    hyb = SeqTrainer(SeqConfig(zero1=True, **base), ds)
    chunk = -(-hyb._hplan.rep_total // 4)  # dp*sp = 4 owners
    assert hyb.opt_state.m_flat.addressable_shards[0].data.size == chunk
    _, weight_tp = hyb._hplan.split(hyb.params)
    for m_leaf, w_leaf in zip(hyb.opt_state.m_tp, weight_tp):
        assert (m_leaf.addressable_shards[0].data.shape
                == w_leaf.addressable_shards[0].data.shape)
    r_rep = rep.train(log=lambda s: None)
    r_hyb = hyb.train(log=lambda s: None)
    assert np.isclose(r_hyb.final_loss, r_rep.final_loss, rtol=1e-5), (
        r_hyb.final_loss, r_rep.final_loss
    )
    for a, b in zip(jax.tree.leaves(r_rep.params),
                    jax.tree.leaves(r_hyb.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        )


def test_seq_trainer_zero1_tp_checkpoint_elastic(tmp_path):
    """zero1 x tp checkpoints are topology- AND mode-free in both
    directions: a plain sequence-parallel save resumes under the hybrid
    zero1 x tp=2 cube (params-shaped m/v re-shard onto flat dp x sp
    chunks + tp shards on load), and a hybrid save gathers back to the
    params-shaped host form and resumes under plain tp=1; both match
    the uninterrupted plain golden run."""
    ds = synthesize_copy(
        num_train=32, num_test=16, seq_len=T, vocab=SPEC.vocab, seed=24
    )
    base = dict(batch_size=16, learning_rate=1e-3, eval_every=0,
                scheme="ring", spec=SPEC, seed=14)
    plain = dict(num_workers=2)
    hybrid = dict(num_workers=2, data_parallel=2, tensor_parallel=2,
                  zero1=True)
    golden = SeqTrainer(SeqConfig(epochs=2, **plain, **base), ds).train(
        log=lambda s: None
    )
    for tag, save_kw, resume_kw in (
        ("plain->hybrid", plain, hybrid), ("hybrid->plain", hybrid, plain)
    ):
        ckdir = str(tmp_path / f"ck_{tag.replace('->', '_')}")
        SeqTrainer(SeqConfig(epochs=1, **save_kw, **base), ds).train(
            log=lambda s: None, checkpoint_dir=ckdir
        )
        crossed = SeqTrainer(SeqConfig(epochs=2, **resume_kw, **base),
                             ds).train(
            log=lambda s: None, checkpoint_dir=ckdir, resume=True
        )
        assert crossed.resumed_from_step == 2, tag
        for a, b in zip(jax.tree.leaves(golden.params),
                        jax.tree.leaves(crossed.params)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4,
                err_msg=tag,
            )


def test_zero1_tp_step_uses_true_reduce_scatter():
    """The hybrid step's replicated-subtree gradients move via a TRUE
    fused reduce-scatter over the combined (dp, sp) axes — each device
    receives only its ~rep_total/(dp*sp)-element chunk — never a
    full-subtree (or full-flat) all-reduce. Pins the tentpole's
    collective schedule through the same optimized-HLO audit
    benchmarks/collective_bytes.py publishes (the LM analogue of
    test_sync_strategies.test_sharded_step_uses_true_reduce_scatter)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
    from benchmarks.collective_bytes import audit_lm

    row = audit_lm("zero1", 2, 2, tp=2)
    rep_total = row["rep_total"]
    chunk = -(-rep_total // 4)  # dp*sp = 4 chunk owners
    rs = [o for o in row["collectives"] if o["op"] == "reduce-scatter"]
    assert any(o["max_elems"] == chunk for o in rs), (chunk, rs)
    for o in row["collectives"]:
        if o["op"] == "all-reduce":
            # Legit all-reduces remain: scalar loss sums, the tp
            # activation completions, and per-tp-shard weight-grad
            # reductions — all strictly smaller than the replicated
            # subtree a regression to psum-everything would move.
            assert o["max_elems"] < rep_total, o


def test_seq_trainer_remat_same_numbers_less_memory():
    """remat=True is the SAME training computation (jax.checkpoint
    recomputes, never reassociates differently at these sizes — losses
    and params agree to recompute tolerance) with a strictly smaller
    saved-residual footprint at long sequence: the per-block saved state
    drops from the ring sweep's residuals to the block input."""
    ds = synthesize_copy(
        num_train=64, num_test=32, seq_len=T, vocab=SPEC.vocab, seed=17
    )
    base = dict(epochs=1, batch_size=16, learning_rate=1e-3, eval_every=0,
                num_workers=8, scheme="ring", spec=SPEC, seed=10)
    plain = SeqTrainer(SeqConfig(**base), ds).train(log=lambda s: None)
    rem = SeqTrainer(SeqConfig(remat=True, **base), ds).train(
        log=lambda s: None
    )
    assert np.isclose(rem.final_loss, plain.final_loss, rtol=1e-4), (
        rem.final_loss, plain.final_loss
    )
    for a, b in zip(jax.tree.leaves(plain.params),
                    jax.tree.leaves(rem.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        )

    # Memory: pin the autodiff-level contract — bytes of residuals the
    # backward pass SAVES across the fwd/bwd boundary. (XLA:CPU's
    # compiled temp_size does not expose buffer liveness — measured
    # unchanged under remat even as saved residuals drop 122x — so the
    # framework-level quantity is the trustworthy, backend-independent
    # one; jax._src.ad_checkpoint.saved_residuals is the programmatic
    # twin of the public print_saved_residuals. Private symbol: skip
    # the memory half, not the suite, if a JAX upgrade moves it.)
    adc = pytest.importorskip("jax._src.ad_checkpoint")

    T_ = 2048
    params = transformer.init_lm_params(jax.random.PRNGKey(19), SPEC)
    toks = jnp.zeros((2, T_), jnp.int32)
    tgts = jnp.zeros((2, T_), jnp.int32)
    wts = jnp.ones((2, T_), jnp.float32)
    attn = functools.partial(ring.full_attention, causal=True)

    def res_bytes(remat):
        def loss(p):
            n, d = transformer.lm_loss_sums(
                p, toks, tgts, wts, SPEC, attn_fn=attn, remat=remat
            )
            return n / d

        res = adc.saved_residuals(loss, params)
        return sum(
            int(np.prod(r[0].shape)) * r[0].dtype.itemsize
            for r in res if hasattr(r[0], "shape")
        )

    b_plain, b_rem = res_bytes(False), res_bytes(True)
    # Measured 465MB -> 3.8MB at these shapes; require 10x so the bound
    # survives minor autodiff changes without going stale.
    assert b_rem * 10 < b_plain, (b_plain, b_rem)


def test_seq_trainer_activation_memory_scales_with_shard():
    """The product-level memory law (the op-level twin is
    test_ring_attention_memory_is_blockwise): the COMPILED span program's
    per-device temp memory — activations, ring tiles, and the autodiff
    residuals XLA saves across the ring steps — must shrink as the same
    global sequence shards over more devices. At fixed global tokens the
    dominant saved-residual term is W tiles of (T/W)^2 = O(T^2/W), so
    widening W=2 -> W=8 must cut per-device temp by ~4x; require >3x so
    the bound survives fusion/layout drift without going stale."""
    import jax.numpy as jnp

    def temp_bytes(W):
        T_ = 1024
        ds = synthesize_copy(
            num_train=4, num_test=2, seq_len=T_, vocab=SPEC.vocab, seed=20
        )
        tr = SeqTrainer(
            SeqConfig(num_workers=W, scheme="ring", batch_size=4, spec=SPEC),
            ds,
        )
        xs = tr.stage_batches(ds.tokens, 1, 4)
        ys = tr.stage_batches(ds.targets, 1, 4)
        ws = tr.stage_batches(ds.weights, 1, 4)
        c = tr.span_program(1).lower(
            tr.params, tr.opt_state, xs, ys, ws, jnp.int32(0)
        ).compile()
        return c.memory_analysis().temp_size_in_bytes

    t2, t8 = temp_bytes(2), temp_bytes(8)
    assert t2 > 3 * t8, (t2, t8)


def test_flash_attention_matches_oracle():
    """ops/attention.py off-TPU routes the kernel's pure-JAX reference —
    fwd and grads must match the repo oracle (the TPU Pallas kernel is
    the same math; lm_bench measures it on hardware)."""
    from ddl_tpu.ops.attention import flash_attention_bthd

    key = jax.random.PRNGKey(14)
    q, k, v = (jax.random.normal(s, (2, 64, 4, 16))
               for s in jax.random.split(key, 3))
    oracle = ring.full_attention(q, k, v, causal=True)
    got = flash_attention_bthd(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(oracle),
                               atol=2e-6, rtol=1e-5)
    g1 = jax.grad(lambda q: (ring.full_attention(q, k, v, causal=True) ** 2)
                  .sum())(q)
    g2 = jax.grad(lambda q: (flash_attention_bthd(q, k, v, causal=True) ** 2)
                  .sum())(q)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(g1),
                               atol=1e-5, rtol=1e-4)
    # bf16 inputs: output dtype follows q, accumulation stays fp32 (the
    # fallback upcasts like the TPU kernel), so the bf16 result rounds
    # the fp32 oracle rather than drifting.
    qb, kb, vb = (a.astype(jnp.bfloat16) for a in (q, k, v))
    got16 = flash_attention_bthd(qb, kb, vb, causal=True)
    assert got16.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got16, dtype=np.float32), np.asarray(oracle),
        atol=5e-2, rtol=5e-2,
    )


def test_seq_trainer_flash_matches_xla():
    """attn_impl='flash' (reference path on the CPU mesh) trains to the
    same result as the einsum kernel, for both schemes that support it;
    ring + flash is rejected."""
    ds = synthesize_copy(
        num_train=64, num_test=32, seq_len=T, vocab=SPEC.vocab, seed=15
    )
    base = dict(epochs=1, batch_size=16, learning_rate=1e-3, eval_every=0,
                spec=SPEC, seed=8)
    for scheme, w in (("full", 1), ("ulysses", 2)):
        xla = SeqTrainer(
            SeqConfig(num_workers=w, scheme=scheme, **base), ds
        ).train(log=lambda s: None)
        fl = SeqTrainer(
            SeqConfig(num_workers=w, scheme=scheme, attn_impl="flash",
                      **base), ds
        ).train(log=lambda s: None)
        assert np.isclose(fl.final_loss, xla.final_loss, rtol=1e-4), (
            scheme, fl.final_loss, xla.final_loss
        )
        for a, b in zip(jax.tree.leaves(xla.params),
                        jax.tree.leaves(fl.params)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5, rtol=1e-3
            )
    with pytest.raises(ValueError, match="flash"):
        SeqTrainer(
            SeqConfig(num_workers=8, scheme="ring", attn_impl="flash",
                      **base), ds
        )
