"""Sync-strategy integration tests on the 8-device virtual CPU mesh
(SURVEY.md §4b): sync-DP ≡ single-process up to float tolerance, sharded ≡
unsharded for every layout, and correct (non-reference-bug) aggregation.

All tests run the narrow-width instance of the architecture family
(conftest.SMALL_SPECS) — strategy code is model-agnostic, so the collective
and sharding paths exercised are identical to the full model at ~1/400 the
single-core cost; full-width numerics are pinned in test_model.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ddl_tpu.data import one_hot
from ddl_tpu.models import cnn
from ddl_tpu.ops import adam_init, adam_update
from ddl_tpu.parallel.mesh import make_mesh
from ddl_tpu.strategies.sync import (
    make_dp_step,
    make_sharded_step,
    resolve_layout,
    sharded_adam_init,
)
from ddl_tpu.train.config import TrainConfig

GB = 32  # global batch


@pytest.fixture(scope="module")
def batch(small_dataset):
    x = jnp.asarray(small_dataset.x_train[:GB])
    y = jnp.asarray(one_hot(small_dataset.y_train[:GB]))
    return x, y


@pytest.fixture(scope="module")
def init(small_params):
    return small_params, adam_init(small_params)


def _sizes(params):
    return {k: int(np.prod(v.shape)) if v.shape else 1 for k, v in params.items()}


def _single_steps(params, opt, x, y, n, lr=1e-4):
    """Oracle: sequential full-batch steps on one device (no dropout)."""
    @jax.jit
    def step(params, opt, x, y):
        grads = jax.grad(cnn.loss_fn)(params, x, y, dropout_rng=None)
        return adam_update(params, opt, grads, lr=lr)

    for _ in range(n):
        params, opt = step(params, opt, x, y)
    return params


def _max_abs_diff(a, b):
    return max(
        jax.tree.leaves(jax.tree.map(lambda u, v: float(jnp.max(jnp.abs(u - v))), a, b))
    )


def test_dp_matches_single_chip(batch, init):
    """psum-mean DP over 8 devices ≡ one-device training on the same global
    batch (keep_prob=1 ⇒ no dropout divergence)."""
    x, y = batch
    params, opt = init
    W = 8
    cfg = TrainConfig(num_workers=W, keep_prob=1.0, batch_size=GB)
    mesh = make_mesh(W)
    step = make_dp_step(cfg, mesh)
    rep = NamedSharding(mesh, P())
    p, o = jax.device_put(params, rep), jax.device_put(opt, rep)
    rng = jax.random.PRNGKey(9)
    for i in range(3):
        p, o, loss = step(p, o, x, y, jax.random.fold_in(rng, i))
    oracle = _single_steps(params, opt, x, y, 3)
    assert _max_abs_diff(p, oracle) < 2e-5


def test_dp_sum_compat_scales_update(batch, init):
    """grad_reduction='sum' reproduces the reference's summed aggregation
    (mnist_sync/parameter_server.py:36-37): equivalent to a single-chip step
    whose gradient is W times larger."""
    x, y = batch
    params, opt = init
    W = 8
    mesh = make_mesh(W)
    cfg = TrainConfig(
        num_workers=W, keep_prob=1.0, batch_size=GB, grad_reduction="sum"
    )
    step = make_dp_step(cfg, mesh)
    p, o, _ = step(params, opt, x, y, jax.random.PRNGKey(0))

    @jax.jit
    def oracle_step(params, opt):
        grads = jax.grad(cnn.loss_fn)(params, x, y, dropout_rng=None)
        grads = jax.tree.map(lambda g: g * W, grads)
        return adam_update(params, opt, grads, lr=cfg.learning_rate)

    op, _ = oracle_step(params, opt)
    assert _max_abs_diff(p, op) < 2e-5


@pytest.mark.parametrize(
    "policy,num_ps",
    # num_ps=14 > 8 devices: the reference's any-split topology
    # (run.sh "14 8"); surplus shards fold round-robin (layout.fold_shards).
    [("flat", 8), ("block", 4), ("zigzag", 7), ("lpt", 8), ("zigzag", 14)],
)
def test_sharded_matches_dp(batch, init, policy, num_ps):
    """ZeRO-1 sharded update ≡ replicated update for every layout policy —
    Adam is elementwise, so ownership layout must not change numerics."""
    x, y = batch
    params, opt = init
    W = 8
    mesh = make_mesh(W)
    cfg = TrainConfig(
        num_workers=W, num_ps=num_ps, layout=policy, keep_prob=1.0, batch_size=GB
    )
    layout = resolve_layout(cfg, W, _sizes(params))
    assert layout is not None
    step = make_sharded_step(cfg, mesh, layout, cnn.param_shapes(params))
    sopt = sharded_adam_init(mesh, layout)
    p = params
    rng = jax.random.PRNGKey(9)
    for i in range(2):
        p, sopt, loss = step(p, sopt, x, y, jax.random.fold_in(rng, i))
    oracle = _single_steps(params, opt, x, y, 2)
    assert _max_abs_diff(p, oracle) < 2e-5


def test_sharded_state_is_sharded(init):
    """The ZeRO-1 memory property: each device holds 1/S of Adam m/v."""
    params, _ = init
    W = 8
    mesh = make_mesh(W)
    cfg = TrainConfig(num_workers=W, num_ps=W, layout="flat", keep_prob=1.0)
    layout = resolve_layout(cfg, W, _sizes(params))
    sopt = sharded_adam_init(mesh, layout)
    shards = sopt.m.addressable_shards
    assert len(shards) == W
    assert shards[0].data.shape[0] * W == sopt.m.shape[0]


def test_multiworker_aggregation_is_mean_not_doubled(batch, init):
    """Regression vs the reference's aliased-buffer double-count bug
    (mnist_sync_sharding/parameter_server.py:43-47,77-80 — SURVEY.md §3.5):
    with identical data on all workers and mean reduction, the aggregated
    gradient equals the single-worker gradient exactly."""
    x, y = batch
    params, opt = init
    W = 8
    mesh = make_mesh(W)
    # shard_data=False: every worker sees the identical full batch.
    cfg = TrainConfig(
        num_workers=W, keep_prob=1.0, batch_size=GB, shard_data=False
    )
    step = make_dp_step(cfg, mesh)
    p, o, _ = step(params, opt, x, y, jax.random.PRNGKey(0))
    oracle = _single_steps(params, opt, x, y, 1)
    assert _max_abs_diff(p, oracle) < 1e-6


def test_sharded_step_uses_true_reduce_scatter(batch, init):
    """The var-aligned sharded step's only all-reduce is the SCALAR loss:
    gradients move via reduce-scatter (each device receives ~max_shard
    elements), never a full-vector all-reduce (every device receiving all
    ``total`` reduced elements — ~2x the reduce bytes on a ring). Pins the
    round-4 collective-schedule fix; benchmarks/collective_bytes.py reports
    the same audit for every policy."""
    x, y = batch
    params, _ = init
    W = 8
    mesh = make_mesh(W)
    cfg = TrainConfig(
        num_workers=W, num_ps=7, layout="zigzag", keep_prob=1.0, batch_size=GB
    )
    layout = resolve_layout(cfg, W, _sizes(params))
    step = make_sharded_step(cfg, mesh, layout, cnn.param_shapes(params))
    sopt = sharded_adam_init(mesh, layout)
    txt = step.lower(
        params, sopt, x, y, jax.random.PRNGKey(0)
    ).compile().as_text()

    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
    from benchmarks.collective_bytes import collective_ops

    ops = collective_ops(txt)
    assert any(o["op"] == "reduce-scatter" for o in ops), (
        "expected a reduce-scatter of the grads"
    )
    # Tuple-aware: max_elems covers every member of a fused result, so a
    # full-vector all-reduce cannot hide behind a scalar sibling.
    for o in ops:
        if o["op"] == "all-reduce":
            assert o["max_elems"] <= 1, f"non-scalar all-reduce survived: {o}"
