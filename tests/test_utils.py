"""Checkpoint + metrics unit tests (gap-fill subsystems, SURVEY.md §5)."""

import jax
import numpy as np
import pytest

from ddl_tpu.models import cnn
from ddl_tpu.ops import adam_init
from ddl_tpu.utils import StepTimer, load_checkpoint, save_checkpoint


def test_checkpoint_roundtrip(tmp_path):
    params = cnn.init_params(jax.random.PRNGKey(0))
    opt = adam_init(params)
    path = tmp_path / "ckpt.npz"
    save_checkpoint(path, {"params": params, "opt": opt}, step=7,
                    extra={"accuracy": 0.99})
    like = {"params": params, "opt": adam_init(params)}
    tree, step, extra = load_checkpoint(path, like)
    assert step == 7
    assert extra["accuracy"] == 0.99
    for n in cnn.PARAM_NAMES:
        np.testing.assert_array_equal(tree["params"][n], np.asarray(params[n]))
    assert int(tree["opt"].step) == 0


def test_checkpoint_shape_mismatch(tmp_path):
    params = cnn.init_params(jax.random.PRNGKey(0))
    path = tmp_path / "ckpt.npz"
    save_checkpoint(path, {"p": params["v13"]})
    with pytest.raises(ValueError):
        load_checkpoint(path, {"p": params["v12"]})


def test_checkpoint_atomic_no_partial(tmp_path):
    # A failed save must not clobber the existing checkpoint.
    path = tmp_path / "ckpt.npz"
    save_checkpoint(path, {"a": np.arange(3.0)}, step=1)

    class Boom:
        pass

    with pytest.raises(Exception):
        save_checkpoint(path, {"a": Boom()})  # not array-convertible
    tree, step, _ = load_checkpoint(path, {"a": np.zeros(3)})
    assert step == 1
    leftovers = [p for p in path.parent.iterdir() if ".tmp" in p.name]
    assert not leftovers


def test_step_timer():
    t = StepTimer(batch_size=10, warmup=1)
    for _ in range(4):
        with t.step():
            pass
    s = t.stats()
    assert s.steps == 3
    assert s.images_per_sec > 0


def _timer_with(times_s):
    """A StepTimer whose recorded step durations are exactly
    ``times_s`` — percentile math must be pinnable on KNOWN samples,
    not on wall-clock noise."""
    t = StepTimer()
    t._times = list(times_s)
    t._images = [1] * len(times_s)
    return t


def test_step_stats_percentiles_known_samples():
    """p50/p95/p99 on [10, 20, 30, 40] ms: the contract is
    np.percentile's LINEAR-INTERPOLATION definition (not nearest-rank) —
    p50 = midpoint 25ms, p95 = 38.5ms, p99 = 39.7ms. A silent switch to
    nearest-rank would report 30/40/40 and skew every serving SLO row
    (BASELINE.md percentile columns)."""
    s = _timer_with([0.010, 0.020, 0.030, 0.040]).stats()
    assert s.steps == 4
    assert s.mean_ms == pytest.approx(25.0)
    assert s.p50_ms == pytest.approx(25.0)
    assert s.p95_ms == pytest.approx(38.5)
    assert s.p99_ms == pytest.approx(39.7)
    assert s.total_s == pytest.approx(0.100)


def test_step_stats_percentiles_n1_n2_edges():
    """The n=1 and n=2 edges, where nearest-rank and interpolation
    definitions diverge most: one sample means EVERY percentile is that
    sample; two samples interpolate between them (p50 = midpoint,
    p95/p99 near — but below — the max; nearest-rank would snap all
    three to the max)."""
    s1 = _timer_with([0.012]).stats()
    assert (s1.p50_ms, s1.p95_ms, s1.p99_ms) == (
        pytest.approx(12.0), pytest.approx(12.0), pytest.approx(12.0)
    )
    s2 = _timer_with([0.010, 0.030]).stats()
    assert s2.p50_ms == pytest.approx(20.0)
    assert s2.p95_ms == pytest.approx(29.0)  # 10 + 0.95 * 20
    assert s2.p99_ms == pytest.approx(29.8)  # 10 + 0.99 * 20
    assert s2.p50_ms < s2.p95_ms < s2.p99_ms < 30.0


def test_step_stats_empty_constructs_all_fields_explicitly():
    """The n=0 StepStats (ISSUE 5 satellite): every field pinned to
    exactly zero BY NAME — the old positional 6-tuple silently leaned
    on the p99_ms default, one field reorder away from assigning a
    percentile into total_s."""
    from ddl_tpu.utils.metrics import StepStats

    z = StepStats.from_times([])
    assert (z.steps, z.mean_ms, z.p50_ms, z.p95_ms, z.p99_ms,
            z.total_s, z.images_per_sec) == (0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0)
    assert z == StepStats(steps=0, mean_ms=0.0, p50_ms=0.0, p95_ms=0.0,
                          p99_ms=0.0, total_s=0.0, images_per_sec=0.0)


def test_step_stats_tokens_per_sec_alias_and_line_unit():
    """``tokens_per_sec`` is the honestly-named read of the throughput
    field for the token-counting paths (LM/serve), and ``line()`` can
    label the unit (ISSUE 5 satellite — token throughput was reported
    under the misnamed img/s)."""
    from ddl_tpu.utils.metrics import StepStats

    s = StepStats.from_times([0.5, 0.5], images=[100, 100])
    assert s.images_per_sec == pytest.approx(200.0)
    assert s.tokens_per_sec == s.images_per_sec
    assert s.line().endswith("200 img/s")
    assert s.line(unit="tok/s").endswith("200 tok/s")


def test_step_stats_warmup_exclusion_and_empty():
    """Warmup steps leave the percentile window (but stay in total_s,
    the throughput bracket); an all-warmup timer yields the zero
    StepStats rather than a nan percentile."""
    t = StepTimer(warmup=2)
    t._times = [1.000, 1.000, 0.010, 0.030]
    t._images = [1, 1, 1, 1]
    s = t.stats()
    assert s.steps == 2
    assert s.p50_ms == pytest.approx(20.0)
    assert t.total_s == pytest.approx(2.040)
    empty = StepTimer(warmup=2)
    empty._times = [1.0]
    empty._images = [1]
    z = empty.stats()
    assert z.steps == 0 and z.p99_ms == 0.0


def test_force_within_passes_normal_and_raises_on_hang():
    """Accelerator-death detection (force_within): a completing fetch is
    transparent, a genuinely wedged one raises with the --resume recovery
    route, and an error inside the fetch surfaces as itself (never masked
    by the timeout message)."""
    import time as _time

    import jax.numpy as jnp
    import pytest

    from ddl_tpu.train import trainer as tr

    # Normal path: completes, no error (timeout generous).
    tr.force_within(jnp.arange(4.0), 30.0, "test fetch")

    # Hang path: monkeypatch-free — a tree whose leaf access blocks.
    class Wedged:
        ndim, size = 1, 1

        def __getitem__(self, idx):
            _time.sleep(60)

    from ddl_tpu.parallel.mesh import AcceleratorTimeout

    with pytest.raises(AcceleratorTimeout, match="--resume"):
        tr.force_within(Wedged(), 0.2, "wedged fetch")

    # <= 0 disables the watchdog entirely (negative is NOT an instant
    # timeout): the wedged fetch is simply not guarded... so use a real
    # tree to prove the call goes straight through.
    tr.force_within(jnp.arange(4.0), -1.0, "unguarded fetch")
    assert tr.guarded(lambda: 7, 0.0, "plain call") == 7

    # Error path: the real exception propagates, not the timeout wording.
    class Broken:
        ndim, size = 1, 1

        def __getitem__(self, idx):
            raise ValueError("device exploded")

    with pytest.raises(ValueError, match="device exploded"):
        tr.force_within(Broken(), 30.0, "broken fetch")


def test_wait_backend_retries_until_window_closes(monkeypatch):
    """wait_backend keeps probing (subprocess probes are retryable, unlike
    the wedged in-process probe) and gives up only when the window closes —
    the behavior that prevents a transient tunnel outage from nulling a
    bench round (BENCH_r03.json)."""
    from ddl_tpu.parallel import mesh

    calls = []

    def fake_probe(timeout_s=120.0):
        calls.append(timeout_s)
        return "tpu" if len(calls) >= 3 else "down"  # up on the third probe

    monkeypatch.setattr(mesh, "probe_backend_subprocess", fake_probe)
    logs = []
    assert mesh.wait_backend(
        window_s=60.0, interval_s=0.01, probe_timeout_s=1.0,
        log=logs.append,
    )
    assert len(calls) == 3
    assert any("retrying" in m for m in logs)
    assert any("after 3 probes" in m for m in logs)

    # Window exhausted: returns False instead of looping forever.
    calls.clear()
    monkeypatch.setattr(mesh, "probe_backend_subprocess",
                        lambda timeout_s=120.0: (calls.append(1), "down")[1])
    assert not mesh.wait_backend(
        window_s=0.05, interval_s=0.01, probe_timeout_s=1.0
    )
    assert len(calls) >= 2  # probed more than once inside the window

    # window_s <= 0 means exactly one probe (the old single-shot behavior).
    calls.clear()
    assert not mesh.wait_backend(window_s=0.0, interval_s=0.01)
    assert len(calls) == 1

    # A live NON-TPU backend is deterministic: fail fast, never retry —
    # a CPU-only host must not spin out the whole window (and a CPU
    # fallback must never greenlight a TPU measurement).
    calls.clear()
    monkeypatch.setattr(mesh, "probe_backend_subprocess",
                        lambda timeout_s=120.0: (calls.append(1), "cpu")[1])
    logs.clear()
    assert not mesh.wait_backend(
        window_s=60.0, interval_s=0.01, probe_timeout_s=1.0, log=logs.append
    )
    assert len(calls) == 1
    assert any("not TPU" in m for m in logs)


def test_probe_backend_subprocess_timeout_is_down():
    """A hung child (the tunnel handshake blocking) reads as 'backend still
    down' — TimeoutExpired maps to "down", never an exception.
    Deterministic regardless of tunnel state: the timeout is shorter than
    Python startup, so the child can never answer in time."""
    from ddl_tpu.parallel.mesh import probe_backend_subprocess

    assert probe_backend_subprocess(timeout_s=0.05) == "down"


def test_bench_cached_last_measured_reads_record(monkeypatch, tmp_path):
    """bench.py's dead-tunnel JSON must carry the LAST REAL hardware
    number, clearly labelled as a cache — and return None (never a
    fabricated block) when no record exists or it is corrupt."""
    import json

    import bench

    rec = {"value": 123456.7, "unit": "images/s", "batch": 2000,
           "mfu_pct": 33.0, "vs_baseline": 300.0}
    results = tmp_path / "benchmarks" / "results"
    results.mkdir(parents=True)
    (results / "bench_tpu.json").write_text(json.dumps(rec))
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    got = bench.cached_last_measured()
    assert got["value"] == 123456.7 and got["mfu_pct"] == 33.0
    assert got["source"] == "benchmarks/results/bench_tpu.json"
    assert "CACHED" in got["note"] and "NOT measured" in got["note"]
    assert got["recorded_utc"].endswith("Z")
    # The derived ratio carries FIELD-LOCAL provenance: a driver parsing
    # .vs_baseline.value can never mistake the stale comparison for a
    # current one (round-5 verdict weak #6).
    assert got["vs_baseline"]["value"] == 300.0
    assert got["vs_baseline"]["measured_utc"] == got["recorded_utc"]
    assert "stale" in got["vs_baseline"]["note"]
    # A record without the ratio simply omits the field (no null stub).
    (results / "bench_tpu.json").write_text(
        json.dumps({**rec, "vs_baseline": None})
    )
    assert "vs_baseline" not in bench.cached_last_measured()
    # A null-value record is a dead-tunnel artifact, not a hardware
    # measurement: relaying it as "CACHED from the last successful run"
    # would launder the failure (round-5 advice #2).
    (results / "bench_tpu.json").write_text(
        json.dumps({**rec, "value": None})
    )
    assert bench.cached_last_measured() is None
    # Corrupt record -> None, not an exception (the error JSON must
    # still be emitted inside the driver's timeout).
    (results / "bench_tpu.json").write_text("{not json")
    assert bench.cached_last_measured() is None
    (results / "bench_tpu.json").unlink()
    assert bench.cached_last_measured() is None


def test_bench_conv_matmul_env_validated_before_probe(monkeypatch):
    """A BENCH_CONV_MATMUL typo must die as a clean SystemExit at config
    time — BEFORE the probe window is spent — not as a KeyError deep in
    jit tracing during the first sweep row (round-5 advice #1)."""
    import pytest

    import bench

    monkeypatch.setenv("BENCH_CONV_MATMUL", "tails")
    with pytest.raises(SystemExit, match="tails"):
        bench._conv_matmul_mode()
    monkeypatch.setenv("BENCH_CONV_MATMUL", "tail")
    assert bench._conv_matmul_mode() == "tail"


def test_steps_scan_matches_lax_scan():
    """steps_scan's three regimes (k==1 inlined, k<=cap unrolled off-TPU,
    k>cap rolled) are all exactly lax.scan semantics: same carry, same
    stacked outputs — the XLA:CPU while-op pathology fix must never change
    what a span computes."""
    import jax
    import jax.numpy as jnp

    from ddl_tpu.train.trainer import SCAN_UNROLL_CAP, steps_scan

    def body(c, xy):
        a, b = xy
        c = c * 0.5 + a - b
        return c, c * 2.0

    for k in (1, 3, SCAN_UNROLL_CAP, SCAN_UNROLL_CAP + 8):
        xs = (jnp.arange(k, dtype=jnp.float32),
              jnp.linspace(0.0, 1.0, k))
        init = jnp.float32(1.0)
        want_c, want_y = jax.lax.scan(body, init, xs)
        got_c, got_y = jax.jit(
            lambda i, x: steps_scan(body, i, x, k)
        )(init, xs)
        np.testing.assert_allclose(got_c, want_c, rtol=1e-6, err_msg=f"k={k}")
        np.testing.assert_allclose(got_y, want_y, rtol=1e-6, err_msg=f"k={k}")
        assert got_y.shape == (k,)
