"""config.target_accuracy early stop (powers benchmarks/time_to_accuracy):
training ends at the FIRST eval that reaches the target, in every trainer
family — the recorded replacement for the reference's eyeball oracle
(accuracy printed, never acted on, mnist_sync/worker.py:71-75)."""

import numpy as np

from ddl_tpu.strategies.async_ps import AsyncTrainer
from ddl_tpu.strategies.sync import SyncTrainer
from ddl_tpu.train import SingleChipTrainer, TrainConfig


def _assert_stopped_at_first_crossing(result, target):
    accs = [a for _, _, a in result.history]
    crossings = [i for i, a in enumerate(accs) if a >= target]
    assert crossings, "target never reached — test setup too hard"
    # Every eval before the stop is below target; the run ended AT the
    # first crossing (no later evals recorded).
    assert crossings[0] == len(accs) - 1
    assert result.final_accuracy >= target or result.final_accuracy == accs[-1]


def test_single_stops_at_target(small_dataset, small_params):
    # A trivially reachable target (random init scores ~0.1 on 10 classes):
    # the run must end at the very first eval, not after 50 epochs.
    cfg = TrainConfig(epochs=50, batch_size=256, eval_every=2,
                      target_accuracy=0.02, seed=0)
    r = SingleChipTrainer(cfg, small_dataset, init=small_params).train(
        log=lambda s: None
    )
    assert len(r.history) == 1
    _assert_stopped_at_first_crossing(r, 0.02)


def test_sync_stops_at_target(small_dataset, small_params):
    cfg = TrainConfig(epochs=50, batch_size=256, eval_every=2,
                      target_accuracy=0.02, seed=0, num_workers=8,
                      num_ps=4, layout="block")
    r = SyncTrainer(cfg, small_dataset, init=small_params).train(
        log=lambda s: None
    )
    assert len(r.history) == 1
    _assert_stopped_at_first_crossing(r, 0.02)


def test_async_stops_at_target(small_dataset, small_params):
    cfg = TrainConfig(epochs=50, batch_size=32, eval_every=2,
                      target_accuracy=0.02, seed=0, num_workers=8)
    r = AsyncTrainer(cfg, small_dataset, init=small_params).train(
        log=lambda s: None
    )
    assert len(r.history) == 1
    _assert_stopped_at_first_crossing(r, 0.02)


def test_unreachable_target_runs_all_epochs(small_dataset, small_params):
    cfg = TrainConfig(epochs=2, batch_size=512, eval_every=3,
                      target_accuracy=1.01, seed=0)
    r = SingleChipTrainer(cfg, small_dataset, init=small_params).train(
        log=lambda s: None
    )
    # 4 batches/epoch -> spans [0],[1..3]; evals at batch 0 and 3 x 2 epochs.
    assert len(r.history) == 4
    assert all(a < 1.01 for _, _, a in r.history)
