"""Fault-tolerant training & serving (ISSUE 6): the deterministic
fault-injection matrix.

Every recovery path the resilience layer claims is driven here by the
seeded injector (``ddl_tpu.resilience.faults``) — never by a mock:

- preemption (a REAL SIGTERM) at an arbitrary step + ``--resume auto``
  reproduces the uninterrupted run's params bit-for-bit (replicated AND
  the hybrid 2x2x2 dp x sp x tp cube);
- a NaN-injected step is SKIPPED in-graph with params unchanged (all
  four seq step bodies + the single-chip CNN step), the run still
  converges, and ``guard=False`` compiles the identical pre-change
  program;
- a corrupt/truncated latest checkpoint is verified out by
  ``find_latest_valid`` and resume proceeds from the previous retained
  save;
- a stalled serve request is evicted at its deadline with its pinned
  prefix refs released, co-resident requests bit-identical either way;
  overload sheds with a structured status.
"""

import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.data.lm import synthesize_copy, synthesize_prompts
from ddl_tpu.models.transformer import TINY_SPEC
from ddl_tpu.resilience import (
    FaultInjector,
    FaultSpec,
    GuardMonitor,
    corrupt_checkpoint,
    parse_fault,
    truncate_checkpoint,
)
from ddl_tpu.strategies.seq import SeqConfig, SeqTrainer
from ddl_tpu.utils.checkpoint import (
    find_latest_valid,
    load_checkpoint,
    load_params,
    save_checkpoint,
    verify_checkpoint,
)

SPEC = TINY_SPEC
T = 32

quiet = lambda s: None


def _copy_ds(seed, num_train=64, num_test=16):
    return synthesize_copy(num_train=num_train, num_test=num_test,
                           seq_len=T, vocab=SPEC.vocab, seed=seed)


def _assert_trees_equal(a, b, **kw):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        if kw:
            np.testing.assert_allclose(np.asarray(x), np.asarray(y), **kw)
        else:
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- checkpoint hardening -----------------------------------------------------


def test_checkpoint_manifest_retention_and_rolling(tmp_path):
    """keep=N retains the last N step-stamped saves (rolling file =
    hardlink of the newest), every save carries a checksum manifest,
    and verify_checkpoint passes on intact files."""
    d = tmp_path / "ck"
    path = d / "ckpt.npz"
    for step in range(1, 6):
        save_checkpoint(path, {"a": np.full(4, float(step))},
                        step=step, keep=3)
    names = sorted(os.listdir(d))
    retained = [n for n in names if n.startswith("ckpt-")
                and n.endswith(".npz")]
    assert retained == [f"ckpt-{s:08d}.npz" for s in (3, 4, 5)]
    assert "ckpt.npz" in names
    for n in retained + ["ckpt.npz"]:
        assert (d / (n + ".manifest.json")).exists()
        assert verify_checkpoint(d / n)
    # Rolling file IS the newest retained save (same content).
    tree, step, _ = load_checkpoint(path, {"a": np.zeros(4)})
    assert step == 5 and tree["a"][0] == 5.0
    found = find_latest_valid(d)
    assert found is not None and found[1] == 5
    # max_step bounds the search (the guard's rollback contract).
    assert find_latest_valid(d, max_step=4)[1] == 4


def test_find_latest_valid_skips_corrupt_and_truncated(tmp_path):
    d = tmp_path / "ck"
    path = d / "ckpt.npz"
    save_checkpoint(path, {"a": np.arange(8.0)}, step=1, keep=3)
    save_checkpoint(path, {"a": np.arange(8.0) + 1}, step=2, keep=3)
    # Corrupt the LATEST (the rolling file is a hardlink of it, so both
    # names go bad together — exactly the torn-latest scenario).
    corrupt_checkpoint(path)
    assert not verify_checkpoint(path)
    assert not verify_checkpoint(d / "ckpt-00000002.npz")
    skipped = []
    found = find_latest_valid(d, log=skipped.append)
    assert found is not None and found[1] == 1
    assert any("skipping" in s for s in skipped)
    tree, step, _ = load_checkpoint(found[0], {"a": np.zeros(8)})
    assert step == 1 and tree["a"][3] == 3.0
    # Truncation of the survivor too -> nothing valid remains.
    truncate_checkpoint(found[0])
    assert find_latest_valid(d) is None


def test_checkpoint_mismatch_error_names_missing_and_unexpected(tmp_path):
    """ISSUE 6 satellite, both directions: the file lacking expected
    leaves names them path-qualified AND names the file's own
    unexpected keys."""
    path = tmp_path / "ckpt.npz"
    save_checkpoint(path, {"a": np.zeros(2), "b": np.ones(2)})
    with pytest.raises(KeyError) as ei:
        load_checkpoint(path, {"a": np.zeros(2), "c": np.zeros(2)})
    msg = str(ei.value)
    assert "['c']" in msg and "missing" in msg
    assert "['b']" in msg and "unexpected" in msg
    # Other direction: template a SUBSET of the file loads fine (extra
    # keys are simply never read — the documented contract).
    tree, _, _ = load_checkpoint(path, {"b": np.zeros(2)})
    assert tree["b"][0] == 1.0


def test_load_params_mismatch_names_keys(tmp_path):
    path = tmp_path / "ckpt.npz"
    save_checkpoint(path, {"params": {"w": np.zeros(3)}, "opt": np.zeros(1)})
    with pytest.raises(KeyError) as ei:
        load_params(path, {"w": np.zeros(3), "missing": np.zeros(2)})
    msg = str(ei.value)
    assert "missing" in msg and "['missing']" in msg
    # Matching subtree still loads from the trainer layout.
    tree, _, _ = load_params(path, {"w": np.zeros(3)})
    assert tree["w"].shape == (3,)


# -- fault specs / guard policy (host-side units) -----------------------------


def test_parse_fault_specs():
    s = parse_fault("nan_grads@3x2")
    assert (s.kind, s.step, s.count, s.once) == ("nan_grads", 3, 2, True)
    assert parse_fault("nan_grads@3x2!").once is False
    assert parse_fault("sigterm@5").step == 5
    assert parse_fault("corrupt_ckpt").kind == "corrupt_ckpt"
    assert parse_fault("stall@7").step == 7
    with pytest.raises(ValueError, match="unknown fault kind"):
        parse_fault("bogus@1")
    with pytest.raises(ValueError, match="integer"):
        parse_fault("nan_grads@x")


def test_guard_monitor_escalation_policy():
    mon = GuardMonitor(max_bad_steps=3, max_rollbacks=1)
    assert not mon.observe([0, 1, 1], first_gstep=0)  # streak of 2
    assert mon.streak_start == 1
    assert not mon.observe([0], first_gstep=3)  # streak broken
    assert mon.streak_start is None
    assert mon.observe([1, 1, 1], first_gstep=4)  # trips at 3
    assert mon.streak_start == 4
    assert mon.skipped_steps == 5
    mon.rolled_back(2)
    assert mon.consecutive == 0 and mon.rollbacks == 1
    mon.observe([1, 1, 1], first_gstep=2)
    with pytest.raises(RuntimeError, match="max_rollbacks"):
        mon.rolled_back(2)
    with pytest.raises(ValueError):
        GuardMonitor(max_bad_steps=-1)


def test_guard_monitor_trip_preserves_streak_start():
    """A healthy flag AFTER the trip inside the same span belongs to
    the abandoned (to-be-replayed) timeline — it must not reset the
    rollback bound (a None streak_start would let the rollback pick a
    checkpoint saved DURING the streak)."""
    mon = GuardMonitor(max_bad_steps=3)
    assert mon.observe([0, 0, 1, 1, 1, 0, 0, 0], first_gstep=10)
    assert mon.streak_start == 12
    # Flags past the trip were discarded unprocessed.
    assert mon.skipped_steps == 3


def test_discard_newer_prunes_abandoned_timeline(tmp_path):
    """Rollback prunes retained saves newer than the rollback step and
    re-points the rolling file at the newest survivor, so a crash
    before the replay overtakes them cannot hand --resume auto (or a
    plain --resume) a stale higher-step file."""
    from ddl_tpu.utils.checkpoint import discard_newer

    d = tmp_path / "ck"
    path = d / "ckpt.npz"
    for step in (1, 2, 3):
        save_checkpoint(path, {"a": np.full(2, float(step))},
                        step=step, keep=3)
    discard_newer(d, 1)
    names = sorted(n for n in os.listdir(d) if n.endswith(".npz"))
    assert names == ["ckpt-00000001.npz", "ckpt.npz"]
    assert find_latest_valid(d)[1] == 1
    tree, step, _ = load_checkpoint(path, {"a": np.zeros(2)})
    assert step == 1 and tree["a"][0] == 1.0
    assert verify_checkpoint(path)


# -- NaN guard: in-graph skip across every step body --------------------------


def _poisoned_span(trainer, ds, batch, *, bs=16, bn=4):
    """(program, args) for a 1-step guarded span whose batch ``batch``
    has one NaN loss weight — the direct params-unchanged pin."""
    prog = trainer.span_program(1, guard=True)
    xs = trainer.stage_batches(ds.tokens, bn, bs)
    ys = trainer.stage_batches(ds.targets, bn, bs)
    w = np.array(ds.weights, copy=True)
    w[batch * bs, 0] = np.nan
    ws = trainer.stage_batches(w, bn, bs)
    return prog, (xs, ys, ws)


def test_seq_guard_skips_nan_step_params_unchanged():
    """Acceptance (b), device half, replicated body: the poisoned step
    leaves params AND optimizer state bit-identical (identity applied
    in-graph) and raises the skip flag; the clean step updates."""
    ds = _copy_ds(8)
    tr = SeqTrainer(SeqConfig(epochs=1, eval_every=0, batch_size=16,
                              num_workers=1, scheme="full", spec=SPEC), ds)
    prog, (xs, ys, ws) = _poisoned_span(tr, ds, batch=1)
    p0 = jax.tree.map(jnp.copy, tr.params)
    o0 = jax.tree.map(jnp.copy, tr.opt_state)
    p1, o1, loss, skipped = prog(p0, o0, xs, ys, ws, jnp.int32(1))
    assert int(np.asarray(skipped)[0]) == 1
    _assert_trees_equal(tr.params, p1)
    _assert_trees_equal(tr.opt_state, o1)
    # Clean batch: flag low, params move.
    p2, o2, loss2, sk2 = prog(
        jax.tree.map(jnp.copy, tr.params),
        jax.tree.map(jnp.copy, tr.opt_state), xs, ys, ws, jnp.int32(0),
    )
    assert int(np.asarray(sk2)[0]) == 0
    assert np.isfinite(float(loss2))
    moved = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(tr.params), jax.tree.leaves(p2))
    )
    assert moved


def test_guard_skips_in_zero1_hybrid_and_pipeline_bodies():
    """The SAME in-graph skip contract in the other three seq step
    bodies: zero1 (flat-chunk sharded Adam), the hybrid zero1 x tp cube
    body, and the pipeline schedule-scan body. One poisoned step each —
    params and optimizer state bit-unchanged, flag up."""
    ds = _copy_ds(9)
    configs = {
        "zero1": SeqConfig(epochs=1, eval_every=0, batch_size=16,
                           num_workers=2, scheme="ring", zero1=True,
                           spec=SPEC),
        "hybrid": SeqConfig(epochs=1, eval_every=0, batch_size=16,
                            num_workers=2, data_parallel=2,
                            tensor_parallel=2, scheme="ring", zero1=True,
                            spec=SPEC),
        "pipeline": SeqConfig(epochs=1, eval_every=0, batch_size=16,
                              num_workers=1, scheme="full",
                              pipeline_parallel=2, microbatches=2,
                              spec=SPEC),
    }
    for name, cfg in configs.items():
        tr = SeqTrainer(cfg, ds)
        prog, (xs, ys, ws) = _poisoned_span(tr, ds, batch=0)
        p0 = jax.tree.map(jnp.copy, tr.params)
        o0 = jax.tree.map(jnp.copy, tr.opt_state)
        p1, o1, _, skipped = prog(p0, o0, xs, ys, ws, jnp.int32(0))
        assert int(np.asarray(skipped)[0]) == 1, name
        _assert_trees_equal(tr.params, p1)
        _assert_trees_equal(tr.opt_state, o1)


def test_guard_off_compiles_identical_program():
    """Acceptance (b), program-identity half: guard=False lowers to the
    EXACT same HLO as the pre-change default (the flag is a Python
    branch), and guard=True is genuinely a different program."""
    ds = _copy_ds(8)
    tr = SeqTrainer(SeqConfig(epochs=1, eval_every=0, batch_size=16,
                              num_workers=1, scheme="full", spec=SPEC), ds)
    xs = tr.stage_batches(ds.tokens, 4, 16)
    ys = tr.stage_batches(ds.targets, 4, 16)
    ws = tr.stage_batches(ds.weights, 4, 16)
    args = (tr.params, tr.opt_state, xs, ys, ws, jnp.int32(0))
    default = tr.span_program(2).lower(*args).as_text()
    off = tr.span_program(2, guard=False).lower(*args).as_text()
    on = tr.span_program(2, guard=True).lower(*args).as_text()
    assert default == off
    assert default != on


def test_single_chip_guard_skips_and_converges(small_dataset, small_params):
    """The CNN step body honours the same contract: an injected-NaN
    batch is skipped (counted in the result), every other step trains,
    and the final state is finite."""
    from ddl_tpu.models import cnn
    from ddl_tpu.train import SingleChipTrainer, TrainConfig

    cfg = TrainConfig(epochs=1, batch_size=256, eval_every=0, seed=5,
                      conv_channels=cnn.TINY_CONV_CHANNELS,
                      fc_sizes=cnn.TINY_FC_SIZES)
    inj = FaultInjector(FaultSpec(kind="nan_grads", step=2))
    r = SingleChipTrainer(cfg, small_dataset, init=small_params).train(
        log=quiet, guard=True, fault_injector=inj
    )
    assert r.skipped_steps == 1 and r.rollbacks == 0
    for v in r.params.values():
        assert np.isfinite(np.asarray(v)).all()


def test_seq_guard_converges_with_injected_nan():
    """Acceptance (b), end to end: with the guard on, a NaN-injected
    run completes finite and lands at the clean run's loss (the skipped
    batch's contribution is the only difference)."""
    ds = _copy_ds(11)
    cfg = SeqConfig(epochs=2, eval_every=0, batch_size=16, num_workers=1,
                    scheme="full", spec=SPEC, seed=3)
    clean = SeqTrainer(cfg, ds).train(log=quiet)
    inj = FaultInjector(FaultSpec(kind="inf_grads", step=1))
    faulted = SeqTrainer(cfg, ds).train(log=quiet, guard=True,
                                        fault_injector=inj)
    # Batch 1 is poisoned on both epoch passes -> exactly 2 skips.
    assert faulted.skipped_steps == 2
    assert np.isfinite(faulted.final_loss)
    assert abs(faulted.final_loss - clean.final_loss) < 0.15 * clean.final_loss


def test_seq_guard_rollback_reseeds_to_checkpoint():
    """Escalation: K consecutive bad steps roll back to the last good
    checkpoint; the transient fault heals and the replayed data stream
    (re-seeded by step position) finishes BIT-IDENTICAL to the clean
    run — the strongest possible rollback-correctness pin."""
    import tempfile

    ds = _copy_ds(8)
    cfg = SeqConfig(epochs=1, eval_every=1, batch_size=16, num_workers=1,
                    scheme="full", spec=SPEC, seed=3)
    clean = SeqTrainer(cfg, ds).train(log=quiet)
    d = tempfile.mkdtemp()
    inj = FaultInjector(FaultSpec(kind="nan_grads", step=1, count=2))
    r = SeqTrainer(cfg, ds).train(
        log=quiet, checkpoint_dir=d, checkpoint_every=1,
        max_bad_steps=2, fault_injector=inj,
    )
    assert r.rollbacks == 1 and r.skipped_steps == 2
    _assert_trees_equal(clean.params, r.params)
    assert r.final_accuracy == clean.final_accuracy


def test_guard_rollback_without_checkpoint_raises():
    ds = _copy_ds(8, num_train=32)
    cfg = SeqConfig(epochs=1, eval_every=1, batch_size=16, num_workers=1,
                    scheme="full", spec=SPEC)
    inj = FaultInjector(FaultSpec(kind="nan_grads", step=0))
    with pytest.raises(RuntimeError, match="no checkpoint_dir"):
        SeqTrainer(cfg, ds).train(log=quiet, max_bad_steps=1,
                                  fault_injector=inj)


def test_persistent_fault_exhausts_rollbacks():
    """A fault that does NOT heal (once=False — persistently bad data)
    re-trips after every rollback; the bound turns a silent livelock
    into a diagnosed failure."""
    import tempfile

    ds = _copy_ds(8, num_train=32)
    cfg = SeqConfig(epochs=1, eval_every=1, batch_size=16, num_workers=1,
                    scheme="full", spec=SPEC)
    inj = FaultInjector(FaultSpec(kind="nan_grads", step=1, once=False))
    with pytest.raises(RuntimeError, match="max_rollbacks"):
        SeqTrainer(cfg, ds).train(
            log=quiet, checkpoint_dir=tempfile.mkdtemp(),
            checkpoint_every=1, max_bad_steps=1, max_rollbacks=1,
            fault_injector=inj,
        )


# -- preemption: SIGTERM at an arbitrary step + --resume auto -----------------


def _with_cli_signal_flag():
    """The CLI's real SIGTERM/SIGINT flag handler, plus the originals
    for restoration (the handler self-resets to SIG_DFL on delivery —
    a leaked handler would kill the test process on the next signal)."""
    from ddl_tpu.cli import _install_sigterm_flag

    saved = (signal.getsignal(signal.SIGTERM),
             signal.getsignal(signal.SIGINT))
    return _install_sigterm_flag(True), saved


def _restore_signals(saved):
    signal.signal(signal.SIGTERM, saved[0])
    signal.signal(signal.SIGINT, saved[1])


def test_sigterm_resume_auto_bit_identical_replicated(tmp_path):
    """Acceptance (a), replicated: a REAL SIGTERM delivered by the
    injector once step 1 completes drains the span, writes the final
    checkpoint, and stops; --resume auto discovers it and the stitched
    run is bit-identical to the uninterrupted one."""
    ds = _copy_ds(12)
    cfg = SeqConfig(epochs=2, eval_every=2, batch_size=16, num_workers=1,
                    scheme="full", spec=SPEC, seed=4)
    golden = SeqTrainer(cfg, ds).train(log=quiet)
    d = str(tmp_path / "ck")
    term, saved = _with_cli_signal_flag()
    try:
        inj = FaultInjector(FaultSpec(kind="sigterm", step=1))
        pre = SeqTrainer(cfg, ds).train(
            log=quiet, checkpoint_dir=d, fault_injector=inj,
            should_stop=lambda: term["flag"],
        )
    finally:
        _restore_signals(saved)
    assert pre.preempted
    assert find_latest_valid(d) is not None
    resumed = SeqTrainer(cfg, ds).train(log=quiet, checkpoint_dir=d,
                                        resume="auto")
    assert 0 < resumed.resumed_from_step < 8
    assert not resumed.preempted
    _assert_trees_equal(golden.params, resumed.params)
    assert resumed.final_accuracy == golden.final_accuracy


def test_preempt_resume_auto_bit_identical_hybrid_cube(tmp_path):
    """Acceptance (a), hybrid 2x2x2: the zero1 x tp cube's sharded
    optimizer state survives preempt -> auto-resume bit-identically
    (flat dp x sp chunks and tp-local m/v round-trip the layout-free
    checkpoint form)."""
    ds = _copy_ds(23, num_train=32)
    cfg = SeqConfig(epochs=2, eval_every=1, batch_size=16, num_workers=2,
                    data_parallel=2, tensor_parallel=2, scheme="ring",
                    zero1=True, spec=SPEC, seed=13)
    golden = SeqTrainer(cfg, ds).train(log=quiet)
    d = str(tmp_path / "ck")
    polls = {"n": 0}

    def stop():
        polls["n"] += 1
        return polls["n"] > 1  # preempt after the first span

    pre = SeqTrainer(cfg, ds).train(log=quiet, checkpoint_dir=d,
                                    should_stop=stop)
    assert pre.preempted
    resumed = SeqTrainer(cfg, ds).train(log=quiet, checkpoint_dir=d,
                                        resume="auto")
    assert resumed.resumed_from_step >= 1
    _assert_trees_equal(golden.params, resumed.params)


def test_writer_tracer_flush_on_signal_exit(small_dataset, small_params,
                                            tmp_path):
    """ISSUE 6 satellite: on the signal-handler exit path (real SIGTERM
    -> drain -> preempted return -> the CLI's finally-close), the
    MetricsWriter ends with a forced final snapshot and the Tracer's
    JSONL holds the completed spans — the incident is auditable."""
    from ddl_tpu.models import cnn
    from ddl_tpu.obs import MetricRegistry, MetricsWriter
    from ddl_tpu.obs.trace import Tracer, read_jsonl
    from ddl_tpu.train import SingleChipTrainer, TrainConfig

    cfg = TrainConfig(epochs=2, batch_size=256, eval_every=2, seed=5,
                      conv_channels=cnn.TINY_CONV_CHANNELS,
                      fc_sizes=cnn.TINY_FC_SIZES)
    mpath = tmp_path / "metrics.jsonl"
    tpath = tmp_path / "trace.jsonl"
    registry = MetricRegistry()
    writer = MetricsWriter(mpath, registry, interval_s=3600)
    tracer = Tracer(tpath)
    term, saved = _with_cli_signal_flag()
    try:
        inj = FaultInjector(FaultSpec(kind="sigterm", step=1))
        r = SingleChipTrainer(cfg, small_dataset, init=small_params).train(
            log=quiet, checkpoint_dir=str(tmp_path / "ck"),
            fault_injector=inj, should_stop=lambda: term["flag"],
            metrics=registry, metrics_writer=writer, tracer=tracer,
        )
    finally:
        _restore_signals(saved)
        tracer.close()
        writer.close()
    assert r.preempted
    recs = [json.loads(line) for line in open(mpath) if line.strip()]
    assert recs[0]["record"] == "manifest"
    # interval_s=3600 means the ONLY snapshot is the forced final flush
    # on close — exactly the signal-exit guarantee under test.
    assert recs[-1]["record"] == "snapshot"
    names = {m["name"] for m in recs[-1]["metrics"]}
    assert "train_step" in names
    spans = [rec for rec in read_jsonl(tpath) if rec["type"] == "span"]
    assert any(rec["name"] == "train/span" for rec in spans)


# -- corrupt latest checkpoint: resume falls back -----------------------------


def test_corrupt_latest_checkpoint_resume_auto_falls_back(tmp_path):
    """Acceptance (c): corrupt the latest save (rolling + newest
    retained share an inode, so both go bad — the realistic torn-latest
    case); --resume auto verifies it out, resumes from the previous
    retained save, and still finishes identical to the clean run."""
    ds = _copy_ds(14)
    cfg = SeqConfig(epochs=2, eval_every=2, batch_size=16, num_workers=1,
                    scheme="full", spec=SPEC, seed=6)
    golden = SeqTrainer(cfg, ds).train(log=quiet)
    d = str(tmp_path / "ck")
    one = SeqConfig(epochs=1, eval_every=2, batch_size=16, num_workers=1,
                    scheme="full", spec=SPEC, seed=6)
    SeqTrainer(one, ds).train(log=quiet, checkpoint_dir=d,
                              checkpoint_every=1)
    latest = find_latest_valid(d)
    assert latest is not None and latest[1] == 4
    corrupt_checkpoint(os.path.join(d, "ckpt.npz"))
    fallback = find_latest_valid(d)
    assert fallback is not None and fallback[1] < 4
    logs = []
    resumed = SeqTrainer(cfg, ds).train(
        log=logs.append, checkpoint_dir=d, resume="auto"
    )
    assert resumed.resumed_from_step == fallback[1]
    assert any("skipping corrupt" in s for s in logs)
    _assert_trees_equal(golden.params, resumed.params)


# -- serve: deadlines, stall eviction, shedding -------------------------------


def _serve_engine(tp, **kw):
    from ddl_tpu.serve import InferenceEngine, ServeConfig

    return InferenceEngine(ServeConfig(
        spec=SPEC, slots=2, capacity=64, tensor_parallel=tp, **kw
    ))


def test_stalled_request_evicted_at_deadline_releases_pins():
    """Acceptance (d): a stalled request (injector never advances its
    prefill) is evicted at its total deadline with a structured status;
    the prefix entry it pinned at admission is released (pool reusable
    afterwards) — at tp=1 AND tp=2 — and co-resident requests' tokens
    are bit-identical to a run without the stalled request."""
    from ddl_tpu.serve import Request, Scheduler

    prompts = synthesize_prompts(num=3, min_len=6, max_len=10,
                                 vocab=SPEC.vocab, seed=0)
    shared = np.concatenate([prompts[0], prompts[0][1:4]]).astype(np.int32)
    for tp in (1, 2):
        eng = _serve_engine(tp, prefix_slots=2)
        base = [
            Request(id=0, prompt=prompts[0], max_new_tokens=4),
            Request(id=2, prompt=prompts[2], max_new_tokens=4, arrival=1),
        ]
        stalled = Request(id=1, prompt=shared, max_new_tokens=4, arrival=1,
                          deadline_s=0.02)
        inj = FaultInjector(FaultSpec(kind="stall", step=1))
        done, _ = Scheduler(eng, injector=inj).run(base + [stalled])
        assert done[1].status == "deadline_exceeded"
        assert done[1].tokens == []
        assert done[0].status == "ok" and done[2].status == "ok"
        # Request 1's admission pinned the prefix entry request 0
        # registered; eviction must have released every ref.
        assert all(e.refs == 0 for e in eng.prefix._entries.values())
        # Pool reusable afterwards: a fresh request can still hit it.
        again, _ = Scheduler(eng).run(
            [Request(id=3, prompt=shared, max_new_tokens=2)]
        )
        assert again[3].status == "ok"
        # Co-resident determinism: same ids on a fresh engine WITHOUT
        # the stalled neighbour produce the same tokens bit-for-bit.
        eng2 = _serve_engine(tp, prefix_slots=2)
        done2, _ = Scheduler(eng2).run(base)
        assert done2[0].tokens == done[0].tokens
        assert done2[2].tokens == done[2].tokens


def test_serve_shed_admission_and_metrics():
    """Overload sheds at FIRST eligibility with status 'shed' (never
    occupying a slot), counts into the registry, and admitted traffic
    completes normally."""
    from ddl_tpu.obs import MetricRegistry
    from ddl_tpu.serve import Request, Scheduler

    prompts = synthesize_prompts(num=4, min_len=4, max_len=8,
                                 vocab=SPEC.vocab, seed=1)
    eng = _serve_engine(1)
    reg = MetricRegistry()
    sched = Scheduler(eng, shed_threshold=2, registry=reg)
    done, _ = sched.run([
        Request(id=i, prompt=p, max_new_tokens=2)
        for i, p in enumerate(prompts)
    ])
    statuses = [done[i].status for i in sorted(done)]
    assert statuses.count("shed") == 2
    assert statuses.count("ok") == 2
    for i in sorted(done):
        if done[i].status == "shed":
            assert done[i].admitted_step == -1 and done[i].tokens == []
    assert reg.counter("serve_shed_total").value() == 2
    assert reg.counter("serve_requests_completed_total").value() == 2


def test_scheduler_validates_resilience_config():
    """ISSUE 6 satellite: deadline/shed misconfiguration is rejected at
    CONSTRUCTION (and per-request deadlines at submit), naming the
    offending value — mirroring _validate's style."""
    from ddl_tpu.serve import Request, Scheduler

    eng = _serve_engine(1)
    with pytest.raises(ValueError, match="ttft_deadline_s.*-1"):
        Scheduler(eng, ttft_deadline_s=-1)
    with pytest.raises(ValueError, match="deadline_s.*0"):
        Scheduler(eng, deadline_s=0.0)
    with pytest.raises(ValueError, match="shed_threshold \\(1\\)"):
        Scheduler(eng, shed_threshold=1)  # below slots=2
    sched = Scheduler(eng)
    bad = Request(id=0, prompt=np.ones(4, np.int32), max_new_tokens=2,
                  ttft_deadline_s=0.0)
    with pytest.raises(ValueError, match="request 0: ttft_deadline_s"):
        sched.run([bad])
    # A stalled request with NO applicable deadline would never
    # terminate — rejected at submit.
    inj = FaultInjector(FaultSpec(kind="stall", step=0))
    with pytest.raises(ValueError, match="stall fault"):
        Scheduler(eng, injector=inj).run([
            Request(id=0, prompt=np.ones(4, np.int32), max_new_tokens=2)
        ])


def test_queued_request_expires_without_admission():
    """A queued-but-never-admitted request past its TTFT deadline
    cancels with status 'deadline_exceeded' and admitted_step == -1 (it
    held no slot, pinned nothing), while the in-flight requests finish
    normally. Both slots are taken at tick 0, so request 2 can only
    wait; tick 1's sweep (one prefill+decode dispatch later — far past
    0.1 ms of wall clock) expires it before any slot frees."""
    from ddl_tpu.serve import Request, Scheduler

    prompts = synthesize_prompts(num=3, min_len=4, max_len=8,
                                 vocab=SPEC.vocab, seed=2)
    eng = _serve_engine(1)
    done, _ = Scheduler(eng).run([
        Request(id=0, prompt=prompts[0], max_new_tokens=3),
        Request(id=1, prompt=prompts[1], max_new_tokens=3),
        Request(id=2, prompt=prompts[2], max_new_tokens=3,
                ttft_deadline_s=1e-4),
    ])
    assert done[0].status == "ok" and done[1].status == "ok"
    assert done[2].status == "deadline_exceeded"
    assert done[2].tokens == [] and done[2].admitted_step == -1
