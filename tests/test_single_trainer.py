"""Single-chip trainer smoke + convergence tests (replaces the reference's
eyeball accuracy oracle, single.py:17-21; SURVEY.md section 4c).

Runs the narrow test model (conftest.SMALL_SPECS); trainer code is
model-agnostic and full-width numerics are pinned in test_model.py."""

import jax
import numpy as np

from ddl_tpu.train import SingleChipTrainer, TrainConfig
from ddl_tpu.train.trainer import eval_spans


def test_trains_and_converges(small_dataset, small_params):
    cfg = TrainConfig(
        epochs=8, batch_size=64, learning_rate=3e-3, eval_every=0, seed=0
    )
    trainer = SingleChipTrainer(cfg, small_dataset, init=small_params)
    result = trainer.train(log=lambda s: None)
    # 256 steps of Adam(3e-3) on the separable procedural set reach ~0.9
    # on the narrow model; full-width runs reach >99% (bench).
    assert result.final_accuracy > 0.7
    assert result.wall_time_s > 0
    assert len(result.history) == 0  # eval_every=0 disables periodic eval


def test_deterministic_given_seed(small_dataset, small_params):
    cfg = TrainConfig(epochs=1, batch_size=256, eval_every=0, seed=3)
    r1 = SingleChipTrainer(cfg, small_dataset, init=small_params).train(log=lambda s: None)
    r2 = SingleChipTrainer(cfg, small_dataset, init=small_params).train(log=lambda s: None)
    for k in r1.params:
        np.testing.assert_array_equal(r1.params[k], r2.params[k])


def test_eval_history(small_dataset, small_params):
    cfg = TrainConfig(epochs=1, batch_size=256, eval_every=4, seed=0)
    result = SingleChipTrainer(cfg, small_dataset, init=small_params).train(
        log=lambda s: None
    )
    batches = [b for _, b, _ in result.history]
    assert batches == [0, 4]  # 2048/256 = 8 batches -> evals at 0 and 4


def test_eval_spans():
    # Reference cadence: eval after every batch cnt % eval_every == 0
    # (worker.py:71-72) -> spans [0], [1..10], ..., no-eval tail.
    spans = eval_spans(25, 10)
    assert spans == [(0, 1, True), (1, 10, True), (11, 10, True), (21, 4, False)]
    assert eval_spans(500, 10)[-1] == (491, 9, False)
    assert eval_spans(8, 0) == [(0, 8, False)]  # eval_every=0: one chunk
    assert eval_spans(0, 10) == []
    # Total batches covered == batch_num, no overlaps.
    for bn, ee in [(500, 10), (7, 3), (1, 10), (13, 1)]:
        sp = eval_spans(bn, ee)
        assert sum(k for _, k, _ in sp) == bn
        assert [f for f, _, _ in sp] == list(
            np.cumsum([0] + [k for _, k, _ in sp[:-1]])
        )


def test_multiple_train_calls_do_not_invalidate_state(small_dataset, small_params):
    # The chunk programs donate params/opt; train() must copy first so the
    # trainer (and any shared init tree) survives repeated calls.
    cfg = TrainConfig(epochs=1, batch_size=512, eval_every=0, seed=1)
    trainer = SingleChipTrainer(cfg, small_dataset, init=small_params)
    trainer.train(log=lambda s: None)
    trainer.train(log=lambda s: None)  # would raise if buffers were donated
    np.asarray(small_params["v0"])  # shared init still alive
