"""Single-chip trainer smoke + convergence tests (replaces the reference's
eyeball accuracy oracle, single.py:17-21; SURVEY.md section 4c).

Runs the narrow test model (conftest.SMALL_SPECS); trainer code is
model-agnostic and full-width numerics are pinned in test_model.py."""

import jax
import numpy as np

from ddl_tpu.train import SingleChipTrainer, TrainConfig


def test_trains_and_converges(small_dataset, small_params):
    cfg = TrainConfig(
        epochs=8, batch_size=64, learning_rate=3e-3, eval_every=0, seed=0
    )
    trainer = SingleChipTrainer(cfg, small_dataset, init=small_params)
    result = trainer.train(log=lambda s: None)
    # 256 steps of Adam(3e-3) on the separable procedural set reach ~0.9
    # on the narrow model; full-width runs reach >99% (bench).
    assert result.final_accuracy > 0.7
    assert result.wall_time_s > 0
    assert len(result.history) == 0  # eval_every=0 disables periodic eval


def test_deterministic_given_seed(small_dataset, small_params):
    cfg = TrainConfig(epochs=1, batch_size=256, eval_every=0, seed=3)
    r1 = SingleChipTrainer(cfg, small_dataset, init=small_params).train(log=lambda s: None)
    r2 = SingleChipTrainer(cfg, small_dataset, init=small_params).train(log=lambda s: None)
    for k in r1.params:
        np.testing.assert_array_equal(r1.params[k], r2.params[k])


def test_eval_history(small_dataset, small_params):
    cfg = TrainConfig(epochs=1, batch_size=256, eval_every=4, seed=0)
    result = SingleChipTrainer(cfg, small_dataset, init=small_params).train(
        log=lambda s: None
    )
    batches = [b for _, b, _ in result.history]
    assert batches == [0, 4]  # 2048/256 = 8 batches -> evals at 0 and 4
