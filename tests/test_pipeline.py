"""Pipeline parallelism (ddl_tpu/pipeline, models/partition stage split,
SeqTrainer pipeline mode).

The oracle chain, as everywhere in this repo: the W=1 full-attention
``SeqTrainer`` is the reference numerics; the pipelined trainers (GPipe
and 1F1B, alone and composed with dp / tp) must reproduce its loss,
accuracy, and parameter trajectories on the 8-device virtual mesh to
stated tolerance (atol 1e-5 / rtol 1e-4 — microbatch gradient
accumulation and the backward's activation recompute reassociate fp32
sums; there is no other numerical difference). Checkpoints must cross
the pp ↔ non-pp boundary in both directions.
"""

import numpy as np
import pytest

import jax

from ddl_tpu.data.lm import synthesize_copy
from ddl_tpu.models.partition import (
    pipeline_param_specs,
    stack_blocks,
    stage_partition,
    unstack_blocks,
)
from ddl_tpu.models.transformer import TINY_SPEC, init_lm_params
from ddl_tpu.pipeline.schedule import (
    IDLE,
    bubble_fraction,
    buffer_slots,
    max_in_flight,
    predicted_bubble,
    schedule_tables,
)
from ddl_tpu.strategies.seq import SeqConfig, SeqTrainer

SPEC = TINY_SPEC
T = 32

# The stated pipeline parity tolerance (microbatch-sum + recompute
# reassociation only).
TOL = dict(atol=1e-5, rtol=1e-4)


def _params_close(a, b, **kw):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **kw)


# -- schedules ---------------------------------------------------------------


@pytest.mark.parametrize("kind", ["gpipe", "1f1b"])
@pytest.mark.parametrize("pp,m", [(2, 1), (2, 4), (4, 2), (4, 8), (3, 5)])
def test_schedule_tables_wellformed(kind, pp, m):
    """Every (stage, microbatch) forward and backward appears exactly
    once, in microbatch order per stage, and respects the dependency
    model: F(s,j) after F(s-1,j), B(s,j) after B(s+1,j) (last stage:
    after its own F(s,j)) — each with at least one tick of ppermute
    latency. Both schedules fill the same 2*(m+pp-1)-tick envelope."""
    f_tab, b_tab = schedule_tables(kind, pp, m)
    assert f_tab.shape == b_tab.shape == (pp, 2 * (m + pp - 1))
    f_tick = {}
    b_tick = {}
    for s in range(pp):
        fs = [(t, int(f_tab[s, t])) for t in range(f_tab.shape[1])
              if f_tab[s, t] != IDLE]
        bs = [(t, int(b_tab[s, t])) for t in range(b_tab.shape[1])
              if b_tab[s, t] != IDLE]
        assert [j for _, j in fs] == list(range(m)), (kind, s)
        assert [j for _, j in bs] == list(range(m)), (kind, s)
        # At most one unit of work per (stage, tick).
        assert not {t for t, _ in fs} & {t for t, _ in bs}, (kind, s)
        f_tick.update({(s, j): t for t, j in fs})
        b_tick.update({(s, j): t for t, j in bs})
    for s in range(pp):
        for j in range(m):
            if s > 0:
                assert f_tick[(s, j)] > f_tick[(s - 1, j)], (kind, s, j)
            if s < pp - 1:
                assert b_tick[(s, j)] > b_tick[(s + 1, j)], (kind, s, j)
            else:
                assert b_tick[(s, j)] > f_tick[(s, j)], (kind, s, j)


@pytest.mark.parametrize("pp,m", [(2, 4), (2, 8), (4, 8)])
def test_schedule_memory_and_bubble(pp, m):
    """The schedules' defining difference is warmup MEMORY, not bubble:
    GPipe holds M in-flight stage inputs at its widest stage, 1F1B only
    min(pp, M); with equal-cost ticks both realize the closed-form
    bubble (pp-1)/(m+pp-1) — the analytic model pipeline_bubble.py
    falsifies against wall-clock."""
    g = schedule_tables("gpipe", pp, m)
    o = schedule_tables("1f1b", pp, m)
    assert max_in_flight(*g) == m
    assert max_in_flight(*o) == min(pp, m)
    assert buffer_slots(*g)["save"] == m
    assert buffer_slots(*o)["save"] == min(pp, m)
    expect = predicted_bubble(pp, m)
    assert bubble_fraction(*g) == pytest.approx(expect)
    assert bubble_fraction(*o) == pytest.approx(expect)
    assert expect == pytest.approx((pp - 1) / (m + pp - 1))


# -- stage partition / param layout ------------------------------------------


def test_stage_partition_contract():
    part = stage_partition(SPEC, 2)  # TINY_SPEC: 2 layers
    assert part.layers_per_stage == 1
    assert list(part.stage_layers(0)) == [0]
    assert list(part.stage_layers(1)) == [1]
    with pytest.raises(ValueError, match="divide num_layers"):
        stage_partition(SPEC, 3)  # 2 % 3

    params = jax.tree.map(
        np.asarray, init_lm_params(jax.random.PRNGKey(0), SPEC)
    )
    stacked = stack_blocks(params)
    assert stacked["blocks"]["wq"].shape == (2, 32, 32)
    back = unstack_blocks(stacked)
    _params_close(params, back, atol=0)

    from jax.sharding import PartitionSpec as P

    from ddl_tpu.parallel.mesh import PP_AXIS, TP_AXIS

    specs = pipeline_param_specs(SPEC, 2, tensor_parallel=2)
    # Every block leaf leads with the pp axis; Megatron col/row follow.
    assert specs["blocks"]["wq"] == P(PP_AXIS, None, TP_AXIS)
    assert specs["blocks"]["wo"] == P(PP_AXIS, TP_AXIS, None)
    assert specs["blocks"]["ln1_g"] == P(PP_AXIS)
    # embed/head/final-LN stay replicated (grads psum-broadcast over pp).
    assert specs["embed"] == specs["head"] == P()
    with pytest.raises(ValueError, match="divide num_layers"):
        pipeline_param_specs(SPEC, 3)


# -- trainer parity against the non-pipelined oracle -------------------------


def test_pipeline_trainer_matches_oracle():
    """pp=2 GPipe and 1F1B — alone, x dp=2, and x tp=2 — are the same
    math as the W=1 full-attention oracle: identical short trainings
    agree in final loss, eval accuracy (the forward-only pipeline eval
    path), and every parameter, to the stated microbatch/recompute
    tolerance. Also pins the placement: each pp position's addressable
    block shard is exactly its stage's L/pp layers."""
    ds = synthesize_copy(
        num_train=32, num_test=16, seq_len=T, vocab=SPEC.vocab, seed=30
    )
    base = dict(epochs=2, batch_size=16, learning_rate=1e-3, eval_every=0,
                num_workers=1, scheme="full", spec=SPEC, seed=15)
    oracle = SeqTrainer(SeqConfig(**base), ds).train(log=lambda s: None)
    configs = {
        "pp2_gpipe": SeqConfig(pipeline_parallel=2, microbatches=4,
                               pipeline_schedule="gpipe", **base),
        "pp2_1f1b": SeqConfig(pipeline_parallel=2, microbatches=4,
                              pipeline_schedule="1f1b", **base),
        "dp2_pp2": SeqConfig(pipeline_parallel=2, microbatches=2,
                             data_parallel=2, **base),
        "tp2_pp2": SeqConfig(pipeline_parallel=2, microbatches=2,
                             tensor_parallel=2,
                             pipeline_schedule="1f1b", **base),
        "dp2_tp2_pp2": SeqConfig(pipeline_parallel=2, microbatches=2,
                                 data_parallel=2, tensor_parallel=2,
                                 **base),
    }
    for tag, cfg in configs.items():
        tr = SeqTrainer(cfg, ds)
        wq = tr.params["blocks"]["wq"]  # stacked [L, e, e'], pp-sharded
        shard = wq.addressable_shards[0].data.shape
        e = SPEC.d_model
        assert shard[0] == SPEC.num_layers // 2, (tag, shard)
        assert shard[2] == (e // 2 if cfg.tensor_parallel > 1 else e), tag
        r = tr.train(log=lambda s: None)
        assert np.isclose(r.final_loss, oracle.final_loss, rtol=1e-4), (
            tag, r.final_loss, oracle.final_loss
        )
        assert abs(r.final_accuracy - oracle.final_accuracy) < 1e-6, tag
        _params_close(oracle.params, r.params, err_msg=tag, **TOL)


def test_pipeline_checkpoint_elastic(tmp_path):
    """pp-topology checkpoints are topology-free in BOTH directions: a
    pp=2 save (stacked, stage-sharded live state written in the standard
    per-layer form) resumes into a non-pp world, and a plain save
    resumes under pp=2/1F1B; both match the uninterrupted plain golden
    run."""
    ds = synthesize_copy(
        num_train=32, num_test=16, seq_len=T, vocab=SPEC.vocab, seed=31
    )
    base = dict(batch_size=16, learning_rate=1e-3, eval_every=0,
                num_workers=1, scheme="full", spec=SPEC, seed=16)
    pp_kw = dict(pipeline_parallel=2, microbatches=2)
    golden = SeqTrainer(SeqConfig(epochs=2, **base), ds).train(
        log=lambda s: None
    )
    for tag, save_kw, resume_kw in (
        ("pp->plain", pp_kw, {}),
        ("plain->pp", {}, dict(pipeline_schedule="1f1b", **pp_kw)),
    ):
        ckdir = str(tmp_path / tag.replace(">", "_"))
        SeqTrainer(SeqConfig(epochs=1, **save_kw, **base), ds).train(
            log=lambda s: None, checkpoint_dir=ckdir
        )
        crossed = SeqTrainer(
            SeqConfig(epochs=2, **resume_kw, **base), ds
        ).train(log=lambda s: None, checkpoint_dir=ckdir, resume=True)
        assert crossed.resumed_from_step == 2, tag
        _params_close(golden.params, crossed.params, err_msg=tag, **TOL)


def test_pipeline_rejects_bad_configs():
    """validate_topology: every rejected composition fails fast with a
    fix in the message, before any device work (CI satellite)."""
    ds = synthesize_copy(num_train=16, num_test=4, seq_len=T,
                         vocab=SPEC.vocab, seed=0)
    ok = dict(num_workers=1, scheme="full", batch_size=16, spec=SPEC)
    good = SeqConfig(pipeline_parallel=2, microbatches=2, **ok)
    good.validate_topology()  # the valid baseline must not raise
    cases = [
        (dict(pipeline_parallel=3, microbatches=3),
         "divide num_layers"),  # 2 % 3
        (dict(pipeline_parallel=2, microbatches=1), "microbatches > 1"),
        (dict(pipeline_parallel=1, microbatches=2),
         "requires pipeline_parallel"),
        (dict(pipeline_parallel=2, microbatches=3),
         "divide the global batch"),  # 16 % 3
        (dict(pipeline_parallel=2, microbatches=4, data_parallel=3),
         "divide the global batch"),  # 16 % (3*4)
        (dict(pipeline_parallel=2, microbatches=2, zero1=True), "zero1"),
        (dict(pipeline_parallel=2, microbatches=2,
              pipeline_schedule="zigzag"), "pipeline_schedule"),
        (dict(pipeline_parallel=0), "pipeline_parallel"),
        (dict(microbatches=0), "microbatches"),
    ]
    for kw, match in cases:
        cfg = SeqConfig(**{**ok, **kw})
        with pytest.raises(ValueError, match=match):
            cfg.validate_topology()
    # Sequence x pipeline is rejected (composition matrix).
    with pytest.raises(ValueError, match="num_workers=1"):
        SeqConfig(num_workers=2, scheme="ring", batch_size=16, spec=SPEC,
                  pipeline_parallel=2, microbatches=2).validate_topology()
    # The trainer routes through the same gate.
    with pytest.raises(ValueError, match="microbatches > 1"):
        SeqTrainer(SeqConfig(pipeline_parallel=2, microbatches=1, **ok),
                   ds)


@pytest.mark.slow
def test_pipeline_learns_copy_task_slow():
    """End to end through the pipeline (pp=2, 1F1B, 10 epochs): the copy
    task's scored targets live half a sequence back, so accuracy >>
    chance certifies the whole pipelined training path — microbatch
    streaming, manual backward, grad accumulation, Adam, the forward-
    only pipeline eval. Long sweep, excluded from tier-1 (slow marker —
    the schedule/parity pins above cover the gate)."""
    ds = synthesize_copy(
        num_train=256, num_test=64, seq_len=T, vocab=SPEC.vocab, seed=33
    )
    cfg = SeqConfig(
        epochs=10, batch_size=32, learning_rate=3e-3, eval_every=0,
        num_workers=1, scheme="full", pipeline_parallel=2, microbatches=4,
        pipeline_schedule="1f1b", spec=SPEC, seed=1,
    )
    result = SeqTrainer(cfg, ds).train(log=lambda s: None)
    chance = 1.0 / (SPEC.vocab - 1)
    assert result.final_accuracy > 10 * chance, (
        result.final_accuracy, result.history
    )


def test_pipeline_step_collective_schedule():
    """The compiled pipeline step's cross-stage traffic is ACTIVATION
    ppermutes — collective-permutes of [mb, T, E] blocks (one forward
    activation + one backward cotangent hop per tick) — and never a
    param-sized collective over pp: block gradients stay stage-resident
    (the audit benchmarks/collective_bytes.py publishes)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
    from benchmarks.collective_bytes import audit_lm

    row = audit_lm("pipeline", 1, 1, pp=2, microbatches=4)
    permutes = [o for o in row["collectives"]
                if o["op"] == "collective-permute"]
    assert permutes, row["collectives"]
    # The audit trains batch 8 over 4 microbatches at seq_len 8*sp:
    # activation blocks are [mb=2, T=8, E=d_model].
    act_elems = 2 * 8 * SPEC.d_model
    assert any(o["max_elems"] == act_elems for o in permutes), (
        act_elems, permutes
    )
    # No collective moves anything params-sized: the largest transfer
    # in the whole schedule is bounded well below the param count.
    total = row["total_params"]
    for o in row["collectives"]:
        assert o["max_elems"] < total, o
    assert row["predicted_bubble"] == pytest.approx(1 / 5)
