"""Communication observability plane (ISSUE 20).

The acceptance pins:

- **Parser oracle**: ``obs.comms.collective_ops`` on hand-written HLO —
  tuple-shaped fused results count every member, replica groups recover
  from explicit braces, iota (with transpose) and collective-permute
  source/target pairs, bytes are exact integers.
- **Live ledger == recount**: the gauges a metered train/serve run
  publishes equal an INDEPENDENT recount of the optimized HLO — same
  integers — at dp2, zero1, hybrid (zero1+tp2) and pp2 train shapes and
  for the paged serve prefill/decode programs (tp=2: the tp psums are
  real wire bytes).
- **Off path pinned**: no registry -> ``program_text`` is never called
  (a monkeypatched bomb proves it) and the engine caches hold BARE
  jitted programs — compiled programs unchanged by construction.
- **Precision wire**: bf16 policy halves the non-scalar gradient
  collective bytes of the AS-WRITTEN schedule (pre-optimization HLO —
  the CPU backend's optimizer folds bf16 collectives back to f32, so
  only that text shows what a bf16-honoring interconnect moves):
  fp32 == 2 * bf16 EXACTLY.
- **Host byte plane**: ``handoff_bytes_total{path=preempt}`` across a
  preempt -> adopt round trip equals the ``serve.cache.kv_row_bytes``
  oracle for the moved pages — fp32 AND int8 pools, tp=1 AND tp=2 —
  and the int8 row is >= 3x smaller at head_dim 16 (3.2x exactly).
"""

from __future__ import annotations

import types
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.data.lm import synthesize_copy
from ddl_tpu.models.transformer import TINY_SPEC
from ddl_tpu.obs import MetricRegistry
from ddl_tpu.obs.comms import (
    CPU_NOMINAL_ICI_BW,
    ICI_BW_BY_KIND,
    collective_ops,
    fit_roofline,
    ici_bw_per_device,
    mesh_axis_partitions,
    program_text,
    publish_program_ledger,
    roofline,
)
from ddl_tpu.serve import InferenceEngine, Request, Scheduler, ServeConfig
from ddl_tpu.serve.cache import kv_row_bytes
from ddl_tpu.strategies.seq import SeqConfig, SeqTrainer

SPEC = TINY_SPEC


def _prompt(n, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(1, SPEC.vocab, size=n, dtype=np.int32)


def _ds(bs, nb, seq_len):
    return synthesize_copy(num_train=nb * bs, num_test=8, seq_len=seq_len,
                           vocab=SPEC.vocab, seed=0)


def _train_cfg(**kw):
    kw.setdefault("spec", SPEC)
    kw.setdefault("epochs", 1)
    kw.setdefault("eval_every", 0)
    kw.setdefault("seed", 0)
    return SeqConfig(**kw)


# -- parser oracle (hand-written HLO) -----------------------------------------

_HLO = """\
HloModule handwritten
%ar = (f32[5882]{0}, f32[]) all-reduce(f32[5882]{0} %a, f32[] %b), replica_groups={{0,2},{1,3}}, to_apply=%sum
%rs = bf16[608]{0} reduce-scatter(bf16[4864]{0} %c), replica_groups=[2,4]<=[8], dimensions={0}
%ag = f32[2432]{0} all-gather(f32[608]{0} %d), replica_groups=[4,2]<=[2,4]T(1,0), dimensions={0}
%cp = f32[2,8,16]{2,1,0} collective-permute(f32[2,8,16]{2,1,0} %e), source_target_pairs={{0,1},{1,2},{2,0},{4,5},{5,4}}
%add.1 = f32[4]{0} add(f32[4]{0} %x, f32[4]{0} %y)
"""


def test_parser_oracle_handwritten_hlo():
    ops = collective_ops(_HLO)
    assert [o["op"] for o in ops] == [
        "all-reduce", "reduce-scatter", "all-gather", "collective-permute",
    ]
    ar, rs, ag, cp = ops
    # Tuple-shaped fused result: BOTH members count (5882 floats + the
    # scalar sibling) — a fused full-vector all-reduce can't hide.
    assert ar["bytes"] == 5882 * 4 + 4
    assert ar["max_elems"] == 5882
    assert ar["dtype"] == "f32"
    assert ar["groups"] == [[0, 2], [1, 3]]
    # iota form [2,4]<=[8]: arange(8) reshaped row-major.
    assert rs["bytes"] == 608 * 2
    assert rs["groups"] == [[0, 1, 2, 3], [4, 5, 6, 7]]
    # iota + transpose [4,2]<=[2,4]T(1,0): the strided partition.
    assert ag["bytes"] == 2432 * 4
    assert ag["groups"] == [[0, 4], [1, 5], [2, 6], [3, 7]]
    # permute pairs union into connected components.
    assert cp["bytes"] == 2 * 8 * 16 * 4
    assert sorted(cp["groups"]) == [[0, 1, 2], [4, 5]]


# -- mesh-axis attribution ----------------------------------------------------

def test_mesh_axis_attribution():
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "sp"))
    parts = mesh_axis_partitions(mesh)
    dp_part = frozenset(frozenset({c, c + 4}) for c in range(4))
    sp_part = frozenset((frozenset(range(4)), frozenset(range(4, 8))))
    all_part = frozenset((frozenset(range(8)),))
    assert parts[dp_part] == "dp"
    assert parts[sp_part] == "sp"
    assert parts[all_part] == "dpxsp"

    reg = MetricRegistry()
    hlo = "\n".join((
        "%a = f32[256]{0} all-reduce(f32[256]{0} %x), "
        "replica_groups={{0,4},{1,5},{2,6},{3,7}}",
        "%b = f32[64]{0} all-reduce(f32[64]{0} %y), "
        "replica_groups={{0,1,2,3},{4,5,6,7}}",
        "%c = f32[16]{0} all-reduce(f32[16]{0} %z)",
        "%d = f32[8]{0} all-reduce(f32[8]{0} %w), "
        "replica_groups={{0,2},{1,3}}",
    ))
    led = publish_program_ledger(reg, hlo, program="probe[0]", mesh=mesh)
    assert led["by_axis"] == {
        "dp": 1024, "sp": 256, "dpxsp": 64, "unknown": 32,
    }
    assert led["total_bytes"] == 1376
    ga = reg.gauge("collective_axis_bytes")
    assert ga.value(axis="dp", program="probe[0]") == 1024
    assert ga.value(axis="unknown", program="probe[0]") == 32
    assert reg.gauge("collective_bytes_total").value(
        program="probe[0]") == 1376

    # Size-1-axis collision keeps the SMALLEST subset's label: on a
    # dp=2, tp=1 mesh an all-device op is a dp op, not dpxtp.
    mesh2 = Mesh(np.array(jax.devices()[:2]).reshape(2, 1), ("dp", "tp"))
    parts2 = mesh_axis_partitions(mesh2)
    assert parts2[frozenset((frozenset({0, 1}),))] == "dp"
    assert parts2[frozenset((frozenset({0}), frozenset({1})))] == "tp"

    # No mesh: everything lands under axis="unknown".
    reg2 = MetricRegistry()
    led2 = publish_program_ledger(reg2, hlo, program="probe[1]")
    assert set(led2["by_axis"]) == {"unknown"}
    assert led2["total_bytes"] == 1376


# -- ICI bandwidth table ------------------------------------------------------

def test_ici_bw_override_table_and_fallback():
    assert ici_bw_per_device(None, 5e9) == 5e9
    with pytest.raises(ValueError):
        ici_bw_per_device(None, 0.0)
    with pytest.raises(ValueError):
        ici_bw_per_device(None, -1.0)
    # CPU falls back to the nominal anchor, silently (not an error).
    assert ici_bw_per_device(jax.devices()[0]) == CPU_NOMINAL_ICI_BW
    table = dict(ICI_BW_BY_KIND)
    v4 = types.SimpleNamespace(device_kind="TPU v4", platform="tpu")
    assert ici_bw_per_device(v4) == table["v4"]
    v5p = types.SimpleNamespace(device_kind="TPU v5p slice", platform="tpu")
    assert ici_bw_per_device(v5p) == table["v5p"]
    # An unknown ACCELERATOR warns (once per kind) before anchoring to
    # the CPU nominal — silent would read as hopelessly comms-bound.
    weird = types.SimpleNamespace(device_kind="frobnicator-9000",
                                  platform="gpu")
    with pytest.warns(UserWarning, match="unknown accelerator"):
        assert ici_bw_per_device(weird) == CPU_NOMINAL_ICI_BW
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert ici_bw_per_device(weird) == CPU_NOMINAL_ICI_BW


# -- the two-roofline model ---------------------------------------------------

def test_roofline_model_and_fit_recovery():
    r = roofline(1e9, 1e6, 4, 1e9, 1e8)
    assert r["compute_time_model_s"] == pytest.approx(0.25)
    assert r["comms_time_model_s"] == pytest.approx(0.01)
    assert r["step_time_model_s"] == pytest.approx(0.25)
    assert r["bound"] == "compute"
    assert r["comms_fraction"] == pytest.approx(0.01 / 0.26)
    assert roofline(1e6, 1e9, 4, 1e9, 1e8)["bound"] == "comms"

    # Synthetic rows generated by a known (peak, bw) pair: the fit must
    # recover it exactly — that's the falsification contract.
    peak, bw = 2.0e9, 5.0e7
    rows = [
        {"flops": f, "bytes": b, "measured_s": max(f / peak, b / bw)}
        for f, b in ((1e9, 1e6), (1e6, 1e9), (5e8, 2e8),
                     (2e9, 1e5), (3e7, 6e8))
    ]
    fit = fit_roofline(rows)
    assert fit is not None
    assert fit["max_rel_err"] < 1e-9
    assert fit["fitted_peak_flops"] == pytest.approx(peak, rel=1e-9)
    assert fit["fitted_bw_bytes_per_s"] == pytest.approx(bw, rel=1e-9)
    # A 1-row fit is unfalsifiable; zero/missing measurements drop.
    assert fit_roofline(rows[:1]) is None
    assert fit_roofline([{"flops": 1e9, "bytes": 1e6, "measured_s": 0.0},
                         {"flops": 1e9, "bytes": 1e6}]) is None


# -- live train ledger == independent recount ---------------------------------
#
# The recount goes through ``program_text`` IN THE TEST BODY on purpose:
# that name is the test_markers comms gate — these tests compile real
# multi-device programs, so they must be visible to the topology audit
# (the literal config tuples below are its sweep surface).

def _span_compiled(tr, p, ds, nb, bs):
    """Independent recompile of span program ``p`` exactly as the
    metered run dispatched it (metrics on -> ``health=True``)."""
    k = int(p[len("train_span["):-1])
    xs = tr.stage_batches(ds.tokens, nb, bs)
    ys = tr.stage_batches(ds.targets, nb, bs)
    ws = tr.stage_batches(ds.weights, nb, bs)
    return (tr.span_program(k, health=True)
            .lower(tr.params, tr.opt_state, xs, ys, ws, jnp.int32(0))
            .compile())


def _assert_program_ledger(reg, p, ops):
    """The published ledger for program ``p`` must be EXACTLY the
    by-hand recount's integers — total, per kind, and the axis
    attribution must partition the same total."""
    assert ops, f"{p}: no collectives in a multi-device program?"
    total = sum(o["bytes"] for o in ops)
    assert reg.gauge("collective_bytes_total").value(program=p) == total
    by_kind: dict[str, int] = {}
    for o in ops:
        by_kind[o["op"]] = by_kind.get(o["op"], 0) + o["bytes"]
    gb = reg.gauge("collective_bytes")
    for kind, want in by_kind.items():
        assert gb.value(kind=kind, program=p) == want
    ga = reg.gauge("collective_axis_bytes")
    axis_total = sum(ga.value(**ls) for ls in ga.label_sets()
                     if ls["program"] == p)
    assert axis_total == total


def _span_programs(reg):
    g = reg.gauge("collective_bytes_total")
    progs = sorted(ls["program"] for ls in g.label_sets())
    assert "eval[0]" in progs
    spans = [p for p in progs if p.startswith("train_span[")]
    assert spans
    return spans


def test_live_ledger_matches_recount_dp2_and_zero1():
    for cfg, nb, bs, seq_len in (
        (_train_cfg(batch_size=8, num_workers=1, data_parallel=2,
                    scheme="full"), 1, 8, 8),
        (_train_cfg(batch_size=8, num_workers=2, data_parallel=2,
                    scheme="ring", zero1=True), 1, 8, 16),
    ):
        ds = _ds(bs, nb, seq_len)
        reg = MetricRegistry()
        tr = SeqTrainer(cfg, ds)
        tr.train(log=lambda s: None, metrics=reg)
        for p in _span_programs(reg):
            ops = collective_ops(
                program_text(_span_compiled(tr, p, ds, nb, bs))
            )
            _assert_program_ledger(reg, p, ops)


def test_live_ledger_matches_recount_hybrid_and_pp2():
    for cfg, nb, bs, seq_len in (
        (_train_cfg(batch_size=4, num_workers=2, data_parallel=2,
                    tensor_parallel=2, scheme="ring", zero1=True),
         1, 4, 16),
        (_train_cfg(batch_size=4, num_workers=1, pipeline_parallel=2,
                    microbatches=2, scheme="full"), 1, 4, 8),
    ):
        ds = _ds(bs, nb, seq_len)
        reg = MetricRegistry()
        tr = SeqTrainer(cfg, ds)
        tr.train(log=lambda s: None, metrics=reg)
        for p in _span_programs(reg):
            ops = collective_ops(
                program_text(_span_compiled(tr, p, ds, nb, bs))
            )
            _assert_program_ledger(reg, p, ops)


# -- live serve ledger == independent recount ---------------------------------

def test_serve_paged_ledger_matches_recount():
    from ddl_tpu.serve import engine as engine_mod

    reg = MetricRegistry()
    cfg = ServeConfig(spec=SPEC, slots=1, capacity=32, page_size=8,
                      num_pages=8, tensor_parallel=2)
    eng = InferenceEngine(cfg)
    sched = Scheduler(eng, registry=reg)
    done, _ = sched.run([Request(id=0, prompt=_prompt(6, 3),
                                 max_new_tokens=4)])
    assert done[0].status == "ok"
    g = reg.gauge("collective_bytes_total")
    progs = {ls["program"] for ls in g.label_sets()}
    assert any(p.startswith("prefill[") for p in progs)
    assert any(p.startswith("decode[") for p in progs)
    checked = 0
    for cache, kind in ((eng._prefill_fns, "prefill"),
                        (eng._decode_paged_fns, "decode")):
        for key, fn in cache.items():
            assert isinstance(fn, engine_mod._LedgeredProgram)
            if fn._compiled is None:  # built but never dispatched
                assert f"{kind}[{key}]" not in progs
                continue
            ops = collective_ops(program_text(fn._compiled))
            want = sum(o["bytes"] for o in ops)
            # tp=2: the per-block tensor-parallel psums are REAL wire
            # bytes — a zero here would mean the ledger parsed nothing.
            assert want > 0
            assert g.value(program=f"{kind}[{key}]") == want
            checked += 1
    assert checked >= 2


# -- off path: no registry, no HLO fetch, bare programs -----------------------

def test_off_path_never_fetches_hlo(monkeypatch):
    from ddl_tpu.obs import comms
    from ddl_tpu.serve import engine as engine_mod

    def _bomb(compiled):
        raise AssertionError("registry-less run fetched HLO text")

    monkeypatch.setattr(comms, "program_text", _bomb)
    # Trainer without metrics: the ledger block is never entered.
    ds = _ds(bs=8, nb=1, seq_len=8)
    cfg = _train_cfg(batch_size=8, num_workers=1, scheme="full")
    SeqTrainer(cfg, ds).train(log=lambda s: None)
    # Scheduler without a registry: no ledger hook, and the engine
    # caches hold BARE jitted programs — not _LedgeredProgram wrappers —
    # so the compiled artifacts are unchanged by construction.
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=1, capacity=32,
                                      page_size=8, num_pages=8))
    sched = Scheduler(eng)
    done, _ = sched.run([Request(id=0, prompt=_prompt(5, 1),
                                 max_new_tokens=3)])
    assert done[0].status == "ok"
    assert eng.ledger_hook is None
    for fn in (*eng._prefill_fns.values(),
               *eng._decode_paged_fns.values()):
        assert not isinstance(fn, engine_mod._LedgeredProgram)


# -- precision policy halves the gradient wire --------------------------------

def test_bf16_halves_gradient_wire_bytes_exactly():
    ds = _ds(bs=8, nb=1, seq_len=8)

    def wire(precision):
        cfg = _train_cfg(batch_size=8, num_workers=1, data_parallel=2,
                         scheme="full", precision=precision)
        tr = SeqTrainer(cfg, ds)
        xs = tr.stage_batches(ds.tokens, 1, 8)
        ys = tr.stage_batches(ds.targets, 1, 8)
        ws = tr.stage_batches(ds.weights, 1, 8)
        low = tr.span_program(1).lower(tr.params, tr.opt_state, xs, ys,
                                       ws, jnp.int32(0))
        # The AS-WRITTEN schedule: pre-optimization HLO. The CPU
        # backend's optimizer folds bf16 collectives back to f32
        # (converts are free host-side), so only this text shows the
        # bytes a bf16-honoring interconnect would move. Non-scalar
        # all-reduce/reduce-scatter = the gradient reductions (the
        # scalar loss/denominator psums stay fp32 under the policy).
        ops = collective_ops(low.as_text(dialect="hlo"))
        return sum(o["bytes"] for o in ops
                   if o["op"] in ("all-reduce", "reduce-scatter")
                   and o["max_elems"] > 1)

    fp32, bf16 = wire("fp32"), wire("bf16")
    assert bf16 > 0
    assert fp32 == 2 * bf16


# -- host byte plane: preempt -> adopt round trip == kv_row_bytes oracle ------

def _pin_handoff_roundtrip(tp, kv_dtype):
    reg = MetricRegistry()
    cfg = ServeConfig(spec=SPEC, slots=1, capacity=32, page_size=8,
                      num_pages=8, tensor_parallel=tp, kv_dtype=kv_dtype)
    eng = InferenceEngine(cfg)
    s = Scheduler(eng, registry=reg)
    s.begin()
    s.submit(Request(id=0, prompt=_prompt(6, 3), max_new_tokens=6))
    for _ in range(3):
        s.tick()
    pre = s.preempt(0)
    pages = int(pre.pos.shape[0])
    assert pages > 0
    oracle = pages * cfg.page_size * kv_row_bytes(SPEC, kv_dtype,
                                                  np.float32)
    assert eng.handoff_bytes(pages) == oracle
    c = reg.get("handoff_bytes_total")
    assert c is not None
    assert int(c.value(path="preempt")) == oracle
    s.adopt(pre)
    # The load side counts nothing: one round trip stays ONE count.
    assert int(c.value(path="preempt")) == oracle
    while not s.idle:
        s.tick()
    done, _ = s.collect()
    s.release()
    assert done[0].status == "ok"


@pytest.mark.parametrize("tp", [1, 2])
def test_handoff_roundtrip_bytes_oracle_fp32(tp):
    _pin_handoff_roundtrip(tp, None)


@pytest.mark.parametrize("tp", [1, 2])
def test_handoff_roundtrip_bytes_oracle_int8(tp):
    _pin_handoff_roundtrip(tp, "int8")


def test_int8_handoff_compression_ratio():
    # TINY_SPEC head_dim = 32/2 = 16: fp32 row = 2*L*H*16*4, int8 row =
    # 2*L*H*(16+4) — 3.2x exactly, comfortably over the >=3x pin.
    fp32_row = kv_row_bytes(SPEC, None, np.float32)
    int8_row = kv_row_bytes(SPEC, "int8", np.float32)
    assert fp32_row / int8_row == pytest.approx(3.2)
    assert fp32_row >= 3 * int8_row
