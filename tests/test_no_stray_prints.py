"""Tier-1 AST audit: no ``print(`` in library code (ISSUE 5 satellite;
the pattern of test_markers.py).

The obs layer exists so subsystems report through the tracer/registry
(or the trainers' injected ``log`` callbacks) instead of ad-hoc stdout
writes that no tool can consume. This audit makes that rule MECHANICAL:
any ``print(...)`` call in ``ddl_tpu/`` outside ``cli.py`` (the
user-facing launcher, whose job IS stdout) fails the suite. Strings
that merely contain the word (docstrings, subprocess probe source) are
not calls and pass; ``log=print`` default arguments are Name
references, not calls, and pass too. Pure AST — no imports, no
execution; runs in milliseconds."""

from __future__ import annotations

import ast
import pathlib

# The user-facing launcher: stdout is its interface. EVERYTHING else in
# the package reports through obs (tracer/registry) or a log callback.
ALLOWED_FILES = {"cli.py"}


def print_calls(tree) -> list[int]:
    """Line numbers of every ``print(...)`` CALL in a module's AST."""
    return [
        node.lineno
        for node in ast.walk(tree)
        if isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "print"
    ]


def test_no_print_calls_outside_cli():
    pkg = pathlib.Path(__file__).parent.parent / "ddl_tpu"
    violations = []
    for path in sorted(pkg.rglob("*.py")):
        if path.name in ALLOWED_FILES:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        violations += [
            (str(path.relative_to(pkg)), line) for line in print_calls(tree)
        ]
    assert not violations, (
        f"print() calls in library code: {violations} — route them "
        "through the obs tracer/registry or the trainer log callback "
        "(only cli.py may print; README Observability)"
    )


def test_audit_detector_self_pinned():
    """Pin the detector on synthetic sources so its teeth cannot rot:
    calls flag (module level, nested, keyword-arg'd); docstrings,
    string literals containing 'print(', ``log=print`` defaults and
    ``sys.stdout.write`` do not."""
    flagged = ast.parse(
        "print('a')\n"
        "def f():\n"
        "    print('b', flush=True)\n"
        "class C:\n"
        "    def m(self):\n"
        "        if True:\n"
        "            print('c')\n"
    )
    assert print_calls(flagged) == [1, 3, 7]
    clean = ast.parse(
        '"""print(docstring)"""\n'
        "import sys\n"
        "code = \"import jax; print(jax.devices())\"\n"
        "def g(log=print):\n"
        "    log('fine')\n"
        "    sys.stdout.write('also fine')\n"
    )
    assert print_calls(clean) == []
