"""Adam tests against a hand-rolled numpy oracle of the TF1 formulation
(the reference's tf.compat.v1.train.AdamOptimizer, model/model.py:93)."""

import numpy as np
import jax
import jax.numpy as jnp

from ddl_tpu.ops import adam_init, adam_update


def _numpy_tf_adam(params, grads_seq, lr=1e-4, b1=0.9, b2=0.999, eps=1e-8):
    """TF1 Adam: p -= lr * sqrt(1-b2^t)/(1-b1^t) * m / (sqrt(v) + eps)."""
    p = {k: v.copy() for k, v in params.items()}
    m = {k: np.zeros_like(v) for k, v in params.items()}
    v = {k: np.zeros_like(x) for k, x in params.items()}
    for t, grads in enumerate(grads_seq, start=1):
        lr_t = lr * np.sqrt(1 - b2**t) / (1 - b1**t)
        for k in p:
            m[k] = b1 * m[k] + (1 - b1) * grads[k]
            v[k] = b2 * v[k] + (1 - b2) * grads[k] ** 2
            p[k] -= lr_t * m[k] / (np.sqrt(v[k]) + eps)
    return p


def test_adam_matches_tf_formula():
    rng = np.random.default_rng(0)
    params = {
        "w": rng.standard_normal((4, 3)).astype(np.float32),
        "b": rng.standard_normal((3,)).astype(np.float32),
    }
    grads_seq = [
        {k: rng.standard_normal(v.shape).astype(np.float32) for k, v in params.items()}
        for _ in range(5)
    ]
    expected = _numpy_tf_adam(params, grads_seq)

    p = {k: jnp.asarray(v) for k, v in params.items()}
    state = adam_init(p)
    for grads in grads_seq:
        p, state = adam_update(p, state, {k: jnp.asarray(g) for k, g in grads.items()})
    for k in params:
        np.testing.assert_allclose(np.asarray(p[k]), expected[k], rtol=1e-5, atol=1e-7)
    assert int(state.step) == 5


def test_adam_jit_and_tree_structure():
    params = {"a": jnp.ones((2, 2)), "nested": {"b": jnp.zeros((3,))}}
    state = adam_init(params)
    grads = jax.tree.map(jnp.ones_like, params)
    step = jax.jit(lambda p, s, g: adam_update(p, s, g))
    p2, s2 = step(params, state, grads)
    assert jax.tree.structure(p2) == jax.tree.structure(params)
    assert jax.tree.structure(s2.m) == jax.tree.structure(params)
    # First step with all-ones grads: update ~= lr * g/|g| = lr.
    np.testing.assert_allclose(
        np.asarray(p2["a"]), np.ones((2, 2)) - 1e-4, rtol=1e-4
    )
