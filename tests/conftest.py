"""Test environment: force an 8-device virtual CPU mesh so every multi-chip
strategy is exercised hermetically (SURVEY.md section 4b).

The TPU tunnel's sitecustomize registers its PJRT plugin and forces
``jax_platforms`` programmatically, so env vars alone are not enough — we
must override the config after importing jax and before any backend is
initialized."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Raise the CPU in-process collective rendezvous abort threshold: on a
# loaded single-core host the 8 device threads can legitimately skew past
# the default ~40s and the runtime HARD-ABORTS the process (see
# mesh.extend_cpu_collective_timeouts). 300s (not the 900s bench default):
# a REAL collective deadlock should still abort with the rendezvous
# diagnostic well inside the suite's documented 600s chunk timeouts.
from ddl_tpu.parallel.mesh import extend_cpu_collective_timeouts  # noqa: E402

extend_cpu_collective_timeouts(kill_s=300)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    f"tests need the 8-device virtual CPU mesh, got {jax.devices()}"
)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from ddl_tpu.data import load_mnist  # noqa: E402
from ddl_tpu.models import cnn  # noqa: E402

# Narrow-width instance of the reference architecture family: identical
# structure (14 vars, 4 conv+pool stages, 2 dropout FCs) at ~1/400 the
# FLOPs, so multi-device integration tests fit a single-core CPU host.
# Full-width parity with the torch oracle is covered in test_model.py.
# Same widths as the CLI --tiny preset and the driver dryrun.
SMALL_SPECS = cnn.make_param_specs(
    conv_channels=cnn.TINY_CONV_CHANNELS, fc_sizes=cnn.TINY_FC_SIZES
)


@pytest.fixture(scope="session")
def small_dataset():
    """A small deterministic procedural dataset shared across tests."""
    return load_mnist(path=None, synthetic_train=2048, synthetic_test=512, seed=7)


@pytest.fixture(scope="session")
def small_params():
    """Params for the narrow test model (see SMALL_SPECS)."""
    return cnn.init_params(jax.random.PRNGKey(3), specs=SMALL_SPECS)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
