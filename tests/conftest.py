"""Test environment: force an 8-device virtual CPU mesh before JAX loads,
so every multi-chip strategy is exercised hermetically (SURVEY.md section 4b)."""

import os

# Force CPU even when the environment pins a TPU platform (JAX_PLATFORMS=axon):
# tests must be hermetic and exercise the 8-device virtual mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from ddl_tpu.data import load_mnist  # noqa: E402


@pytest.fixture(scope="session")
def small_dataset():
    """A small deterministic procedural dataset shared across tests."""
    return load_mnist(path=None, synthetic_train=2048, synthetic_test=512, seed=7)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
