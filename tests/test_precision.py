"""Mixed precision end-to-end (ISSUE 19): the precision policy's two
contracts and the int8 KV pool's one.

Training: ``precision="fp32"`` (and ``None``) must compile the
BYTE-IDENTICAL pre-policy program in every step body — the off-path
discipline is pinned as lowered-HLO text equality over the strategy
matrix (plain / zero1 / tp / zero1+tp / pipeline) and the single-chip
CNN step. ``precision="bf16"`` trains: its loss trajectory tracks the
fp32 run at bf16 tolerance while master weights and Adam moments stay
fp32 leaves (the arXiv 2204.06514 split ddl_tpu.precision documents).

Serving: ``kv_dtype="int8"`` stores the paged pool as int8 rows with
fp32 per-head scales. Off-path the fp32 pool must flatten to its three
historical leaves and compile programs that mention no ``s8`` — the
same byte-identity discipline, at the pytree/HLO level. On-path: greedy
tokens match the fp32 pool on the tiny spec, quantization error is
bounded by half a scale step, and a dumped page set survives
preempt/adopt spill→restore BIT-identically (payload, scales, and
positions) with the continuation matching an unpreempted oracle — at
tp=1 in tier-1 and tp=2 under the slow marker.

Every scheduler-driving test stays inside the tier-1 audit budget
(tests/test_markers.py: <= 64 estimated tokens, <= 2 topologies — the
ISSUE 19 variant ledger included).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu import precision
from ddl_tpu.data.lm import synthesize_copy, synthesize_mixed_traffic
from ddl_tpu.models import cnn
from ddl_tpu.models.transformer import TINY_SPEC, LMSpec
from ddl_tpu.ops import kv_cache
from ddl_tpu.serve import (
    ClassSpec,
    InferenceEngine,
    Request,
    Router,
    RouterConfig,
    Scheduler,
    ServeConfig,
)
from ddl_tpu.serve.cache import kv_row_bytes
from ddl_tpu.strategies.seq import SeqConfig, SeqTrainer
from ddl_tpu.train.config import TrainConfig
from ddl_tpu.train.trainer import make_train_step
from ddl_tpu.utils import load_checkpoint, save_checkpoint

SPEC = LMSpec(vocab=17, d_model=8, num_heads=2, num_layers=2, d_ff=16)

# The conftest's narrow CNN (same widths as the CLI --tiny preset).
CNN_SPECS = cnn.make_param_specs(
    conv_channels=cnn.TINY_CONV_CHANNELS, fc_sizes=cnn.TINY_FC_SIZES
)


# -- policy resolution --------------------------------------------------------


def test_policy_resolution_matrix():
    """The ONE resolution rule (precision.resolve): None/None is fp32,
    a bare legacy compute_dtype stays the pre-policy bf16 (compute
    casts, fp32 reductions), the named policies engage fully, and the
    two knobs disagreeing is a loud error — not a silent mislabel."""
    p = precision.resolve(None, None)
    assert p.name == "fp32" and not p.is_mixed and p.mfu_kind == "fp32"
    assert p.compute_dtype is None and not p.reduces_in_bf16

    legacy = precision.resolve(None, "bfloat16")
    assert legacy.name == "bf16" and legacy.legacy and legacy.is_mixed
    assert legacy.compute_dtype == jnp.bfloat16
    assert not legacy.reduces_in_bf16  # pre-policy programs unchanged
    assert legacy.mfu_kind == "bf16"  # ...but the MXU row is honest

    full = precision.resolve("bf16", None)
    assert full.reduces_in_bf16 and full.mfu_kind == "bf16"
    assert precision.resolve("fp32", None).compute_dtype is None
    # Agreeing knobs are allowed; disagreeing knobs raise.
    assert precision.resolve("bf16", "bfloat16").reduces_in_bf16
    with pytest.raises(ValueError, match="conflicts"):
        precision.resolve("fp32", "bfloat16")
    with pytest.raises(ValueError, match="unknown precision"):
        precision.resolve("fp16", None)
    with pytest.raises(ValueError, match="KV-STORAGE"):
        precision.resolve(None, "int8")


def test_grad_cast_hooks_touch_only_float_leaves():
    """cast_grads moves float leaves to bf16 and upcast_grads back to
    fp32; integer leaves (step counters, token ids) pass through both
    untouched; and for fp32/legacy policies BOTH hooks are Python-level
    identity — the very same tree object, so the off-path step bodies
    trace the pre-policy program."""
    tree = {"w": jnp.ones((3,), jnp.float32), "step": jnp.int32(7)}
    for p in (precision.resolve(None, None),
              precision.resolve(None, "bfloat16")):
        assert p.cast_grads(tree) is tree
        assert p.upcast_grads(tree) is tree
    p = precision.resolve("bf16", None)
    down = p.cast_grads(tree)
    assert down["w"].dtype == jnp.bfloat16
    assert down["step"].dtype == jnp.int32
    up = p.upcast_grads(down)
    assert up["w"].dtype == jnp.float32 and up["step"].dtype == jnp.int32


# -- fp32 off-path: byte-identical programs -----------------------------------


def _span_hlo(cfg, ds):
    tr = SeqTrainer(cfg, ds)
    xs = tr.stage_batches(ds.tokens, 2, 4)
    ys = tr.stage_batches(ds.targets, 2, 4)
    ws = tr.stage_batches(ds.weights, 2, 4)
    return tr.span_program(2).lower(
        tr.params, tr.opt_state, xs, ys, ws, jnp.int32(0)
    ).as_text()


def test_fp32_policy_seq_programs_byte_identical():
    """precision="fp32" lowers the byte-identical program in EVERY seq
    step body — plain, zero1, tensor-parallel, the hybrid zero1+tp, and
    the pipeline schedule. HLO text equality, the strongest off-path
    pin the repo uses (stricter than numerics: no reordered op
    survives)."""
    ds = synthesize_copy(num_train=8, num_test=4, seq_len=8,
                         vocab=SPEC.vocab, seed=0)
    base = dict(batch_size=4, scheme="full", num_workers=1, spec=SPEC,
                epochs=1)
    for extra in ({}, {"zero1": True}, {"tensor_parallel": 2},
                  {"zero1": True, "tensor_parallel": 2},
                  {"pipeline_parallel": 2, "microbatches": 2}):
        a = _span_hlo(SeqConfig(**base, **extra), ds)
        b = _span_hlo(SeqConfig(**base, **extra, precision="fp32"), ds)
        assert a == b, f"fp32 policy changed the {extra or 'plain'} program"


def test_fp32_policy_cnn_step_byte_identical():
    """The single-chip CNN trainer's step under precision="fp32" is the
    byte-identical default program (make_train_step reads the policy's
    compute_dtype: None = the no-cast path)."""
    from ddl_tpu.ops.optimizers import adam_init

    params = cnn.init_params(jax.random.PRNGKey(0), specs=CNN_SPECS)
    opt = adam_init(params)
    x = jnp.zeros((4, 28, 28, 1), jnp.float32)
    y = jnp.zeros((4, 10), jnp.float32)
    rng = jax.random.PRNGKey(1)

    def hlo(cfg):
        step = make_train_step(cfg)
        return jax.jit(step).lower(params, opt, x, y, rng).as_text()

    assert hlo(TrainConfig()) == hlo(TrainConfig(precision="fp32"))


def test_fp32_paged_serve_off_path_no_int8():
    """Off-path serve discipline at both levels: a full-precision paged
    cache flattens to its THREE historical leaves (the None scale
    fields vanish from the pytree, so donation/sharding treat the cache
    exactly as before ISSUE 19), and the lowered fp32 decode program
    text mentions no s8 — the int8 pool left zero trace."""
    cfg = dict(spec=TINY_SPEC, slots=2, capacity=32, page_size=8,
               num_pages=16)
    eng = InferenceEngine(ServeConfig(**cfg))
    assert not eng.quantized
    assert len(jax.tree.leaves(eng.cache)) == 3
    txt = eng._decode_paged(2).lower(
        eng.params, eng.cache,
        np.zeros(2, np.int32), np.zeros(2, np.int32),
        np.zeros(2, np.int32), np.zeros(2, bool),
        np.zeros((2, eng.max_pages), np.int32),
    ).as_text()
    assert " s8[" not in txt
    # The int8 pool carries exactly the two extra scale planes.
    q = InferenceEngine(ServeConfig(**cfg, kv_dtype="int8"))
    assert q.quantized and len(jax.tree.leaves(q.cache)) == 5


# -- bf16 on-path: trains, tracks fp32, masters stay fp32 ---------------------


def test_bf16_cnn_loss_tracks_fp32_masters_stay_fp32():
    """Five bf16 CNN steps on fixed data: every loss is finite and
    within bf16 tolerance of the fp32 trajectory, and the params
    leaving each step are STILL fp32 leaves (master weights — the
    in-loss cast's transpose upcasts cotangents, so Adam runs fp32)."""
    from ddl_tpu.ops.optimizers import adam_init

    key = jax.random.PRNGKey(2)
    params0 = cnn.init_params(key, specs=CNN_SPECS)
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 28, 28, 1))
    y = jax.nn.one_hot(
        jax.random.randint(jax.random.PRNGKey(4), (8,), 0, 10), 10
    )

    def run(cfg):
        step = jax.jit(make_train_step(cfg))
        p, o = params0, adam_init(params0)
        losses = []
        for i in range(5):
            p, o, loss = step(p, o, x, y, jax.random.PRNGKey(i))
            losses.append(float(loss))
        return losses, p

    cfg = dict(learning_rate=1e-3, keep_prob=1.0)
    ref, p_ref = run(TrainConfig(**cfg))
    got, p_bf = run(TrainConfig(**cfg, precision="bf16"))
    assert all(np.isfinite(got)), got
    np.testing.assert_allclose(got, ref, rtol=0.1, atol=0.05)
    for leaf in jax.tree.leaves(p_bf):
        assert leaf.dtype == jnp.float32
    # The trajectories agree loss-wise AND the masters stay close.
    for a, b in zip(jax.tree.leaves(p_bf), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-2)


def test_bf16_lm_loss_tracks_fp32():
    """The distributed twin: one 2-step LM span under precision="bf16"
    (bf16 activations AND bf16 gradient reduction) lands within bf16
    tolerance of the fp32 span, with fp32 master params out."""
    ds = synthesize_copy(num_train=8, num_test=4, seq_len=8,
                         vocab=SPEC.vocab, seed=0)
    base = dict(batch_size=4, scheme="full", num_workers=1, spec=SPEC,
                epochs=1)

    def run(cfg):
        tr = SeqTrainer(cfg, ds)
        xs = tr.stage_batches(ds.tokens, 2, 4)
        ys = tr.stage_batches(ds.targets, 2, 4)
        ws = tr.stage_batches(ds.weights, 2, 4)
        out = tr.span_program(2)(tr.params, tr.opt_state, xs, ys, ws,
                                 jnp.int32(0))
        return float(out[2]), out[0]

    ref, _ = run(SeqConfig(**base, precision="fp32"))
    got, params = run(SeqConfig(**base, precision="bf16"))
    assert np.isfinite(got)
    assert abs(got - ref) < 0.1 * abs(ref) + 0.05, (got, ref)
    for leaf in jax.tree.leaves(params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32


# -- int8 KV pool -------------------------------------------------------------


def test_int8_quantize_dequantize_error_bound():
    """The op-level contract: per-head symmetric absmax — dequantized
    error is bounded by half a scale step elementwise, all-zero rows
    round-trip EXACTLY (scale 1.0, payload 0), payload is int8 in
    [-127, 127], and the scale drops the trailing head axis."""
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 3, 4, 16)) * 3.0
    x = x.at[0, 0].set(0.0)  # an all-zero head row
    q, scale = kv_cache.quantize_rows(x)
    assert q.dtype == jnp.int8 and scale.dtype == jnp.float32
    assert scale.shape == x.shape[:-1]
    assert int(jnp.max(jnp.abs(q))) <= 127
    back = kv_cache.dequantize_rows(q, scale, jnp.float32)
    err = np.asarray(jnp.abs(back - x))
    bound = np.asarray(scale)[..., None] / 2 + 1e-7
    assert (err <= bound).all(), err.max()
    np.testing.assert_array_equal(np.asarray(back[0, 0]), 0.0)
    # bf16 storage dtype out: the cast happens AFTER the exact fp32
    # multiply, so the result is the bf16 rounding of the fp32 dequant.
    back16 = kv_cache.dequantize_rows(q, scale, jnp.bfloat16)
    assert back16.dtype == jnp.bfloat16


def test_kv_row_bytes_envelope():
    """The byte-envelope arithmetic the bench sizes pools with: fp32
    rows cost 2*L*H*D*4, int8 rows 2*L*H*(D+4) (1-byte payload + the
    amortized 4-byte per-head scale), compression 4D/(D+4) — 3.2x at
    head_dim 16."""
    s = TINY_SPEC
    L, H, D = s.num_layers, s.num_heads, s.d_model // s.num_heads
    assert kv_row_bytes(s, None) == 2 * L * H * D * 4
    assert kv_row_bytes(s, "int8") == 2 * L * H * (D + 4)
    ratio = kv_row_bytes(s, None) / kv_row_bytes(s, "int8")
    assert ratio == pytest.approx(4 * D / (D + 4))
    with pytest.raises(ValueError, match="kv_dtype"):
        kv_row_bytes(s, "fp8")


def test_serve_kv_dtype_validation_both_directions():
    """Loud ctor (the PR 4/6 pattern): unknown kv_dtype and int8 on the
    contiguous layout are construction errors naming the fix; the
    matching good config constructs quantized."""
    good = dict(spec=TINY_SPEC, slots=2, capacity=32)
    with pytest.raises(ValueError, match="kv_dtype"):
        InferenceEngine(ServeConfig(**good, page_size=8, kv_dtype="fp8"))
    with pytest.raises(ValueError, match="paged"):
        InferenceEngine(ServeConfig(**good, kv_dtype="int8"))
    eng = InferenceEngine(ServeConfig(**good, page_size=8,
                                      kv_dtype="int8"))
    assert eng.quantized and eng.cache.k.dtype == jnp.int8
    assert eng.cache.k_scale.dtype == jnp.float32


def test_int8_tokens_match_fp32_greedy():
    """On-path acceptance at tier-1 scale: the int8 pool's greedy
    tokens equal the fp32 pool's on the tiny spec (per-head absmax at
    these magnitudes leaves the argmax untouched — the bench measures
    the general-tolerance version at scale)."""
    cfg = dict(spec=TINY_SPEC, slots=2, capacity=32, page_size=8,
               num_pages=16)
    host = jax.device_get(InferenceEngine(ServeConfig(**cfg)).params)
    prompt = (np.arange(1, 11) * 3) % TINY_SPEC.vocab

    def run(extra):
        s = Scheduler(InferenceEngine(ServeConfig(**cfg, **extra),
                                      params=host))
        done, _ = s.run([Request(id=1, prompt=prompt, max_new_tokens=8)])
        return done[1].tokens

    assert run(dict(kv_dtype="int8")) == run(dict())


def _preempt_adopt_roundtrip(tp: int):
    """Spill→restore: preempt mid-decode, adopt elsewhere, require the
    restored pages BIT-identical (payload + scales + pos) and the
    continuation equal to an unpreempted oracle."""
    cfg = dict(spec=TINY_SPEC, slots=2, capacity=32, page_size=8,
               num_pages=16, kv_dtype="int8", tensor_parallel=tp)
    host = jax.device_get(InferenceEngine(ServeConfig(**cfg)).params)
    prompt = (np.arange(1, 11) * 3) % TINY_SPEC.vocab
    mk = lambda: Scheduler(InferenceEngine(ServeConfig(**cfg),
                                           params=host))
    req = lambda: Request(id=11, prompt=prompt, max_new_tokens=8)
    src, oracle, dst = mk(), mk(), mk()
    for s in (src, oracle, dst):
        s.begin()
    src.submit(req())
    oracle.submit(req())
    for _ in range(4):
        src.tick()
        oracle.tick()
    pre = src.preempt(11)
    # Int8 pools travel as (payload, scale) pairs end to end.
    assert isinstance(pre.k, tuple) and isinstance(pre.v, tuple)
    assert pre.k[0].dtype == np.int8 and pre.k[1].dtype == np.float32
    slot = dst.adopt(pre)
    # The restored slot's pages are the dumped bytes, bit for bit.
    (k2, ks2), (v2, vs2), pos2 = dst.engine.dump_slot_pages(slot)
    np.testing.assert_array_equal(k2, pre.k[0])
    np.testing.assert_array_equal(ks2, pre.k[1])
    np.testing.assert_array_equal(v2, pre.v[0])
    np.testing.assert_array_equal(vs2, pre.v[1])
    np.testing.assert_array_equal(pos2, pre.pos)
    while not oracle.idle:
        oracle.tick()
    while not dst.idle:
        dst.tick()
    want, _ = oracle.collect()
    got, _ = dst.collect()
    assert got[11].tokens == want[11].tokens


def test_int8_preempt_adopt_bit_identical_tp1():
    _preempt_adopt_roundtrip(1)


@pytest.mark.slow
def test_int8_preempt_adopt_bit_identical_tp2():
    """tp=2: per-shard heads dump/restore through the SAME pair
    protocol — the assembled host arrays round-trip bitwise and the
    adopted continuation matches the tp=2 oracle."""
    _preempt_adopt_roundtrip(2)


def test_int8_dump_needs_matching_pool():
    """Mismatched hand-offs fail LOUDLY in both directions: an int8
    dump refuses to land in a full-precision pool and vice versa — a
    silent dequant-to-garbage would poison the adopted request's whole
    continuation."""
    base = dict(spec=TINY_SPEC, slots=2, capacity=32, page_size=8,
                num_pages=16)
    fp = InferenceEngine(ServeConfig(**base))
    q = InferenceEngine(ServeConfig(**base, kv_dtype="int8"))
    k = np.zeros((TINY_SPEC.num_layers, 1, 8, TINY_SPEC.num_heads,
                  TINY_SPEC.d_model // TINY_SPEC.num_heads), np.float32)
    pos = np.zeros((1, 8), np.int32)
    with pytest.raises(ValueError, match="full-precision"):
        fp.load_slot_pages(0, (k, k[..., 0]), (k, k[..., 0]), pos)
    with pytest.raises(ValueError, match="int8 pool"):
        q.load_slot_pages(0, k, k, pos)


def test_int8_disagg_handoff_transparent():
    """The third compressed hand-off surface (with preempt/adopt and
    crash requeue): a 1-prefill + 1-decode int8 fleet reproduces the
    int8 colocated fleet's tokens on the same seeded stream — the
    per-tick prefill→decode page transfer moves (payload, scale) pairs
    without a dequant round-trip — with every multi-token request
    crossing exactly once and both quantized pools byte-whole after."""
    traffic = synthesize_mixed_traffic(
        classes={"chat": dict(rate=0.6, prompt_min=6, prompt_max=10,
                              max_new_tokens=4)},
        horizon=8, vocab=TINY_SPEC.vocab, seed=1, max_requests=6,
    )
    cfg = ServeConfig(spec=TINY_SPEC, slots=2, capacity=32, page_size=8,
                      num_pages=12, kv_dtype="int8")
    rc = RouterConfig(serve=cfg, replicas=2, classes=(ClassSpec("chat"),))
    done_c, _ = Router(rc).run(traffic)

    r_dis = Router(dataclasses.replace(rc, roles=("prefill", "decode")))
    done_d, stats_d = r_dis.run(traffic)

    assert {i: done_d[i].tokens for i in done_d} == \
        {i: done_c[i].tokens for i in done_c}
    multi = sum(1 for c in done_c.values() if len(c.tokens) > 1)
    assert stats_d.disagg["handoffs"] == multi > 0
    assert stats_d.disagg["handoff_pages"] > 0
    for eng in r_dis.engines:
        assert eng.quantized
        assert eng.pages.free == eng.num_pages
        assert eng.pages.reserved == 0


# -- checkpoint dtype pins ----------------------------------------------------


def test_checkpoint_dtype_mismatch_names_leaf(tmp_path):
    """Loading a checkpoint into a template whose leaf dtype differs is
    a ValueError NAMING the leaf and both dtypes (ISSUE 19 satellite:
    precision policies keep master state fp32 — a silent cast on load
    would let a bf16-template restore masquerade as the saved run)."""
    path = tmp_path / "ckpt.npz"
    tree = {"w": np.ones((3,), np.float32), "n": np.int32(2)}
    save_checkpoint(path, tree, step=1)
    got, step, _ = load_checkpoint(path, tree)
    assert step == 1 and got["w"].dtype == np.float32
    bad = {"w": np.ones((3,), np.float16), "n": np.int32(2)}
    with pytest.raises(ValueError, match=r"w.*float32"):
        load_checkpoint(path, bad)
