"""The million-request digital twin (serve.sim + serve.scenarios,
ISSUE 18).

THE parity pin: the cost-model engine replays the two pinned CI
scenarios — bulk_burst and replica_crash — TICK-FOR-TICK against the
real fleet: identical controller event timelines, identical per-class
request/shed tallies, identical per-request admission ticks and final
statuses, across two fresh sim runs AND against the real engine.  The
twin's only deltas are the token VALUES (hashed, not sampled) and the
clock (virtual, not wall) — every control-plane decision is the real
one, because the sim runs the real scheduler/router/controller code
against mirrored host bookkeeping.

Transparency: a twin run is always LABELLED — ``fleet_engine_sim`` in
the registry, ``engine_kind`` in the fleet digest and ``/healthz`` —
and renders through the SAME obs.analyze incident table as a real run.

Scale: the slow-marked smoke replays a 1,000,000-request diurnal trace
over a 128-replica sim fleet on CPU inside the CI wall budget — the
policy-search envelope no real CPU fleet could touch.
"""

import dataclasses
import json
import urllib.request

import numpy as np
import pytest

from ddl_tpu.models.transformer import TINY_SPEC
from ddl_tpu.obs import MetricRegistry, Tracer
from ddl_tpu.obs.export import MetricsExporter
from ddl_tpu.obs.goodput import fleet_summary, phase_cost_fit
from ddl_tpu.obs.slo import SloMonitor
from ddl_tpu.obs.trace import NULL_TRACER
from ddl_tpu.resilience.faults import (
    FaultSpec,
    FaultStorm,
    parse_fault_storm,
)
from ddl_tpu.serve import (
    AutoscaleConfig,
    FleetController,
    Request,
    Router,
    Scheduler,
    ServeConfig,
)
from ddl_tpu.serve.engine_iface import ServeEngine, engine_kind
from ddl_tpu.serve.scenarios import (
    BULK_BURST,
    DIURNAL,
    REPLICA_CRASH,
    SCENARIOS,
    get_scenario,
    parse_scenario,
)
from ddl_tpu.serve.sim import CostModel, CostModelEngine, sim_engine_factory

SPEC = TINY_SPEC


def _prompt(n, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(1, SPEC.vocab, size=n, dtype=np.int32)


def _arm(scn, *, sim):
    """One fleet run of scenario ``scn`` — real engines or the
    cost-model twin, everything else identical (the parity harness)."""
    factory = sim_engine_factory() if sim else None
    traffic = scn.build_traffic(SPEC.vocab)
    reg, tr = MetricRegistry(), Tracer()
    mon = None
    if scn.slo_rule_classes:
        mon = SloMonitor(scn.slo_rules(), reg, tracer=tr)
    ctrl = scn.make_controller()
    router = Router(scn.router_config(SPEC, engine_factory=factory),
                    registry=reg, tracer=tr, slo_monitor=mon,
                    controller=ctrl)
    done, stats = router.run(traffic)
    return done, stats, ctrl, mon, reg, tr


def _assert_tick_parity(real, sim):
    """Controller timeline + per-request admission/status + per-class
    tallies identical between two arms."""
    done_a, stats_a, ctrl_a = real[0], real[1], real[2]
    done_b, stats_b, ctrl_b = sim[0], sim[1], sim[2]
    assert ctrl_b.events == ctrl_a.events
    assert sorted(done_b) == sorted(done_a)
    assert {i: done_b[i].status for i in done_b} == \
        {i: done_a[i].status for i in done_a}
    assert {i: done_b[i].admitted_step for i in done_b} == \
        {i: done_a[i].admitted_step for i in done_a}
    for c in stats_a.per_class:
        a, b = stats_a.per_class[c], stats_b.per_class[c]
        assert (b.requests, b.shed) == (a.requests, a.shed), c
    assert stats_b.router_sheds == stats_a.router_sheds


def test_bulk_burst_twin_parity_tick_for_tick():
    """THE parity pin, scenario 1: the autoscaled bulk-burst run —
    scale_out/drain/scale_in timeline, every admission tick, every
    shed, the SLO burn ledger — replays identically on the cost-model
    twin, across two fresh twin runs, and each arm self-labels its
    engine kind in the fleet digest."""
    real = _arm(BULK_BURST, sim=False)
    sim1 = _arm(BULK_BURST, sim=True)
    sim2 = _arm(BULK_BURST, sim=True)
    _assert_tick_parity(real, sim1)
    _assert_tick_parity(sim1, sim2)
    assert real[2].scale_outs >= 1  # the scenario actually scaled
    for name in ("bulk_shed", "chat_shed"):
        assert sim1[3].cumulative(name) == real[3].cumulative(name)
    assert fleet_summary(sim1[4])["engine_kind"] == "sim"
    assert fleet_summary(real[4])["engine_kind"] == "real"


def test_replica_crash_twin_parity_tick_for_tick():
    """THE parity pin, scenario 2: the seeded replica crash — crash
    tick, requeue count, heal, exactly-once completion — replays
    identically on the twin; the crashed replica's stats slot reads
    None in both arms and the crash counters agree."""
    real = _arm(REPLICA_CRASH, sim=False)
    sim1 = _arm(REPLICA_CRASH, sim=True)
    sim2 = _arm(REPLICA_CRASH, sim=True)
    _assert_tick_parity(real, sim1)
    _assert_tick_parity(sim1, sim2)
    for arm in (real, sim1, sim2):
        done, stats, ctrl = arm[0], arm[1], arm[2]
        assert ctrl.crashes == 1
        assert all(done[i].status == "ok" for i in done)
        assert stats.replica[1] is None
        assert stats.fleet["crashes"] == 1
    assert sim1[2].requeues == real[2].requeues
    crash_a = [r for r in real[5].records if r["name"] == "replica_crash"]
    crash_b = [r for r in sim1[5].records if r["name"] == "replica_crash"]
    assert len(crash_a) == len(crash_b) == 1
    assert crash_a[0]["attrs"]["replica"] == crash_b[0]["attrs"]["replica"]


def test_twin_run_renders_through_analyze_report():
    """Transparency: a twin run's trace renders through the SAME
    obs.analyze fleet-incident table as a real run — no special-cased
    sim path, same FLEET_EVENTS kinds."""
    from ddl_tpu.obs.analyze import build_report

    arm = _arm(BULK_BURST, sim=True)
    rep = build_report(arm[5].records)
    kinds = [f["kind"] for f in rep["fleet_incidents"]]
    assert "scale_out" in kinds and "drain" in kinds
    assert rep["incidents"]["scale_out"] >= 1


def test_sim_engine_satisfies_serve_engine_protocol():
    """The control-plane contract: both engines satisfy the
    runtime-checkable ServeEngine protocol and self-report their kind
    (the twin can never masquerade — engine_kind defaults to real only
    for engines predating the interface)."""
    eng = CostModelEngine(ServeConfig(spec=SPEC, slots=1, capacity=32,
                                      page_size=8, num_pages=8))
    assert isinstance(eng, ServeEngine)
    assert engine_kind(eng) == "sim"
    assert engine_kind(object()) == "real"  # pre-interface default


def test_sim_engine_scheduler_roundtrip_and_virtual_time():
    """The cost-model engine drives the REAL scheduler end to end
    (paged admission, warmup ladder, prefix pool) — deterministic
    hashed tokens across two fresh engines, a monotone virtual-time
    ledger per phase, pools byte-whole after release."""
    cfg = ServeConfig(spec=SPEC, slots=2, capacity=32, page_size=8,
                      num_pages=12, prefix_slots=4)
    reqs = [Request(id=i, prompt=_prompt(6, 30 + i), max_new_tokens=4)
            for i in range(3)]
    eng = CostModelEngine(cfg)
    sched = Scheduler(eng)
    sched.warmup(reqs)  # the real warmup ladder, no compiles
    done, stats = sched.run(reqs)
    assert sorted(done) == [0, 1, 2]
    assert all(done[i].status == "ok" for i in done)
    assert all(len(done[i].tokens) == 4 for i in done)
    vt = eng.virtual_time()
    assert vt["prefill"] > 0 and vt["decode"] > 0
    assert vt["total"] == pytest.approx(
        vt["prefill"] + vt["decode"] + vt["handoff"])
    assert eng.pages.free == eng.num_pages and eng.pages.reserved == 0
    done2, _ = Scheduler(CostModelEngine(cfg)).run(reqs)
    assert {i: done2[i].tokens for i in done2} == \
        {i: done[i].tokens for i in done}


def test_sim_engine_preempt_adopt_bit_identical():
    """The twin mirrors the page hand-off: a request preempted off sim
    scheduler A and adopted on sim B emits the SAME hashed tokens as
    the unpreempted sim oracle (sampling state is (seed, request_id,
    token_index) in both worlds), the hand-off charges virtual
    hand-off time, and both pools read byte-whole."""
    cfg = ServeConfig(spec=SPEC, slots=1, capacity=32, page_size=8,
                      num_pages=8)
    req = Request(id=0, prompt=_prompt(6, 3), max_new_tokens=6)
    done_o, _ = Scheduler(CostModelEngine(cfg)).run([req])

    eng_a, eng_b = CostModelEngine(cfg), CostModelEngine(cfg)
    sa, sb = Scheduler(eng_a), Scheduler(eng_b)
    sa.begin()
    sb.begin()
    sa.submit(req)
    for _ in range(3):
        sa.tick()
    pre = sa.preempt(0)
    assert pre.k.shape[1] == pre.pos.shape[0]  # pages, table order
    sb.adopt(pre)
    while not sb.idle:
        sb.tick()
    done_a, _ = sa.collect()
    done_b, _ = sb.collect()
    sa.release()
    sb.release()
    assert done_a == {} and done_b[0].status == "ok"
    assert done_b[0].tokens == done_o[0].tokens
    assert eng_a.virtual_time()["handoff"] > 0  # the dump was charged
    for eng in (eng_a, eng_b):
        assert eng.pages.free == eng.num_pages
        assert eng.pages.reserved == 0


def test_sim_engine_rejects_speculation():
    """Loud-config: speculative decoding has no cost model (draft
    acceptance depends on token VALUES, which the twin hashes) — a
    speculate_k config is a named error, not silently-wrong numbers."""
    with pytest.raises(ValueError, match="cost-model"):
        CostModelEngine(ServeConfig(spec=SPEC, slots=2, capacity=32,
                                    page_size=8, num_pages=16,
                                    speculate_k=2))


def test_cost_model_fit_roundtrip_and_loud_errors():
    """phase_cost_fit: per-phase costs from a live registry and from a
    metrics JSONL agree exactly (last snapshot wins); a phase the run
    never attributed is a loud error naming it; the fitted dict feeds
    CostModel.from_phase_fit, which requires both serve phases."""
    reg = MetricRegistry()
    reg.gauge("time_in_seconds").set(1.2, phase="prefill")
    reg.gauge("time_in_seconds").set(0.8, phase="decode")
    reg.counter("serve_prefill_tokens_total").inc(1000)
    for _ in range(200):
        reg.histogram("serve_decode_step_seconds").observe(0.004)
    fit = phase_cost_fit(reg)
    assert fit["prefill_s_per_token"] == pytest.approx(0.0012)
    assert fit["decode_s_per_tick"] == pytest.approx(0.004)
    with pytest.raises(ValueError, match="handoff"):
        phase_cost_fit(reg, phases=("prefill", "decode", "handoff"))
    with pytest.raises(ValueError, match="unknown fit phase"):
        phase_cost_fit(reg, phases=("warp",))
    cm = CostModel.from_phase_fit(fit)
    assert cm.prefill_s_per_token == pytest.approx(0.0012)
    with pytest.raises(ValueError, match="decode_s_per_tick"):
        CostModel.from_phase_fit({"prefill_s_per_token": 1e-4})


def test_phase_cost_fit_from_metrics_jsonl(tmp_path):
    """The offline path: the fit reads the LAST snapshot of a
    MetricsWriter JSONL (costs are cumulative ratios) and matches the
    live-registry fit bit for bit; a snapshot-less file is loud."""
    reg = MetricRegistry()
    reg.gauge("time_in_seconds").set(0.6, phase="prefill")
    reg.gauge("time_in_seconds").set(0.4, phase="decode")
    reg.counter("serve_prefill_tokens_total").inc(500)
    for _ in range(100):
        reg.histogram("serve_decode_step_seconds").observe(0.004)
    path = tmp_path / "metrics.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"record": "manifest", "run": "x"}) + "\n")
        f.write(json.dumps({"record": "snapshot", "metrics": [
            {"name": "time_in_seconds", "kind": "gauge",
             "labels": {"phase": "prefill"}, "value": 99.0},
        ]}) + "\n")
        f.write(json.dumps({"record": "snapshot",
                            "metrics": reg.snapshot()}) + "\n")
    assert phase_cost_fit(str(path)) == phase_cost_fit(reg)
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"record": "manifest"}) + "\n")
    with pytest.raises(ValueError, match="no snapshot"):
        phase_cost_fit(str(empty))


def test_healthz_carries_engine_kind():
    """/healthz transparency: the fleet digest (and thus the health
    endpoint) labels the engine kind via the non-creating registry
    read — absent on a registry no router ever stamped."""
    reg = MetricRegistry()
    assert "engine_kind" not in fleet_summary(reg)
    assert not [m.name for m in reg.metrics()]  # read created nothing
    reg.gauge("fleet_engine_sim").set(1.0)
    reg.gauge("fleet_replicas_active").set(2)
    assert fleet_summary(reg)["engine_kind"] == "sim"
    with MetricsExporter(reg, 0) as exp:
        health = json.loads(urllib.request.urlopen(
            exp.url("/healthz")
        ).read())
    assert health["engine_kind"] == "sim"
    reg.gauge("fleet_engine_sim").set(0.0)
    assert fleet_summary(reg)["engine_kind"] == "real"


def test_scenario_library_grammar_and_validation():
    """The scenario surface: every named scenario parses, overrides
    apply (and are rejected on pinned-request scenarios), unknown
    names/keys are loud, and the fault-storm grammar sequences
    multi-crash schedules one per tick."""
    assert set(SCENARIOS) == {"bulk_burst", "replica_crash", "diurnal",
                              "crash_storm", "role_mix",
                              "longtail_prefix"}
    scn, over = parse_scenario("diurnal:horizon=128,rate_scale=2.5")
    assert scn.name == "diurnal"
    assert over == {"horizon": 128, "rate_scale": 2.5}
    with pytest.raises(ValueError, match="unknown scenario"):
        get_scenario("rush_hour")
    with pytest.raises(ValueError, match="bad scenario override"):
        parse_scenario("diurnal:frobs=2")
    with pytest.raises(ValueError, match="pins an explicit request"):
        REPLICA_CRASH.build_traffic(SPEC.vocab, rate_scale=2.0)
    # The pinned request list is the test_fleet recipe, verbatim.
    reqs = REPLICA_CRASH.build_traffic(SPEC.vocab)
    assert [r.arrival for r in reqs] == [0, 0, 1, 1]
    np.testing.assert_array_equal(reqs[0].prompt, _prompt(6, 10))

    storm = parse_fault_storm("replica_crash@3:1;replica_crash@3:2")
    assert isinstance(storm, FaultStorm)
    assert storm.crashes_replica(3) == 1  # one per tick, step order
    assert storm.crashes_replica(3) == 2
    assert storm.crashes_replica(4) is None
    assert not storm.crash_pending
    storm.rearm()
    assert storm.crash_pending and storm.spec.step == 3
    with pytest.raises(ValueError, match="replica_crash faults only"):
        FaultStorm((FaultSpec(kind="stall", step=1),))


@pytest.mark.slow
def test_million_request_twin_scale_smoke():
    """THE scale pin: a 1,000,000-request diurnal trace over a
    128-replica cost-model fleet completes on CPU inside the CI wall
    budget (the twin-parity job's bound) — every request reaches a
    terminal decision, the overwhelming majority serve clean, and the
    per-class ledgers account for every arrival exactly once. No
    registry, no kept trace: the pure control-plane envelope."""
    import time

    scn = dataclasses.replace(DIURNAL, slots=8, capacity=64,
                              shed_threshold=16)
    t0 = time.perf_counter()
    traffic = scn.build_traffic(SPEC.vocab, horizon=3000,
                                rate_scale=425.0, max_requests=1_000_000)
    assert len(traffic) == 1_000_000
    ctrl = FleetController(AutoscaleConfig(
        max_replicas=128, min_replicas=128, preempt=False,
        backlog_per_replica=1e9))
    router = Router(
        scn.router_config(SPEC, replicas=128,
                          engine_factory=sim_engine_factory()),
        tracer=NULL_TRACER, controller=ctrl)
    done, stats = router.run(traffic)
    wall = time.perf_counter() - t0
    assert wall < 570.0, f"1M-request twin run took {wall:.0f}s"
    assert len(done) == 1_000_000
    ok = sum(1 for d in done.values() if d.status == "ok")
    assert ok >= 900_000  # the fleet actually served, not shed, the load
    assert sum(s.requests for s in stats.per_class.values()) == 1_000_000
