"""Disaggregated prefill/decode serving (ddl_tpu/serve/disagg.py,
ISSUE 15).

The acceptance chain: a seeded mixed-traffic stream served by a
1-prefill + 1-decode fleet emits tokens IDENTICAL (per (seed, id,
token_index)) to the same stream on a 2-replica mixed fleet, and the
per-step decode logits on the DESTINATION replica equal the colocated
run's bitwise at tp=1 AND tp=2 — the hand-off moves pages as bits
through the one compiled whole-page write program. Role grammar,
both-sides validation, per-role controller healing, the per-role
``/healthz`` digest and the analyze fleet-incident rendering ride
along.
"""

import dataclasses

import numpy as np
import pytest

from ddl_tpu.data.lm import synthesize_mixed_traffic
from ddl_tpu.models.transformer import TINY_SPEC
from ddl_tpu.obs import MetricRegistry
from ddl_tpu.obs.analyze import build_report
from ddl_tpu.obs.goodput import SERVE_PHASES, fleet_summary
from ddl_tpu.obs.trace import FLEET_EVENTS
from ddl_tpu.resilience.faults import FaultInjector, FaultSpec
from ddl_tpu.serve import (
    AutoscaleConfig,
    ClassSpec,
    FleetController,
    RoleScale,
    Router,
    RouterConfig,
    ServeConfig,
    parse_autoscale_spec,
    parse_roles_spec,
    validate_roles,
)

SPEC = TINY_SPEC


def _traffic():
    return synthesize_mixed_traffic(
        classes={"chat": dict(rate=0.6, prompt_min=6, prompt_max=10,
                              max_new_tokens=4)},
        horizon=8, vocab=SPEC.vocab, seed=1, max_requests=6,
    )


def _record_decode_rows(router, rows):
    """Record every ACTIVE slot's decode logits row keyed by
    (request_id, lengths) across ALL the fleet's engines — placement
    and hand-off independent, so one recorder aligns a colocated run
    with a disaggregated one."""
    for eng in router.engines:
        d0 = eng.decode

        def dec(last, lengths, rids, act, *, _d0=d0, **kw):
            nxt, lg = _d0(last, lengths, rids, act, **kw)
            lg = np.asarray(lg)
            for s in range(len(act)):
                if act[s]:
                    rows[(int(rids[s]), int(lengths[s]))] = lg[s].copy()
            return nxt, lg

        eng.decode = dec


def test_parse_roles_spec_and_validation():
    """Grammar + the both-sides invariant: counts must sum to
    --replicas, a split fleet needs somewhere for arrivals to land AND
    somewhere for held prefixes to go, and every error names its
    offender."""
    assert parse_roles_spec("prefill=1,decode=2", 3) == \
        ("prefill", "decode", "decode")
    # Replica ids follow SEGMENT order — "decode=1,prefill=1" makes
    # replica 0 the decode specialist, exactly as written.
    assert parse_roles_spec("decode=1,prefill=1", 2) == \
        ("decode", "prefill")
    assert parse_roles_spec("mixed=2", 2) == ("mixed", "mixed")
    with pytest.raises(ValueError, match="sum to it"):
        parse_roles_spec("prefill=1,decode=1", 3)
    with pytest.raises(ValueError, match="unknown role"):
        parse_roles_spec("verify=1,decode=1", 2)
    with pytest.raises(ValueError, match="ROLE=COUNT"):
        parse_roles_spec("prefill", 1)
    with pytest.raises(ValueError, match="named twice"):
        parse_roles_spec("decode=1,decode=1", 2)
    with pytest.raises(ValueError, match="no prefill-capable"):
        parse_roles_spec("decode=2", 2)
    with pytest.raises(ValueError, match="no decode-"):
        parse_roles_spec("prefill=2", 2)
    # The symmetric starvation: decode replicas with only mixed peers
    # would never receive a hand-off (sources are prefill-only) nor an
    # arrival — dead capacity, rejected loudly.
    with pytest.raises(ValueError, match="idle forever"):
        parse_roles_spec("decode=1,mixed=1", 2)
    with pytest.raises(ValueError, match="no prefill-capable"):
        validate_roles(("decode",))
    # Router-side structural validation: length mismatch and the paged
    # requirement are ctor errors, never mid-run hangs.
    with pytest.raises(ValueError, match="one role per replica"):
        Router(RouterConfig(
            serve=ServeConfig(spec=SPEC, page_size=8, capacity=32),
            replicas=2, classes=(ClassSpec("chat"),),
            roles=("prefill",),
        ))
    with pytest.raises(ValueError, match="paged KV layout"):
        Router(RouterConfig(
            serve=ServeConfig(spec=SPEC),
            replicas=2, classes=(ClassSpec("chat"),),
            roles=("prefill", "decode"),
        ))


@pytest.mark.parametrize("tp", [1, 2])
def test_disagg_transparency_pin(tp):
    """THE disaggregation pin: same seeded stream, 1-prefill+1-decode
    fleet vs 2-replica mixed fleet — tokens identical per (seed, id,
    token_index), and every per-step decode logits row on the
    destination replica bitwise equals the colocated run's, tp=1 AND
    tp=2. Hand-offs are counted, traced, and leave both pools
    byte-whole."""
    cfg = ServeConfig(spec=SPEC, slots=2, capacity=32, page_size=8,
                      num_pages=12, tensor_parallel=tp)
    traffic = _traffic()
    classes = (ClassSpec("chat"),)
    rc = RouterConfig(serve=cfg, replicas=2, classes=classes)

    rows_m, rows_d = {}, {}
    r_mixed = Router(rc)
    _record_decode_rows(r_mixed, rows_m)
    done_m, _ = r_mixed.run(traffic)

    reg = MetricRegistry()
    r_dis = Router(dataclasses.replace(rc, roles=("prefill", "decode")),
                   registry=reg)
    _record_decode_rows(r_dis, rows_d)
    done_d, stats_d = r_dis.run(traffic)

    assert {i: done_d[i].tokens for i in done_d} == \
        {i: done_m[i].tokens for i in done_m}
    assert set(rows_m) == set(rows_d)
    for key, row in rows_m.items():
        np.testing.assert_array_equal(row, rows_d[key])
    # Every multi-token request crossed the fleet exactly once.
    multi = sum(1 for c in done_m.values() if len(c.tokens) > 1)
    assert stats_d.disagg["handoffs"] == multi
    assert int(reg.counter("handoff_total").value()) == multi
    assert int(reg.counter("handoff_pages_total").value()) \
        == stats_d.disagg["handoff_pages"] > 0
    names = [r["name"] for r in r_dis.tracer.records]
    assert "handoff" in names and "handoff" in FLEET_EVENTS
    # The decode work all happened on the decode replica: the prefill
    # replica's scheduler never ran a decode step.
    assert stats_d.replica[0].decode_steps == 0
    assert stats_d.replica[1].decode_steps > 0
    # Pools byte-whole on both sides after the run.
    for eng in r_dis.engines:
        assert eng.pages.free == eng.num_pages
        assert eng.pages.reserved == 0
    # The hand-off time was attributed: the source replica's goodput
    # phase vocabulary carries "handoff" (SERVE_PHASES grew it).
    assert "handoff" in SERVE_PHASES
    gp = r_dis.scheds[0].goodput
    assert gp is not None and gp.phases["handoff"] > 0.0


def test_disagg_tick_reproducible_and_role_digests():
    """Two fresh runs of the same seeded stream hand off at IDENTICAL
    ticks (deterministic host state only), and the role story is
    visible end-to-end: fleet_replicas_active{role=} gauges, the
    fleet_summary /healthz digest, and the analyze fleet-incident
    table's handoff rows with page counts."""
    cfg = ServeConfig(spec=SPEC, slots=2, capacity=32, page_size=8,
                      num_pages=12)
    traffic = _traffic()
    reg = MetricRegistry()
    router = Router(RouterConfig(serve=cfg, replicas=2,
                                 classes=(ClassSpec("chat"),),
                                 roles=("prefill", "decode")),
                    registry=reg)
    done_a, stats_a = router.run(traffic)
    events_a = list(router.disagg.events)
    router.reset()
    done_b, stats_b = router.run(traffic)
    assert events_a == router.disagg.events
    assert {i: done_a[i].tokens for i in done_a} == \
        {i: done_b[i].tokens for i in done_b}
    # Per-role gauges + the non-creating /healthz digest.
    g = reg.gauge("fleet_replicas_active")
    assert g.value(role="prefill") == 1 and g.value(role="decode") == 1
    digest = fleet_summary(reg)
    assert digest["replicas_by_role"] == {"prefill": 1, "decode": 1}
    assert digest["handoffs_total"] == stats_a.disagg["handoffs"] * 2
    # Analyze renders the handoff rows from the ONE shared
    # FLEET_EVENTS tuple, pages included.
    rep = build_report(
        [r for r in router.tracer.records]
    )
    hand = [f for f in rep["fleet_incidents"] if f["kind"] == "handoff"]
    assert hand and all(f["pages"] >= 1 and f["src"] == 0
                        and f["dst"] == 1 for f in hand)
    assert rep["incidents"]["handoff"] == len(hand)


def test_disagg_role_aware_crash_heal():
    """Role-aware healing: a crashed DECODE replica heals with a
    decode replica (not a mixed one — replacing the phase it killed),
    every request still completes exactly once with status ok, and the
    scale_out event names the role."""
    cfg = ServeConfig(spec=SPEC, slots=2, capacity=32, page_size=8,
                      num_pages=12)
    traffic = _traffic()
    inj = FaultInjector(FaultSpec(kind="replica_crash", step=4,
                                  replica=1))
    ctrl = FleetController(AutoscaleConfig(max_replicas=2,
                                           min_replicas=2),
                           injector=inj)
    router = Router(RouterConfig(serve=cfg, replicas=2,
                                 classes=(ClassSpec("chat"),),
                                 roles=("prefill", "decode")),
                    injector=inj, controller=ctrl)
    done, stats = router.run(traffic)
    assert ctrl.crashes == 1
    assert router.roles[2] == "decode"
    heal = [dict(e[2]) for e in ctrl.events if e[1] == "scale_out"]
    assert any(e.get("role") == "decode" and e.get("reason") == "heal"
               for e in heal)
    assert all(done[i].status == "ok" for i in done)
    assert stats.disagg["roles"] == {"prefill": 1, "decode": 1}


def _crashed_prefill_fleet():
    """A prefill=2,decode=1 fleet at fleet-wide min 3 with one prefill
    replica crashed mid-run — the finding-3 scenario: role floors alone
    (1 each) would leave the fleet at 2 < min_replicas forever. Helper
    holds the literals (the test_slo/_burst_arm budget pattern)."""
    cfg = ServeConfig(spec=SPEC, slots=2, capacity=32, page_size=8,
                      num_pages=12)
    inj = FaultInjector(FaultSpec(kind="replica_crash", step=3,
                                  replica=0))
    ctrl = FleetController(
        AutoscaleConfig(max_replicas=3, min_replicas=3, preempt=False),
        injector=inj,
    )
    router = Router(RouterConfig(serve=cfg, replicas=3,
                                 classes=(ClassSpec("chat"),),
                                 roles=("prefill", "prefill", "decode")),
                    injector=inj, controller=ctrl)
    done, stats = router.run(_traffic())
    return router, ctrl, done


def test_role_fleet_crash_heals_fleet_wide_minimum():
    """The fleet-wide floor holds on role fleets too: with per-role
    floors already satisfied (1 prefill + 1 decode live), a crash that
    drops the total below min_replicas still heals — topped up with
    the thinnest role — instead of sitting one replica short for the
    rest of the run (scale-in honors the min on the way down; crashes
    must not be the one path under it)."""
    router, ctrl, done = _crashed_prefill_fleet()
    assert ctrl.crashes == 1
    assert len(router.live_ids()) >= 3
    heal_roles = [dict(e[2]).get("role") for e in ctrl.events
                  if e[1] == "scale_out"
                  and dict(e[2]).get("reason") == "heal"]
    # Post-crash both roles sit at count 1 (floors satisfied); the
    # fleet-wide top-up breaks the tie deterministically — lowest
    # count first, then role name, so "decode" wins the 1-1 tie.
    assert heal_roles == ["decode"]
    assert all(done[i].status == "ok" for i in done)


def test_role_knobs_without_role_fleet_rejected_at_bind():
    """Finding-2 hardening: per-role autoscale knobs on an all-mixed
    fleet (or naming a role the fleet does not run) are bind-time
    config errors — the burn-rules discipline, not a silently-never-
    firing floor."""
    cfg = ServeConfig(spec=SPEC, slots=2, capacity=32, page_size=8,
                      num_pages=12)
    acfg = parse_autoscale_spec("decode.min=1", max_replicas=2)
    with pytest.raises(ValueError, match="need a disaggregated fleet"):
        Router(RouterConfig(serve=cfg, replicas=2,
                            classes=(ClassSpec("chat"),)),
               controller=FleetController(acfg))
    acfg2 = parse_autoscale_spec("mixed.min=1", max_replicas=2)
    with pytest.raises(ValueError, match="fleet does not run"):
        Router(RouterConfig(serve=cfg, replicas=2,
                            classes=(ClassSpec("chat"),),
                            roles=("prefill", "decode")),
               controller=FleetController(acfg2))


def test_per_role_autoscale_spec_parses_and_validates():
    """The ROLE.key=val grammar: per-role overrides land on RoleScale
    records, unknown roles/keys are named errors, and the config-level
    duplicate check fires."""
    acfg = parse_autoscale_spec(
        "backlog=3,prefill.backlog=2,decode.min=1,decode.max=2,"
        "prefill.sustain=1,decode.idle=4",
        max_replicas=4,
    )
    pf = acfg.role_scale("prefill")
    dc = acfg.role_scale("decode")
    assert pf.backlog_per_replica == 2.0 and pf.sustain_ticks == 1
    assert dc.min_replicas == 1 and dc.max_replicas == 2
    assert dc.idle_ticks == 4
    # Unset roles inherit all-default records.
    assert acfg.role_scale("mixed").backlog_per_replica is None
    with pytest.raises(ValueError, match="unknown role"):
        parse_autoscale_spec("verify.backlog=2", max_replicas=2)
    with pytest.raises(ValueError, match="per-role autoscale key"):
        parse_autoscale_spec("decode.burn=x", max_replicas=2)
    with pytest.raises(ValueError, match="must be > 0"):
        parse_autoscale_spec("decode.backlog=0", max_replicas=2)
    with pytest.raises(ValueError, match="duplicate role"):
        AutoscaleConfig(max_replicas=2,
                        roles=(RoleScale("decode"), RoleScale("decode")))
