"""Multi-host launch path (SURVEY.md §5 distributed comm backend; parity
target: mpiexec MPMD spanning processes, mnist_sync/run.sh:3).

Real multi-host needs multiple hosts; what is testable on one box is
(a) the per-process data-feeding math as pure functions, (b) the
process-count=1 degenerate world end-to-end (jax.distributed.initialize +
CLI --multihost), and (c) that the trainers' placement path (multihost.put)
is exactly device_put in a 1-process world.
"""

import json
import subprocess
import sys

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ddl_tpu.parallel import multihost
from ddl_tpu.parallel.mesh import DP_AXIS, make_mesh


# Whether this jaxlib's XLA:CPU client can RUN computations whose arrays
# span OS processes: the 0.4 line raises "Multiprocess computations
# aren't implemented on the CPU backend" on the first dispatch
# (coordination/initialize works — only execution is missing);
# cross-host CPU collectives (gloo) landed with the 0.5 jaxlib line —
# the SAME version threshold mesh's collective-flags gate encodes, so
# reuse it rather than fork the parse.
from ddl_tpu.parallel.mesh import (  # noqa: E402
    _cpu_collective_flags_supported as _cpu_multiprocess_supported,
)

requires_multiprocess_cpu = pytest.mark.skipif(
    not _cpu_multiprocess_supported(),
    reason="jaxlib's XLA:CPU predates multi-process computation support "
           "(\"Multiprocess computations aren't implemented on the CPU "
           "backend\") — two-OS-process worlds need the 0.5 jaxlib line",
)


def test_local_worker_rows_single_process_owns_all():
    mesh = make_mesh(8)
    np.testing.assert_array_equal(
        multihost.local_worker_rows(mesh), np.arange(8)
    )


def test_sharded_dims_ignores_size_one_axes():
    """_sharded_dims drives put()'s multi-process slicing: axes of mesh
    size 1 (the dp row of a [1, W] lm mesh) must read as replicated."""
    from ddl_tpu.parallel.mesh import make_mesh_2d

    mesh = make_mesh_2d(1, 8)
    dims = multihost._sharded_dims(mesh, P(None, DP_AXIS, "sp"))
    assert dims == [(2, ("sp",), 8)]  # dp (size 1) contributes nothing
    assert multihost._sharded_dims(mesh, P()) == []
    combined = multihost._sharded_dims(mesh, P((DP_AXIS, "sp")))
    assert combined == [(0, (DP_AXIS, "sp"), 8)]


def test_axis_positions_single_process_owns_all():
    from ddl_tpu.parallel.mesh import make_mesh_2d

    mesh = make_mesh_2d(2, 4)
    np.testing.assert_array_equal(
        multihost._axis_positions(mesh, ("sp",)), np.arange(4)
    )
    np.testing.assert_array_equal(
        multihost._axis_positions(mesh, (DP_AXIS, "sp")), np.arange(8)
    )


def test_local_slice_extracts_owner_blocks():
    # 8-way split of 16 rows: process owning mesh rows [2, 3] must feed
    # global rows [4, 5, 6, 7] — the multi-process data-feeding math.
    a = np.arange(16 * 3).reshape(16, 3)
    out = multihost.local_slice(a, 0, 8, np.array([2, 3]))
    np.testing.assert_array_equal(out, a[4:8])
    # Axis 1 (the async [R, W, bs, ...] layout).
    b = np.arange(2 * 8 * 5).reshape(2, 8, 5)
    out = multihost.local_slice(b, 1, 8, np.array([7]))
    np.testing.assert_array_equal(out, b[:, 7:8])


def test_put_degenerates_to_device_put():
    mesh = make_mesh(8)
    a = np.arange(32, dtype=np.float32).reshape(8, 4)
    sharded = multihost.put(mesh, P(DP_AXIS), a)
    assert sharded.sharding == NamedSharding(mesh, P(DP_AXIS))
    np.testing.assert_array_equal(np.asarray(sharded), a)
    rep = multihost.put(mesh, P(), a)
    assert rep.sharding.is_fully_replicated
    np.testing.assert_array_equal(np.asarray(rep), a)


def test_put_tree_single_spec_and_spec_tree():
    mesh = make_mesh(8)
    tree = {"a": np.zeros((8, 2), np.float32), "b": np.ones((4,), np.float32)}
    out = multihost.put_tree(mesh, P(), tree)
    assert out["a"].sharding.is_fully_replicated
    specs = {"a": P(DP_AXIS), "b": P()}
    out = multihost.put_tree(mesh, specs, tree)
    assert out["a"].sharding == NamedSharding(mesh, P(DP_AXIS))
    assert out["b"].sharding.is_fully_replicated


class _FakeDev:
    def __init__(self, pid):
        self.process_index = pid


def _fake_mesh(shape: dict, owner) -> object:
    """A stand-in Mesh for the pure staging math: ``owner(coords) ->
    process id`` assigns every device. Lets the multi-dim slab path be
    pinned without a second OS process (the jaxlib here cannot RUN one
    — see requires_multiprocess_cpu — but the extraction logic is pure)."""
    import types

    dims = tuple(shape.values())
    devs = np.empty(dims, dtype=object)
    for idx in np.ndindex(*dims):
        devs[idx] = _FakeDev(owner(dict(zip(shape, idx))))
    return types.SimpleNamespace(
        axis_names=tuple(shape), shape=shape, devices=devs
    )


def test_check_rectangular_accepts_slabs_and_rejects_diagonals(monkeypatch):
    """The 3-D [dp, sp, tp] staging contract: a process whose devices
    form a full cartesian block over the sharded dims (the tp-world
    topology — process p owns the sp=p slab, all tp columns) passes and
    yields per-dim positions; a diagonal assignment (no block to hand
    ``make_array_from_process_local_data``) is rejected up front."""
    shape = {"dp": 1, "sp": 2, "tp": 2}
    slab = _fake_mesh(shape, lambda c: c["sp"])
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    # A leaf sharded over BOTH (dp, sp) [dim 0] and tp [dim 1] — the
    # hybrid optimizer's worst case. Process 1 = sp row 1, every tp.
    dims = [(0, ("dp", "sp"), 2), (1, ("tp",), 2)]
    pos = multihost._check_rectangular(slab, dims)
    np.testing.assert_array_equal(pos[0], [1])
    np.testing.assert_array_equal(pos[1], [0, 1])
    # The extraction those positions drive: one slab per dim.
    a = np.arange(4 * 6).reshape(4, 6)
    out = multihost.local_slice(a, 0, 2, pos[0])
    out = multihost.local_slice(out, 1, 2, pos[1])
    np.testing.assert_array_equal(out, a[2:4, :])
    # Diagonal ownership: process 0 holds (sp=0, tp=0) and (sp=1, tp=1).
    diag = _fake_mesh(shape, lambda c: int(c["sp"] != c["tp"]))
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    with pytest.raises(ValueError, match="rectangular"):
        multihost._check_rectangular(diag, dims)


def test_multihost_world_process_count_1():
    """The degenerate one-process world, end-to-end in a fresh interpreter:
    jax.distributed.initialize (self-hosted coordinator) -> CLI --multihost
    trains a tiny sync_sharding run on the virtual mesh."""
    proc = subprocess.run(
        [sys.executable, "-m", "ddl_tpu", "sync_sharding", "--multihost",
         "--num-processes", "1",
         "--platform", "cpu", "--tiny", "--num-workers", "8", "--num-ps", "4",
         "--batch-size", "16", "--synthetic-train", "256",
         "--synthetic-test", "64", "--eval-every", "0", "--json"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "multihost: process 0/1" in proc.stdout
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert 0.0 <= payload["final_accuracy"] <= 1.0


def test_multihost_initialize_explicit_world(tmp_path):
    """Explicit coordinator/process args (the multi-host launch shape) in a
    fresh interpreter, then jax.process_count()/local_worker_rows through
    the initialized world."""
    code = """
import jax
jax.config.update("jax_platforms", "cpu")
from ddl_tpu.parallel.mesh import set_cpu_device_count
set_cpu_device_count(4)
from ddl_tpu.parallel import multihost
from ddl_tpu.parallel.mesh import make_mesh
port = multihost.free_port()
multihost.initialize(f"localhost:{port}", num_processes=1, process_id=0)
assert multihost.process_count() == 1
mesh = make_mesh(4)
import numpy as np
rows = multihost.local_worker_rows(mesh)
np.testing.assert_array_equal(rows, np.arange(4))
out = multihost.put(mesh, jax.sharding.PartitionSpec("dp"),
                    np.arange(8, dtype=np.float32))
np.testing.assert_array_equal(np.asarray(out), np.arange(8))
multihost.shutdown()
print("EXPLICIT-WORLD-OK")
"""
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "EXPLICIT-WORLD-OK" in proc.stdout


def _run_world(cmds: list[list[str]], timeout: float) -> list[str]:
    """Launch one subprocess per command as a jax.distributed world, reap
    them all, and return their stdouts. Kills survivors on any failure (a
    hung collective would otherwise leak the children — and the coordinator
    port — past the test and stall pytest shutdown). Children get a clean
    platform env: the conftest CPU-mesh overrides must not leak in."""
    import os

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for cmd in cmds
    ]
    try:
        outs = [p.communicate(timeout=timeout) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"process failed:\n{err[-2000:]}"
    return [out for out, _ in outs]


@pytest.mark.parametrize("variant,extra", [
    ("sync", []),
    # ZeRO-1 across processes: reduce-scatter / all-gather (and the shard
    # state split) cross the process boundary over gloo.
    ("sync_sharding", ["--num-ps", "2", "--layout", "flat"]),
    # Sharded Hogwild serve: the two all_to_all exchanges cross processes.
    ("async_sharding", ["--num-ps", "2"]),
])
@requires_multiprocess_cpu
def test_two_process_world_trains_end_to_end(variant, extra):
    """REAL multi-controller training — two OS processes (the analogue of
    the reference's mpiexec spanning nodes, mnist_sync/run.sh:3) join one
    jax.distributed world (gloo over localhost), each owning ONE cpu device
    of a 2-worker mesh, feeding its own data shard, and training to
    identical results. This is the multi-process path for real, not the
    process-count=1 degenerate case."""
    port = multihost.free_port()
    common = [
        sys.executable, "-m", "ddl_tpu", variant, "--multihost",
        "--coordinator", f"127.0.0.1:{port}", "--num-processes", "2",
        "--platform", "cpu", "--num-workers", "2", "--tiny",
        "--batch-size", "16", "--synthetic-train", "96",
        "--synthetic-test", "64", "--eval-every", "3", "--json",
    ] + extra
    outs = _run_world(
        [common + ["--process-id", str(i)] for i in (0, 1)], timeout=280
    )
    payloads = []
    for i, out in enumerate(outs):
        assert f"multihost: process {i}/2, 2 global devices" in out
        payloads.append(json.loads(out.strip().splitlines()[-1]))
    # Same SPMD program, same global data -> both controllers report the
    # identical result.
    assert payloads[0]["final_accuracy"] == payloads[1]["final_accuracy"]
    assert payloads[0]["step_stats"]["steps"] > 0
    assert payloads[0]["config"]["num_workers"] == 2


def test_mesh_skipping_a_process_is_rejected():
    """A mesh whose rows all land on one process would strand the others
    (no addressable shard to contribute); make_mesh must reject it with a
    clear error instead of the deep StopIteration it used to surface."""
    port = multihost.free_port()
    code = f"""
import jax
jax.config.update("jax_platforms", "cpu")
from ddl_tpu.parallel.mesh import set_cpu_device_count
set_cpu_device_count(2)
import sys
from ddl_tpu.parallel import multihost
from ddl_tpu.parallel.mesh import make_mesh
multihost.initialize("127.0.0.1:{port}", num_processes=2,
                     process_id=int(sys.argv[1]))
try:
    make_mesh(2)  # both rows on process 0
except ValueError as e:
    assert "owns no row" in str(e), e
    print("MESH-GUARD-OK")
multihost.shutdown()
"""
    outs = _run_world(
        [[sys.executable, "-c", code, str(i)] for i in (0, 1)], timeout=120
    )
    for out in outs:
        assert "MESH-GUARD-OK" in out


def test_multihost_worker_count_must_split_over_processes():
    """--num-workers not divisible by --num-processes on the CPU platform
    fails fast (the per-process device count could not make the global
    world equal the worker count)."""
    proc = subprocess.run(
        [sys.executable, "-m", "ddl_tpu", "sync", "--multihost",
         "--coordinator", "127.0.0.1:1", "--num-processes", "2",
         "--process-id", "0", "--platform", "cpu", "--num-workers", "3"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode != 0
    assert "not divisible by" in proc.stderr


@pytest.mark.parametrize("variant,extra", [
    ("sync", []),
    # Sharded: the preemption save exercises the cross-process
    # replicate_for_host + logical-order conversion of ZeRO-1 m/v.
    ("sync_sharding", ["--num-ps", "2", "--layout", "flat"]),
])
@requires_multiprocess_cpu
def test_preemption_agreement_across_processes(tmp_path, variant, extra):
    """SIGTERM delivered to ONE process of a two-process world: the
    preemption flag goes through multihost.agree_flag, so BOTH controllers
    stop at the same span (mismatched stop points would deadlock the next
    span's collectives), checkpoint, and exit 0."""
    import os
    import signal as sig

    port = multihost.free_port()
    d = str(tmp_path / "ck")
    common = [
        sys.executable, "-m", "ddl_tpu", variant, "--multihost",
        "--coordinator", f"127.0.0.1:{port}", "--num-processes", "2",
        "--platform", "cpu", "--num-workers", "2", "--tiny",
        "--batch-size", "16", "--synthetic-train", "96",
        "--synthetic-test", "64", "--eval-every", "2", "--epochs", "200",
        "--checkpoint-dir", d, "--json",
    ] + extra
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONUNBUFFERED"] = "1"
    procs = [
        subprocess.Popen(
            common + ["--process-id", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for i in (0, 1)
    ]
    try:
        for line in procs[0].stdout:
            if line.startswith("epoch:"):
                procs[0].send_signal(sig.SIGTERM)  # process 0 ONLY
                break
        outs = [p.communicate(timeout=280) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"process failed:\n{err[-2000:]}"
        payload = json.loads(out.strip().splitlines()[-1])
        assert payload["preempted"] is True  # both, though only p0 was signaled
    assert os.path.exists(os.path.join(d, "ckpt.npz"))


_RING_WORLD = """
import sys
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")  # before any backend touch
import jax.numpy as jnp

from ddl_tpu.parallel import multihost, ring
from ddl_tpu.parallel.mesh import DP_AXIS, make_mesh

multihost.initialize(coordinator_address="127.0.0.1:{port}",
                     num_processes=2, process_id={pid})
assert jax.process_count() == 2
mesh = make_mesh(2)

B, T, H, D = 2, 16, 2, 8
rng = np.random.default_rng(0)  # same seed both processes: identical input
q, k, v = (jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
           for _ in range(3))
oracle = ring.full_attention(q, k, v, causal=True)

spec = jax.sharding.PartitionSpec(None, DP_AXIS)
qs, ks, vs = (multihost.put(mesh, spec, np.asarray(a)) for a in (q, k, v))
out = ring.make_ring_attention(mesh, causal=True)(qs, ks, vs)

from jax.experimental import multihost_utils
got = multihost_utils.process_allgather(out, tiled=True)
assert got.shape == oracle.shape, (got.shape, oracle.shape)
np.testing.assert_allclose(np.asarray(got), np.asarray(oracle), atol=2e-4)
print("RING-WORLD-OK")
multihost.shutdown()
"""


@requires_multiprocess_cpu
def test_two_process_ring_attention():
    """Ring attention across a REAL two-process world: the ppermute ring
    crosses the OS-process boundary over gloo (the DCN analogue), and the
    result still matches the single-device oracle exactly. Long-context
    sequence parallelism composes with the multi-host backend."""
    port = multihost.free_port()
    outs = _run_world(
        [[sys.executable, "-c",
          _RING_WORLD.format(port=port, pid=pid)] for pid in (0, 1)],
        timeout=280,
    )
    for out in outs:
        assert "RING-WORLD-OK" in out


@requires_multiprocess_cpu
def test_two_process_lm_world_trains_end_to_end():
    """The lm variant across a REAL two-process world: each process owns
    one device of the 2-way sequence-parallel mesh, so every ring-attention
    ppermute hop in training (fwd AND the transposed grads) crosses the
    OS-process boundary over gloo; both controllers report the identical
    result."""
    port = multihost.free_port()
    common = [
        sys.executable, "-m", "ddl_tpu", "lm", "--multihost",
        "--coordinator", f"127.0.0.1:{port}", "--num-processes", "2",
        "--platform", "cpu", "--num-workers", "2", "--seq-scheme", "ring",
        "--seq-len", "32", "--vocab", "16", "--d-model", "32", "--heads",
        "2", "--layers", "2", "--d-ff", "64", "--train-seqs", "32",
        "--test-seqs", "16", "--batch-size", "16", "--eval-every", "0",
        "--json",
    ]
    outs = _run_world(
        [common + ["--process-id", str(i)] for i in (0, 1)], timeout=280
    )
    payloads = []
    for i, out in enumerate(outs):
        assert f"multihost: process {i}/2, 2 global devices" in out
        payloads.append(json.loads(out.strip().splitlines()[-1]))
    assert payloads[0]["final_accuracy"] == payloads[1]["final_accuracy"]
    assert payloads[0]["final_loss"] == payloads[1]["final_loss"]
    assert payloads[0]["config"]["scheme"] == "ring"


@requires_multiprocess_cpu
def test_two_process_tp_world_trains_end_to_end():
    """Tensor parallelism across a REAL two-process world — the lifted
    single-controller restriction: a 1x2x2 [dp, sp, tp] mesh spans two
    OS processes (two cpu devices each; process p owns the sp=p slab),
    so every Megatron completion psum rides gloo between tp peers
    in-process while the ring's ppermute and — with --zero1 — the
    hybrid sharded optimizer's reduce-scatter/all-gather over the
    combined (dp, sp) axes cross the process boundary. Staging
    exercises multihost.put's multi-dim path: tp-sharded param leaves
    slice their tp dim, the (dp, sp)-flat optimizer chunks slice theirs,
    and the tp-replicated data dims stay slabs. Both controllers report
    identical results."""
    port = multihost.free_port()
    common = [
        sys.executable, "-m", "ddl_tpu", "lm", "--multihost",
        "--coordinator", f"127.0.0.1:{port}", "--num-processes", "2",
        "--platform", "cpu", "--num-workers", "2", "--tensor-parallel",
        "2", "--zero1", "--seq-scheme", "ring", "--seq-len", "32",
        "--vocab", "16", "--d-model", "32", "--heads", "2", "--layers",
        "2", "--d-ff", "64", "--train-seqs", "32", "--test-seqs", "16",
        "--batch-size", "16", "--eval-every", "0", "--json",
    ]
    outs = _run_world(
        [common + ["--process-id", str(i)] for i in (0, 1)], timeout=280
    )
    payloads = []
    for i, out in enumerate(outs):
        assert f"multihost: process {i}/2, 4 global devices" in out
        payloads.append(json.loads(out.strip().splitlines()[-1]))
    assert payloads[0]["final_loss"] == payloads[1]["final_loss"]
    assert payloads[0]["final_accuracy"] == payloads[1]["final_accuracy"]
    assert payloads[0]["config"]["tensor_parallel"] == 2
    assert payloads[0]["config"]["zero1"] is True


@requires_multiprocess_cpu
def test_two_process_lm_world_zigzag_matches_contiguous():
    """The balanced zigzag layout across a REAL two-process world: the
    travelling kpos crosses the OS-process boundary with its K/V block,
    and the permuted staging happens per-controller — the run must agree
    with the contiguous-layout world on the same config (same math,
    different placement; attention-reassociation tolerance)."""
    results = {}
    for layout in ("contiguous", "zigzag"):
        port = multihost.free_port()
        common = [
            sys.executable, "-m", "ddl_tpu", "lm", "--multihost",
            "--coordinator", f"127.0.0.1:{port}", "--num-processes", "2",
            "--platform", "cpu", "--num-workers", "2", "--seq-scheme",
            "ring", "--seq-layout", layout, "--seq-len", "32", "--vocab",
            "16", "--d-model", "32", "--heads", "2", "--layers", "2",
            "--d-ff", "64", "--train-seqs", "32", "--test-seqs", "16",
            "--batch-size", "16", "--eval-every", "0", "--json",
        ]
        outs = _run_world(
            [common + ["--process-id", str(i)] for i in (0, 1)], timeout=280
        )
        payloads = [json.loads(o.strip().splitlines()[-1]) for o in outs]
        assert payloads[0]["final_loss"] == payloads[1]["final_loss"]
        results[layout] = payloads[0]
    assert np.isclose(
        results["zigzag"]["final_loss"],
        results["contiguous"]["final_loss"], rtol=1e-3,
    ), results
    assert abs(results["zigzag"]["final_accuracy"]
               - results["contiguous"]["final_accuracy"]) < 0.05
