"""Donated-buffer paths, exercised off-TPU (round-3 verdict weak #6).

On a real TPU every step program donates params/optimizer state (halving
peak HBM for the update), but the multi-device CPU test mesh must disable
donation (``mesh.donation_for``: the in-process CPU AllReduce deadlocks on
donated replicated inputs under shard_map) — so the DONATED variants of the
shard_map programs would otherwise first execute on the first real chip.
A 1-device CPU mesh is exempt from that deadlock: these tests run every
strategy family's program with donation ACTIVE, so aliasing bugs (a buffer
donated twice, a donated input re-read) surface in CI, not on the chip.
"""

import jax
import numpy as np
import pytest

from ddl_tpu.parallel.mesh import (
    donation_for,
    make_mesh,
    pallas_interpret_for,
)
from ddl_tpu.strategies.async_ps import AsyncTrainer
from ddl_tpu.strategies.sync import SyncTrainer
from ddl_tpu.train import SingleChipTrainer, TrainConfig


def test_donation_active_on_single_device_cpu_mesh():
    """The exemption these tests rely on: 1-device CPU meshes donate."""
    m1 = make_mesh(1)
    assert donation_for(m1, 0, 1) == (0, 1)
    m8 = make_mesh(8)
    assert donation_for(m8, 0, 1) == ()


def test_pallas_interpret_selection():
    """The product path must select COMPILED (non-interpret) Pallas on TPU
    meshes and interpreter mode elsewhere — asserted via a stub so the TPU
    branch is pinned without hardware."""
    import types

    assert pallas_interpret_for(make_mesh(1)) is True  # CPU test mesh

    fake_tpu = types.SimpleNamespace(
        devices=np.asarray([types.SimpleNamespace(platform="tpu")])
    )
    assert pallas_interpret_for(fake_tpu) is False


@pytest.mark.parametrize(
    "family,kw",
    [
        ("sync_dp", dict()),
        ("sync_sharded", dict(num_ps=2, layout="zigzag")),
        ("sync_sharded_flat", dict(num_ps=2, layout="flat")),
        ("async", dict()),
        ("async_sharded", dict(num_ps=2, layout="block")),
    ],
)
def test_strategies_run_with_donation_on(
    family, kw, small_dataset, small_params
):
    """Every strategy family's step/span program executes end-to-end with
    donation active (W=1 mesh) and matches the same run on the no-donation
    path numerically — donation must be a pure memory optimization."""
    cfg = TrainConfig(
        epochs=1, batch_size=256, eval_every=4, keep_prob=1.0, seed=3,
        num_workers=1, **kw,
    )
    cls = AsyncTrainer if family.startswith("async") else SyncTrainer
    mesh = make_mesh(1)
    assert donation_for(mesh, 0) == (0,)  # donation really is on
    r = cls(cfg, small_dataset, mesh=mesh, init=small_params).train(
        log=lambda s: None
    )
    # Determinism across two donated runs (a reused donated buffer would
    # poison the second run's inputs or crash outright).
    r2 = cls(cfg, small_dataset, mesh=mesh, init=small_params).train(
        log=lambda s: None
    )
    assert r.final_accuracy == r2.final_accuracy
    for k in r.params:
        np.testing.assert_array_equal(r.params[k], r2.params[k], err_msg=k)
    assert np.isfinite(r.final_accuracy)
