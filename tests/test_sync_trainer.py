"""Device-resident sync path tests: ``make_sync_epoch`` parity with the
per-step programs it chunks, and ``SyncTrainer`` end-to-end against the
single-chip oracle (the reference loop it replaces:
mnist_sync/worker.py:60-72).

All on the 8-device virtual CPU mesh with the narrow model family
(conftest.SMALL_SPECS).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ddl_tpu.data import one_hot
from ddl_tpu.models import cnn
from ddl_tpu.ops import adam_init
from ddl_tpu.parallel.mesh import DP_AXIS, make_mesh
from ddl_tpu.strategies.sync import (
    SyncTrainer,
    make_dp_step,
    make_sharded_step,
    make_sync_epoch,
    resolve_layout,
    sharded_adam_init,
)
from ddl_tpu.train import SingleChipTrainer, TrainConfig

W = 8
GB = 32  # global batch
B = 4  # batches in the staged epoch


def _sizes(params):
    return {k: int(np.prod(v.shape)) if v.shape else 1 for k, v in params.items()}


@pytest.fixture(scope="module")
def epoch_batches(small_dataset):
    """B global batches [B, GB, ...] in reference order."""
    n = B * GB
    x = np.asarray(small_dataset.x_train[:n]).reshape(B, GB, -1)
    y = one_hot(small_dataset.y_train[:n]).reshape(B, GB, -1)
    return x, y


def _staged(mesh, x, y):
    """Trainer staging layout: [W, B, GB/W, ...] with worker w's slice of
    every batch on device w (mirrors SyncTrainer._stage_epoch)."""
    pb = GB // W
    xs = np.ascontiguousarray(
        x.reshape(B, W, pb, x.shape[-1]).transpose(1, 0, 2, 3)
    )
    ys = np.ascontiguousarray(
        y.reshape(B, W, pb, y.shape[-1]).transpose(1, 0, 2, 3)
    )
    sh = NamedSharding(mesh, P(DP_AXIS))
    return jax.device_put(xs, sh), jax.device_put(ys, sh)


def _max_abs_diff(a, b):
    return max(
        jax.tree.leaves(
            jax.tree.map(lambda u, v: float(jnp.max(jnp.abs(u - v))), a, b)
        )
    )


@pytest.mark.parametrize("variant", ["dp", "sharded"])
def test_sync_epoch_matches_per_step_path(
    small_params, epoch_batches, variant
):
    """The docstring claim at make_sync_epoch: span chunking feeds the same
    dropout stream as the per-step path, so k scanned steps reproduce k
    sequential step() calls — up to XLA fusion reassociation between the
    two separately-compiled programs (~1 ulp; exact equality is not
    guaranteed across compilations). Dropout ON to pin the rng plumbing;
    span offset (first=1, goff=7) exercised so resume/eval chunking is
    covered."""
    mesh = make_mesh(W)
    x, y = epoch_batches
    cfg = TrainConfig(
        num_workers=W, num_ps=4 if variant == "sharded" else 1,
        layout="zigzag", batch_size=GB, keep_prob=0.5, seed=0,
    )
    shapes = cnn.param_shapes(small_params)
    layout = resolve_layout(cfg, W, _sizes(small_params))
    if variant == "dp":
        assert layout is None
        step = make_dp_step(cfg, mesh)
        opt0 = jax.device_put(
            adam_init(small_params), NamedSharding(mesh, P())
        )
    else:
        step = make_sharded_step(cfg, mesh, layout, shapes)
        opt0 = sharded_adam_init(mesh, layout)
    params0 = jax.device_put(small_params, NamedSharding(mesh, P()))
    rng_base = jax.random.PRNGKey(11)
    first, k, goff = 1, 3, 7

    # Per-step oracle: k sequential calls on the batch-sharded stream.
    data_sh = NamedSharding(mesh, P(DP_AXIS))
    p_ref, o_ref = params0, opt0
    for j in range(k):
        xb = jax.device_put(jnp.asarray(x[first + j]), data_sh)
        yb = jax.device_put(jnp.asarray(y[first + j]), data_sh)
        p_ref, o_ref, _ = step(
            p_ref, o_ref, xb, yb, jax.random.fold_in(rng_base, goff + j)
        )

    # Device-resident span: one compiled program.
    xs, ys = _staged(mesh, x, y)
    run = make_sync_epoch(cfg, mesh, layout, shapes, k)
    p_span, o_span, _ = run(
        params0, opt0, xs, ys, jnp.int32(first), jnp.int32(goff), rng_base
    )
    assert _max_abs_diff(p_ref, p_span) < 1e-7
    if variant == "sharded":
        np.testing.assert_allclose(
            np.asarray(o_ref.m), np.asarray(o_span.m), atol=1e-7
        )
        np.testing.assert_allclose(
            np.asarray(o_ref.v), np.asarray(o_span.v), atol=1e-7
        )


@pytest.mark.parametrize("num_ps,layout", [(1, "block"), (4, "lpt")])
def test_sync_trainer_matches_single_chip(
    small_dataset, small_params, num_ps, layout
):
    """SyncTrainer over the 8-device mesh ≡ SingleChipTrainer on the same
    global batch stream (keep_prob=1 ⇒ no dropout divergence; mean
    reduction over equal shards ≡ full-batch gradient)."""
    cfg_s = TrainConfig(epochs=2, batch_size=256, eval_every=3,
                        keep_prob=1.0, seed=1)
    single = SingleChipTrainer(cfg_s, small_dataset, init=small_params).train(
        log=lambda s: None
    )
    cfg_m = TrainConfig(epochs=2, batch_size=256, eval_every=3,
                        keep_prob=1.0, seed=1, num_workers=W,
                        num_ps=num_ps, layout=layout)
    multi = SyncTrainer(cfg_m, small_dataset, init=small_params).train(
        log=lambda s: None
    )
    assert _max_abs_diff(single.params, multi.params) < 2e-5
    # Same eval cadence as the reference (worker.py:71-72).
    assert [(e, b) for e, b, _ in multi.history] == [
        (e, b) for e, b, _ in single.history
    ]


def test_sync_trainer_repeated_train_is_safe(small_dataset, small_params):
    """The span programs donate params/opt on TPU; train() must copy first
    so the trainer (and the shared init tree) survives repeated calls
    (mirror of test_single_trainer.py donation test)."""
    cfg = TrainConfig(epochs=1, batch_size=512, eval_every=0, seed=2,
                      num_workers=W, num_ps=W, layout="flat")
    trainer = SyncTrainer(cfg, small_dataset, init=small_params)
    trainer.train(log=lambda s: None)
    # Second call continues from the updated trainer state; it would raise
    # on donated/deleted buffers if train() skipped the defensive copies.
    trainer.train(log=lambda s: None)
    np.asarray(small_params["v0"])  # shared init still alive
