"""Data pipeline tests: reference pickle-format parity + procedural
determinism (reference loader semantics: mnist_sync/model/model.py:6-14),
plus the LM prompt generator's determinism/bounds contract."""

import os
import pickle

import numpy as np
import pytest

from ddl_tpu.data import load_mnist, one_hot
from ddl_tpu.data.lm import synthesize_prompts, synthesize_shared_prefix_prompts
from ddl_tpu.data.mnist import synthesize


def test_synthetic_shapes_and_ranges(small_dataset):
    ds = small_dataset
    assert ds.x_train.shape == (2048, 784)
    assert ds.x_test.shape == (512, 784)
    assert ds.x_train.dtype == np.float32
    assert ds.y_train.dtype == np.int32
    assert ds.x_train.min() >= 0.0 and ds.x_train.max() <= 1.0
    assert set(np.unique(ds.y_train)) == set(range(10))


def test_synthetic_deterministic():
    x1, y1 = synthesize(256, seed=42)
    x2, y2 = synthesize(256, seed=42)
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(y1, y2)
    x3, _ = synthesize(256, seed=43)
    assert not np.array_equal(x1, x3)


def test_class_balance():
    _, y = synthesize(1000, seed=0)
    counts = np.bincount(y, minlength=10)
    assert counts.min() == counts.max() == 100


def test_synthesize_prompts_deterministic_per_seed():
    """Same seed -> identical prompt SET (lengths and payloads);
    different seeds -> different; the serving benches depend on this to
    compare runs (the batching-invariance pins replay one prompt list
    across arrival patterns)."""
    a = synthesize_prompts(num=16, min_len=4, max_len=24, vocab=64, seed=9)
    b = synthesize_prompts(num=16, min_len=4, max_len=24, vocab=64, seed=9)
    c = synthesize_prompts(num=16, min_len=4, max_len=24, vocab=64, seed=10)
    assert len(a) == 16
    assert all(np.array_equal(x, y) for x, y in zip(a, b))
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))


def test_synthesize_prompts_lengths_always_in_bounds():
    """Lengths stay inside [min_len, max_len] INCLUSIVE across many
    seeds (an off-by-one in the uniform draw would only surface rarely),
    and the degenerate min_len == max_len case is exact — every prompt
    that length, not max_len±1."""
    for seed in range(8):
        for p in synthesize_prompts(num=32, min_len=3, max_len=7,
                                    vocab=16, seed=seed):
            assert 3 <= len(p) <= 7, (seed, len(p))
    fixed = synthesize_prompts(num=8, min_len=5, max_len=5, vocab=16,
                               seed=0)
    assert all(len(p) == 5 for p in fixed)
    with pytest.raises(ValueError, match="min_len"):
        synthesize_prompts(num=4, min_len=0, max_len=4, vocab=16, seed=0)


def test_shared_prefix_prompts_determinism_and_structure():
    """ISSUE 4 satellite: the shared-prefix workload generator is
    seed-deterministic, returns n_families * per_family prompts
    ROUND-ROBIN across families (prompt i and i + n_families share a
    family), every prompt opens with its family's exact prefix_len
    prefix and differs beyond it in length or payload."""
    kw = dict(n_families=3, per_family=4, prefix_len=10, tail_min=2,
              tail_max=7, vocab=32)
    a = synthesize_shared_prefix_prompts(seed=5, **kw)
    b = synthesize_shared_prefix_prompts(seed=5, **kw)
    c = synthesize_shared_prefix_prompts(seed=6, **kw)
    assert len(a) == 12
    assert all(np.array_equal(x, y) for x, y in zip(a, b))
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))
    for i, p in enumerate(a):
        fam = a[i % 3]  # the family's first prompt (round-robin order)
        np.testing.assert_array_equal(p[:10], fam[:10])
    # Families are distinct at this vocab/length (astronomically likely
    # under the uniform draw; a collision would only EASE a prefix
    # cache, never corrupt it — see the generator docstring).
    assert not np.array_equal(a[0][:10], a[1][:10])


def test_shared_prefix_prompts_bounds_and_validation():
    """Lengths stay in [prefix_len + tail_min, prefix_len + tail_max]
    inclusive across seeds; prompts are BOS-led with payload in
    [1, vocab); malformed configs fail fast."""
    for seed in range(6):
        ps = synthesize_shared_prefix_prompts(
            n_families=2, per_family=5, prefix_len=6, tail_min=1,
            tail_max=4, vocab=16, seed=seed,
        )
        lens = {len(p) for p in ps}
        assert lens <= set(range(7, 11)), lens
        for p in ps:
            assert p.dtype == np.int32 and p[0] == 0
            assert (p[1:] >= 1).all() and (p[1:] < 16).all()
    # The degenerate fixed-tail case is exact.
    ps = synthesize_shared_prefix_prompts(n_families=1, per_family=3,
                                          prefix_len=5, tail_min=3,
                                          tail_max=3, vocab=8, seed=0)
    assert all(len(p) == 8 for p in ps)
    with pytest.raises(ValueError, match="prefix_len"):
        synthesize_shared_prefix_prompts(prefix_len=1)
    with pytest.raises(ValueError, match="tail_min"):
        synthesize_shared_prefix_prompts(tail_min=5, tail_max=4)
    with pytest.raises(ValueError, match="n_families"):
        synthesize_shared_prefix_prompts(n_families=0)
    with pytest.raises(ValueError, match="vocab"):
        synthesize_shared_prefix_prompts(vocab=1)


def test_longtail_prompts_structure_and_validation():
    """ISSUE 7 satellite: the long-tail mix generator is
    seed-deterministic, returns num_short + num_long prompts with the
    longs EXACTLY long_len tokens, spread through the shorts (never a
    head-of-line burst), sharing their long_prefix_len prefix; BOS-led
    int32 payloads in [1, vocab); malformed configs fail fast."""
    from ddl_tpu.data.lm import synthesize_longtail_prompts

    kw = dict(num_short=10, num_long=2, short_min=4, short_max=12,
              long_len=48, vocab=32)
    a = synthesize_longtail_prompts(seed=3, **kw)
    b = synthesize_longtail_prompts(seed=3, **kw)
    c = synthesize_longtail_prompts(seed=4, **kw)
    assert len(a) == 12
    assert all(np.array_equal(x, y) for x, y in zip(a, b))
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))
    longs = [p for p in a if len(p) == 48]
    shorts = [p for p in a if len(p) != 48]
    assert len(longs) == 2 and len(shorts) == 10
    assert all(4 <= len(p) <= 12 for p in shorts)
    # Longs share the default long_len // 2 prefix but diverge after.
    np.testing.assert_array_equal(longs[0][:24], longs[1][:24])
    assert not np.array_equal(longs[0][24:], longs[1][24:])
    # Longs are spread, not front-loaded: neither occupies the head.
    long_positions = [i for i, p in enumerate(a) if len(p) == 48]
    assert long_positions[0] > 0 and long_positions[1] > long_positions[0] + 1
    for p in a:
        assert p.dtype == np.int32 and p[0] == 0
        assert (p[1:] >= 1).all() and (p[1:] < 32).all()
    # A shorts-only or longs-only mix is legal — and shorts-only must
    # not touch (or choke on) long parameters at all.
    assert len(synthesize_longtail_prompts(num_short=3, num_long=0)) == 3
    assert len(synthesize_longtail_prompts(num_short=3, num_long=0,
                                           long_len=1)) == 3
    only_long = synthesize_longtail_prompts(num_short=0, num_long=2,
                                            long_len=32)
    assert all(len(p) == 32 for p in only_long)
    with pytest.raises(ValueError, match="at least one"):
        synthesize_longtail_prompts(num_short=0, num_long=0)
    with pytest.raises(ValueError, match="short_min"):
        synthesize_longtail_prompts(short_min=8, short_max=4)
    with pytest.raises(ValueError, match="long_len"):
        synthesize_longtail_prompts(long_len=10, short_max=12)
    with pytest.raises(ValueError, match="long_prefix_len"):
        synthesize_longtail_prompts(long_len=48, long_prefix_len=99)
    with pytest.raises(ValueError, match="vocab"):
        synthesize_longtail_prompts(vocab=1)


def test_mixed_traffic_determinism_and_structure():
    """ISSUE 8 satellite: the mixed-traffic generator is
    seed-deterministic (ids, arrivals, classes, families, prompts),
    arrivals are sorted with sequential ids, family classes share their
    exact prefix, and max_requests truncates the stream in (arrival,
    id) order."""
    from ddl_tpu.data.lm import synthesize_mixed_traffic

    classes = {"chat": dict(rate=0.8, prompt_min=6, prompt_max=10,
                            max_new_tokens=2, families=2,
                            family_prefix_len=4),
               "bulk": dict(rate=0.4, prompt_min=6, prompt_max=12,
                            max_new_tokens=2)}
    a = synthesize_mixed_traffic(classes=classes, horizon=16, vocab=32,
                                 seed=7)
    b = synthesize_mixed_traffic(classes=classes, horizon=16, vocab=32,
                                 seed=7)
    c = synthesize_mixed_traffic(classes=classes, horizon=16, vocab=32,
                                 seed=8)
    assert len(a) == len(b) > 0
    assert all(
        x.id == y.id and x.arrival == y.arrival
        and x.traffic_class == y.traffic_class and x.family == y.family
        and np.array_equal(x.prompt, y.prompt)
        for x, y in zip(a, b)
    )
    assert len(a) != len(c) or any(
        not np.array_equal(x.prompt, y.prompt) for x, y in zip(a, c)
    )
    assert [m.id for m in a] == list(range(len(a)))
    arrivals = [m.arrival for m in a]
    assert arrivals == sorted(arrivals)
    assert all(0 <= t < 16 for t in arrivals)
    for m in a:
        assert m.prompt.dtype == np.int32 and m.prompt[0] == 0
        assert (m.prompt[1:] >= 1).all() and (m.prompt[1:] < 32).all()
        lo, hi = (6, 10) if m.traffic_class == "chat" else (6, 12)
        assert lo <= len(m.prompt) <= hi
        assert (m.family >= 0) == (m.traffic_class == "chat")
    # Family members open with the SAME 4-token prefix; distinct
    # families differ (astronomically likely at this vocab).
    chat = [m for m in a if m.traffic_class == "chat"]
    by_fam = {}
    for m in chat:
        by_fam.setdefault(m.family, []).append(m)
    for fam, members in by_fam.items():
        for m in members:
            np.testing.assert_array_equal(m.prompt[:4],
                                          members[0].prompt[:4])
    if len(by_fam) == 2:
        f0, f1 = (ms[0] for ms in by_fam.values())
        assert not np.array_equal(f0.prompt[:4], f1.prompt[:4])
    capped = synthesize_mixed_traffic(classes=classes, horizon=16,
                                      vocab=32, seed=7, max_requests=5)
    assert len(capped) == 5
    assert all(np.array_equal(x.prompt, y.prompt)
               for x, y in zip(capped, a[:5]))


def test_mixed_traffic_poisson_burst_and_diurnal():
    """Arrival statistics: the empirical per-tick rate tracks the
    Poisson mean over a long horizon; a burst window's rate is
    multiplied; a diurnal ramp moves arrivals from trough to peak."""
    from ddl_tpu.data.lm import synthesize_mixed_traffic

    one = {"c": dict(rate=0.4, prompt_min=4, prompt_max=6,
                     max_new_tokens=1)}
    long_run = synthesize_mixed_traffic(classes=one, horizon=1500,
                                        vocab=16, seed=1)
    mean = len(long_run) / 1500
    # 1500 ticks at lam=0.4: sd of the mean ~ 0.016 — +-0.08 is 5 sigma.
    assert abs(mean - 0.4) < 0.08, mean

    bursty = synthesize_mixed_traffic(classes=one, horizon=60, vocab=16,
                                      seed=2, burst=(20, 10, 8.0, "c"))
    inside = sum(1 for m in bursty if 20 <= m.arrival < 30)
    outside = len(bursty) - inside
    # Window rate ~3.2/tick vs 0.4/tick outside: the window dominates.
    assert inside / 10 > 3 * max(outside, 1) / 50, (inside, outside)

    wave = synthesize_mixed_traffic(classes=one, horizon=64, vocab=16,
                                    seed=3, diurnal_amplitude=0.9,
                                    diurnal_period=64)
    peak = sum(1 for m in wave if m.arrival < 32)  # sin >= 0 half
    trough = len(wave) - peak
    assert peak > trough, (peak, trough)


def test_mixed_traffic_validation():
    """Malformed scenario specs fail fast naming the offender."""
    from ddl_tpu.data.lm import synthesize_mixed_traffic

    ok = {"c": dict(rate=0.5, prompt_min=4, prompt_max=8,
                    max_new_tokens=2)}
    with pytest.raises(ValueError, match="at least one traffic class"):
        synthesize_mixed_traffic(classes={})
    with pytest.raises(ValueError, match="horizon"):
        synthesize_mixed_traffic(classes=ok, horizon=0)
    with pytest.raises(ValueError, match="vocab"):
        synthesize_mixed_traffic(classes=ok, vocab=1)
    with pytest.raises(ValueError, match="max_requests"):
        synthesize_mixed_traffic(classes=ok, max_requests=-1)
    with pytest.raises(ValueError, match="unknown spec keys"):
        synthesize_mixed_traffic(classes={"c": dict(rate=1, nope=2)})
    with pytest.raises(ValueError, match="rate"):
        synthesize_mixed_traffic(classes={"c": dict(rate=-1)})
    with pytest.raises(ValueError, match="prompt_min"):
        synthesize_mixed_traffic(
            classes={"c": dict(rate=1, prompt_min=9, prompt_max=4)}
        )
    with pytest.raises(ValueError, match="max_new_tokens"):
        synthesize_mixed_traffic(
            classes={"c": dict(rate=1, max_new_tokens=0)}
        )
    with pytest.raises(ValueError, match="family_prefix_len"):
        synthesize_mixed_traffic(classes={
            "c": dict(rate=1, prompt_min=4, prompt_max=8, families=2,
                      family_prefix_len=4)
        })
    with pytest.raises(ValueError, match="burst"):
        synthesize_mixed_traffic(classes=ok, burst=(1, 2))
    with pytest.raises(ValueError, match="burst"):
        synthesize_mixed_traffic(classes=ok, burst=(0, 0, 2.0))
    with pytest.raises(ValueError, match="not a traffic class"):
        synthesize_mixed_traffic(classes=ok, burst=(0, 2, 2.0, "nope"))
    with pytest.raises(ValueError, match="diurnal_amplitude"):
        synthesize_mixed_traffic(classes=ok, diurnal_amplitude=1.5,
                                 diurnal_period=8)
    with pytest.raises(ValueError, match="diurnal_period"):
        synthesize_mixed_traffic(classes=ok, diurnal_amplitude=0.5)


def test_one_hot_matches_get_dummies_semantics():
    y = np.array([3, 0, 9, 3])
    oh = one_hot(y)
    assert oh.shape == (4, 10)
    assert oh.dtype == np.float32
    np.testing.assert_array_equal(oh.argmax(axis=1), y)
    np.testing.assert_array_equal(oh.sum(axis=1), np.ones(4))


def test_load_reference_pickle_format(tmp_path):
    """The 3-way deeplearning.net pickle the reference consumes
    (model.py:8-11): (train, valid, test); valid is discarded."""
    xt = np.random.default_rng(0).random((20, 784)).astype(np.float32)
    yt = np.arange(20) % 10
    xv = np.zeros((5, 784), np.float32)
    blob = ((xt, yt), (xv, np.zeros(5, int)), (xt[:10], yt[:10]))
    path = tmp_path / "mnist.pkl"
    with open(path, "wb") as f:
        pickle.dump(blob, f)
    ds = load_mnist(path=os.fspath(path))
    np.testing.assert_allclose(ds.x_train, xt)
    np.testing.assert_array_equal(ds.y_train, yt)
    assert ds.num_test == 10
