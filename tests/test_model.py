"""Model math tests against an independent torch oracle.

The reference validates nothing (SURVEY.md section 4); here the JAX CNN's
forward, loss, and gradients are checked against a from-scratch torch CPU
implementation of the same architecture (mnist_sync/model/model.py:17-106).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import torch
import torch.nn.functional as F

from ddl_tpu.models import cnn


def _torch_forward(params_np, x_np):
    """Reference-architecture forward in torch (NCHW), from the same
    weights. Returns logits."""
    x = torch.from_numpy(x_np).reshape(-1, 28, 28, 1).permute(0, 3, 1, 2)

    def conv_block(h, w, b):
        # TF 'SAME' for 5x5 stride-1 == pad 2.
        w_t = torch.from_numpy(w).permute(3, 2, 0, 1)  # HWIO -> OIHW
        h = F.conv2d(h, w_t, torch.from_numpy(b), padding=2)
        h = F.relu(h)
        # TF 'SAME' 2x2/2 maxpool == ceil_mode with edge-clipped windows.
        return F.max_pool2d(h, 2, 2, ceil_mode=True)

    h = conv_block(x, params_np["v0"], params_np["v1"])
    h = conv_block(h, params_np["v2"], params_np["v3"])
    h = conv_block(h, params_np["v4"], params_np["v5"])
    h = conv_block(h, params_np["v6"], params_np["v7"])
    # Match JAX NHWC flatten order: [N, 2, 2, 256].
    h = h.permute(0, 2, 3, 1).reshape(-1, 2 * 2 * 256)
    h = F.relu(h @ torch.from_numpy(params_np["v8"]) + torch.from_numpy(params_np["v9"]))
    h = h @ torch.from_numpy(params_np["v10"]) + torch.from_numpy(params_np["v11"])
    return h @ torch.from_numpy(params_np["v12"]) + torch.from_numpy(params_np["v13"])


@pytest.fixture(scope="module")
def setup():
    key = jax.random.PRNGKey(0)
    params = cnn.init_params(key)
    params_np = {k: np.asarray(v) for k, v in params.items()}
    x = np.random.default_rng(1).random((8, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[np.arange(8) % 10]
    return params, params_np, x, y


def test_param_specs():
    sizes = cnn.param_sizes()
    assert cnn.num_params() == 2_656_010  # SURVEY.md section 2.1 total
    assert sizes["v8"] == 1_048_576 and sizes["v13"] == 10
    assert list(cnn.PARAM_NAMES) == [f"v{i}" for i in range(14)]


def test_forward_matches_torch(setup):
    params, params_np, x, _ = setup
    logits_jax = np.asarray(
        cnn.apply_fn(params, jnp.asarray(x), precision=jax.lax.Precision.HIGHEST)
    )
    logits_torch = _torch_forward(params_np, x).detach().numpy()
    np.testing.assert_allclose(logits_jax, logits_torch, rtol=1e-4, atol=1e-4)


def test_loss_and_grads_match_torch(setup):
    params, params_np, x, y = setup
    loss_jax, grads = jax.value_and_grad(cnn.loss_fn)(
        params,
        jnp.asarray(x),
        jnp.asarray(y),
        dropout_rng=None,
        precision=jax.lax.Precision.HIGHEST,
    )

    tparams = {k: torch.from_numpy(v).requires_grad_(True) for k, v in params_np.items()}

    def forward_with(tp):
        x_t = torch.from_numpy(x).reshape(-1, 28, 28, 1).permute(0, 3, 1, 2)

        def conv_block(h, w, b):
            h = F.conv2d(h, w.permute(3, 2, 0, 1), b, padding=2)
            return F.max_pool2d(F.relu(h), 2, 2, ceil_mode=True)

        h = conv_block(x_t, tp["v0"], tp["v1"])
        h = conv_block(h, tp["v2"], tp["v3"])
        h = conv_block(h, tp["v4"], tp["v5"])
        h = conv_block(h, tp["v6"], tp["v7"])
        h = h.permute(0, 2, 3, 1).reshape(-1, 1024)
        h = F.relu(h @ tp["v8"] + tp["v9"])
        h = h @ tp["v10"] + tp["v11"]
        logits = h @ tp["v12"] + tp["v13"]
        logp = F.log_softmax(logits, dim=-1)
        return -(torch.from_numpy(y) * logp).sum(dim=-1).mean()

    loss_torch = forward_with(tparams)
    loss_torch.backward()
    np.testing.assert_allclose(float(loss_jax), float(loss_torch), rtol=1e-4)
    for name in cnn.PARAM_NAMES:
        np.testing.assert_allclose(
            np.asarray(grads[name]),
            tparams[name].grad.numpy(),
            rtol=1e-3,
            atol=1e-5,
            err_msg=f"grad mismatch for {name}",
        )


def test_dropout_semantics():
    """TF dropout: kept values scaled by 1/keep_prob; eval = identity."""
    params = cnn.init_params(jax.random.PRNGKey(0))
    x = jnp.ones((4, 784))
    eval_logits = cnn.apply_fn(params, x, dropout_rng=None)
    eval_logits2 = cnn.apply_fn(params, x, dropout_rng=None)
    np.testing.assert_array_equal(np.asarray(eval_logits), np.asarray(eval_logits2))
    # Train mode with different keys differs.
    l1 = cnn.apply_fn(params, x, dropout_rng=jax.random.PRNGKey(1))
    l2 = cnn.apply_fn(params, x, dropout_rng=jax.random.PRNGKey(2))
    assert not np.allclose(np.asarray(l1), np.asarray(l2))
    # keep_prob=1.0 with a key == eval exactly.
    l3 = cnn.apply_fn(params, x, dropout_rng=jax.random.PRNGKey(1), keep_prob=1.0)
    np.testing.assert_allclose(np.asarray(l3), np.asarray(eval_logits), rtol=1e-6)


def test_glorot_init_stats():
    """Init is glorot-uniform (TF1 get_variable default, model.py:24-86)."""
    params = cnn.init_params(jax.random.PRNGKey(3))
    w = np.asarray(params["v8"])  # [1024, 1024]
    limit = np.sqrt(6.0 / (1024 + 1024))
    assert np.abs(w).max() <= limit
    assert w.std() == pytest.approx(limit / np.sqrt(3), rel=0.05)


def test_first_conv_matmul_matches_conv():
    """The patches-matmul first conv (MXU lane-waste fix for cin=1,
    cnn._patches_block) is numerically the conv path: same logits for
    eval AND the same dropout stream for train mode."""
    from jax import lax

    params = cnn.init_params(jax.random.PRNGKey(5))
    x = jax.random.uniform(jax.random.PRNGKey(6), (8, 784))
    a = cnn.apply_fn(params, x, precision=lax.Precision.HIGHEST)
    b = cnn.apply_fn(
        params, x, precision=lax.Precision.HIGHEST, first_conv_matmul=True
    )
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    key = jax.random.PRNGKey(7)
    at = cnn.apply_fn(params, x, dropout_rng=key,
                      precision=lax.Precision.HIGHEST)
    bt = cnn.apply_fn(params, x, dropout_rng=key,
                      precision=lax.Precision.HIGHEST,
                      first_conv_matmul=True)
    np.testing.assert_allclose(np.asarray(at), np.asarray(bt), atol=1e-5)


def test_conv_matmul_modes_match_conv():
    """Every patches-matmul mode (first/tail/all — any cin, any spatial
    size, cnn.CONV_MATMUL_MODES) reproduces the conv lowering's logits,
    fwd AND grad — the numerics contract behind --conv-matmul."""
    from jax import lax

    params = cnn.init_params(jax.random.PRNGKey(8))
    x = jax.random.uniform(jax.random.PRNGKey(9), (8, 784))
    y = jax.nn.one_hot(jnp.arange(8) % 10, 10)
    ref = cnn.apply_fn(params, x, precision=lax.Precision.HIGHEST)
    g_ref = jax.grad(cnn.loss_fn)(
        params, x, y, dropout_rng=None, precision=lax.Precision.HIGHEST
    )
    for mode in ("first", "tail", "first+tail", "all"):
        got = cnn.apply_fn(
            params, x, precision=lax.Precision.HIGHEST, conv_matmul=mode
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=1e-5, err_msg=mode
        )
        g = jax.grad(cnn.loss_fn)(
            params, x, y, dropout_rng=None,
            precision=lax.Precision.HIGHEST, conv_matmul=mode,
        )
        for k in g_ref:
            np.testing.assert_allclose(
                np.asarray(g[k]), np.asarray(g_ref[k]),
                atol=2e-5, rtol=1e-4, err_msg=f"{mode}:{k}",
            )
