"""Speculative decoding (ddl_tpu/serve/speculate.py, ISSUE 15).

The acceptance chain: greedy-accept speculative decode (k in {2, 4})
produces tokens AND per-accepted-step logits BIT-IDENTICAL to plain
greedy decode at tp=1 AND tp=2 — the verify rides FREE SLOTS of the one
batched decode call (draft lanes over page-aliased tables), so every
verified row is the SAME compiled program computing the same
row-independent math. ``speculate_accepted_total`` /
``speculate_proposed_total`` give a measured acceptance rate, and
``speculate_k=0`` compiles the byte-identical pre-speculation decode
program (HLO-text pinned) with the Python branch fully off-path.
"""

import numpy as np
import pytest

from ddl_tpu.models.transformer import TINY_SPEC
from ddl_tpu.obs import MetricRegistry
from ddl_tpu.serve import (
    InferenceEngine,
    Request,
    Scheduler,
    ServeConfig,
    greedy_accept,
    propose_draft,
)

SPEC = TINY_SPEC


def _prompt(n, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(1, SPEC.vocab, size=n, dtype=np.int32)


def _record_decode_rows(eng, rows):
    """Record every ACTIVE slot's logits row keyed by (request_id,
    lengths) — the (request, token-index) coordinate both plain decode
    and the draft lanes use, so the same recorder aligns the two runs.
    Last write wins: a rejected lane's row is recomputed (correctly) by
    the later step that actually emits that position."""
    d0 = eng.decode

    def dec(last, lengths, rids, act, **kw):
        nxt, lg = d0(last, lengths, rids, act, **kw)
        lg = np.asarray(lg)
        for s in range(len(act)):
            if act[s]:
                rows[(int(rids[s]), int(lengths[s]))] = lg[s].copy()
        return nxt, lg

    eng.decode = dec


def test_propose_draft_lookup_semantics():
    """The matcher: longest suffix n-gram first, RIGHTMOST earlier
    occurrence, draft truncated to k and to what the source holds;
    'prompt' restricts the source to the prompt window; no match is an
    empty draft, not an error."""
    ctx = np.asarray([1, 5, 6, 7, 9, 5, 6, 7], np.int32)
    # Suffix (5,6,7) matched at position 1; the continuation runs on
    # through the source: [9, 5, 6, 7], truncated by k.
    np.testing.assert_array_equal(propose_draft(ctx, 4), [9, 5, 6, 7])
    np.testing.assert_array_equal(propose_draft(ctx, 2), [9, 5])
    # Rightmost match wins: two earlier (2,3) occurrences, the later
    # one's continuation is proposed.
    ctx2 = np.asarray([2, 3, 4, 2, 3, 8, 2, 3], np.int32)
    np.testing.assert_array_equal(propose_draft(ctx2, 2), [8, 2])
    # k truncates.
    np.testing.assert_array_equal(propose_draft(ctx2, 1), [8])
    # prompt-only lookup ignores the generated tail.
    ctx3 = np.asarray([4, 5, 9, 9, 4, 5], np.int32)
    np.testing.assert_array_equal(
        propose_draft(ctx3, 2, method="prompt", prompt_len=4), [9, 9]
    )
    # No recurring suffix: empty.
    assert propose_draft(np.arange(1, 7, dtype=np.int32), 3).size == 0
    assert propose_draft(ctx, 0).size == 0
    with pytest.raises(ValueError, match="unknown speculate method"):
        propose_draft(ctx, 2, method="beam")
    with pytest.raises(ValueError, match="prompt_len"):
        propose_draft(ctx, 2, method="prompt")
    # Acceptance rule: longest matching prefix, pure arithmetic.
    assert greedy_accept([3, 4], [3, 4, 9]) == 2
    assert greedy_accept([3, 7], [3, 4, 9]) == 1
    assert greedy_accept([8], [3, 4]) == 0
    assert greedy_accept([], [3]) == 0


@pytest.mark.parametrize("tp", [1, 2])
@pytest.mark.parametrize("k", [2, 4])
def test_speculative_decode_bit_identical(tp, k):
    """THE speculation pin: speculative greedy decode emits the SAME
    tokens as plain greedy decode AND, per accepted step, the SAME
    logits row bitwise — at tp=1 and tp=2, k=2 and k=4 (draft lanes are
    the decode program's own row-independent math). The pool reads
    byte-whole afterwards (lane aliases are pure incref/decref)."""
    cfg = ServeConfig(spec=SPEC, slots=4, capacity=64, page_size=8,
                      num_pages=24, tensor_parallel=tp)
    reqs = [Request(id=i, prompt=_prompt(8, i), max_new_tokens=12)
            for i in range(2)]

    rows_plain, rows_spec = {}, {}
    eng_p = InferenceEngine(cfg)
    _record_decode_rows(eng_p, rows_plain)
    done_p, stats_p = Scheduler(eng_p).run(reqs)

    import dataclasses

    reg = MetricRegistry()
    eng_s = InferenceEngine(dataclasses.replace(cfg, speculate_k=k))
    _record_decode_rows(eng_s, rows_spec)
    done_s, stats_s = Scheduler(eng_s, registry=reg).run(reqs)

    assert {i: done_s[i].tokens for i in done_s} == \
        {i: done_p[i].tokens for i in done_p}
    # Every (request, token-index) logits row the plain run produced
    # exists in the speculative run — bitwise equal (the speculative
    # run may hold EXTRA rows: lanes computed past an eos/finish).
    for key, row in rows_plain.items():
        np.testing.assert_array_equal(row, rows_spec[key])
    # The acceptance ledger measured a real rate.
    prop = int(reg.counter("speculate_proposed_total").value())
    acc = int(reg.counter("speculate_accepted_total").value())
    assert prop > 0 and 0 <= acc <= prop
    # Same emitted tokens, fewer (or equal) target-model steps — the
    # whole point of the lanes.
    assert stats_s.decode_tokens == stats_p.decode_tokens
    assert stats_s.decode_steps <= stats_p.decode_steps
    for eng in (eng_p, eng_s):
        assert eng.pages.free == eng.num_pages
        assert eng.pages.reserved == 0


def test_speculate_accepts_on_looping_stream():
    """Greedy decode of the tiny model settles into a token loop; the
    n-gram draft nails the loop, so a long-enough run ACCEPTS drafts
    and emits more than one token per target step — the decode-
    throughput lever measured end-to-end (seeded, deterministic)."""
    cfg = ServeConfig(spec=SPEC, slots=4, capacity=64, page_size=8,
                      num_pages=24, speculate_k=4)
    reg = MetricRegistry()
    eng = InferenceEngine(cfg)
    done, stats = Scheduler(eng, registry=reg).run(
        [Request(id=0, prompt=_prompt(8, 0), max_new_tokens=16)]
    )
    acc = int(reg.counter("speculate_accepted_total").value())
    assert acc >= 1
    assert len(done[0].tokens) == 16
    # Decode emits max_new - 1 tokens (the first came from prefill) in
    # FEWER calls: more than one emitted token per target step.
    assert stats.decode_tokens == 15
    assert stats.decode_tokens / stats.decode_steps > 1.0


def test_speculate_k0_compiles_byte_identical_program():
    """The off-path pin: speculation adds NO program shapes — the k=4
    engine's decode program lowers to byte-identical HLO text as the
    k=0 engine's (config rides only the Python branch), and a k=0 run
    never consults the draft machinery at all (propose_draft poisoned
    under it runs clean)."""
    import dataclasses

    import jax.numpy as jnp

    base = ServeConfig(spec=SPEC, slots=2, capacity=32, page_size=8,
                       num_pages=8)
    texts = []
    for cfg in (base, dataclasses.replace(base, speculate_k=4)):
        eng = InferenceEngine(cfg)
        S = cfg.slots
        zeros = jnp.zeros(S, jnp.int32)
        lowered = eng._decode_paged(1).lower(
            eng.params, eng.cache, zeros, zeros, zeros,
            jnp.zeros(S, bool), jnp.zeros((S, 1), jnp.int32),
        )
        texts.append(lowered.as_text())
    assert texts[0] == texts[1]

    from ddl_tpu.serve import scheduler as sched_mod

    def boom(*a, **kw):  # pragma: no cover - the pin is it never runs
        raise AssertionError("propose_draft consulted with speculate_k=0")

    orig = sched_mod.propose_draft
    sched_mod.propose_draft = boom
    try:
        eng = InferenceEngine(base)
        done, _ = Scheduler(eng).run(
            [Request(id=0, prompt=_prompt(6, 1), max_new_tokens=3)]
        )
        assert done[0].status == "ok"
    finally:
        sched_mod.propose_draft = orig


def test_speculate_config_validation_is_loud():
    """Loud-ctor discipline: every structural requirement of the lane
    design is a named config error, never a silent no-speculate or a
    mid-run lane failure."""
    with pytest.raises(ValueError, match="paged KV layout"):
        InferenceEngine(ServeConfig(spec=SPEC, speculate_k=2))
    with pytest.raises(ValueError, match="temperature=0"):
        InferenceEngine(ServeConfig(spec=SPEC, page_size=8,
                                    capacity=32, speculate_k=2,
                                    temperature=0.7))
    with pytest.raises(ValueError, match="slots >= 2"):
        InferenceEngine(ServeConfig(spec=SPEC, slots=1, page_size=8,
                                    capacity=32, speculate_k=2))
    with pytest.raises(ValueError, match="speculate_method"):
        InferenceEngine(ServeConfig(spec=SPEC, speculate_method="beam"))
    with pytest.raises(ValueError, match="speculate_k must be >= 0"):
        InferenceEngine(ServeConfig(spec=SPEC, speculate_k=-1))


def test_speculate_full_occupancy_degrades_to_plain():
    """No free slots, no lanes: a fully-occupied speculative batch
    serves plain decode's exact tokens with zero proposals — the
    documented "when k hurts" degradation is graceful, not an error."""
    cfg = ServeConfig(spec=SPEC, slots=2, capacity=32, page_size=8,
                      num_pages=12)
    reqs = [Request(id=i, prompt=_prompt(6, i), max_new_tokens=4)
            for i in range(2)]
    eng_p = InferenceEngine(cfg)
    done_p, _ = Scheduler(eng_p).run(reqs)

    import dataclasses

    reg = MetricRegistry()
    eng_s = InferenceEngine(dataclasses.replace(cfg, speculate_k=2))
    done_s, _ = Scheduler(eng_s, registry=reg).run(reqs)
    assert {i: done_s[i].tokens for i in done_s} == \
        {i: done_p[i].tokens for i in done_p}
    # Both slots occupied every decode tick: no lane ever existed.
    assert reg.get("speculate_proposed_total") is None
