"""Live SLO control plane tests (ISSUE 10): burn-rate window math
pinned against a brute-force recompute, the seeded burst scenario
firing the bulk-class alert (and only it) deterministically, the
analytic-FLOPs/MFU oracles at rel 1e-6, the /metrics endpoint
byte-identical to the in-process export mid-run, and the off-path pins
(no monitor -> no slo_* metrics)."""

import json
import urllib.error
import urllib.request

import pytest

from ddl_tpu.data.lm import synthesize_mixed_traffic, synthesize_prompts
from ddl_tpu.models.transformer import TINY_SPEC
from ddl_tpu.obs import MetricRegistry, Tracer
from ddl_tpu.obs import cost
from ddl_tpu.obs.export import MetricsExporter
from ddl_tpu.obs.memory import MemorySampler, record_compile
from ddl_tpu.obs.slo import SloMonitor, SloRule, parse_slo_rules

SPEC = TINY_SPEC


# -- rule validation and grammar ---------------------------------------------


def test_slo_rule_validation():
    ok = SloRule(name="r", metric="m", target_s=0.5)
    assert ok.budget == pytest.approx(0.1)
    with pytest.raises(ValueError, match="exactly one"):
        SloRule(name="r", metric="m")  # neither mode
    with pytest.raises(ValueError, match="exactly one"):
        SloRule(name="r", metric="m", target_s=1.0, total_metric="t")
    with pytest.raises(ValueError, match="objective"):
        SloRule(name="r", metric="m", target_s=1.0, objective=1.0)
    with pytest.raises(ValueError, match="fast_window"):
        SloRule(name="r", metric="m", target_s=1.0, fast_window=8,
                slow_window=8)
    with pytest.raises(ValueError, match="threshold"):
        SloRule(name="r", metric="m", target_s=1.0, threshold=0)
    with pytest.raises(ValueError, match="target_s"):
        SloRule(name="r", metric="m", target_s=-1.0)
    # Dict labels normalize to a sorted tuple (hashable, order-free).
    a = SloRule(name="r", metric="m", target_s=1.0,
                labels={"b": 2, "a": 1})
    assert a.labels == (("a", "1"), ("b", "2"))
    with pytest.raises(ValueError, match="at least one rule"):
        SloMonitor([], MetricRegistry())
    with pytest.raises(ValueError, match="duplicate"):
        SloMonitor([ok, ok], MetricRegistry())


def test_parse_slo_rules_grammar():
    rules = parse_slo_rules(
        "bulk:metric=router_shed_total,total=router_requests_total,"
        "label.class=bulk,objective=0.5,fast=4,slow=8,threshold=2;"
        "ttft:metric=serve_ttft_seconds,target=0.25"
    )
    assert [r.name for r in rules] == ["bulk", "ttft"]
    assert rules[0].total_metric == "router_requests_total"
    assert rules[0].labels == (("class", "bulk"),)
    assert rules[0].objective == 0.5 and rules[0].threshold == 2.0
    assert rules[1].target_s == 0.25 and rules[1].total_metric is None
    for bad, msg in [
        ("", "no rules"),
        ("noname", "NAME:key=val"),
        ("r:target=1", "metric= is required"),
        ("r:metric=m,target=1,bogus=2", "unknown key"),
        ("r:metric=m,target=1;r:metric=m,target=1", "duplicate"),
    ]:
        with pytest.raises(ValueError, match=msg):
            parse_slo_rules(bad)


# -- window math vs brute force ----------------------------------------------


def test_burn_rate_pinned_to_brute_force_recompute():
    """THE window-math pin: the streaming evaluator's per-tick burn
    rates (both windows, histogram AND counter mode) equal a
    brute-force recompute over the test's own full per-tick log —
    including the attach-time baseline, partial-history windows, and
    the edge-triggered alert transitions (alert -> clear -> alert
    counts two)."""
    reg = MetricRegistry()
    h = reg.histogram("lat")
    bad_c = reg.counter("bad")
    tot_c = reg.counter("tot")
    # Pre-attach history must be baseline, not burn.
    h.observe_many([9.0, 9.0])
    bad_c.inc(5, cls="x")
    tot_c.inc(5, cls="x")
    hr = SloRule(name="h", metric="lat", target_s=0.5, objective=0.8,
                 fast_window=3, slow_window=6)
    cr = SloRule(name="c", metric="bad", total_metric="tot",
                 labels={"cls": "x"}, objective=0.5, fast_window=2,
                 slow_window=4)
    mon = SloMonitor([hr, cr], reg)
    # Scripted stream: (histogram samples, counter bad inc, counter
    # total inc) per tick — hot, cooling, idle, hot again.
    script = [
        ([0.9, 0.9], 2, 2), ([0.9, 0.1], 1, 2), ([0.1], 0, 3),
        ([], 0, 0), ([0.1, 0.1], 0, 2), ([0.1], 0, 2),
        ([0.9, 0.9, 0.9], 2, 2), ([0.9, 0.9], 2, 2),
    ]
    # The test's own cumulative log, seeded with the attach baselines.
    log_h = [(2, 2)]
    log_c = [(5, 5)]
    alerts_seen = {"h": 0, "c": 0}

    def brute(rule, log, window):
        i = max(0, len(log) - 1 - window)
        m0, t0 = log[i]
        m1, t1 = log[-1]
        total = t1 - t0
        if total <= 0:
            return 0.0
        return ((m1 - m0) / total) / rule.budget

    for samples, binc, tinc in script:
        h.observe_many(samples)
        if binc:
            bad_c.inc(binc, cls="x")
        if tinc:
            tot_c.inc(tinc, cls="x")
        entered = mon.tick()
        for name in entered:
            alerts_seen[name] += 1
        log_h.append((log_h[-1][0] + sum(1 for v in samples if v > 0.5),
                      log_h[-1][1] + len(samples)))
        log_c.append((log_c[-1][0] + binc, log_c[-1][1] + tinc))
        for rule, log in ((hr, log_h), (cr, log_c)):
            for window, w in (("fast", rule.fast_window),
                              ("slow", rule.slow_window)):
                want = brute(rule, log, w)
                assert mon.burn_rate(rule.name, window) == want
                assert reg.gauge("slo_burn_rate").value(
                    rule=rule.name, window=window
                ) == want
        assert mon.cumulative("h") == log_h[-1]
        assert mon.cumulative("c") == log_c[-1]
    # The histogram rule went hot (ticks 1-2 windows), cooled below
    # threshold, and re-fired on the tail burst: edge-triggered count
    # matches both the monitor's ledger and the registry counter.
    assert mon.alerts("h") == alerts_seen["h"] >= 2
    assert reg.counter("slo_alerts_total").value(rule="h") == \
        mon.alerts("h")
    assert reg.counter("slo_alerts_total").value(rule="c") == \
        mon.alerts("c")
    assert mon.fired_ticks("h")[0] >= 1


# -- serve integration: streaming ≡ post-hoc ---------------------------------


def test_monitor_misses_pinned_to_request_slo_samples():
    """On a live serve run the monitor's cumulative (misses, total)
    equals a brute-force count over ``request_slo_samples`` of the same
    run's trace — the streaming evaluator and the post-hoc derivation
    are one definition. A monitor-less twin run publishes NO slo_*
    metrics (off-path pin), and warmup advances no windows."""
    from ddl_tpu.serve import InferenceEngine, Request, Scheduler, ServeConfig
    from ddl_tpu.serve.scheduler import request_slo_samples

    prompts = synthesize_prompts(num=3, min_len=4, max_len=8,
                                 vocab=SPEC.vocab, seed=5)
    reqs = [Request(id=i, prompt=p, max_new_tokens=4, arrival=i)
            for i, p in enumerate(prompts)]
    target = 1e-9  # every TTFT on this host misses: misses == total
    rule = SloRule(name="ttft", metric="serve_ttft_seconds",
                   target_s=target, objective=0.5, fast_window=2,
                   slow_window=4)
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=2, capacity=32))
    reg, tr = MetricRegistry(), Tracer()
    mon = SloMonitor([rule], reg, tracer=tr)
    sched = Scheduler(eng, tracer=tr, registry=reg, slo_monitor=mon)
    sched.warmup(reqs)
    assert mon.ticks == 0, "warmup must not advance burn-rate windows"
    assert not tr.records
    done, stats = sched.run(reqs)
    samples = request_slo_samples(tr.records)
    brute_misses = sum(1 for t, _ in samples.values() if t > target)
    assert mon.cumulative("ttft") == (brute_misses, stats.ttft.steps)
    assert brute_misses == 3  # all served requests missed the 1ns target
    assert mon.alerts("ttft") >= 1
    assert any(r["name"] == "slo_alert" and r["attrs"]["rule"] == "ttft"
               for r in tr.records)
    assert reg.counter("slo_alerts_total").value(rule="ttft") == \
        mon.alerts("ttft")

    # Off-path pin: same run shape without a monitor -> the registry
    # holds not one slo_* name.
    eng2 = InferenceEngine(ServeConfig(spec=SPEC, slots=2, capacity=32))
    reg2 = MetricRegistry()
    Scheduler(eng2, registry=reg2).run([
        Request(id=i, prompt=p, max_new_tokens=4, arrival=i)
        for i, p in enumerate(prompts)
    ])
    assert not [m.name for m in reg2.metrics()
                if m.name.startswith("slo_")]


# -- the seeded burst scenario -----------------------------------------------


def _burst_run():
    """One seeded burst run: 1-replica router, slots=1, bulk-targeted
    burst, priority shedding with bulk margin 1 — returns the monitor
    and tracer. Counter-mode rules over the router's live
    {class=}-labeled shed/request counters."""
    from ddl_tpu.serve import ServeConfig
    from ddl_tpu.serve.router import ClassSpec, Router, RouterConfig

    traffic = synthesize_mixed_traffic(
        classes={
            "chat": dict(rate=0.3, prompt_min=4, prompt_max=8,
                         max_new_tokens=2),
            "bulk": dict(rate=0.4, prompt_min=4, prompt_max=8,
                         max_new_tokens=2),
        },
        horizon=16, vocab=SPEC.vocab, seed=0,
        burst=(4, 6, 6.0, "bulk"), max_requests=16,
    )
    rules = tuple(
        SloRule(name=f"{c}_shed", metric="router_shed_total",
                total_metric="router_requests_total",
                labels={"class": c}, objective=0.5, fast_window=3,
                slow_window=6)
        for c in ("bulk", "chat")
    )
    reg, tr = MetricRegistry(), Tracer()
    mon = SloMonitor(rules, reg, tracer=tr)
    cfg = RouterConfig(
        serve=ServeConfig(spec=SPEC, slots=1, capacity=64),
        replicas=1,
        classes=(ClassSpec("chat", priority=0),
                 ClassSpec("bulk", priority=1, shed_margin=1)),
        shed_threshold=2,
    )
    router = Router(cfg, registry=reg, tracer=tr, slo_monitor=mon)
    done, rstats = router.run(traffic)
    return mon, tr, rstats


def test_router_histogram_rule_live_ttft():
    """Histogram-mode rules are LIVE in router mode: the router
    observes router_ttft_seconds{class=} per global tick from the
    shared trace (serve_* histograms land in per-replica registries
    the monitor never sees), so a TTFT rule over it fires mid-run; the
    live series equals the post-hoc request_slo_samples derivation —
    one definition, two consumers. A monitor built on a FOREIGN
    registry is rejected at the ctor."""
    from ddl_tpu.serve import ServeConfig
    from ddl_tpu.serve.router import ClassSpec, Router, RouterConfig
    from ddl_tpu.serve.scheduler import request_slo_samples

    traffic = synthesize_mixed_traffic(
        classes={"chat": dict(rate=0.5, prompt_min=4, prompt_max=8,
                              max_new_tokens=2)},
        horizon=8, vocab=SPEC.vocab, seed=3, max_requests=6,
    )
    rule = SloRule(name="chat_ttft", metric="router_ttft_seconds",
                   labels={"class": "chat"}, target_s=1e-9,
                   objective=0.5, fast_window=2, slow_window=4)
    reg, tr = MetricRegistry(), Tracer()
    mon = SloMonitor([rule], reg, tracer=tr)
    cfg = RouterConfig(serve=ServeConfig(spec=SPEC, slots=2, capacity=32),
                       replicas=1, classes=(ClassSpec("chat"),))
    rec0 = len(tr.records)
    done, _ = Router(cfg, registry=reg, tracer=tr, slo_monitor=mon).run(
        traffic
    )
    # Every served chat request missed the 1ns target, live.
    samples = request_slo_samples(tr.records[rec0:])
    ttfts = sorted(t for t, _ in samples.values())
    assert ttfts and len(done) == len(traffic)
    assert mon.cumulative("chat_ttft") == (len(ttfts), len(ttfts))
    assert mon.alerts("chat_ttft") >= 1
    # The live histogram holds exactly the post-hoc per-request TTFTs.
    assert sorted(reg.histogram("router_ttft_seconds").values(
        **{"class": "chat"}
    )) == ttfts

    with pytest.raises(ValueError, match="different registry"):
        Router(cfg, registry=MetricRegistry(), slo_monitor=mon)
    with pytest.raises(ValueError, match="registry"):
        Router(cfg, slo_monitor=mon)


def test_burn_rate_rejects_unknown_window():
    reg = MetricRegistry()
    mon = SloMonitor(
        [SloRule(name="r", metric="m", target_s=1.0)], reg
    )
    with pytest.raises(ValueError, match="fast.*slow"):
        mon.burn_rate("r", "Fast")


def test_peak_flops_warns_once_on_unknown_accelerator():
    """An accelerator kind missing from the peak table warns (once per
    kind) instead of silently anchoring MFU to the CPU nominal; CPU
    devices stay silent."""
    import warnings

    class Gpu:
        device_kind = "NVIDIA H100 80GB HBM3"
        platform = "gpu"

    cost._warned_kinds.discard(Gpu.device_kind.lower())
    with pytest.warns(UserWarning, match="peak-flops"):
        assert cost.peak_flops_per_device(Gpu()) == \
            cost.CPU_NOMINAL_PEAK_FLOPS
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # second call: latched silent
        cost.peak_flops_per_device(Gpu())
        cost.peak_flops_per_device(None)  # CPU path never warns


def test_burst_scenario_fires_bulk_alert_only_deterministically():
    """THE scenario pin: the seeded bulk burst drives bulk's shed
    fraction over budget — the bulk_shed alert fires — while chat's
    burn stays 0.0 the whole run (green). Two runs from the same seed
    fire at the SAME monitor ticks with the SAME final burns."""
    mon1, tr1, rstats1 = _burst_run()
    # Bulk alerted; chat never did — and never even burned.
    assert mon1.alerts("bulk_shed") >= 1
    assert mon1.fired_ticks("bulk_shed")
    assert mon1.alerts("chat_shed") == 0
    assert mon1.burn_rate("chat_shed", "fast") == 0.0
    assert mon1.burn_rate("chat_shed", "slow") == 0.0
    assert mon1.cumulative("chat_shed")[0] == 0  # zero chat sheds
    assert rstats1.per_class["bulk"].shed > 0
    assert rstats1.per_class["chat"].shed == 0
    # Attempts include sheds: router_requests_total counts EVERY
    # arrival of the class (counted before the shed decision), so an
    # all-shed window has a non-empty denominator and burns — the
    # worst overload can never read 0.0.
    for c in ("bulk", "chat"):
        assert mon1.registry.counter("router_requests_total").value(
            **{"class": c}
        ) == rstats1.per_class[c].requests
    # The alert is in the trace, attributed to the bulk rule only.
    alert_rules = {r["attrs"]["rule"] for r in tr1.records
                   if r["name"] == "slo_alert"}
    assert alert_rules == {"bulk_shed"}

    # Determinism: a fresh router/registry/monitor from the same seed
    # replays the identical alert timeline.
    mon2, _, rstats2 = _burst_run()
    assert mon2.fired_ticks("bulk_shed") == mon1.fired_ticks("bulk_shed")
    assert mon2.alerts("bulk_shed") == mon1.alerts("bulk_shed")
    for name in ("bulk_shed", "chat_shed"):
        assert mon2.cumulative(name) == mon1.cumulative(name)
        for w in ("fast", "slow"):
            assert mon2.burn_rate(name, w) == mon1.burn_rate(name, w)
    assert rstats2.per_class["bulk"].shed == rstats1.per_class["bulk"].shed


# -- analytic FLOPs / MFU oracles --------------------------------------------


def test_lm_flops_match_hand_computed_oracle():
    """train_mfu's numerator for one LM config vs an independently
    hand-written arithmetic expansion, at rel 1e-6 (they are integers —
    the tolerance is the acceptance bar's, equality is the reality)."""
    # LMSpec: vocab=32, d_model=32, heads=2, layers=2, d_ff=64.
    B, T, e, f, v, L = 4, 32, 32, 64, 32, 2
    qkvo = 8 * T * e * e            # 4 projections, 2*T*e*e each
    attn = 4 * T * T * e            # QK^T + AV over the full T x T
    mlp = 4 * T * e * f             # w1 + w2
    head = 2 * B * T * e * v
    fwd = L * B * (qkvo + attn + mlp) + head
    assert cost.lm_forward_flops(SPEC, B, T) == pytest.approx(
        fwd, rel=1e-6
    )
    assert cost.lm_forward_flops(SPEC, B, T) == fwd
    assert cost.lm_train_step_flops(SPEC, B, T) == 3 * fwd
    # remat recomputes the blocks' forward (not the head) once more.
    assert cost.lm_train_step_flops(SPEC, B, T, remat=True) == \
        3 * fwd + L * B * (qkvo + attn + mlp)


def test_cnn_flops_match_hand_computed_oracle():
    """Same bar for the CNN family at the tiny widths: each SAME conv
    is 2*H*W*cout*(25*cin), pools/bias/relu uncounted, three FCs."""
    batch = 10
    conv = (2 * 28 * 28 * 4 * (25 * 1)
            + 2 * 14 * 14 * 8 * (25 * 4)
            + 2 * 7 * 7 * 8 * (25 * 8)
            + 2 * 4 * 4 * 8 * (25 * 8))
    fc = 2 * (2 * 2 * 8) * 32 + 2 * 32 * 16 + 2 * 16 * 10
    fwd = conv + fc
    got = cost.cnn_train_step_flops(batch, (4, 8, 8, 8), (32, 16))
    assert got == pytest.approx(3 * batch * fwd, rel=1e-6)
    assert got == 3 * batch * fwd
    # The full-width default is the reference model.
    assert cost.cnn_forward_flops() == cost.cnn_forward_flops(
        (32, 64, 128, 256), (1024, 512), 10, 1
    )


def test_serve_flops_paged_aware_and_peak_table():
    """Decode FLOPs track the ATTENDED width — the paged bucket's
    residency vs the contiguous capacity — and the peak table resolves
    device kinds with the override winning."""
    e, f, v, L = 32, 64, 32, 2
    per_tok = lambda W: L * (8 * e * e + 4 * e * W + 4 * e * f) + 2 * e * v
    assert cost.serve_decode_flops_per_token(SPEC, 16) == per_tok(16)
    assert cost.serve_decode_flops_per_token(SPEC, 256) == per_tok(256)
    # Paged residency of 2 pages x 8 rows vs a 256-row ring: the
    # attention term shrinks 16x, everything else is identical.
    small, big = per_tok(16), per_tok(256)
    assert big - small == L * 4 * e * (256 - 16)
    assert cost.serve_prefill_flops(SPEC, 8, 64) == \
        L * (8 * 8 * e * e + 4 * 8 * 64 * e + 4 * 8 * e * f) \
        + 2 * 8 * e * v

    class Dev:
        def __init__(self, kind):
            self.device_kind = kind

    assert cost.peak_flops_per_device(Dev("TPU v4")) == 275e12
    assert cost.peak_flops_per_device(Dev("TPU v5p slice")) == 459e12
    assert cost.peak_flops_per_device(Dev("cpu")) == \
        cost.CPU_NOMINAL_PEAK_FLOPS
    assert cost.peak_flops_per_device(None) == cost.CPU_NOMINAL_PEAK_FLOPS
    assert cost.peak_flops_per_device(Dev("TPU v4"), override=1e12) == 1e12
    with pytest.raises(ValueError):
        cost.peak_flops_per_device(None, override=-1)
    assert cost.mfu(1e10, 0.5, 2, 1e10) == pytest.approx(1.0)
    assert cost.mfu(1e10, 0.0, 2, 1e10) == 0.0


def test_train_mfu_gauge_matches_recompute_lm_and_cnn():
    """Integration: the train_mfu gauge each trainer publishes equals
    the analytic FLOPs over the SAME span bracket the registry's
    train_span_seconds histogram recorded, under a --peak-flops
    override (exact floats — one formula, two evaluation sites)."""
    from ddl_tpu.data import load_mnist
    from ddl_tpu.data.lm import synthesize_copy
    from ddl_tpu.strategies.seq import SeqConfig, SeqTrainer
    from ddl_tpu.train import SingleChipTrainer, TrainConfig

    peak = 1e12
    # LM: one span of one step.
    ds = synthesize_copy(num_train=8, num_test=8, seq_len=32,
                         vocab=SPEC.vocab, seed=0)
    cfg = SeqConfig(epochs=1, batch_size=8, num_workers=1, scheme="full",
                    eval_every=0, spec=SPEC)
    reg = MetricRegistry()
    SeqTrainer(cfg, ds).train(log=lambda s: None, metrics=reg,
                              peak_flops=peak)
    span_s = reg.histogram("train_span_seconds").values()[-1]
    flops = cost.lm_train_step_flops(SPEC, 8, 32)
    assert reg.gauge("train_mfu").value() == \
        cost.mfu(flops * 1, span_s, 1, peak)
    assert reg.counter("xla_compiles_total").value(kind="train_span") >= 1

    # CNN: narrow model, one span of one step.
    mnist = load_mnist(path=None, synthetic_train=64, synthetic_test=16,
                       seed=7)
    tcfg = TrainConfig(epochs=1, batch_size=64, eval_every=0, seed=0,
                       conv_channels=(4, 8, 8, 8), fc_sizes=(32, 16))
    reg2 = MetricRegistry()
    SingleChipTrainer(tcfg, mnist).train(log=lambda s: None, metrics=reg2,
                                         peak_flops=peak)
    span_s2 = reg2.histogram("train_span_seconds").values()[-1]
    flops2 = cost.cnn_train_step_flops(64, (4, 8, 8, 8), (32, 16))
    assert reg2.gauge("train_mfu").value() == \
        cost.mfu(flops2 * 1, span_s2, 1, peak)
    assert reg2.counter("xla_compiles_total").value(kind="eval") >= 1


# -- /metrics endpoint --------------------------------------------------------


def test_metrics_endpoint_byte_identical_during_live_serve_run():
    """THE export pin: mid-run (externally-driven scheduler, between
    ticks) GET /metrics returns EXACTLY the bytes of the in-process
    prometheus_text() — the endpoint is transport, not a second
    formatter. Plus /healthz and the 404 path."""
    from ddl_tpu.serve import InferenceEngine, Request, Scheduler, ServeConfig

    prompts = synthesize_prompts(num=2, min_len=4, max_len=8,
                                 vocab=SPEC.vocab, seed=2)
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=2, capacity=32))
    reg = MetricRegistry()
    sched = Scheduler(eng, registry=reg)
    with MetricsExporter(reg, 0) as exp:
        sched.begin()
        for i, p in enumerate(prompts):
            sched.submit(Request(id=i, prompt=p, max_new_tokens=4))
        for _ in range(3):
            sched.tick()
        # Mid-run, between ticks: nothing mutates the registry while
        # the handler snapshots, so equality is byte-exact.
        body = urllib.request.urlopen(exp.url("/metrics")).read()
        assert body == reg.prometheus_text().encode("utf-8")
        assert b"serve_decode_tokens_total" in body
        while not sched.idle:
            sched.tick()
        done, _ = sched.collect()
        assert len(done) == 2
        body2 = urllib.request.urlopen(exp.url("/metrics")).read()
        assert body2 == reg.prometheus_text().encode("utf-8")
        health = json.loads(urllib.request.urlopen(
            exp.url("/healthz")
        ).read())
        # ISSUE 11: /healthz carries the compact goodput digest next
        # to liveness — equal to the live gauge, absent keys for
        # detectors this run never attached.
        assert health["status"] == "ok"
        assert health["goodput_fraction"] == \
            reg.gauge("goodput_fraction").value()
        assert "last_anomaly_tick" not in health
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(exp.url("/nope"))
        assert e.value.code == 404


# -- memory watermarks + compile counters ------------------------------------


def test_memory_sampler_guarded_and_latching():
    """memory_stats()-less backends (this XLA:CPU) latch the sampler
    off after one probe; a reporting device fills the watermark
    gauges."""
    import jax

    reg = MetricRegistry()
    s = MemorySampler(reg, [jax.devices()[0]])
    first = s.sample()
    if not first:  # this container: CPU returns None
        assert s.supported is False
        assert s.sample() is False  # latched: no re-probe
        assert not [m.name for m in reg.metrics()]

    class FakeDev:
        @staticmethod
        def memory_stats():
            return {"bytes_in_use": 10, "peak_bytes_in_use": 20,
                    "bytes_limit": 100}

    class DeadDev:
        @staticmethod
        def memory_stats():
            raise RuntimeError("no stats on this backend")

    reg2 = MetricRegistry()
    s2 = MemorySampler(reg2, [FakeDev(), DeadDev()])
    assert s2.sample() is True and s2.supported is True
    assert reg2.gauge("device_memory_bytes_in_use").value(device=0) == 10
    assert reg2.gauge("device_memory_peak_bytes").value(device=0) == 20
    assert reg2.gauge("device_memory_bytes_limit").value(device=0) == 100
    assert reg2.gauge("device_memory_bytes_in_use").value(device=1) is None


def test_compile_counters_and_spans():
    """record_compile moves the counter, observes the bracket when
    given one (a real span in the trace), and degrades to an event
    without one; the engine's builds feed it through the scheduler
    hook (pinned live in test_train_mfu / the serve integration
    above)."""
    reg, tr = MetricRegistry(), Tracer()
    record_compile(reg, tr, "train_span", t0=1.0, t1=1.5, k=3)
    record_compile(reg, tr, "prefill", key=8)
    record_compile(None, tr, "decode")  # registry-less: trace only
    record_compile(reg, None, "decode")  # tracer-less: count only
    assert reg.counter("xla_compiles_total").value(kind="train_span") == 1
    assert reg.counter("xla_compiles_total").value(kind="prefill") == 1
    assert reg.counter("xla_compiles_total").value(kind="decode") == 1
    assert reg.histogram("xla_compile_seconds").values(
        kind="train_span"
    ) == [0.5]
    names = [(r["name"], r["type"]) for r in tr.records]
    assert names == [("compile", "span"), ("compile", "event"),
                     ("compile", "event")]
    assert tr.records[0]["dur_s"] == pytest.approx(0.5)
