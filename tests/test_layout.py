"""Layout-policy unit tests (SURVEY.md §4a: sharding index math + greedy
ordering vs the reference's algorithms)."""

import numpy as np
import pytest

from ddl_tpu.models import cnn
from ddl_tpu.parallel.layout import (
    assign_layout,
    lpt_order,
    zigzag_order,
)

NAMES = list(cnn.PARAM_NAMES)
SIZES = cnn.param_sizes()


def test_zigzag_matches_reference_order():
    # The exact greedy order the reference produces for the 14-var CNN
    # (mnist_sync_sharding_greedy/worker.py:14-30; SURVEY.md §2.2).
    expected = "v13 v8 v1 v6 v3 v10 v5 v4 v7 v2 v11 v12 v0 v9".split()
    assert zigzag_order(NAMES, SIZES) == expected


def test_block_partition_reference_semantics():
    # L = num_vars // num_ps per shard, last shard absorbs the remainder
    # (mnist_sync_sharding/parameter_server.py:30-32).
    a = assign_layout("block", 4, NAMES, SIZES)
    counts = [sum(1 for n in NAMES if a.var_to_shard[n] == s) for s in range(4)]
    assert counts == [3, 3, 3, 5]
    # Routing parity: var i belongs to shard min(i // L, S-1)
    # (mnist_sync_sharding/worker.py:33-36).
    for i, n in enumerate(NAMES):
        assert a.var_to_shard[n] == min(i // 3, 3)


@pytest.mark.parametrize("policy", ["block", "zigzag", "lpt", "flat"])
@pytest.mark.parametrize("num_shards", [1, 2, 4, 7, 8])
def test_assignment_partitions_all_elements(policy, num_shards):
    if policy != "flat" and num_shards > len(NAMES):
        pytest.skip("var-granular needs shards <= vars")
    a = assign_layout(policy, num_shards, NAMES, SIZES)
    assert sum(a.shard_sizes) == a.total == sum(SIZES.values())
    # Shard ranges are contiguous and disjoint in flat space.
    off = 0
    for st, sz in zip(a.shard_starts, a.shard_sizes):
        assert st == off
        off += sz
    # Every var appears exactly once in the order.
    assert sorted(a.order) == sorted(NAMES)
    # var_offsets consistent with order.
    off = 0
    for n in a.order:
        assert a.var_offsets[n] == off
        off += SIZES[n]


def test_var_aligned_boundaries():
    for policy in ("block", "zigzag", "lpt"):
        a = assign_layout(policy, 4, NAMES, SIZES)
        # Each shard's element range is exactly the sum of its vars.
        for s in range(4):
            mine = [n for n in a.order if a.var_to_shard[n] == s]
            assert a.shard_sizes[s] == sum(SIZES[n] for n in mine)


def test_zigzag_balances_seven_shards():
    # At 7 shards zigzag pairs each big tensor with a tiny one: every shard
    # holds exactly one of the 7 largest tensors (SURVEY.md §2.2).
    a = assign_layout("zigzag", 7, NAMES, SIZES)
    big7 = sorted(SIZES.values())[-7:]
    per_shard_max = []
    for s in range(7):
        mine = [SIZES[n] for n in a.order if a.var_to_shard[n] == s]
        per_shard_max.append(max(mine))
    assert sorted(per_shard_max) == sorted(big7)


def test_lpt_beats_zigzag_at_two_shards():
    # SURVEY.md §2.2: zigzag is actively worse than naive at 2 shards
    # (2.39M vs 264k); LPT must do better.
    z = assign_layout("zigzag", 2, NAMES, SIZES)
    l = assign_layout("lpt", 2, NAMES, SIZES)
    assert l.balance < z.balance
    assert max(z.shard_sizes) > 2_000_000  # the pathological split
    assert max(l.shard_sizes) < 1_500_000


def test_lpt_order_groups_by_shard():
    order, counts = lpt_order(NAMES, SIZES, 3)
    assert sum(counts) == len(NAMES)
    assert sorted(order) == sorted(NAMES)


def test_flat_equal_chunks():
    a = assign_layout("flat", 8, NAMES, SIZES)
    # Shard boundaries are the ceil-split rounded UP to the TPU lane width
    # (128), so every shard slice is tile-aligned (layout.LANE).
    chunk = -(-(-(-a.total // 8)) // 128) * 128
    assert a.max_shard == chunk
    assert a.shard_starts == tuple(min(s * chunk, a.total) for s in range(8))
    assert a.balance == pytest.approx(max(a.shard_sizes) / (a.total / 8))
    assert a.var_to_shard is None


def test_max_shard_lane_aligned():
    for policy, shards in (("block", 4), ("zigzag", 7), ("lpt", 3), ("flat", 8)):
        a = assign_layout(policy, shards, NAMES, SIZES)
        assert a.max_shard % 128 == 0
        assert a.max_shard >= max(a.shard_sizes)
        assert a.max_shard - max(a.shard_sizes) < 128


def test_reassembly_index_roundtrip():
    from ddl_tpu.parallel.collectives import reassembly_index

    for policy, shards in (("block", 4), ("zigzag", 7), ("lpt", 8), ("flat", 8)):
        a = assign_layout(policy, shards, NAMES, SIZES)
        rng = np.random.default_rng(0)
        flat = rng.standard_normal(a.total).astype(np.float32)
        m = a.max_shard
        # Simulate per-shard padded slices, then reassemble.
        padded = np.zeros((len(a.shard_starts), m), np.float32)
        for s, (st, sz) in enumerate(zip(a.shard_starts, a.shard_sizes)):
            padded[s, :sz] = flat[st : st + sz]
        idx = reassembly_index(a)
        np.testing.assert_array_equal(padded.reshape(-1)[idx], flat)


def test_fold_shards_reference_any_split():
    """num_ps > num_devices (the reference's ``run.sh 7 2`` — 7 PS over 2
    workers, mnist_sync_sharding/parameter_server.py:30-32): surplus shards
    fold round-robin, shard s -> device s % W, preserving each shard's
    variable grouping."""
    from ddl_tpu.parallel.layout import fold_shards

    base = assign_layout("zigzag", 7, NAMES, SIZES)
    folded = fold_shards(base, 2, SIZES)
    assert folded.num_shards == 2
    assert folded.policy == "zigzag"
    # Partition invariants hold after folding.
    assert sum(folded.shard_sizes) == folded.total == sum(SIZES.values())
    assert sorted(folded.order) == sorted(NAMES)
    # Ownership: exactly the round-robin fold of the base assignment.
    for n in NAMES:
        assert folded.var_to_shard[n] == base.var_to_shard[n] % 2
    # Device 0's vars come from shards 0, 2, 4, 6 in that order.
    d0 = [n for n in folded.order if folded.var_to_shard[n] == 0]
    expected = [n for s in (0, 2, 4, 6) for n in base.order
                if base.var_to_shard[n] == s]
    assert d0 == expected


def test_fold_shards_noop_when_enough_devices():
    from ddl_tpu.parallel.layout import fold_shards

    base = assign_layout("lpt", 4, NAMES, SIZES)
    assert fold_shards(base, 8, SIZES) is base


def test_resolve_layout_folds_surplus_shards():
    """resolve_layout accepts any num_ps split like the reference launcher;
    flat re-splits over the mesh, var-granular policies fold."""
    from ddl_tpu.strategies.sync import resolve_layout
    from ddl_tpu.train.config import TrainConfig

    folded = resolve_layout(
        TrainConfig(num_workers=2, num_ps=7, layout="block"), 2, SIZES
    )
    assert folded is not None and folded.num_shards == 2
    flat = resolve_layout(
        TrainConfig(num_workers=2, num_ps=7, layout="flat"), 2, SIZES
    )
    assert flat is not None and flat.num_shards == 2
    assert flat.shard_sizes == assign_layout("flat", 2, NAMES, SIZES).shard_sizes


def test_fold_shards_invariants_random_sweep():
    """Partition invariants hold for arbitrary variable tables and any
    (policy, num_shards, num_devices) combination — the fold is pure
    (name, size) math, so sweep it broadly."""
    from ddl_tpu.parallel.layout import fold_shards

    rng = np.random.default_rng(7)
    for trial in range(25):
        n_vars = int(rng.integers(2, 20))
        names = [f"t{i}" for i in range(n_vars)]
        sizes = {n: int(rng.integers(1, 5000)) for n in names}
        policy = ["block", "zigzag", "lpt"][trial % 3]
        S = int(rng.integers(1, n_vars + 1))
        W = int(rng.integers(1, 9))
        base = assign_layout(policy, S, names, sizes)
        folded = fold_shards(base, W, sizes)
        assert folded.num_shards == min(S, W)
        assert sum(folded.shard_sizes) == folded.total == sum(sizes.values())
        assert sorted(folded.order) == sorted(names)
        if S > W:
            for n in names:
                assert folded.var_to_shard[n] == base.var_to_shard[n] % W
        # Contiguous disjoint shard ranges.
        off = 0
        for st, sz in zip(folded.shard_starts, folded.shard_sizes):
            assert st == off
            off += sz


def test_resolve_layout_clamps_num_ps_beyond_num_vars():
    """num_ps > num_vars (the reference's degenerate run.sh 20 2, where
    most PS own ZERO variables and the worker routing divides by zero,
    mnist_sync_sharding/worker.py:33): var-granular policies clamp to one
    shard per variable — the maximum var-aligned parallelism that exists —
    then fold onto the mesh as usual; flat honors any split exactly."""
    from ddl_tpu.strategies.sync import resolve_layout
    from ddl_tpu.train.config import TrainConfig

    n_vars = len(SIZES)
    for policy in ("block", "zigzag", "lpt"):
        lay = resolve_layout(
            TrainConfig(num_workers=4, num_ps=n_vars + 6, layout=policy),
            4, SIZES,
        )
        assert lay is not None and lay.num_shards == 4
        # Every variable owned exactly once, no empty base shards implied.
        assert sorted(lay.order) == sorted(SIZES)
        assert sum(lay.shard_sizes) == sum(SIZES.values())
    flat = resolve_layout(
        TrainConfig(num_workers=4, num_ps=n_vars + 6, layout="flat"), 4, SIZES
    )
    assert flat is not None and flat.num_shards == 4
