"""Sequence/context parallelism vs the single-device oracle.

Both schemes (ring attention over ppermute, Ulysses over all_to_all) must
reproduce exact full attention — forward AND gradients, causal and not —
on the 8-device virtual mesh. The oracle is ``ring.full_attention`` on
the unsharded arrays (itself pinned against a hand-rolled softmax here).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.parallel import ring
from ddl_tpu.parallel.mesh import make_mesh

B, T, H, D = 2, 64, 8, 16


def _qkv(seed: int = 0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape, dtype=jnp.float32) for k in ks)


def test_full_attention_matches_manual_softmax():
    q, k, v = _qkv()
    out = ring.full_attention(q, k, v)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    expect = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(out, expect, atol=1e-5)


def test_full_attention_shard_offsets():
    """q_offset/k_offset make causal masking correct on sequence SHARDS:
    rows computed from a q-shard against the full K/V with the shard's
    absolute offset equal the corresponding rows of the unsharded
    output."""
    q, k, v = _qkv(seed=4)
    full = ring.full_attention(q, k, v, causal=True)
    t0 = T // 2
    shard = ring.full_attention(
        q[:, t0:], k, v, causal=True, q_offset=t0, k_offset=0
    )
    np.testing.assert_allclose(
        np.asarray(shard), np.asarray(full[:, t0:]), atol=1e-5
    )


def test_full_attention_causal_masks_future():
    q, k, v = _qkv()
    out = ring.full_attention(q, k, v, causal=True)
    # Row t of the causal output only sees k/v[<=t]: recompute row T//2
    # from the truncated sequence.
    t = T // 2
    trunc = ring.full_attention(
        q[:, t : t + 1], k[:, : t + 1], v[:, : t + 1]
    )
    np.testing.assert_allclose(out[:, t], trunc[:, 0], atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_oracle(causal):
    mesh = make_mesh(8)
    q, k, v = _qkv()
    out = ring.make_ring_attention(mesh, causal=causal)(q, k, v)
    expect = ring.full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), expect, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_oracle(causal):
    mesh = make_mesh(8)
    q, k, v = _qkv(seed=1)
    out = ring.make_ulysses_attention(mesh, causal=causal)(q, k, v)
    expect = ring.full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), expect, atol=2e-4)


@pytest.mark.parametrize("scheme", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sequence_parallel_grads_match_oracle(scheme, causal):
    mesh = make_mesh(8)
    q, k, v = _qkv(seed=2)
    make = (
        ring.make_ring_attention if scheme == "ring"
        else ring.make_ulysses_attention
    )
    sp_fn = make(mesh, causal=causal)

    def loss_sp(q, k, v):
        return (sp_fn(q, k, v) ** 2).sum()

    def loss_oracle(q, k, v):
        return (ring.full_attention(q, k, v, causal=causal) ** 2).sum()

    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    g_oracle = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
    for gr, go in zip(g_sp, g_oracle):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(go), atol=5e-3, rtol=1e-3
        )


def test_ulysses_rejects_indivisible_heads():
    mesh = make_mesh(8)
    q = k = v = jnp.zeros((B, T, 4, D))  # 4 heads on 8 devices
    with pytest.raises(ValueError, match="num_heads"):
        ring.make_ulysses_attention(mesh)(q, k, v)


def test_ring_attention_bf16_inputs_stay_bf16():
    """State is fp32 internally; output dtype follows q (the MXU path)."""
    mesh = make_mesh(8)
    q, k, v = (a.astype(jnp.bfloat16) for a in _qkv(seed=3))
    out = ring.make_ring_attention(mesh)(q, k, v)
    assert out.dtype == jnp.bfloat16
    expect = ring.full_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), expect, atol=5e-2, rtol=5e-2
    )


def test_ring_attention_trains_end_to_end():
    """Sequence parallelism composes with the training machinery: a tiny
    attention model (QKV projections -> ring attention over the mesh ->
    output projection) trains under shard_map with the repo's Adam, data
    sequence-sharded across all 8 devices; grads flow through ppermute."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddl_tpu.ops import adam_init, adam_update
    from ddl_tpu.parallel.mesh import DP_AXIS

    mesh = make_mesh(8)
    E, Hh, Dd = 16, 4, 4
    key = jax.random.PRNGKey(9)
    kx, kq, kk, kv, ko = jax.random.split(key, 5)
    x = jax.random.normal(kx, (2, T, E))
    # Learnable cross-position target: every position must predict the
    # GLOBAL sequence mean — information only attention over the whole
    # (sharded) sequence can gather. (A noise-prediction target has a
    # loss floor of var(x); this one is drivable toward 0.)
    target = jnp.broadcast_to(x.mean(axis=1, keepdims=True), x.shape)
    w = {
        "q": jax.random.normal(kq, (E, Hh * Dd)) * 0.1,
        "k": jax.random.normal(kk, (E, Hh * Dd)) * 0.1,
        "v": jax.random.normal(kv, (E, Hh * Dd)) * 0.1,
        "o": jax.random.normal(ko, (Hh * Dd, E)) * 0.1,
    }

    def shard_loss(w, x, tgt):
        B, Tl = x.shape[:2]
        heads = lambda a: a.reshape(B, Tl, Hh, Dd)
        attn = ring.ring_attention_shard(
            heads(x @ w["q"]), heads(x @ w["k"]), heads(x @ w["v"]),
            axis_name=DP_AXIS, axis_size=8, causal=False,
        )
        pred = attn.reshape(B, Tl, Hh * Dd) @ w["o"]
        # This shard's LOCAL mean — the caller pmeans value and grads
        # explicitly (mean of per-shard means is exact because every
        # shard holds T/8 positions). Keeping the collective OUT of the
        # differentiated function means no gradient rides a pmean
        # transpose, whose rule differs across JAX generations
        # (ddl_tpu.compat) — the same explicit-reduction pattern the
        # seq trainer's step bodies use.
        return jnp.mean((pred - tgt) ** 2)

    seq = NamedSharding(mesh, P(None, DP_AXIS))
    rep = NamedSharding(mesh, P())
    x = jax.device_put(x, seq)
    target = jax.device_put(target, seq)
    w = jax.device_put(w, rep)
    opt = jax.device_put(adam_init(w), rep)

    def body(w, x, tgt):
        l_local, grads = jax.value_and_grad(shard_loss)(w, x, tgt)
        return (jax.lax.pmean(l_local, DP_AXIS),
                jax.tree.map(lambda g: jax.lax.pmean(g, DP_AXIS), grads))

    @jax.jit
    def step(w, opt, x, tgt):
        loss, grads = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(None, DP_AXIS), P(None, DP_AXIS)),
            out_specs=(P(), P()),
            check_vma=False,  # local-grads mode: explicit pmean owns it
        )(w, x, tgt)
        w, opt = adam_update(w, opt, grads, lr=1e-2)
        return w, opt, loss

    losses = []
    for _ in range(8):
        w, opt, loss = step(w, opt, x, target)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.9, losses


def test_ring_attention_shorter_kv_causal():
    """Tq != Tk per shard: the causal block-skip must NOT fire when
    Tk < Tq (a j > i block can still hold attended positions); result
    equals the oracle over the shorter K/V sequence."""
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.parallel.mesh import DP_AXIS

    mesh = make_mesh(8)
    q, _, _ = _qkv(seed=5)          # [B, 64, H, D]
    _, k, v = _qkv(seed=6)
    k, v = k[:, : T // 2], v[:, : T // 2]  # [B, 32, H, D] -> Tk=4/shard

    out = jax.jit(
        jax.shard_map(
            lambda q, k, v: ring.ring_attention_shard(
                q, k, v, axis_name=DP_AXIS, axis_size=8, causal=True
            ),
            mesh=mesh,
            in_specs=(P(None, DP_AXIS),) * 3,
            out_specs=P(None, DP_AXIS),
        )
    )(q, k, v)
    expect = ring.full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), expect, atol=2e-4)


def test_ring_attention_memory_is_blockwise():
    """The point of ring attention: per-device temp memory scales with the
    (T/P)^2 block, not the T^2 score matrix. Pinned via XLA's compiled
    memory analysis — the ring program's per-device temp must be a small
    fraction of single-device full attention's at the same global shape."""
    mesh = make_mesh(8)
    Tbig = 2048
    q = jnp.zeros((1, Tbig, 4, 16))

    ring_c = ring.make_ring_attention(mesh).lower(q, q, q).compile()
    full_c = jax.jit(ring.full_attention).lower(q, q, q).compile()
    ring_tmp = ring_c.memory_analysis().temp_size_in_bytes
    full_tmp = full_c.memory_analysis().temp_size_in_bytes
    # Full attention materializes B*H*T^2 fp32 scores (~67 MB here); the
    # ring tile is (T/8)^2 per (B, H). Require a 4x margin so the bound
    # is robust to fusion/layout choices, not a brittle exact number.
    assert full_tmp > 4 * ring_tmp, (ring_tmp, full_tmp)


def test_ring_attention_longer_kv_causal():
    """Tk > Tq per shard: on devices whose diagonal block is FULLY masked
    (i*Tk > qpos_max), the streaming state is briefly poisoned by
    exp(0)=1 tiles and must be wiped by the first real block's
    correction factor — numerically delicate, so pin it against the
    oracle (every row attends block j=0, so the wipe always happens)."""
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.parallel.mesh import DP_AXIS

    mesh = make_mesh(8)
    q, _, _ = _qkv(seed=7)
    _, k, v = _qkv(seed=8)
    q = q[:, : T // 2]  # [B, 32, H, D] -> Tq=4/shard, Tk=8/shard

    out = jax.jit(
        jax.shard_map(
            lambda q, k, v: ring.ring_attention_shard(
                q, k, v, axis_name=DP_AXIS, axis_size=8, causal=True
            ),
            mesh=mesh,
            in_specs=(P(None, DP_AXIS),) * 3,
            out_specs=P(None, DP_AXIS),
        )
    )(q, k, v)
    expect = ring.full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), expect, atol=2e-4)


def _zigzag_shard_out(q, k, v, *, nsub=None):
    """Run the zigzag-layout ring on zigzag-permuted inputs; return the
    output mapped back to natural order (global [B, T, H, D])."""
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.parallel.mesh import DP_AXIS

    mesh = make_mesh(8)
    perm = ring.zigzag_permutation(8, q.shape[1])
    inv = np.argsort(perm)
    out = jax.jit(
        jax.shard_map(
            lambda q, k, v: ring.ring_attention_shard(
                q, k, v, axis_name=DP_AXIS, axis_size=8, causal=True,
                layout="zigzag", **({} if nsub is None else {"nsub": nsub}),
            ),
            mesh=mesh,
            in_specs=(P(None, DP_AXIS),) * 3,
            out_specs=P(None, DP_AXIS),
        )
    )(q[:, perm], k[:, perm], v[:, perm])
    return np.asarray(out)[:, inv]


def test_ring_attention_zigzag_matches_oracle():
    """The balanced two-ended layout is EXACT: zigzag-permuted inputs
    through layout='zigzag' (default nsub=2 sub-tile skipping) reproduce
    the contiguous oracle after mapping back to natural order."""
    q, k, v = _qkv(seed=10)
    out = _zigzag_shard_out(q, k, v)
    expect = ring.full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, np.asarray(expect), atol=2e-4)


def test_ring_attention_zigzag_nsub1_matches_oracle():
    """nsub is a skip granularity, never a numerics knob: zigzag at tile
    granularity (nothing skips — every tile holds some unmasked work)
    equals the oracle too."""
    q, k, v = _qkv(seed=11)
    out = _zigzag_shard_out(q, k, v, nsub=1)
    expect = ring.full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, np.asarray(expect), atol=2e-4)


def test_ring_attention_zigzag_grads_match_oracle():
    """Gradients flow through the sub-tile conds and the travelling
    positions: d/dq,k,v of a zigzag ring loss == the oracle's grads."""
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.parallel.mesh import DP_AXIS

    mesh = make_mesh(8)
    q, k, v = _qkv(seed=12)
    perm = ring.zigzag_permutation(8, T)
    inv = np.argsort(perm)

    smapped = jax.shard_map(
        lambda q, k, v: ring.ring_attention_shard(
            q, k, v, axis_name=DP_AXIS, axis_size=8, causal=True,
            layout="zigzag",
        ),
        mesh=mesh,
        in_specs=(P(None, DP_AXIS),) * 3,
        out_specs=P(None, DP_AXIS),
        # All specs sharded (nothing to certify) and the causal zigzag
        # sub-tile conds defeat pre-vma JAX's checker — same rationale
        # as ring._make_wrapper.
        check_vma=False,
    )

    def loss_zz(q, k, v):
        return (smapped(q[:, perm], k[:, perm], v[:, perm]) ** 2).sum()

    def loss_oracle(q, k, v):
        return (ring.full_attention(q, k, v, causal=True) ** 2).sum()

    g_zz = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
    g_or = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
    for gr, go in zip(g_zz, g_or):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(go), atol=5e-3, rtol=1e-3
        )


def test_contiguous_nsub2_matches_oracle():
    """The generalized sub-tile loop is layout-independent: contiguous
    layout at nsub=2 (finer skip granularity) equals the oracle."""
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.parallel.mesh import DP_AXIS

    mesh = make_mesh(8)
    q, k, v = _qkv(seed=13)
    out = jax.jit(
        jax.shard_map(
            lambda q, k, v: ring.ring_attention_shard(
                q, k, v, axis_name=DP_AXIS, axis_size=8, causal=True, nsub=2
            ),
            mesh=mesh,
            in_specs=(P(None, DP_AXIS),) * 3,
            out_specs=P(None, DP_AXIS),
        )
    )(q, k, v)
    expect = ring.full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), expect, atol=2e-4)


def test_zigzag_permutation_matches_positions():
    """The staging gather and the in-shard position math are the same
    layout: slot t of the permuted sequence holds original position
    zigzag_positions(t // t_local)[t % t_local] — if these ever diverge,
    RoPE and the causal mask would disagree with the data movement."""
    P_, Tn = 8, 64
    perm = ring.zigzag_permutation(P_, Tn)
    t_local = Tn // P_
    for i in range(P_):
        np.testing.assert_array_equal(
            perm[i * t_local:(i + 1) * t_local],
            np.asarray(ring.zigzag_positions(i, P_, t_local)),
        )
    # A permutation (bijective), and device 0 holds both sequence ends.
    assert sorted(perm.tolist()) == list(range(Tn))
    assert perm[0] == 0 and perm[t_local - 1] == Tn - 1


def test_causal_work_profile_zigzag_is_balanced():
    """The analytic work model (same skip rule as the runtime lax.cond):
    contiguous leaves device P-1 computing a full tile on EVERY ring step
    (critical path = P tiles) while zigzag spreads the causal triangle —
    every device does the same total and the critical path halves."""
    P_ = 8
    cont = ring.causal_work_profile(P_, "contiguous")
    zz = ring.causal_work_profile(P_, "zigzag")
    # Per-device totals: contiguous spans 1..P tiles; zigzag is EXACTLY
    # balanced at (2P+1)/4 per device.
    assert cont.sum(axis=1).max() == P_ and cont.sum(axis=1).min() == 1
    np.testing.assert_allclose(zz.sum(axis=1), (2 * P_ + 1) / 4)
    # Lockstep critical path: sum over steps of the busiest device.
    crit_cont = cont.max(axis=0).sum()
    crit_zz = zz.max(axis=0).sum()
    assert crit_cont == P_
    assert crit_zz == (2 * P_ + 1) / 4
    assert crit_zz < 0.6 * crit_cont


def test_ring_attention_custom_striped_positions():
    """Explicit qpos/kpos: a strided layout (device i holds positions
    i, i+8, i+16, ...) must reproduce the oracle — pins that kpos
    genuinely travels the ring with its K/V block and that causal
    masking/skipping follow the travelling positions, not device order."""
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.parallel.mesh import DP_AXIS

    mesh = make_mesh(8)
    q, k, v = _qkv(seed=9)
    Pn = 8
    # Global permutation sending device i's rows to positions i + 8*ar.
    order = np.arange(T).reshape(T // Pn, Pn).T.reshape(-1)  # [0,8,..,1,9..]
    inv = np.argsort(order)

    def shard_fn(q, k, v):
        i = jax.lax.axis_index(DP_AXIS)
        pos = i + Pn * jnp.arange(T // Pn)
        return ring.ring_attention_shard(
            q, k, v, axis_name=DP_AXIS, axis_size=Pn, causal=True,
            qpos=pos, kpos=pos,
        )

    out = jax.jit(
        jax.shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(None, DP_AXIS),) * 3, out_specs=P(None, DP_AXIS),
        )
    )(q[:, order], k[:, order], v[:, order])
    expect = ring.full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out)[:, inv], np.asarray(expect), atol=2e-4
    )
