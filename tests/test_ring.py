"""Sequence/context parallelism vs the single-device oracle.

Both schemes (ring attention over ppermute, Ulysses over all_to_all) must
reproduce exact full attention — forward AND gradients, causal and not —
on the 8-device virtual mesh. The oracle is ``ring.full_attention`` on
the unsharded arrays (itself pinned against a hand-rolled softmax here).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.parallel import ring
from ddl_tpu.parallel.mesh import make_mesh

B, T, H, D = 2, 64, 8, 16


def _qkv(seed: int = 0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape, dtype=jnp.float32) for k in ks)


def test_full_attention_matches_manual_softmax():
    q, k, v = _qkv()
    out = ring.full_attention(q, k, v)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    expect = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(out, expect, atol=1e-5)


def test_full_attention_shard_offsets():
    """q_offset/k_offset make causal masking correct on sequence SHARDS:
    rows computed from a q-shard against the full K/V with the shard's
    absolute offset equal the corresponding rows of the unsharded
    output."""
    q, k, v = _qkv(seed=4)
    full = ring.full_attention(q, k, v, causal=True)
    t0 = T // 2
    shard = ring.full_attention(
        q[:, t0:], k, v, causal=True, q_offset=t0, k_offset=0
    )
    np.testing.assert_allclose(
        np.asarray(shard), np.asarray(full[:, t0:]), atol=1e-5
    )


def test_full_attention_causal_masks_future():
    q, k, v = _qkv()
    out = ring.full_attention(q, k, v, causal=True)
    # Row t of the causal output only sees k/v[<=t]: recompute row T//2
    # from the truncated sequence.
    t = T // 2
    trunc = ring.full_attention(
        q[:, t : t + 1], k[:, : t + 1], v[:, : t + 1]
    )
    np.testing.assert_allclose(out[:, t], trunc[:, 0], atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_oracle(causal):
    mesh = make_mesh(8)
    q, k, v = _qkv()
    out = ring.make_ring_attention(mesh, causal=causal)(q, k, v)
    expect = ring.full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), expect, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_oracle(causal):
    mesh = make_mesh(8)
    q, k, v = _qkv(seed=1)
    out = ring.make_ulysses_attention(mesh, causal=causal)(q, k, v)
    expect = ring.full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), expect, atol=2e-4)


@pytest.mark.parametrize("scheme", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sequence_parallel_grads_match_oracle(scheme, causal):
    mesh = make_mesh(8)
    q, k, v = _qkv(seed=2)
    make = (
        ring.make_ring_attention if scheme == "ring"
        else ring.make_ulysses_attention
    )
    sp_fn = make(mesh, causal=causal)

    def loss_sp(q, k, v):
        return (sp_fn(q, k, v) ** 2).sum()

    def loss_oracle(q, k, v):
        return (ring.full_attention(q, k, v, causal=causal) ** 2).sum()

    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    g_oracle = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
    for gr, go in zip(g_sp, g_oracle):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(go), atol=5e-3, rtol=1e-3
        )


def test_ulysses_rejects_indivisible_heads():
    mesh = make_mesh(8)
    q = k = v = jnp.zeros((B, T, 4, D))  # 4 heads on 8 devices
    with pytest.raises(ValueError, match="num_heads"):
        ring.make_ulysses_attention(mesh)(q, k, v)


def test_ring_attention_bf16_inputs_stay_bf16():
    """State is fp32 internally; output dtype follows q (the MXU path)."""
    mesh = make_mesh(8)
    q, k, v = (a.astype(jnp.bfloat16) for a in _qkv(seed=3))
    out = ring.make_ring_attention(mesh)(q, k, v)
    assert out.dtype == jnp.bfloat16
    expect = ring.full_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), expect, atol=5e-2, rtol=5e-2
    )
