"""Goodput attribution + streaming anomaly detection (ISSUE 11).

The acceptance pins:

- **Attribution identity**: per-span/per-tick phase times sum to the
  observed wall time, on the trainer path (compute == the StepTimer's
  own total EXACTLY; guard-skip share splits losslessly) and the serve
  path (tick residual lands in host/idle, nothing on the floor).
- **Off path unchanged**: no registry -> no goodput tracker, no
  goodput gauges; warmup attributes nothing.
- **Deterministic anomalies**: the seeded stall@RID injection and the
  seeded bulk-burst scenario each fire their anomaly at IDENTICAL
  detector ticks across two fresh runs — the host-state signals are
  deterministic functions of the tick clock.
"""

from __future__ import annotations

import json
import math
import urllib.request

import pytest

from ddl_tpu.obs import MetricRegistry, Tracer
from ddl_tpu.obs.anomaly import (
    AnomalyDetector,
    AnomalyRule,
    parse_anomaly_rules,
)
from ddl_tpu.obs.export import MetricsExporter
from ddl_tpu.obs.goodput import (
    GOODPUT_PHASES,
    SERVE_PHASES,
    TRAIN_PHASES,
    GoodputTracker,
    goodput_summary,
)
from ddl_tpu.data.lm import synthesize_mixed_traffic, synthesize_prompts
from ddl_tpu.models.transformer import TINY_SPEC

SPEC = TINY_SPEC


def _phase_gauges(reg):
    g = reg.gauge("time_in_seconds")
    return {ls["phase"]: g.value(**ls) for ls in g.label_sets()}


# -- GoodputTracker unit ------------------------------------------------------


def test_goodput_tracker_identity_and_validation():
    """Pure unit pin: adds and tick residuals always sum back to the
    observed total; unknown phases/kinds and a missing registry are
    loud errors; the gauges equal the tracker state after publish."""
    reg = MetricRegistry()
    with pytest.raises(ValueError, match="kind"):
        GoodputTracker(reg, "router")
    with pytest.raises(ValueError, match="registry"):
        GoodputTracker(None, "serve")
    gp = GoodputTracker(reg, "serve")
    with pytest.raises(ValueError, match="unknown serve phase"):
        gp.add("compute", 1.0)  # a train phase on a serve tracker
    assert set(gp.phases) == set(SERVE_PHASES)
    assert set(GoodputTracker(reg, "train").phases) == set(TRAIN_PHASES)

    # A working tick: sub-brackets + residual == tick wall time.
    gp.begin_tick()
    gp.add("prefill", 0.25)
    gp.add("decode", 0.5)
    gp.end_tick()
    # An idle tick: the whole residual files under idle.
    gp.begin_tick()
    gp.end_tick()
    # A bookkeeping-only tick (work=False): residual is idle, the shed
    # bracket still counts.
    gp.begin_tick()
    gp.add("shed", 0.01, work=False)
    gp.end_tick()
    assert math.isclose(gp.total_s, gp.observed_s, rel_tol=1e-9)
    assert gp.phases["prefill"] == 0.25 and gp.phases["decode"] == 0.5
    assert gp.phases["idle"] > 0.0 and gp.phases["shed"] == 0.01
    assert gp.goodput_s == gp.phases["prefill"] + gp.phases["decode"]
    assert GOODPUT_PHASES["serve"] == ("prefill", "decode")
    gauges = _phase_gauges(reg)
    assert gauges == gp.phases
    assert reg.gauge("time_observed_seconds").value() == gp.observed_s
    assert reg.gauge("goodput_fraction").value() == gp.goodput_fraction
    with pytest.raises(RuntimeError, match="begin_tick"):
        gp.end_tick()


# -- AnomalyDetector unit -----------------------------------------------------


def test_anomaly_rule_validation_and_grammar():
    with pytest.raises(ValueError, match="window"):
        AnomalyRule(signal="x", window=1)
    with pytest.raises(ValueError, match="min_history"):
        AnomalyRule(signal="x", window=4, min_history=5)
    with pytest.raises(ValueError, match="threshold"):
        AnomalyRule(signal="x", threshold=0)
    with pytest.raises(ValueError, match="direction"):
        AnomalyRule(signal="x", direction="up")
    with pytest.raises(ValueError, match="min_scale"):
        AnomalyRule(signal="x", min_scale=0)
    rules = parse_anomaly_rules(
        "itl:window=16,min=4,threshold=8,direction=high,scale=0.001;"
        "pages_free:direction=low"
    )
    assert rules[0] == AnomalyRule(signal="itl", window=16, min_history=4,
                                   threshold=8.0, direction="high",
                                   min_scale=0.001)
    assert rules[1].signal == "pages_free"
    assert rules[1].direction == "low"
    with pytest.raises(ValueError, match="no rules"):
        parse_anomaly_rules(" ; ")
    with pytest.raises(ValueError, match="duplicate"):
        parse_anomaly_rules("a;a")
    with pytest.raises(ValueError, match="unknown key"):
        parse_anomaly_rules("a:objective=0.9")
    with pytest.raises(ValueError, match="duplicate anomaly signal"):
        AnomalyDetector([AnomalyRule(signal="a"), AnomalyRule(signal="a")],
                        MetricRegistry())
    with pytest.raises(ValueError, match="MetricRegistry"):
        AnomalyDetector([AnomalyRule(signal="a")], None)


def test_anomaly_detector_median_mad_edge_trigger():
    """The detection math, hand-checkable: a flat integer baseline has
    MAD 0 (min_scale floors the scale, so the first deviation scores
    decisively), a spike is scored BEFORE it joins the baseline, entry
    is edge-triggered (a sustained excursion counts once), direction
    filters the tail, and every emission surface agrees (counter,
    last-tick gauge, fired_ticks, trace event)."""
    reg, tr = MetricRegistry(), Tracer()
    det = AnomalyDetector(
        [AnomalyRule(signal="q", window=8, min_history=4, threshold=6,
                     direction="high"),
         AnomalyRule(signal="cap", window=8, min_history=4, threshold=6,
                     direction="low")],
        reg, tracer=tr,
    )
    # Ticks 1-4: flat baselines build; nothing can fire (cold history).
    for _ in range(4):
        assert det.tick({"q": 2, "cap": 10}) == []
    assert det.baseline("q") == (2.0, 0.0)
    # Tick 5: q spikes high -> fires; cap spikes HIGH -> direction=low
    # stays silent.
    assert det.tick({"q": 9, "cap": 99}) == ["q"]
    # Tick 6: both excursions sustain -> edge-trigger: no new entry
    # for q; cap drops low -> its first entry.
    assert det.tick({"q": 9, "cap": 0}) == ["cap"]
    # Tick 7: recovery clears the latch...
    assert det.tick({"q": 2, "cap": 10}) == []
    # ...tick 8: a fresh excursion is a NEW entry.
    assert det.tick({"q": 9}) == ["q"]
    assert det.alerts("q") == 2 and det.alerts("cap") == 1
    assert det.fired_ticks("q") == [5, 8]
    assert det.fired_ticks("cap") == [6]
    assert reg.counter("anomaly_total").value(signal="q") == 2
    assert reg.gauge("anomaly_last_tick").value(signal="q") == 8
    events = [r for r in tr.records if r["name"] == "anomaly"]
    assert [e["attrs"]["tick"] for e in events
            if e["attrs"]["signal"] == "q"] == [5, 8]
    ev = events[0]["attrs"]
    assert ev["value"] == 9.0 and ev["median"] == 2.0 and ev["mad"] == 0.0
    assert ev["z"] > 6
    # A noisy baseline scores through 1.4826*MAD: [1,2,3,4] has
    # median 2.5, MAD 1.0 -> z(9) = 6.5/1.4826 ~ 4.4 < 6: no fire.
    det2 = AnomalyDetector(
        [AnomalyRule(signal="s", window=8, min_history=4, threshold=6,
                     direction="high")], MetricRegistry(),
    )
    for v in (1, 2, 3, 4):
        det2.tick({"s": v})
    assert det2.tick({"s": 9}) == []
    assert det2.baseline("s") == (3.0, 1.0)  # 9 joined after scoring
    with pytest.raises(KeyError, match="no anomaly rule"):
        det2.fired_ticks("nope")


# -- serve path: tick identity + off path ------------------------------------


def test_serve_tick_identity_prefix_and_off_path():
    """THE serve identity pin: a live run's phase times sum to the
    observed tick wall time; prefill/decode come from the SAME
    StepTimer brackets the histograms observe; the prefix-copy bracket
    lands under prefix_copy; warmup attributes NOTHING; and without a
    registry there is no tracker at all (off path)."""
    from ddl_tpu.data.lm import synthesize_shared_prefix_prompts
    from ddl_tpu.serve import InferenceEngine, Request, Scheduler, ServeConfig

    prompts = synthesize_shared_prefix_prompts(
        n_families=2, per_family=2, prefix_len=6, tail_min=2, tail_max=4,
        vocab=SPEC.vocab, seed=3,
    )
    reqs = [Request(id=i, prompt=p, max_new_tokens=4, arrival=i)
            for i, p in enumerate(prompts)]
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=2, capacity=32,
                                      prefix_slots=2))
    reg = MetricRegistry()
    sched = Scheduler(eng, registry=reg)
    assert sched.goodput is not None
    sched.warmup(reqs)
    assert sched.goodput.observed_s == 0.0, "warmup must attribute nothing"
    done, stats = sched.run(reqs)
    gp = sched.goodput
    assert math.isclose(gp.total_s, gp.observed_s, rel_tol=1e-9)
    assert gp.phases["prefill"] > 0 and gp.phases["decode"] > 0
    assert gp.phases["prefix_copy"] > 0  # the staggered families hit
    # The attribution reuses the StepTimer brackets EXACTLY: the
    # prefill/decode phases are the histogram sums (same floats,
    # accumulated in the same order).
    assert gp.phases["prefill"] == \
        sum(reg.histogram("serve_prefill_seconds").values())
    assert gp.phases["decode"] == \
        sum(reg.histogram("serve_decode_step_seconds").values())
    gauges = _phase_gauges(reg)
    assert gauges == gp.phases
    assert reg.gauge("goodput_fraction").value() == gp.goodput_fraction
    assert 0.0 < gp.goodput_fraction <= 1.0

    # Off path: no registry -> no tracker, and the registry-less run
    # publishes no goodput names anywhere.
    eng2 = InferenceEngine(ServeConfig(spec=SPEC, slots=2, capacity=32))
    sched2 = Scheduler(eng2)
    assert sched2.goodput is None
    sched2.run([Request(id=0, prompt=prompts[0], max_new_tokens=2)])


def test_anomaly_registry_validation_scheduler_and_router():
    from ddl_tpu.serve import InferenceEngine, Scheduler, ServeConfig
    from ddl_tpu.serve.router import Router, RouterConfig

    det = AnomalyDetector([AnomalyRule(signal="itl")], MetricRegistry())
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=1, capacity=16))
    with pytest.raises(ValueError, match="different registry"):
        Scheduler(eng, registry=MetricRegistry(), anomaly_detector=det)
    # attach_registry enforces the same invariant: swapping the
    # registry under a bound detector/monitor would strand its
    # metrics (and unbind the anomaly feed's inputs).
    reg2 = MetricRegistry()
    det2 = AnomalyDetector([AnomalyRule(signal="itl")], reg2)
    sched = Scheduler(eng, registry=reg2, anomaly_detector=det2)
    with pytest.raises(ValueError, match="strand"):
        sched.attach_registry(MetricRegistry())
    with pytest.raises(ValueError, match="strand"):
        sched.attach_registry(None)
    sched.attach_registry(reg2)  # the SAME registry re-attaches fine
    with pytest.raises(ValueError, match="different registry"):
        Router(RouterConfig(serve=ServeConfig(spec=SPEC, slots=1,
                                              capacity=16), replicas=1),
               registry=MetricRegistry(), anomaly_detector=det)
    with pytest.raises(ValueError, match="registry"):
        Router(RouterConfig(serve=ServeConfig(spec=SPEC, slots=1,
                                              capacity=16), replicas=1),
               anomaly_detector=det)


# -- the deterministic anomaly scenarios --------------------------------------


def _stall_run():
    """One seeded stall run: slots=2, four healthy requests decoding in
    two waves, one stall@9-injected request whose TTFT deadline bounds
    the run. The active_slots signal drops to 0 at every wave
    completion tick — a deterministic function of the token schedule,
    scored against a flat baseline of 2s."""
    from ddl_tpu.resilience.faults import FaultInjector, parse_fault
    from ddl_tpu.serve import InferenceEngine, Request, Scheduler, ServeConfig

    prompts = synthesize_prompts(num=4, min_len=4, max_len=8,
                                 vocab=SPEC.vocab, seed=7)
    reqs = [Request(id=i, prompt=p, max_new_tokens=4)
            for i, p in enumerate(prompts)]
    reqs.append(Request(id=9, prompt=prompts[0], max_new_tokens=4,
                        ttft_deadline_s=0.15))
    inj = FaultInjector(parse_fault("stall@9"))
    reg, tr = MetricRegistry(), Tracer()
    det = AnomalyDetector(
        [AnomalyRule(signal="active_slots", window=8, min_history=2,
                     threshold=6, direction="low")], reg, tracer=tr)
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=2, capacity=32))
    sched = Scheduler(eng, registry=reg, tracer=tr, injector=inj,
                      anomaly_detector=det)
    done, _ = sched.run(reqs)
    return det, done


def test_stall_injection_anomaly_fires_at_identical_ticks():
    """THE stall determinism pin: the stall@9 scenario fires the
    active_slots anomaly, every firing happens BEFORE wall-clock
    behavior (the deadline spin) can perturb the tick count, and two
    fresh runs fire at IDENTICAL detector ticks."""
    det1, done1 = _stall_run()
    assert done1[9].status == "deadline_exceeded"  # the stall was real
    assert det1.alerts("active_slots") >= 1
    assert det1.fired_ticks("active_slots")
    det2, done2 = _stall_run()
    assert det2.fired_ticks("active_slots") == \
        det1.fired_ticks("active_slots")
    assert det2.alerts("active_slots") == det1.alerts("active_slots")
    assert [done2[i].tokens for i in sorted(done2)] == \
        [done1[i].tokens for i in sorted(done1)]


def _burst_anomaly_run():
    """The ISSUE-10 seeded bulk-burst scenario, scored by the router's
    backlog anomaly signal instead of (only) the SLO monitor."""
    from ddl_tpu.serve import ServeConfig
    from ddl_tpu.serve.router import ClassSpec, Router, RouterConfig

    traffic = synthesize_mixed_traffic(
        classes={
            "chat": dict(rate=0.3, prompt_min=4, prompt_max=8,
                         max_new_tokens=2),
            "bulk": dict(rate=0.4, prompt_min=4, prompt_max=8,
                         max_new_tokens=2),
        },
        horizon=16, vocab=SPEC.vocab, seed=0,
        burst=(4, 6, 6.0, "bulk"), max_requests=16,
    )
    reg, tr = MetricRegistry(), Tracer()
    det = AnomalyDetector(
        [AnomalyRule(signal="backlog", window=8, min_history=3,
                     threshold=6, direction="high"),
         AnomalyRule(signal="shed_rate", window=8, min_history=3,
                     threshold=6, direction="high")], reg, tracer=tr)
    cfg = RouterConfig(
        serve=ServeConfig(spec=SPEC, slots=1, capacity=64),
        replicas=1,
        classes=(ClassSpec("chat", priority=0),
                 ClassSpec("bulk", priority=1, shed_margin=1)),
        shed_threshold=2,
    )
    router = Router(cfg, registry=reg, tracer=tr, anomaly_detector=det)
    router.run(traffic)
    return det, tr


def test_bulk_burst_anomaly_fires_at_identical_ticks():
    """THE burst determinism pin: the seeded bulk burst drives the
    fleet backlog over its rolling baseline — the anomaly fires, lands
    in the trace, and two fresh runs (fresh router, registry,
    detector) fire at IDENTICAL detector ticks."""
    det1, tr1 = _burst_anomaly_run()
    assert det1.alerts("backlog") >= 1
    assert det1.fired_ticks("backlog")
    assert any(r["name"] == "anomaly"
               and r["attrs"]["signal"] == "backlog"
               for r in tr1.records)
    det2, _ = _burst_anomaly_run()
    for sig in ("backlog", "shed_rate"):
        assert det2.fired_ticks(sig) == det1.fired_ticks(sig)
        assert det2.alerts(sig) == det1.alerts(sig)


# -- trainer path -------------------------------------------------------------


def test_trainer_goodput_identity_and_anomaly_feed(tmp_path):
    """THE trainer identity pin: compute phase == the trainer's own
    train_time_s EXACTLY (same floats, same order), every phase the run
    exercised is nonzero, phases sum to the observed total, and the
    anomaly detector is scored once per span."""
    from ddl_tpu.data.lm import synthesize_copy
    from ddl_tpu.strategies.seq import SeqConfig, SeqTrainer

    reg = MetricRegistry()
    det = AnomalyDetector(
        [AnomalyRule(signal="step_time", min_history=2, threshold=50,
                     direction="high")], reg)
    ds = synthesize_copy(num_train=64, num_test=16, seq_len=16, vocab=32,
                         seed=0)
    cfg = SeqConfig(epochs=1, batch_size=16, eval_every=2, seed=0,
                    num_workers=1, scheme="full")
    trainer = SeqTrainer(cfg, ds)
    res = trainer.train(log=lambda s: None, metrics=reg,
                        checkpoint_dir=str(tmp_path),
                        anomaly_detector=det)
    gauges = _phase_gauges(reg)
    observed = reg.gauge("time_observed_seconds").value()
    assert math.isclose(sum(gauges.values()), observed, rel_tol=1e-9)
    assert gauges["compute"] == res.train_time_s  # EXACT, same floats
    for phase in ("staging", "compile", "eval", "checkpoint_io"):
        assert gauges[phase] > 0, phase
    assert gauges["stall"] == 0.0  # nothing skipped
    assert reg.gauge("goodput_fraction").value() == \
        gauges["compute"] / observed
    # One detector tick per dispatched span: eval_every=2 over 4
    # batches -> spans [0], [1..2], [3].
    assert det.ticks == 3

    # A detector on a foreign registry is a loud error.
    det2 = AnomalyDetector([AnomalyRule(signal="mfu")], MetricRegistry())
    with pytest.raises(ValueError, match="registry"):
        SeqTrainer(cfg, ds).train(log=lambda s: None, metrics=reg,
                                  anomaly_detector=det2)


def test_trainer_guard_skip_stall_attribution(tmp_path):
    """A guarded span with injected NaN steps re-files the skipped
    share as stall — and the split is LOSSLESS: compute + stall still
    equal the trainer's own span total exactly."""
    from ddl_tpu.data.lm import synthesize_copy
    from ddl_tpu.resilience.faults import FaultInjector, parse_fault
    from ddl_tpu.strategies.seq import SeqConfig, SeqTrainer

    reg = MetricRegistry()
    ds = synthesize_copy(num_train=32, num_test=16, seq_len=16, vocab=32,
                         seed=0)
    cfg = SeqConfig(epochs=1, batch_size=16, eval_every=0, seed=0,
                    num_workers=1, scheme="full")
    trainer = SeqTrainer(cfg, ds)
    res = trainer.train(log=lambda s: None, metrics=reg,
                        checkpoint_dir=str(tmp_path), guard=True,
                        fault_injector=FaultInjector(
                            parse_fault("nan_grads@0")))
    assert res.skipped_steps >= 1
    gauges = _phase_gauges(reg)
    assert gauges["stall"] > 0.0
    assert gauges["compute"] + gauges["stall"] == \
        pytest.approx(res.train_time_s, rel=1e-12)
    assert math.isclose(
        sum(gauges.values()),
        reg.gauge("time_observed_seconds").value(), rel_tol=1e-9,
    )


# -- /healthz digest ----------------------------------------------------------


def test_healthz_goodput_summary_live_and_unit():
    """goodput_summary reads NON-creatingly (an empty registry stays
    empty) and the /healthz endpoint surfaces fraction + last anomaly
    tick once a detector fired."""
    reg = MetricRegistry()
    assert goodput_summary(reg) == {}
    assert not [m.name for m in reg.metrics()], \
        "summary of an empty registry must not create metrics"
    gp = GoodputTracker(reg, "serve")
    gp.begin_tick()
    gp.add("decode", 0.05)
    gp.end_tick()
    det = AnomalyDetector(
        [AnomalyRule(signal="q", min_history=2, threshold=6,
                     direction="high")], reg)
    for v in (1, 1, 1, 9):
        det.tick({"q": v})
    summary = goodput_summary(reg)
    assert summary["goodput_fraction"] == gp.goodput_fraction
    assert summary["last_anomaly_tick"] == det.fired_ticks("q")[0]
    assert summary["anomalies_total"] == 1
    with MetricsExporter(reg, 0) as exp:
        health = json.loads(urllib.request.urlopen(
            exp.url("/healthz")
        ).read())
    assert health["status"] == "ok"
    assert health["goodput_fraction"] == gp.goodput_fraction
    assert health["last_anomaly_tick"] == 4
    assert health["anomalies_total"] == 1
