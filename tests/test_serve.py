"""Serving subsystem (ddl_tpu/serve/, ops/kv_cache.py,
transformer.apply_lm_cached, checkpoint.load_params).

The oracle chain extends training's: full-forward ``apply_lm`` is the
reference numerics, and incremental KV-cache decode must reproduce its
logits at every position — for tp=1 and tp=2 meshes — while the
continuous-batching scheduler must produce EXACTLY the tokens each
request would get decoded alone (sampling keys depend only on
(seed, request_id, token_index), never on batch composition).

Fast decode-parity smokes stay unmarked (the tier-1 gate); the long
sweeps (staggered-arrival batching grids, capacity-scale runs) are
``slow`` so tier-1 stays inside its wall budget on the 2-CPU container.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.data.lm import (
    synthesize_copy,
    synthesize_prompts,
    synthesize_shared_prefix_prompts,
)
from ddl_tpu.models import transformer
from ddl_tpu.models.transformer import TINY_SPEC
from ddl_tpu.ops import kv_cache
from ddl_tpu.ops.kv_cache import PAD_POS
from ddl_tpu.parallel import ring
from ddl_tpu.serve import (
    InferenceEngine,
    PrefixIndex,
    Request,
    Scheduler,
    ServeConfig,
)

SPEC = TINY_SPEC


def _oracle_attn():
    return functools.partial(ring.full_attention, causal=True)


def _params(seed=0):
    return transformer.init_lm_params(jax.random.PRNGKey(seed), SPEC)


def _empty_cache(b, c, dtype=jnp.float32):
    shape = (SPEC.num_layers, b, c, SPEC.num_heads, SPEC.head_dim)
    return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
            jnp.full((b, c), PAD_POS, jnp.int32))


# -- ops/kv_cache.py ---------------------------------------------------------


def test_kv_attend_matches_full_attention():
    """attend() against a cache whose rows hold positions 0..T-1 ==
    full_attention over the same q/k/v — same mask constant, same
    einsum, same fp32 softmax."""
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(s, (2, 12, 2, 8))
               for s in jax.random.split(key, 3))
    pos = jnp.broadcast_to(jnp.arange(12), (2, 12))
    got = kv_cache.attend(q, k, v, pos, pos)
    want = ring.full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6, rtol=1e-5)


def test_kv_attend_masks_pad_and_stale_rows():
    """PAD_POS rows are invisible whatever junk their k/v hold: attend
    over a cache with junk beyond the valid prefix == attend over the
    valid prefix alone."""
    key = jax.random.PRNGKey(1)
    q = jax.random.normal(key, (1, 3, 2, 8))
    k = jax.random.normal(jax.random.fold_in(key, 1), (1, 8, 2, 8))
    v = jax.random.normal(jax.random.fold_in(key, 2), (1, 8, 2, 8))
    qpos = jnp.asarray([[0, 1, 2]])
    kpos = jnp.where(jnp.arange(8) < 3, jnp.arange(8), PAD_POS)[None]
    got = kv_cache.attend(q, k, v, qpos, kpos)
    want = ring.full_attention(q, k[:, :3], v[:, :3], causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6, rtol=1e-5)


def test_kv_append_rows_wraps_as_a_ring():
    """append_rows at caller-wrapped indices overwrites the oldest rows —
    the ring-buffer contract (capacity 4, writes at positions 3..5 land
    in rows 3, 0, 1)."""
    cache = jnp.zeros((1, 4, 1, 2))
    new = jnp.arange(6, dtype=jnp.float32).reshape(1, 3, 1, 2) + 1
    rows = jnp.asarray([[3, 0, 1]])  # (3 + arange(3)) % 4
    out = np.asarray(kv_cache.append_rows(cache, new, rows))
    np.testing.assert_array_equal(out[0, 3, 0], [1, 2])
    np.testing.assert_array_equal(out[0, 0, 0], [3, 4])
    np.testing.assert_array_equal(out[0, 1, 0], [5, 6])
    assert (out[0, 2] == 0).all()  # untouched


# -- apply_lm_cached: decode parity ------------------------------------------


def test_incremental_decode_matches_full_forward():
    """THE serving pin: prefill + one-token decode steps reproduce the
    full-forward apply_lm logits at EVERY position, tight tolerance."""
    B, T, C = 2, 24, 32
    params = _params(1)
    ds = synthesize_copy(num_train=B, num_test=B, seq_len=T,
                         vocab=SPEC.vocab, seed=2)
    tokens = jnp.asarray(ds.tokens)
    full = transformer.apply_lm(params, tokens, SPEC, attn_fn=_oracle_attn())
    ck, cv, cpos = _empty_cache(B, C)
    n = 9  # deliberately not a power of two
    outs = []
    lg, ck, cv, cpos = transformer.apply_lm_cached(
        params, tokens[:, :n], ck, cv, cpos, SPEC,
        start=jnp.zeros((B,), jnp.int32),
    )
    outs.append(lg)
    for t in range(n, T):
        lg, ck, cv, cpos = transformer.apply_lm_cached(
            params, tokens[:, t:t + 1], ck, cv, cpos, SPEC,
            start=jnp.full((B,), t, jnp.int32),
        )
        outs.append(lg)
    inc = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(inc), np.asarray(full),
                               atol=2e-5, rtol=1e-4)


def test_rope_extrapolation_beyond_training_length():
    """RoPE is stateless in position: at offsets far past any training
    length (1e6+) the shard-consistency property still holds exactly,
    rotations stay norm-preserving, and prefill-vs-decode position
    handling agrees — apply_lm at a huge pos_offset == the cached path
    fed the same absolute positions (the decode-time extrapolation
    contract, ISSUE 2 satellite)."""
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 2, 8))
    big = 1_000_000
    full = transformer.rope(x, big + jnp.arange(16), 10000.0)
    shard = transformer.rope(x[:, 8:], big + 8 + jnp.arange(8), 10000.0)
    np.testing.assert_allclose(np.asarray(full[:, 8:]), np.asarray(shard),
                               atol=1e-6)
    # Norm preservation per rotated pair: no blowup at extreme angles.
    pairs = np.asarray(full).reshape(2, 16, 2, 4, 2)
    base = np.asarray(x).reshape(2, 16, 2, 4, 2)
    np.testing.assert_allclose(
        np.linalg.norm(pairs, axis=-1), np.linalg.norm(base, axis=-1),
        atol=1e-5, rtol=1e-5,
    )

    # Prefill-vs-decode at the offset: teacher-forced apply_lm with
    # pos_offset=big == prefill + decode steps whose positions override
    # carries the same absolute positions (cache rows stay 0-based —
    # rows and positions are decoupled exactly for this).
    B, T, C = 1, 12, 16
    params = _params(3)
    tokens = jnp.asarray(
        synthesize_copy(num_train=B, num_test=B, seq_len=T,
                        vocab=SPEC.vocab, seed=4).tokens
    )
    full = transformer.apply_lm(params, tokens, SPEC,
                                attn_fn=_oracle_attn(), pos_offset=big)
    ck, cv, cpos = _empty_cache(B, C)
    n = 7
    pos = big + jnp.arange(T, dtype=jnp.int32)
    outs = []
    lg, ck, cv, cpos = transformer.apply_lm_cached(
        params, tokens[:, :n], ck, cv, cpos, SPEC,
        start=jnp.zeros((B,), jnp.int32), positions=pos[None, :n],
    )
    outs.append(lg)
    for t in range(n, T):
        lg, ck, cv, cpos = transformer.apply_lm_cached(
            params, tokens[:, t:t + 1], ck, cv, cpos, SPEC,
            start=jnp.full((B,), t, jnp.int32),
            positions=pos[None, t:t + 1],
        )
        outs.append(lg)
    inc = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(inc), np.asarray(full),
                               atol=2e-5, rtol=1e-4)


# -- the engine on its mesh --------------------------------------------------


@pytest.mark.parametrize("tp", [1, 2])
def test_engine_decode_parity(tp):
    """The compiled (prefill, decode) pair reproduces full-forward
    apply_lm logits at every position — tp=1 and tp=2 serving meshes
    (acceptance pin). Greedy, so tokens are argmax-checkable too."""
    C = 32
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=2, capacity=C,
                                      tensor_parallel=tp))
    params = transformer.init_lm_params(jax.random.PRNGKey(ServeConfig().seed),
                                        SPEC)
    prompt = synthesize_prompts(num=1, min_len=11, max_len=11,
                                vocab=SPEC.vocab, seed=5)[0]
    p = len(prompt)
    tok, prefill_logits = eng.prefill(prompt, slot=1, request_id=7)
    seq = list(prompt) + [tok]
    logits_inc = [prefill_logits]
    last = np.zeros(2, np.int32)
    lengths = np.zeros(2, np.int32)
    ids = np.zeros(2, np.int32)
    active = np.zeros(2, bool)
    for step in range(6):
        last[1], lengths[1], ids[1], active[1] = seq[-1], len(seq) - 1, 7, True
        nxt, lg = eng.decode(last, lengths, ids, active)
        logits_inc.append(lg[1:2])
        seq.append(int(nxt[1]))
    inc = np.concatenate(logits_inc, axis=0)  # [p + 6, V]
    full = transformer.apply_lm(
        params, jnp.asarray(np.asarray(seq[:-1])[None]), SPEC,
        attn_fn=_oracle_attn(),
    )[0]
    np.testing.assert_allclose(inc, np.asarray(full), atol=2e-5, rtol=1e-4)
    # Greedy decode tokens are the full-forward argmaxes.
    np.testing.assert_array_equal(
        np.asarray(seq[p:]), np.argmax(np.asarray(full)[p - 1:], axis=-1)
    )


def test_continuous_batching_matches_isolated_decode():
    """Acceptance pin: staggered arrivals + slot churn (5 requests over
    2 slots) yield bit-identical tokens to each request decoded alone —
    greedy AND seeded temperature/top-k sampling."""
    prompts = synthesize_prompts(num=5, min_len=3, max_len=9,
                                 vocab=SPEC.vocab, seed=6)
    for kw in (dict(temperature=0.0),
               dict(temperature=0.8, top_k=8, seed=11)):
        cfg = ServeConfig(spec=SPEC, slots=2, capacity=32, **kw)
        eng = InferenceEngine(cfg)
        sched = Scheduler(eng)
        reqs = [Request(id=i, prompt=p, max_new_tokens=5, arrival=i % 3)
                for i, p in enumerate(prompts)]
        done, stats = sched.run(reqs)
        assert sorted(done) == list(range(5))
        assert stats.decode_tokens > 0 and stats.latency.p99_ms > 0
        for r in reqs:
            eng.reset()  # same engine (no recompile), fresh cache
            alone, _ = sched.run([Request(id=r.id, prompt=r.prompt,
                                          max_new_tokens=5)])
            assert alone[r.id].tokens == done[r.id].tokens, (kw, r.id)


def test_scheduler_slot_reuse_and_validation():
    """Slot eviction/reuse leaks nothing (more requests than slots, all
    complete with the right lengths); bad requests are rejected up
    front; eos stops a sequence early."""
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=1, capacity=16))
    sched = Scheduler(eng)
    prompts = synthesize_prompts(num=3, min_len=4, max_len=6,
                                 vocab=SPEC.vocab, seed=7)
    done, _ = sched.run([Request(id=i, prompt=p, max_new_tokens=4)
                         for i, p in enumerate(prompts)])
    assert all(len(done[i].tokens) == 4 for i in range(3))
    with pytest.raises(ValueError, match="capacity"):
        sched.run([Request(id=0, prompt=prompts[0], max_new_tokens=99)])
    with pytest.raises(ValueError, match="duplicate"):
        sched.run([Request(id=1, prompt=prompts[0], max_new_tokens=1),
                   Request(id=1, prompt=prompts[1], max_new_tokens=1)])
    # eos: greedy decode is deterministic — find the first greedy token
    # and declare it eos; the run must stop at 1 generated token.
    done, _ = sched.run([Request(id=5, prompt=prompts[0],
                                 max_new_tokens=4)])
    eos = done[5].tokens[0]
    stopped, _ = Scheduler(eng, eos_id=eos).run(
        [Request(id=6, prompt=prompts[0], max_new_tokens=4)]
    )
    assert stopped[6].tokens == [eos]


def test_scheduler_rejects_oversized_prompt_before_any_admit():
    """One prompt longer than the cache capacity among valid requests
    fails the WHOLE submit with a ValueError naming that request id —
    at validation time, before any slot prefills — never mid-run after
    other slots were admitted. The engine keeps no partial state: the
    same valid requests then serve normally on the same engine."""
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=2, capacity=16))
    sched = Scheduler(eng)
    valid = synthesize_prompts(num=2, min_len=4, max_len=6,
                               vocab=SPEC.vocab, seed=11)
    oversized = np.zeros(17, np.int32)  # 17 > capacity 16
    reqs = [
        Request(id=0, prompt=valid[0], max_new_tokens=2),
        Request(id=7, prompt=oversized, max_new_tokens=2),
        Request(id=2, prompt=valid[1], max_new_tokens=2),
    ]
    with pytest.raises(ValueError, match=r"request 7.*exceeds cache"):
        sched.run(reqs)
    # No partial admission happened: the valid pair still serves, and
    # its outputs equal a fresh engine's (nothing leaked into the cache).
    done, _ = sched.run([reqs[0], reqs[2]])
    assert sorted(done) == [0, 2]
    fresh = Scheduler(InferenceEngine(
        ServeConfig(spec=SPEC, slots=2, capacity=16)
    ))
    done2, _ = fresh.run([reqs[0], reqs[2]])
    assert {i: done[i].tokens for i in done} == \
        {i: done2[i].tokens for i in done2}


def test_params_only_checkpoint_load_from_zero1_tp(tmp_path):
    """ISSUE 2 satellite: a checkpoint written by SeqTrainer with
    --zero1 --tensor-parallel (the hybrid optimizer's save path) loads
    params-only into serving meshes (tp=1 AND tp=2 — re-sharding on
    load), and the served logits match full-forward apply_lm under the
    trained params."""
    from ddl_tpu.strategies.seq import SeqConfig, SeqTrainer
    from ddl_tpu.utils.checkpoint import load_params

    ds = synthesize_copy(num_train=32, num_test=8, seq_len=16,
                         vocab=SPEC.vocab, seed=8)
    ckdir = str(tmp_path / "ck")
    SeqTrainer(
        SeqConfig(epochs=1, batch_size=16, eval_every=0, num_workers=2,
                  data_parallel=2, tensor_parallel=2, zero1=True,
                  scheme="ring", spec=SPEC, seed=9),
        ds,
    ).train(log=lambda s: None, checkpoint_dir=ckdir)
    path = str(tmp_path / "ck" / "ckpt.npz")

    template = jax.eval_shape(
        lambda: transformer.init_lm_params(jax.random.PRNGKey(0), SPEC)
    )
    host, step, _ = load_params(path, template)
    assert step == 2  # the epoch-end save recorded its global batch
    prompt = synthesize_prompts(num=1, min_len=8, max_len=8,
                                vocab=SPEC.vocab, seed=10)[0]
    full = transformer.apply_lm(host, jnp.asarray(prompt[None]), SPEC,
                                attn_fn=_oracle_attn())[0]
    for tp in (1, 2):
        eng = InferenceEngine(ServeConfig(spec=SPEC, slots=1, capacity=16,
                                          tensor_parallel=tp))
        eng.load_params(path)
        _, logits = eng.prefill(prompt, slot=0, request_id=0)
        np.testing.assert_allclose(logits, np.asarray(full),
                                   atol=2e-5, rtol=1e-4, err_msg=f"tp={tp}")

    # The params-only contract: the same load works when optimizer state
    # is ABSENT entirely (a bare params export).
    from ddl_tpu.utils.checkpoint import save_checkpoint

    bare = str(tmp_path / "params_only.npz")
    save_checkpoint(bare, host)
    again, _, _ = load_params(bare, template)
    for a, b in zip(jax.tree.leaves(host), jax.tree.leaves(again)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_prompt_generator_contract():
    """synthesize_prompts: deterministic per seed, variable lengths in
    range, BOS-led, payload within vocab (ISSUE 2 satellite)."""
    a = synthesize_prompts(num=12, min_len=3, max_len=20, vocab=32, seed=3)
    b = synthesize_prompts(num=12, min_len=3, max_len=20, vocab=32, seed=3)
    c = synthesize_prompts(num=12, min_len=3, max_len=20, vocab=32, seed=4)
    assert len(a) == 12
    assert all(np.array_equal(x, y) for x, y in zip(a, b))
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))
    lens = {len(x) for x in a}
    assert lens <= set(range(3, 21)) and len(lens) > 1
    for x in a:
        assert x.dtype == np.int32 and x[0] == 0
        assert (x[1:] >= 1).all() and (x[1:] < 32).all()
    with pytest.raises(ValueError, match="min_len"):
        synthesize_prompts(min_len=5, max_len=4)


# -- prefix cache + chunked prefill (ISSUE 4) --------------------------------


def test_kv_copy_prefix_op():
    """ops.kv_cache.copy_prefix: rows [0, n) along the axis take src,
    the rest keep dst — for both the k/v layout ([L, 1, C, H, D],
    axis=2) and a flat [B, C] layout (axis=1)."""
    src = jnp.arange(24, dtype=jnp.float32).reshape(1, 1, 6, 2, 2) + 100
    dst = jnp.arange(24, dtype=jnp.float32).reshape(1, 1, 6, 2, 2)
    out = np.asarray(kv_cache.copy_prefix(dst, src, jnp.int32(4), axis=2))
    np.testing.assert_array_equal(out[0, 0, :4], np.asarray(src)[0, 0, :4])
    np.testing.assert_array_equal(out[0, 0, 4:], np.asarray(dst)[0, 0, 4:])
    flat_src = jnp.ones((2, 5))
    flat_dst = jnp.zeros((2, 5))
    out = np.asarray(kv_cache.copy_prefix(flat_dst, flat_src, jnp.int32(2)))
    np.testing.assert_array_equal(out[:, :2], 1.0)
    np.testing.assert_array_equal(out[:, 2:], 0.0)


def test_kv_copy_prefix_edge_n_zero_and_full_capacity():
    """ISSUE 7 satellite: the copy-range boundaries, exercised directly
    (previously only reached through the engine). n=0 copies NOTHING
    (dst bit-unchanged — an empty hit is a no-op by construction);
    n=capacity copies EVERYTHING (dst == src bitwise — a full-cache hit
    leaves no seam); both ends also hold for the traced-scalar form the
    compiled copy programs use."""
    key = jax.random.PRNGKey(20)
    src = jax.random.normal(key, (1, 1, 6, 2, 8))
    dst = jax.random.normal(jax.random.fold_in(key, 1), (1, 1, 6, 2, 8))
    out0 = np.asarray(kv_cache.copy_prefix(dst, src, jnp.int32(0), axis=2))
    np.testing.assert_array_equal(out0, np.asarray(dst))
    out_full = np.asarray(
        kv_cache.copy_prefix(dst, src, jnp.int32(6), axis=2)
    )
    np.testing.assert_array_equal(out_full, np.asarray(src))
    # Same answers under jit with a TRACED n — the compiled-program
    # form (one program covers every hit length, 0 and capacity
    # included).
    jitted = jax.jit(lambda d, s, n: kv_cache.copy_prefix(d, s, n, axis=2))
    np.testing.assert_array_equal(
        np.asarray(jitted(dst, src, jnp.int32(0))), np.asarray(dst)
    )
    np.testing.assert_array_equal(
        np.asarray(jitted(dst, src, jnp.int32(6))), np.asarray(src)
    )
    # n beyond the axis saturates at "everything" (mask arange < n).
    np.testing.assert_array_equal(
        np.asarray(jitted(dst, src, jnp.int32(99))), np.asarray(src)
    )


def test_kv_attend_all_pad_rows_is_finite_and_length_stable():
    """ISSUE 7 satellite: attend over a cache of ONLY PAD_POS rows (a
    fresh slot / fresh page pool) stays FINITE — the all-masked softmax
    degrades to uniform weights over junk it then multiplies by exactly
    representable values, never NaN/Inf — and adding more masked
    padding never changes a valid query's output BITWISE (masked rows
    contribute exactly 0), which is the property the paged page-count
    buckets stand on (ops.kv_cache.gather_pages and the paged ≡
    contiguous pin in tests/test_serve_paged.py)."""
    key = jax.random.PRNGKey(21)
    q = jax.random.normal(key, (2, 3, 2, 8))
    k = jax.random.normal(jax.random.fold_in(key, 1), (2, 16, 2, 8))
    v = jax.random.normal(jax.random.fold_in(key, 2), (2, 16, 2, 8))
    qpos = jnp.broadcast_to(jnp.arange(3), (2, 3))
    # All-PAD cache: nothing attendable, output must still be finite
    # (free slots and freshly admitted paged slots ride decode exactly
    # like this).
    all_pad = jnp.full((2, 16), PAD_POS)
    out = np.asarray(kv_cache.attend(q, k, v, qpos, all_pad))
    assert np.isfinite(out).all()
    # Length stability: valid rows + masked tail of DIFFERENT lengths
    # produce bitwise-identical outputs (the page-count bucket ladder's
    # correctness condition).
    kpos8 = jnp.where(jnp.arange(8) < 3, jnp.arange(8), PAD_POS)[None]
    kpos16 = jnp.where(jnp.arange(16) < 3, jnp.arange(16), PAD_POS)[None]
    a8 = np.asarray(kv_cache.attend(q[:1], k[:1, :8], v[:1, :8],
                                    qpos[:1], kpos8))
    a16 = np.asarray(kv_cache.attend(q[:1], k[:1], v[:1],
                                     qpos[:1], kpos16))
    np.testing.assert_array_equal(a8, a16)


@pytest.mark.parametrize("chunk", [8, 16])
def test_chunked_prefill_logits_exactly_equal_one_shot(chunk):
    """Acceptance pin: prefilling a prompt in fixed chunks (base
    offsets) produces logits EXACTLY equal — bitwise, not tolerance —
    to the one-shot prefill of the same prompt, partial final chunk
    included. Exactness is what lets chunking default to 'safe to turn
    on': the token stream cannot move."""
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=1, capacity=64))
    prompt = synthesize_prompts(num=1, min_len=21, max_len=21,
                                vocab=SPEC.vocab, seed=14)[0]
    tok_full, logits_full = eng.prefill(prompt, slot=0, request_id=3)
    eng.reset()
    got = []
    tok_last = None
    for base in range(0, len(prompt), chunk):
        tok_last, lg = eng.prefill(prompt[base:base + chunk], slot=0,
                                   request_id=3, base=base)
        got.append(lg)
    np.testing.assert_array_equal(np.concatenate(got, axis=0), logits_full)
    assert tok_last == tok_full  # same sampled element p


def test_prefix_copy_then_tail_prefill_matches_full_prefill():
    """The prefix-reuse device path: register prompt A's rows in the
    pool, admit prompt B (sharing A's first tokens) as copy + tail
    prefill — B's tail logits and first sampled token are EXACTLY the
    full-prefill values (copied rows are bit-identical to recomputed
    rows)."""
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=2, capacity=64,
                                      prefix_slots=1))
    fam = synthesize_shared_prefix_prompts(
        n_families=1, per_family=2, prefix_len=12, tail_min=4, tail_max=4,
        vocab=SPEC.vocab, seed=15,
    )
    a, b = fam[0], fam[1]
    eng.prefill(a, slot=0, request_id=0)
    assert eng.prefix_store(a, 0)
    entry, hit = eng.prefix.match(b)
    assert entry >= 0 and hit >= 12  # at least the family prefix
    hit = min(hit, len(b) - 1)
    # Reference: full prefill of b on a FRESH engine state.
    ref_eng = InferenceEngine(ServeConfig(spec=SPEC, slots=2, capacity=64))
    tok_ref, logits_ref = ref_eng.prefill(b, slot=1, request_id=7)
    # Reused path: copy the hit rows into slot 1, prefill only the tail.
    eng.prefix_fetch(entry, hit, 1)
    tok, tail_logits = eng.prefill(b[hit:], slot=1, request_id=7, base=hit)
    np.testing.assert_array_equal(tail_logits, logits_ref[hit:])
    assert tok == tok_ref
    eng.prefix_release(entry)


def test_prefix_pool_lru_eviction_honors_refcounts():
    """ISSUE 4 satellite pin, on the host index directly: a shared
    prefix with a live reader survives pool pressure (LRU skips pinned
    entries — a full pool of pinned entries SKIPS registration rather
    than evicting); releasing the last reader makes it evictable
    again."""
    idx = PrefixIndex(2)
    e0, s0 = idx.insert([0, 1, 2, 3])
    e1, s1 = idx.insert([0, 5, 6, 7])
    assert {s0, s1} == {0, 1} and len(idx) == 2
    idx.acquire(e0)  # a live request attends e0's rows
    idx.touch(e1)  # e1 is MRU, e0 strictly LRU — refcount must win
    # match() is PURE: it never refreshes LRU stamps (a sub-threshold
    # BOS-only match must not keep a dead entry recent).
    before = idx.entry(e0).last_used
    idx.match([0, 1, 2, 3, 4])
    assert idx.entry(e0).last_used == before
    got = idx.insert([0, 8, 8])  # pressure: must NOT evict pinned e0
    assert got is not None
    e2, _ = got
    assert idx.entry(e0).tokens == (0, 1, 2, 3)  # pinned e0 survives
    assert idx.evictions == 1  # e1 (LRU among ref-0) paid instead
    with pytest.raises(KeyError):
        idx.entry(e1)
    idx.acquire(e2)
    # Both residents pinned: registration is skipped, never an eviction.
    assert idx.insert([0, 9, 9]) is None
    assert idx.skipped_full == 1
    # Releasing the LAST reader frees e0 for the next insertion.
    idx.release(e0)
    got = idx.insert([0, 9, 9])
    assert got is not None
    with pytest.raises(KeyError):
        idx.entry(e0)
    # Matching follows the trie: deepest live coverage wins.
    eid, depth = idx.match([0, 9, 9, 1])
    assert eid == got[0] and depth == 3
    # Releasing an entry nobody holds is a bookkeeping bug, loudly.
    with pytest.raises(ValueError, match="no readers"):
        idx.release(eid)


@pytest.mark.parametrize("tp", [1, 2])
def test_prefix_cache_scheduler_determinism(tp):
    """THE ISSUE 4 acceptance pin: a staggered-arrival shared-prefix
    workload served with the prefix cache ON yields BIT-IDENTICAL
    per-request tokens to the cache-off scheduler run — tp=1 and tp=2 —
    while actually hitting (the stats prove reuse happened, so the pin
    is not vacuous)."""
    prompts = synthesize_shared_prefix_prompts(
        n_families=2, per_family=3, prefix_len=12, tail_min=2, tail_max=6,
        vocab=SPEC.vocab, seed=16,
    )
    reqs = [Request(id=i, prompt=p, max_new_tokens=6, arrival=i % 3)
            for i, p in enumerate(prompts)]
    off = Scheduler(InferenceEngine(ServeConfig(
        spec=SPEC, slots=2, capacity=64, tensor_parallel=tp,
    ))).run(reqs)[0]
    on_eng = InferenceEngine(ServeConfig(
        spec=SPEC, slots=2, capacity=64, tensor_parallel=tp, prefix_slots=2,
    ))
    on, stats = Scheduler(on_eng).run(reqs)
    assert stats.prefix_hits > 0 and stats.prefill_tokens_saved > 0
    assert stats.prefix_lookups == len(reqs)
    assert 0.0 < stats.prefix_hit_rate <= 1.0
    assert stats.ttft.steps == len(reqs) and stats.ttft.p95_ms > 0
    for r in reqs:
        assert on[r.id].tokens == off[r.id].tokens, (tp, r.id)


def test_chunked_prefill_scheduler_determinism_and_stats():
    """Chunked prefill + per-tick budget (and the prefix cache on top)
    cannot move any request's tokens — greedy AND seeded sampling —
    and the inter-token-latency distribution is populated (the metric
    chunking exists to bound)."""
    prompts = synthesize_shared_prefix_prompts(
        n_families=2, per_family=3, prefix_len=12, tail_min=2, tail_max=6,
        vocab=SPEC.vocab, seed=17,
    )
    reqs = [Request(id=i, prompt=p, max_new_tokens=5, arrival=i % 2)
            for i, p in enumerate(prompts)]
    for kw in (dict(temperature=0.0),
               dict(temperature=0.9, top_k=8, seed=12)):
        off = Scheduler(InferenceEngine(ServeConfig(
            spec=SPEC, slots=2, capacity=64, **kw,
        ))).run(reqs)[0]
        on, stats = Scheduler(InferenceEngine(ServeConfig(
            spec=SPEC, slots=2, capacity=64, prefill_chunk=8,
            prefill_budget=8, prefix_slots=2, **kw,
        ))).run(reqs)
        assert stats.itl.steps > 0
        for r in reqs:
            assert on[r.id].tokens == off[r.id].tokens, (kw, r.id)


def test_scheduler_allow_window_opt_in():
    """ISSUE 4 satellite: prompt + max_new_tokens beyond capacity is
    rejected at submit naming the request — the ring would silently
    wrap into sliding-window attention mid-generation — UNLESS the
    caller passes allow_window=True, in which case the run completes
    with the full token count (the window semantics are opt-in, tested
    here end to end: resident length is capped at capacity while
    absolute positions keep growing)."""
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=1, capacity=16))
    prompt = synthesize_prompts(num=1, min_len=6, max_len=6,
                                vocab=SPEC.vocab, seed=18)[0]
    with pytest.raises(ValueError, match=r"request 9.*capacity 16"):
        Scheduler(eng).run([Request(id=9, prompt=prompt,
                                    max_new_tokens=14)])
    done, _ = Scheduler(eng, allow_window=True).run(
        [Request(id=9, prompt=prompt, max_new_tokens=14)]
    )
    assert len(done[9].tokens) == 14  # 6 + 14 = 20 > 16: ring wrapped
    # Unchanged guard: the WINDOW escape hatch never admits a prompt
    # longer than the cache itself.
    with pytest.raises(ValueError, match=r"request 3.*exceeds cache"):
        Scheduler(eng, allow_window=True).run(
            [Request(id=3, prompt=np.zeros(17, np.int32),
                     max_new_tokens=1)]
        )


def test_engine_rejects_bad_prefix_and_chunk_configs():
    """Config validation fails fast with the fix in the message: odd
    chunk sizes, budgets without chunking, budgets below the chunk,
    negative pool widths."""
    for bad in (dict(prefill_chunk=12), dict(prefill_chunk=4),
                dict(prefill_budget=16), dict(prefix_slots=-1),
                dict(prefill_chunk=16, prefill_budget=8)):
        with pytest.raises(ValueError):
            InferenceEngine(ServeConfig(spec=SPEC, slots=1, capacity=32,
                                        **bad))


def test_scheduler_pressure_probe_matches_registry_gauges():
    """ISSUE 8 satellite: ``Scheduler.pressure()`` is pinned EQUAL to
    the per-tick registry gauges (occupied/active slots, queue depth,
    free pages, prefix-pool residency) after every tick of an
    externally-driven run — the router reads load through one probe,
    never private state — and the begin/submit/tick/collect form
    produces exactly ``run``'s completions (run IS that sequence)."""
    from ddl_tpu.obs import MetricRegistry

    cfg = ServeConfig(spec=SPEC, slots=2, capacity=32, page_size=8,
                      num_pages=8, prefix_slots=2)
    eng = InferenceEngine(cfg)
    reg = MetricRegistry()
    sched = Scheduler(eng, registry=reg)
    prompts = synthesize_prompts(num=4, min_len=4, max_len=7,
                                 vocab=SPEC.vocab, seed=21)
    reqs = [Request(id=i, prompt=p, max_new_tokens=3)
            for i, p in enumerate(prompts)]
    sched.begin()
    for r in reqs:
        sched.submit(r)
    pr = sched.pressure()
    assert pr.waiting_eligible == 4 and pr.occupied_slots == 0
    assert pr.pending_total == 4 and pr.outstanding == 4
    assert pr.pages_free == eng.num_pages
    ticks = 0
    while not sched.idle:
        sched.tick()
        ticks += 1
        pr = sched.pressure()
        assert pr.occupied_slots == reg.gauge("serve_occupied_slots").value()
        assert pr.active_slots == reg.gauge("serve_active_slots").value()
        assert pr.waiting_eligible == reg.gauge("serve_queue_depth").value()
        assert pr.pages_free == reg.gauge("serve_kv_pages_free").value()
        assert pr.prefix_entries == \
            reg.gauge("serve_prefix_pool_entries").value()
        assert pr.pages_available <= pr.pages_free
    done, stats = sched.collect()
    assert ticks > 0 and sorted(done) == [0, 1, 2, 3]
    assert stats.decode_tokens > 0
    # The probe is quiescent again, and run() on a fresh engine (same
    # machinery, one call) reproduces the driven run's tokens.
    assert sched.pressure().occupied_slots == 0
    fresh = Scheduler(InferenceEngine(cfg))
    done2, _ = fresh.run(reqs)
    assert {i: done[i].tokens for i in done} == \
        {i: done2[i].tokens for i in done2}
    # Lifecycle guards: tick/collect need an armed run; begin can't
    # stack; release() disarms an aborted run.
    with pytest.raises(RuntimeError, match="begin"):
        sched.tick()
    sched.begin()
    with pytest.raises(RuntimeError, match="already armed"):
        sched.begin()
    sched.release()
    sched.begin()
    sched.release()


# -- long sweeps (excluded from tier-1 via -m 'not slow') --------------------


@pytest.mark.slow
def test_continuous_batching_sweep_slow():
    """The wide grid: arrival patterns x sampling configs x slot widths,
    all pinned against isolated decode — the exhaustive version of the
    fast smoke above."""
    prompts = synthesize_prompts(num=8, min_len=3, max_len=14,
                                 vocab=SPEC.vocab, seed=12)
    for slots in (2, 3):
        for kw in (dict(temperature=0.0), dict(temperature=1.2, seed=5),
                   dict(temperature=0.6, top_k=4, seed=6)):
            eng = InferenceEngine(
                ServeConfig(spec=SPEC, slots=slots, capacity=64, **kw)
            )
            sched = Scheduler(eng)
            reqs = [Request(id=i, prompt=p, max_new_tokens=3 + i % 5,
                            arrival=(i * 2) % 5)
                    for i, p in enumerate(prompts)]
            done, _ = sched.run(reqs)
            for r in reqs:
                eng.reset()
                alone, _ = sched.run([Request(
                    id=r.id, prompt=r.prompt,
                    max_new_tokens=r.max_new_tokens,
                )])
                assert alone[r.id].tokens == done[r.id].tokens, (
                    slots, kw, r.id
                )


@pytest.mark.slow
def test_engine_tp2_long_generation_slow():
    """tp=2 decode far past the prompt (40 steps, capacity 64): logits
    stay pinned to full-forward at every generated position."""
    C = 64
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=1, capacity=C,
                                      tensor_parallel=2))
    params = transformer.init_lm_params(
        jax.random.PRNGKey(ServeConfig().seed), SPEC
    )
    prompt = synthesize_prompts(num=1, min_len=6, max_len=6,
                                vocab=SPEC.vocab, seed=13)[0]
    tok, _ = eng.prefill(prompt, slot=0, request_id=1)
    seq = list(prompt) + [tok]
    for _ in range(40):
        nxt, _ = eng.decode(
            np.asarray([seq[-1]], np.int32),
            np.asarray([len(seq) - 1], np.int32),
            np.asarray([1], np.int32), np.asarray([True]),
        )
        seq.append(int(nxt[0]))
    full = transformer.apply_lm(
        params, jnp.asarray(np.asarray(seq[:-1])[None]), SPEC,
        attn_fn=_oracle_attn(),
    )[0]
    np.testing.assert_array_equal(
        np.asarray(seq[len(prompt):]),
        np.argmax(np.asarray(full)[len(prompt) - 1:], axis=-1),
    )
