"""Async-strategy tests: deterministic seeded staleness schedule
(SURVEY.md §4d), single-worker async ≡ sequential training, and sharded
serve ≡ replicated serve under the same schedule.

Uses the narrow test model (conftest.SMALL_SPECS) — the strategy code is
model-agnostic; see test_sync_strategies.py docstring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.data import one_hot
from ddl_tpu.models import cnn
from ddl_tpu.ops import adam_init, adam_update
from ddl_tpu.parallel.collectives import unflatten_params
from ddl_tpu.parallel.mesh import make_mesh
from ddl_tpu.strategies.async_ps import (
    AsyncTrainer,
    _flat_spec,
    async_schedule,
    async_state_init,
    make_async_round,
)
from ddl_tpu.strategies.sync import resolve_layout
from ddl_tpu.train.config import TrainConfig

BS = 16
_W, _ROUNDS = 4, 3


def test_schedule_is_deterministic_permutations():
    s1 = async_schedule(42, 8, 20)
    s2 = async_schedule(42, 8, 20)
    np.testing.assert_array_equal(s1, s2)
    assert s1.shape == (20, 8)
    for row in s1:
        assert sorted(row.tolist()) == list(range(8))
    assert not np.array_equal(s1, async_schedule(43, 8, 20))


def _data(small_dataset, rounds, workers, shard_data):
    x = small_dataset.x_train
    y = one_hot(small_dataset.y_train)
    if shard_data:
        n = rounds * BS * workers
        xs = x[:n].reshape(workers, rounds, BS, -1).transpose(1, 0, 2, 3)
        ys = y[:n].reshape(workers, rounds, BS, -1).transpose(1, 0, 2, 3)
    else:
        n = rounds * BS
        xs = x[:n].reshape(rounds, BS, -1)
        ys = y[:n].reshape(rounds, BS, -1)
    return jnp.asarray(np.ascontiguousarray(xs)), jnp.asarray(np.ascontiguousarray(ys))


def _sizes(params):
    return {k: int(np.prod(v.shape)) if v.shape else 1 for k, v in params.items()}


def test_one_worker_async_is_sequential(small_dataset, small_params):
    """With W=1 the async PS degenerates to sequential training: push, apply,
    pull every batch — must match the plain Adam loop exactly."""
    cfg = TrainConfig(num_workers=1, keep_prob=1.0, batch_size=BS)
    mesh = make_mesh(1)
    params = small_params
    shapes = cnn.param_shapes(params)
    state = async_state_init(cfg, mesh, None, params)
    run = make_async_round(cfg, mesh, None, shapes)
    rounds = 4
    xs, ys = _data(small_dataset, rounds, 1, shard_data=True)
    rngs = jnp.stack([jax.random.PRNGKey(0)] * rounds)
    scheds = jnp.asarray(async_schedule(0, 1, rounds))
    state, ps_full, _ = run(state, xs, ys, rngs, scheds)

    opt = adam_init(params)
    p = params

    @jax.jit
    def step(p, opt, x, y):
        grads = jax.grad(cnn.loss_fn)(p, x, y, dropout_rng=None)
        return adam_update(p, opt, grads, lr=cfg.learning_rate)

    for r in range(rounds):
        p, opt = step(p, opt, xs[r, 0], ys[r, 0])
    from ddl_tpu.parallel.collectives import flatten_params

    oracle_flat = flatten_params(p, _flat_spec(None, shapes))
    assert float(jnp.max(jnp.abs(ps_full - oracle_flat))) < 1e-6


@pytest.fixture(scope="module")
def async_inputs(small_dataset, small_params):
    xs, ys = _data(small_dataset, _ROUNDS, _W, shard_data=True)
    rngs = jnp.stack(
        [jax.random.fold_in(jax.random.PRNGKey(1), r) for r in range(_ROUNDS)]
    )
    scheds = jnp.asarray(async_schedule(11, _W, _ROUNDS))
    return small_params, xs, ys, rngs, scheds


@pytest.fixture(scope="module")
def replicated_result(async_inputs):
    """One replicated-serve run, shared by every comparison below (the heavy
    round program compiles once per module). Returns (final_state_numpy,
    ps_flat_numpy, schedule)."""
    params, xs, ys, rngs, scheds = async_inputs
    mesh = make_mesh(_W)
    cfg = TrainConfig(num_workers=_W, keep_prob=1.0, batch_size=BS)
    st = async_state_init(cfg, mesh, None, params)
    run = make_async_round(cfg, mesh, None, cnn.param_shapes(params))
    st, ps_rep, _ = run(st, xs, ys, rngs, scheds)
    return jax.tree.map(np.asarray, st), np.asarray(ps_rep)


@pytest.mark.parametrize(
    "policy,num_ps",
    # num_ps=14 > _W devices: reference any-split topology, shards folded
    # round-robin onto the mesh (layout.fold_shards). lpt@_W covers the
    # most-unbalanced owner rows (largest overlap in the slice gather).
    [("block", 4), ("zigzag", 4), ("flat", 4), ("lpt", _W), ("block", 14)],
)
def test_sharded_serve_equals_replicated_serve(
    async_inputs, replicated_result, policy, num_ps
):
    """Under the same schedule, the all_to_all sharded serve must be
    numerically identical to the replicated serve — Adam is elementwise, so
    shard placement cannot change results."""
    params, xs, ys, rngs, scheds = async_inputs
    shapes = cnn.param_shapes(params)
    mesh = make_mesh(_W)
    cfg_sh = TrainConfig(
        num_workers=_W, num_ps=num_ps, layout=policy, keep_prob=1.0, batch_size=BS
    )
    layout = resolve_layout(cfg_sh, _W, _sizes(params))
    st_sh = async_state_init(cfg_sh, mesh, layout, params)
    run_sh = make_async_round(cfg_sh, mesh, layout, shapes)
    _, ps_sh, _ = run_sh(st_sh, xs, ys, rngs, scheds)

    _, ps_rep = replicated_result
    rep_params = unflatten_params(jnp.asarray(ps_rep), _flat_spec(None, shapes))
    sh_params = unflatten_params(ps_sh, _flat_spec(layout, shapes))
    for n in params:
        diff = float(jnp.max(jnp.abs(rep_params[n] - sh_params[n])))
        assert diff < 1e-6, f"{n}: {diff}"


def test_async_staleness_is_real(async_inputs, replicated_result):
    """The worker replicas hold distinct staleness snapshots: only the last
    scheduled worker has the newest params; the update counter advanced by
    W per round (reuses the replicated run — no extra compile)."""
    _, _, _, _, scheds = async_inputs
    st, ps_full = replicated_result
    assert int(st.t) == _W * _ROUNDS
    last = int(np.asarray(scheds)[-1, -1])
    np.testing.assert_allclose(st.workers[last], ps_full, atol=0)
    others = [w for w in range(_W) if w != last]
    assert any(
        np.max(np.abs(st.workers[w] - ps_full)) > 0 for w in others
    )


def test_async_trainer_end_to_end(small_dataset, small_params):
    """AsyncTrainer mechanics + convergence smoke on the narrow model
    (convergence oracle replacing the reference's eyeballed accuracy
    prints, SURVEY.md §4c)."""
    cfg = TrainConfig(
        num_workers=4,
        batch_size=64,
        keep_prob=1.0,
        eval_every=0,
        # 24 epochs, not 8: the convergence knee depends on the init
        # draw, and the random stream behind a fixed seed differs
        # across JAX generations (jax_threefry_partitionable default
        # flips) — 8-12 epochs plateau near 0.45 on the 0.4 line while
        # clearing 0.5 on newer JAX; 24 reaches 1.0 on both (measured).
        # Same robustness fix as the lm copy-task smoke (tests/test_lm.py).
        epochs=24,
        learning_rate=3e-3,
    )
    trainer = AsyncTrainer(cfg, small_dataset, init=small_params)
    result = trainer.train(log=lambda s: None)
    # 24 epochs x 8 rounds x 4 pushes = 768 per-push Adam updates at 3e-3
    # on the easy procedural set: must decisively beat chance (10%).
    assert result.final_accuracy > 0.5
    assert int(trainer.state.t) == 768


def test_per_worker_stale_replica_eval(small_dataset, small_params):
    """The reference's last unique observable (round-3 verdict missing #1):
    every async worker reports accuracy from its OWN stale replica
    (mnist_async/worker.py:71-75). Pins that (a) worker_history carries W
    accuracies per eval point, (b) the replicas genuinely DIVERGE
    mid-training (staleness is real: each worker refreshes at its own push
    point in the schedule), and (c) they converge — final per-worker
    accuracies agree with the authoritative PS accuracy."""
    W = 4
    cfg = TrainConfig(
        num_workers=W,
        batch_size=64,
        keep_prob=1.0,
        eval_every=2,
        epochs=6,
        learning_rate=3e-3,
    )
    trainer = AsyncTrainer(cfg, small_dataset, init=small_params)
    result = trainer.train(log=lambda s: None)

    assert result.worker_history, "async must surface per-worker accuracy"
    assert all(len(accs) == W for _, _, accs in result.worker_history)
    # Eval cadence matches the PS history rows.
    assert [(e, r) for e, r, _ in result.worker_history] == [
        (e, r) for e, r, _ in result.history
    ]

    # (b) Staleness divergence: the replica MATRIX has pairwise-distinct
    # rows after training (worker w's replica = PS params right after w's
    # last push — different push points => different params). Deterministic
    # under the seeded schedule, unlike accuracy ties.
    rows = np.asarray(
        jax.device_get(trainer.state.workers)
    ).reshape(W, -1)
    for i in range(W):
        for j in range(i + 1, W):
            assert not np.array_equal(rows[i], rows[j]), (i, j)

    # (c) Convergence: every worker's final stale accuracy is within a few
    # points of the PS accuracy (all replicas are <= W-1 pushes stale).
    _, _, final_accs = result.worker_history[-1]
    for a in final_accs:
        assert abs(a - result.history[-1][2]) < 0.05

    # Sync trainers don't have worker streams.
    from ddl_tpu.train.trainer import SingleChipTrainer

    r1 = SingleChipTrainer(
        TrainConfig(epochs=1, batch_size=64, eval_every=0, keep_prob=1.0),
        small_dataset, init=small_params,
    ).train(log=lambda s: None)
    assert r1.worker_history is None
