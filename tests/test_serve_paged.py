"""Paged KV cache (ISSUE 7): block-table attention, zero-copy
refcounted prefix sharing, pooled serve capacity.

The oracle chain: the CONTIGUOUS slot-major engine (PR 2-6, retained
behind ``page_size=0``) is the bit-exactness reference — the paged
engine must reproduce its tokens AND per-step logits bitwise through
the whole serving stack (staggered arrivals, prefix sharing, chunked
prefill, deadline eviction), at tp=1 and tp=2. On top of parity, the
paged-only contracts: a prefix hit moves zero K/V rows beyond the one
copy-on-write partial tail page (the ``page_copies`` counter and the
``prefix_map`` trace events assert it), refcounted pages reclaim when
their last holder finishes (pool reusable), and admission pools
capacity across slots ("enough free pages" — a long-tail mix admits
under a pool the slot-major layout must worst-case-reserve).

Every scheduler-driving test stays inside the tier-1 audit budget
(tests/test_markers.py: <= 64 estimated tokens, <= 2 topologies).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.data.lm import (
    synthesize_longtail_prompts,
    synthesize_prompts,
    synthesize_shared_prefix_prompts,
)
from ddl_tpu.models.transformer import TINY_SPEC
from ddl_tpu.obs.trace import Tracer
from ddl_tpu.ops import kv_cache
from ddl_tpu.ops.kv_cache import PAD_POS
from ddl_tpu.serve import (
    InferenceEngine,
    Request,
    Scheduler,
    ServeConfig,
)

SPEC = TINY_SPEC


# -- ops: the block-table primitives ------------------------------------------


def test_table_rows_gather_and_write_roundtrip():
    """The paged device contract end to end at the op level: logical
    rows flatten through the table (unmapped/out-of-reach -> OOB, so
    writes DROP), gathers return pages in logical order, and positions
    travel with rows (PAD_POS where the table is unmapped)."""
    ps, P = 4, 6
    pool = jnp.zeros((P, ps, 3))
    pos = jnp.full((P, ps), PAD_POS)
    # Slot 0 owns pages [2, 0]; slot 1 owns [5]; second entries unmapped.
    table = jnp.asarray([[2, 0], [5, -1]], jnp.int32)
    logical = jnp.asarray([[0, 1, 5], [2, 9, 4]], jnp.int32)
    flat = kv_cache.table_rows(table, logical, ps, P)
    # slot 0: rows 0,1 -> page 2 offsets 0,1 (flat 8,9); row 5 -> page 0
    # offset 1 (flat 1). slot 1: row 2 -> page 5 offset 2 (flat 22);
    # row 9 is beyond the 2-page reach -> drop; row 4 -> page index 1 is
    # UNMAPPED (-1) -> drop.
    np.testing.assert_array_equal(np.asarray(flat),
                                  [[8, 9, 1], [22, 24, 24]])
    new = jnp.arange(2 * 3 * 3, dtype=jnp.float32).reshape(2, 3, 3) + 1
    out = kv_cache.write_rows_flat(pool, new, flat)
    np.testing.assert_array_equal(np.asarray(out)[2, 0], [1, 2, 3])
    np.testing.assert_array_equal(np.asarray(out)[2, 1], [4, 5, 6])
    np.testing.assert_array_equal(np.asarray(out)[0, 1], [7, 8, 9])
    np.testing.assert_array_equal(np.asarray(out)[5, 2], [10, 11, 12])
    # Only the four mapped writes landed; the two dropped rows of slot 1
    # left no trace anywhere in the pool.
    assert float(jnp.abs(out).sum()) == sum(
        float(jnp.abs(new[b, t]).sum()) for b, t in
        [(0, 0), (0, 1), (0, 2), (1, 0)]
    )
    # Gather returns slot 0's pages in TABLE order: page 2 then page 0.
    g = kv_cache.gather_pages(out, table)
    assert g.shape == (2, 2 * ps, 3)
    np.testing.assert_array_equal(np.asarray(g)[0, 0], [1, 2, 3])
    np.testing.assert_array_equal(np.asarray(g)[0, ps + 1], [7, 8, 9])
    # Positions: written rows carry their values, unmapped pages PAD.
    pos2 = kv_cache.write_rows_flat(
        pos, jnp.asarray([[0, 1, 5], [2, 9, 4]]), flat
    )
    kpos = kv_cache.table_positions(pos2, table)
    assert int(kpos[0, 0]) == 0 and int(kpos[0, 1]) == 1
    assert int(kpos[0, ps + 1]) == 5
    assert int(kpos[1, 2]) == 2
    assert (np.asarray(kpos)[1, ps:] == PAD_POS).all()  # unmapped page


# -- validation: loud ctor + loud submit (ISSUE 7 satellite) ------------------


def test_paged_engine_config_validation_both_directions():
    """Bad page geometry is a CONSTRUCTION error naming the fix (the
    PR 4/6 loud-ctor pattern): non-power-of-two page_size, num_pages
    without page_size, num_pages below slots, capacity not tiling into
    pages. The matching good configs construct (both directions)."""
    good = dict(spec=SPEC, slots=2, capacity=32)
    for bad, msg in (
        (dict(page_size=12), "power of two"),
        (dict(page_size=-8), "power of two"),
        (dict(num_pages=8), "requires page_size"),
        (dict(page_size=8, num_pages=1), "below slots"),
        (dict(page_size=8, num_pages=-1), "num_pages"),
        (dict(page_size=64), "multiple"),  # capacity 32 % 64 != 0
    ):
        with pytest.raises(ValueError, match=msg):
            InferenceEngine(ServeConfig(**good, **bad))
    eng = InferenceEngine(ServeConfig(**good, page_size=8, num_pages=2))
    assert eng.paged and eng.max_pages == 4 and eng.num_pages == 2
    # num_pages defaults to the slot-major envelope: slots * max_pages.
    eng = InferenceEngine(ServeConfig(**good, page_size=8))
    assert eng.num_pages == 2 * 4
    # page_size=0 stays the contiguous oracle.
    assert not InferenceEngine(ServeConfig(**good)).paged


def test_paged_scheduler_submit_validation_names_request():
    """Submit-time bounds name the offending request and the fix: the
    block-TABLE reach (capacity) and the whole-POOL reach (num_pages);
    allow_window has no paged semantics and is rejected at
    construction. The same requests admit once sized correctly."""
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=2, capacity=32,
                                      page_size=8, num_pages=5))
    sched = Scheduler(eng)
    ok = Request(id=1, prompt=np.zeros(6, np.int32), max_new_tokens=2)
    with pytest.raises(ValueError, match=r"request 9.*block-table reach"):
        sched.run([ok, Request(id=9, prompt=np.zeros(20, np.int32),
                               max_new_tokens=20)])
    with pytest.raises(ValueError, match=r"request 8.*num_pages=3"):
        # 20 + 12 = 32 rows = 4 pages: INSIDE the table reach (4 pages)
        # but over a 3-page pool — the whole-pool bound fires, naming
        # the pool, not the table.
        Scheduler(InferenceEngine(ServeConfig(
            spec=SPEC, slots=2, capacity=32, page_size=8, num_pages=3,
        ))).run([Request(id=8, prompt=np.zeros(20, np.int32),
                         max_new_tokens=12)])
    with pytest.raises(ValueError, match="allow_window"):
        Scheduler(eng, allow_window=True)
    done, _ = sched.run([ok])
    assert done[1].status == "ok" and len(done[1].tokens) == 2


# -- THE acceptance pin: paged ≡ contiguous, bitwise --------------------------


def _capture_logits(eng):
    """Map ``(request_id, position) -> logits row`` for every logit the
    engine computes, by wrapping its host API (the scheduler drives the
    wrapped engine unchanged): a prefill block at ``base`` contributes
    rows for positions ``base..base+t-1``, a decode tick one row per
    ACTIVE slot at its current length. Position-keyed because prefix
    hit depths may legitimately DIFFER between layouts (paged entries
    register floor-to-page coverage), shifting chunk boundaries — the
    parity contract is that any logit row both layouts compute for the
    same (request, position) is the same row, bitwise. Decode keys also
    return separately: decode schedules must agree exactly."""
    rows: dict[tuple[int, int], np.ndarray] = {}
    decode_keys: set[tuple[int, int]] = set()
    orig_prefill, orig_decode = eng.prefill, eng.decode

    def prefill(prompt, **kw):
        tok, lg = orig_prefill(prompt, **kw)
        base = kw.get("base", 0)
        for j in range(np.asarray(lg).shape[0]):
            rows[(kw["request_id"], base + j)] = np.asarray(lg)[j].copy()
        return tok, lg

    def decode(last, lengths, ids, active, **kw):
        nxt, lg = orig_decode(last, lengths, ids, active, **kw)
        for s in np.nonzero(np.asarray(active, bool))[0]:
            key = (int(ids[s]), int(lengths[s]))
            rows[key] = np.asarray(lg)[s].copy()
            decode_keys.add(key)
        return nxt, lg

    eng.prefill, eng.decode = prefill, decode
    return rows, decode_keys


@pytest.mark.parametrize("tp", [1, 2])
def test_paged_decode_bitwise_equals_contiguous(tp):
    """THE ISSUE 7 acceptance pin: the staggered shared-prefix workload
    with prefix sharing AND chunked prefill on, served by the paged
    engine, produces BIT-IDENTICAL per-request tokens and per-step
    logits to the contiguous oracle — tp=1 and tp=2 — while actually
    sharing (hits > 0, so the pin is not vacuous). Every decode tick's
    (request, position) is computed by BOTH layouts and agrees bitwise
    at whatever page-count bucket the paged engine ran; every prefill
    position computed by both agrees bitwise too (hit depths may differ
    — paged entries cover floor-to-page — so prefill key SETS may
    differ; the shared keys may not)."""
    prompts = synthesize_shared_prefix_prompts(
        n_families=2, per_family=3, prefix_len=12, tail_min=2, tail_max=6,
        vocab=SPEC.vocab, seed=16,
    )
    reqs = [Request(id=i, prompt=p, max_new_tokens=5, arrival=i % 3)
            for i, p in enumerate(prompts)]
    base = dict(spec=SPEC, slots=2, capacity=64, tensor_parallel=tp,
                prefix_slots=2, prefill_chunk=8, prefill_budget=8)
    ec = InferenceEngine(ServeConfig(**base))
    rows_c, dec_c = _capture_logits(ec)
    done_c, _ = Scheduler(ec).run(reqs)
    ep = InferenceEngine(ServeConfig(**base, page_size=8, num_pages=16))
    rows_p, dec_p = _capture_logits(ep)
    done_p, stats = Scheduler(ep).run(reqs)
    assert stats.prefix_hits > 0  # sharing actually happened
    for r in reqs:
        assert done_p[r.id].tokens == done_c[r.id].tokens, (tp, r.id)
    # Decode ticks agree exactly: same (request, position) schedule.
    assert dec_p == dec_c and dec_c
    common = set(rows_c) & set(rows_p)
    assert common >= dec_c  # every decode position is in both
    for key in sorted(common):
        np.testing.assert_array_equal(rows_c[key], rows_p[key],
                                      err_msg=str((tp, key)))


# -- zero-copy sharing + refcounted reclamation -------------------------------


def test_paged_prefix_hit_zero_copy_and_pool_reclaim():
    """Acceptance: a paged prefix hit moves NO K/V rows beyond the one
    copy-on-write partial tail page — asserted via the engine's
    copy-program counter AND the prefix_map trace events (copied_rows
    < page_size, page-aligned hits copy nothing) — and every page
    reclaims when its last holder lets go: slots release at completion,
    entries at eviction, after which the pool is whole and REUSABLE
    (the rerun reproduces the first run's tokens)."""
    prompts = synthesize_shared_prefix_prompts(
        n_families=2, per_family=3, prefix_len=16, tail_min=2, tail_max=6,
        vocab=SPEC.vocab, seed=7,
    )
    reqs = [Request(id=i, prompt=p, max_new_tokens=4, arrival=i % 2)
            for i, p in enumerate(prompts)]
    eng = InferenceEngine(ServeConfig(
        spec=SPEC, slots=2, capacity=64, prefix_slots=2,
        page_size=8, num_pages=16,
    ))
    tracer = Tracer()
    done, stats = Scheduler(eng, tracer=tracer).run(reqs)
    assert stats.prefix_hits > 0
    maps = [r["attrs"] for r in tracer.records
            if r.get("name") == "prefix_map"]
    assert len(maps) == stats.prefix_hits
    for attrs in maps:
        # Zero copies beyond the partial tail page: page-aligned hits
        # copy nothing, unaligned ones exactly hit % page_size rows.
        assert attrs["copied_rows"] == attrs["rows"] % 8
        assert attrs["copied_rows"] < 8
    assert eng.page_copies == sum(1 for a in maps if a["copied_rows"])
    # No contiguous-style full-prefix copy program even exists on this
    # path; the only copies the run made are the tail pages above.
    comp = [r["attrs"] for r in tracer.records
            if r.get("name") == "complete"]
    assert comp and all(a["kv_pages_held"] >= 1 for a in comp)
    # All slots released; only prefix entries still hold pages, every
    # held page carries exactly the live references.
    assert (eng.table_len == 0).all()
    held = sum(len(set(e.pages)) for e in eng.prefix._entries.values())
    assert eng.pages.free == eng.num_pages - held
    assert (eng.pages.refs >= 0).all()
    # Evicting the (zero-ref) entries returns EVERY page: nothing leaks.
    assert eng.reclaim_pages(eng.num_pages)
    assert eng.pages.free == eng.num_pages
    # Pool reusable: the rerun (cold index again) replays identically.
    again, _ = Scheduler(eng).run(reqs)
    for r in reqs:
        assert again[r.id].tokens == done[r.id].tokens


def test_paged_pinned_pages_survive_reclaim_pressure():
    """The refcount half of reclamation, on the engine directly: pages
    mapped by a LIVE slot (and the entry it pinned) survive a full
    reclaim sweep — only zero-ref entries' pages free — and release
    order doesn't matter (slot then entry, or entry then slot)."""
    eng = InferenceEngine(ServeConfig(
        spec=SPEC, slots=2, capacity=32, prefix_slots=2,
        page_size=8, num_pages=8,
    ))
    prompt = np.zeros(16, np.int32)
    eng.prefill(prompt, slot=0, request_id=0)
    assert eng.prefix_store(prompt, 0)  # donates pages 0,1 (zero-copy)
    assert eng.pages.shared == 2
    entry, hit = eng.prefix.match(prompt)
    eng.prefix_fetch(entry, 8, 1)  # page-aligned: zero copies
    assert eng.page_copies == 0
    assert eng.pages.refs[0] == 3  # slot 0 + entry + slot 1
    # Reclaim pressure frees nothing: the only entry is pinned.
    assert not eng.reclaim_pages(eng.num_pages)
    assert eng.prefix.skipped_full == 0  # reclaim, not registration
    eng.release_slot(1)
    eng.prefix_release(entry)
    # Entry now ZERO-REF but its pages are still mapped by live slot 0:
    # evicting it would free nothing — reclaim must leave it resident
    # (a fruitless eviction only burns future hits) and report failure.
    assert not eng.reclaim_pages(eng.num_pages)
    assert len(eng.prefix) == 1
    eng.release_slot(0)
    assert eng.pages.free == eng.num_pages - 2  # entry's 2 pages remain
    assert eng.reclaim_pages(eng.num_pages)  # now actually freeable
    assert eng.pages.free == eng.num_pages


# -- pooled capacity: admission is "enough free pages" ------------------------


def test_paged_pool_admission_defers_until_pages_free():
    """Capacity pooling admits by PAGES, not worst-case slots: a pool
    too small to co-host the head request waits (strict FIFO) and
    admits once a finishing request frees pages — the run completes
    with tokens bit-identical to a generous-pool run, and the deferral
    actually happened (the waiter's admission follows a completion)."""
    prompts = synthesize_longtail_prompts(
        num_short=2, num_long=1, short_min=6, short_max=10, long_len=24,
        long_prefix_len=1, vocab=SPEC.vocab, seed=3,
    )
    reqs = [Request(id=i, prompt=p, max_new_tokens=4)
            for i, p in enumerate(prompts)]
    tight = InferenceEngine(ServeConfig(
        spec=SPEC, slots=2, capacity=32, page_size=8, num_pages=5,
    ))
    sched = Scheduler(tight)
    # Warmup must survive a TIGHT pool too (its compile ladders cap
    # their page use; clone-run residue is reset away first).
    sched.warmup(reqs)
    done_t, _ = sched.run(reqs)
    roomy = InferenceEngine(ServeConfig(
        spec=SPEC, slots=2, capacity=32, page_size=8, num_pages=8,
    ))
    done_r, _ = Scheduler(roomy).run(reqs)
    for r in reqs:
        assert done_t[r.id].status == "ok"
        assert done_t[r.id].tokens == done_r[r.id].tokens, r.id
    # The long request (4 pages of 5) could not co-reside with both
    # shorts: somebody was admitted only after another finished.
    starts = sorted(done_t[i].admitted_step for i in done_t)
    first_finish = min(done_t[i].finished_step for i in done_t)
    assert starts[-1] >= first_finish
    # The generous pool co-hosted freely: both slots filled at step 0.
    assert sorted(done_r[i].admitted_step for i in done_r)[1] == 0
    assert tight.pages.free == tight.num_pages  # nothing leaked


def test_paged_reclaim_evicting_the_matched_entry_is_safe():
    """Admission under page pressure may reclaim the very entry the
    pending request just matched (it was zero-ref — exactly what
    reclaim evicts). The scheduler must re-probe after reclaiming:
    fetching the ghost entry would KeyError and the reservation would
    be undersized. Constructed so the first reclaim evicts the matched
    family prefix AND the re-probed need forces a second reclaim —
    the request then admits as a full prefill with correct tokens."""
    ps = 4
    mk = lambda: InferenceEngine(ServeConfig(
        spec=SPEC, slots=2, capacity=20, prefix_slots=2,
        page_size=ps, num_pages=5,
    ))
    eng = mk()
    prompt_a = np.arange(8, dtype=np.int32) % SPEC.vocab
    prompt_a2 = (np.arange(4, dtype=np.int32) + 9) % SPEC.vocab
    sched = Scheduler(eng)
    sched.run([Request(id=0, prompt=prompt_a, max_new_tokens=2),
               Request(id=1, prompt=prompt_a2, max_new_tokens=2,
                       arrival=1)])
    assert len(eng.prefix) == 2  # both registered, 3 pages pinned
    assert eng.pages.available == 2
    # B shares A's full prompt: matches entry A (2 shared pages), but
    # needs 5 pages total -> need 3 > available 2 -> reclaim evicts the
    # MATCHED zero-ref entry A first (LRU), then A2 on the re-probed
    # round -> full prefill, 5 fresh pages.
    prompt_b = np.concatenate([prompt_a, prompt_a[1:2]]).astype(np.int32)
    done, stats = sched.run([Request(id=7, prompt=prompt_b,
                                     max_new_tokens=11)])
    assert done[7].status == "ok" and len(done[7].tokens) == 11
    assert len(eng.prefix) <= 1  # the old entries were reclaimed
    # Correctness: same tokens as a fresh engine with no cache history.
    fresh, _ = Scheduler(mk()).run([Request(id=7, prompt=prompt_b,
                                            max_new_tokens=11)])
    assert fresh[7].tokens == done[7].tokens


def test_paged_deadline_eviction_releases_pages_and_keeps_parity():
    """The deadline-eviction interaction (acceptance): a stalled
    request admitted onto the paged pool (pages reserved, prefix
    pinned) expires at its deadline — pages AND reservation return to
    the pool, refs release — while co-residents' tokens stay
    bit-identical to the contiguous oracle under the same fault, with
    chunked prefill on (the full ISSUE 6 x ISSUE 7 composition)."""
    from ddl_tpu.resilience.faults import FaultInjector, FaultSpec

    prompts = synthesize_shared_prefix_prompts(
        n_families=1, per_family=3, prefix_len=12, tail_min=2, tail_max=4,
        vocab=SPEC.vocab, seed=9,
    )
    reqs = [
        Request(id=0, prompt=prompts[0], max_new_tokens=4),
        Request(id=1, prompt=prompts[1], max_new_tokens=4, arrival=1,
                deadline_s=0.02),
        Request(id=2, prompt=prompts[2], max_new_tokens=4, arrival=1),
    ]
    outs = {}
    for paged in (0, 8):
        eng = InferenceEngine(ServeConfig(
            spec=SPEC, slots=2, capacity=64, prefix_slots=2,
            prefill_chunk=8, page_size=paged,
            num_pages=16 if paged else 0,
        ))
        inj = FaultInjector(FaultSpec(kind="stall", step=1))
        done, _ = Scheduler(eng, injector=inj).run(reqs)
        assert done[1].status == "deadline_exceeded"
        assert done[0].status == "ok" and done[2].status == "ok"
        outs[paged] = {i: done[i].tokens for i in done}
        if paged:
            # Eviction released the stalled slot's pages + reservation;
            # only prefix entries hold pages now.
            assert (eng.table_len == 0).all()
            assert eng.pages.reserved == 0
            assert eng.reclaim_pages(eng.num_pages)
            assert eng.pages.free == eng.num_pages
            # Pool reusable after eviction (the PR 6 contract, paged).
            again, _ = Scheduler(eng).run(
                [Request(id=3, prompt=prompts[1], max_new_tokens=2)]
            )
            assert again[3].status == "ok"
    assert outs[0] == outs[8]  # paged ≡ contiguous under eviction


def test_release_returns_pool_byte_whole_reservations_included():
    """ISSUE 13 satellite: aborting an armed run mid-flight — occupants
    decoding, admission reservations outstanding, a mid-prefill slot —
    returns the pool BYTE-WHOLE through ``Scheduler.release()``: every
    page back on the free list AND every reservation cancelled (the
    abort path used to sweep only occupied slots' mapped pages; a
    drained/aborted replica must hand back promised-not-yet-mapped
    capacity too). The engine is then fully reusable."""
    eng = InferenceEngine(ServeConfig(
        spec=SPEC, slots=3, capacity=32, page_size=8, num_pages=8,
        prefill_chunk=8,
    ))
    prompts = synthesize_prompts(num=3, min_len=6, max_len=12,
                                 vocab=SPEC.vocab, seed=4)
    sched = Scheduler(eng)
    sched.begin()
    for i, p in enumerate(prompts):
        sched.submit(Request(id=i, prompt=p, max_new_tokens=12))
    for _ in range(2):
        sched.tick()
    # Mid-flight: pages mapped AND reservations outstanding.
    assert eng.pages.free < eng.num_pages
    assert eng.pages.reserved > 0
    # The fixed gap: a reservation on a slot with NO occupant (an
    # admission/adopt interrupted between reserve and install) — the
    # occupant-only sweep missed exactly this.
    free_slot = next(s for s in range(3)
                     if sched._st.occupant[s] is None)
    eng.reserve_pages(free_slot, 1)
    sched.release()
    assert eng.pages.free == eng.num_pages  # every page back
    assert eng.pages.reserved == 0  # every reservation cancelled
    assert (eng.table_len == 0).all()
    assert (eng.reserved_for == 0).all()
    # Reusable: a fresh run on the same engine completes cleanly.
    done, _ = Scheduler(eng).run(
        [Request(id=9, prompt=prompts[0], max_new_tokens=2)]
    )
    assert done[9].status == "ok"


def test_handoff_reservation_accounting_byte_whole():
    """ISSUE 15 satellite: PagePool reservation accounting across a
    prefill->decode hand-off. In one global tick the SOURCE releases
    everything (mapped page refs AND its unconsumed admission
    reservation — ``preempt`` goes through ``release_slot``) while the
    DESTINATION re-reserves the request's remaining worst case; and an
    ABORTED mid-transfer request (preempted, never adopted) leaves both
    pools byte-whole through the hardened ``release()`` sweep — the
    PR 13 pin extended across two engines."""
    cfg = ServeConfig(spec=TINY_SPEC, slots=2, capacity=32, page_size=8,
                      num_pages=8)
    src_eng, dst_eng = InferenceEngine(cfg), InferenceEngine(cfg)
    src, dst = Scheduler(src_eng), Scheduler(dst_eng)
    prompt = np.arange(1, 7, dtype=np.int32)
    req = Request(id=0, prompt=prompt, max_new_tokens=10)
    need = src_eng.pages_needed(6 + 10)
    src.begin()
    dst.begin()
    src.submit(req)
    src.tick()  # admit + prefill + first token: active, pages held
    held = int(src_eng.table_len[0])
    assert held >= 1
    # Mid-flight the source holds mapped pages plus the rest of its
    # admission promise.
    assert src_eng.pages.free == src_eng.num_pages - held
    assert src_eng.pages.reserved == need - held

    pre = src.preempt(0)
    # Source side released in full: refs AND reservations, same tick.
    assert src_eng.pages.free == src_eng.num_pages
    assert src_eng.pages.reserved == 0
    assert int(src_eng.reserved_for[0]) == 0

    slot = dst.adopt(pre)
    # Destination re-reserved the worst case and mapped the moved
    # pages out of that promise.
    assert int(dst_eng.table_len[slot]) == held
    assert dst_eng.pages.reserved == need - held
    assert dst_eng.pages.free == dst_eng.num_pages - held
    done_d = None
    while not dst.idle:
        dst.tick()
    done_d, _ = dst.collect()
    assert done_d[0].status == "ok" and len(done_d[0].tokens) == 10
    src.release()
    dst.release()
    for eng in (src_eng, dst_eng):
        assert eng.pages.free == eng.num_pages
        assert eng.pages.reserved == 0

    # Aborted mid-transfer: preempt again on a fresh run, then DROP the
    # preempted state instead of adopting — release() returns both
    # pools byte-whole (the dumped pages were host copies; nothing on
    # device is pinned by them).
    src.begin()
    dst.begin()
    src.submit(req)
    src.tick()
    pre = src.preempt(0)
    assert pre.pos.shape[0] >= 1  # the dump really carried pages
    src.release()
    dst.release()
    for eng in (src_eng, dst_eng):
        assert eng.pages.free == eng.num_pages
        assert eng.pages.reserved == 0
        assert (eng.table_len == 0).all()
        assert (eng.reserved_for == 0).all()
