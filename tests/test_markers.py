"""Tier-1 marker audit for the serving test surface (ISSUE 4 satellite).

Serve tests are the suite's fastest-growing cost center: every scheduler
run decodes tokens one compiled step at a time, and every topology in a
sweep compiles its own program pair — on the single-host CPU gate that
wall-clock adds up quickly. This audit makes the time-budget rule
MECHANICAL instead of reviewer folklore: any test that drives the serve
``Scheduler`` past either bound below must carry ``@pytest.mark.slow``
(excluded from tier-1 via ``-m 'not slow'``), so serve growth cannot
silently erode the tier-1 budget.

Bounds (per test function, per run):

- **> 64 total generated tokens** — estimated statically as
  ``requests_per_run * max_new_tokens``, where ``requests_per_run`` is
  the larger of the prompt-set size (literal ``num=`` /
  ``n_families * per_family`` of a ``synthesize_*prompts`` call) and
  the count of ``Request(...)`` constructor sites, and
  ``max_new_tokens`` is the largest resolvable int literal passed under
  that keyword. Code inside ``pytest.raises`` blocks is excluded (a
  rejected request generates nothing).
- **> 2 topologies** — the product of literal tuple/list lengths over
  ``for`` loops whose bodies construct ``ServeConfig`` /
  ``InferenceEngine`` (each iteration compiles a fresh engine).
  ``pytest.mark.parametrize`` cases are separate tier-1 tests and are
  deliberately NOT multiplied in.

The estimate is a documented LOWER bound: unresolvable (non-literal)
values contribute nothing, so the audit can miss creative obfuscation
but can never false-positive on plain code. Pure AST — no jax import,
no test execution; runs in milliseconds.
"""

from __future__ import annotations

import ast
import pathlib
import textwrap

MAX_FAST_TOKENS = 64
MAX_FAST_TOPOLOGIES = 2
_PROMPT_SET_FNS = ("synthesize_prompts", "synthesize_shared_prefix_prompts")
_ENGINE_CTORS = ("ServeConfig", "InferenceEngine")


def _call_name(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _const_int(node) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _kw_int(call: ast.Call, name: str) -> int | None:
    for kw in call.keywords:
        if kw.arg == name:
            return _const_int(kw.value)
    return None


def _raises_nodes(fn) -> set[int]:
    """ids of every node inside a ``with pytest.raises(...)`` block —
    requests built there are rejected, not served."""
    skip: set[int] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.With):
            continue
        if any(
            isinstance(item.context_expr, ast.Call)
            and _call_name(item.context_expr) == "raises"
            for item in node.items
        ):
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    skip.add(id(sub))
    return skip


def has_slow_marker(fn) -> bool:
    for dec in fn.decorator_list:
        node = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(node, ast.Attribute) and node.attr == "slow":
            return True
    return False


def estimate(fn) -> tuple[bool, int, int]:
    """``(uses_scheduler, est_tokens_per_run, est_topologies)`` for one
    test function's AST (see module docstring for the metric)."""
    skip = _raises_nodes(fn)
    uses_scheduler = False
    prompt_set = 0
    request_sites = 0
    max_new = 0
    topologies = 1
    for node in ast.walk(fn):
        if id(node) in skip:
            continue
        if isinstance(node, ast.Name) and node.id == "Scheduler":
            uses_scheduler = True
        if isinstance(node, ast.For) and isinstance(
            node.iter, (ast.Tuple, ast.List)
        ):
            sweeps_engine = any(
                isinstance(sub, ast.Call) and _call_name(sub) in _ENGINE_CTORS
                for stmt in node.body
                for sub in ast.walk(stmt)
            )
            if sweeps_engine:
                topologies *= max(1, len(node.iter.elts))
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name == "Request":
            request_sites += 1
            v = _kw_int(node, "max_new_tokens")
            if v is not None:
                max_new = max(max_new, v)
        elif name == "synthesize_prompts":
            v = _kw_int(node, "num")
            if v is not None:
                prompt_set = max(prompt_set, v)
        elif name == "synthesize_shared_prefix_prompts":
            fam = _kw_int(node, "n_families") or 1
            per = _kw_int(node, "per_family") or 1
            prompt_set = max(prompt_set, fam * per)
    tokens = max(prompt_set, request_sites) * max_new
    return uses_scheduler, tokens, topologies


def _audit(tree) -> list[tuple[str, int, int]]:
    """Violations ``(test_name, tokens, topologies)`` in one module."""
    out = []
    for fn in tree.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not fn.name.startswith("test"):
            continue
        uses, tokens, topo = estimate(fn)
        if not uses or has_slow_marker(fn):
            continue
        if tokens > MAX_FAST_TOKENS or topo > MAX_FAST_TOPOLOGIES:
            out.append((fn.name, tokens, topo))
    return out


def test_serve_scheduler_tests_carry_slow_marker():
    """THE audit: every unmarked tier-1 test in this suite that drives
    the serve Scheduler stays within 64 generated tokens per run and
    2 swept topologies; anything bigger must be @pytest.mark.slow."""
    violations = []
    for path in sorted(pathlib.Path(__file__).parent.glob("test_*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        violations += [(path.name, *v) for v in _audit(tree)]
    assert not violations, (
        "serve-scheduler tests exceeding the tier-1 budget without "
        "@pytest.mark.slow (file, test, est_tokens, est_topologies): "
        f"{violations} — mark them slow or shrink the run "
        f"(<= {MAX_FAST_TOKENS} tokens, <= {MAX_FAST_TOPOLOGIES} "
        "topologies)"
    )


def test_audit_estimator_flags_and_permits():
    """Pin the estimator itself on synthetic sources, so the audit's
    teeth cannot rot silently: token overruns flag, topology sweeps
    flag, slow-marked and in-budget tests pass, pytest.raises bodies
    and non-Scheduler tests are exempt."""
    src = textwrap.dedent("""
        import pytest

        def test_token_overrun():
            prompts = synthesize_prompts(num=10, min_len=4, max_len=8)
            reqs = [Request(id=i, prompt=p, max_new_tokens=20)
                    for i, p in enumerate(prompts)]
            Scheduler(InferenceEngine(ServeConfig())).run(reqs)

        def test_topology_sweep():
            for slots in (1, 2, 4):
                eng = InferenceEngine(ServeConfig(slots=slots))
                Scheduler(eng).run([Request(id=0, prompt=p,
                                            max_new_tokens=1)])

        @pytest.mark.slow
        def test_marked_overrun():
            prompts = synthesize_prompts(num=100, min_len=4, max_len=8)
            reqs = [Request(id=i, prompt=p, max_new_tokens=64)
                    for i, p in enumerate(prompts)]
            Scheduler(InferenceEngine(ServeConfig())).run(reqs)

        def test_in_budget():
            ps = synthesize_shared_prefix_prompts(n_families=2,
                                                  per_family=3)
            reqs = [Request(id=i, prompt=p, max_new_tokens=6)
                    for i, p in enumerate(ps)]
            Scheduler(InferenceEngine(ServeConfig())).run(reqs)

        def test_rejected_requests_exempt():
            sched = Scheduler(InferenceEngine(ServeConfig()))
            with pytest.raises(ValueError):
                sched.run([Request(id=0, prompt=p,
                                   max_new_tokens=9999)])

        def test_no_scheduler():
            prompts = synthesize_prompts(num=500, min_len=4, max_len=8)
            assert len(prompts) == 500
    """)
    tree = ast.parse(src)
    names = {v[0] for v in _audit(tree)}
    assert names == {"test_token_overrun", "test_topology_sweep"}
    fns = {f.name: f for f in tree.body
           if isinstance(f, ast.FunctionDef)}
    assert has_slow_marker(fns["test_marked_overrun"])
    uses, tokens, topo = estimate(fns["test_token_overrun"])
    assert uses and tokens == 200 and topo == 1
    _, tokens, topo = estimate(fns["test_topology_sweep"])
    assert tokens == 1 and topo == 3
    _, tokens, _ = estimate(fns["test_in_budget"])
    assert tokens == 36
    uses, tokens, _ = estimate(fns["test_rejected_requests_exempt"])
    assert uses and tokens == 0
