"""Tier-1 marker audits: the serve-scheduler budget (ISSUE 4 satellite)
and the fault-injection trainer budget (ISSUE 6 satellite).

Serve tests are the suite's fastest-growing cost center: every scheduler
run decodes tokens one compiled step at a time, and every topology in a
sweep compiles its own program pair — on the single-host CPU gate that
wall-clock adds up quickly. This audit makes the time-budget rule
MECHANICAL instead of reviewer folklore: any test that drives the serve
``Scheduler`` past either bound below must carry ``@pytest.mark.slow``
(excluded from tier-1 via ``-m 'not slow'``), so serve growth cannot
silently erode the tier-1 budget.

Bounds (per test function, per run):

- **> 64 total generated tokens** — estimated statically as
  ``requests_per_run * max_new_tokens``, where ``requests_per_run`` is
  the larger of the prompt-set size (literal ``num=`` /
  ``n_families * per_family`` / ``num_short + num_long`` of a
  ``synthesize_*prompts`` call — the long-tail generator of the paged
  serve tests included — or ``max_requests=`` of a
  ``synthesize_mixed_traffic`` call, the ISSUE 8 router-stream bound)
  and the count of ``Request(...)`` constructor sites, and
  ``max_new_tokens`` is the largest resolvable int literal passed under
  that keyword to a ``Request(...)`` or a ``dict(...)`` (the mixed-
  traffic class-spec shape). Code inside ``pytest.raises`` blocks is
  excluded (a rejected request generates nothing). Speculative
  decoding (ISSUE 15): the largest ``speculate_k=`` literal ADDS to
  the per-request cost (every verify step computes up to k draft-lane
  rows beyond the token it emits), so the budget reads
  ``requests * (max_new + speculate_k)``; a ``roles=`` keyword
  anywhere marks the test scheduler-driving (disaggregated fleets
  drive schedulers through the router/coordinator surface).
- **> 2 topologies** — the product of literal tuple/list lengths over
  ``for`` loops whose bodies construct ``ServeConfig`` /
  ``InferenceEngine`` (each iteration compiles a fresh engine), AND at
  least the SUM of literal ``replicas=`` over ``Router`` /
  ``RouterConfig`` constructor sites (ISSUE 8: every replica is its own
  compiled engine, and a test building two N-replica routers pays 2N
  compiles), AND at least the SUM of literal ``max_replicas=`` over
  ``Router``/``RouterConfig``/``FleetController``/``AutoscaleConfig``
  sites (ISSUE 13: an autoscaled fleet can grow to its cap, and every
  scaled-out replica compiles its own program ladder — the cap ledger
  already subsumes the seed replicas, so the bound takes the LARGEST of
  the three ledgers, not their sum), AND at least the PRODUCT of the
  count of distinct literal ``precision=`` values and the count of
  distinct literal ``kv_dtype=`` values across call sites (ISSUE 19:
  every precision policy / KV dtype is its own compiled program ladder,
  so an fp32-vs-bf16-vs-int8 A/B/C builds three engines even without a
  ``for`` sweep — ``None`` literals count as a distinct value, and the
  variant ledger competes in the same LARGEST-of-all-ledgers bound).
  ``pytest.mark.parametrize`` cases
  are separate tier-1 tests and are deliberately NOT multiplied in.

**Sim-only exemption (ISSUE 18)**: a test whose every engine is the
cost-model twin — a ``CostModelEngine`` / ``sim_engine_factory`` name
appears, no ``InferenceEngine`` appears, and every ``RouterConfig`` /
``router_config`` site passes ``engine_factory=`` — compiles nothing
and hashes its tokens on a virtual clock, so the per-token budgets
above don't measure its cost; such tests are exempt even at
million-request scale. One real engine anywhere (or one unfactored
router site, which would build real engines) keeps the teeth.

**Comms-ledger extension (ISSUE 20)**: a ``program_text`` /
``publish_program_ledger`` name anywhere marks the test as
compile-driving (the collective-ledger recounts AOT-compile real
multi-device programs — the same wall-clock class as a scheduler
topology), and ``SeqTrainer`` joins the topology ledger: constructor
sites SUM (each trainer compiles its own span/eval program pair) and
literal tuple/list ``for`` sweeps whose bodies construct one MULTIPLY,
exactly like the engine ctors. ``collective_ops`` alone is pure text
parsing and deliberately does NOT mark.

The estimate is a documented LOWER bound: unresolvable (non-literal)
values contribute nothing, so the audit can miss creative obfuscation
but can never false-positive on plain code. Pure AST — no jax import,
no test execution; runs in milliseconds.
"""

from __future__ import annotations

import ast
import pathlib
import textwrap

MAX_FAST_TOKENS = 64
MAX_FAST_TOPOLOGIES = 2
_PROMPT_SET_FNS = ("synthesize_prompts", "synthesize_shared_prefix_prompts",
                   "synthesize_longtail_prompts", "synthesize_mixed_traffic")
_ENGINE_CTORS = ("ServeConfig", "InferenceEngine")
_TRAIN_CTORS = ("SeqTrainer",)
_ROUTER_CTORS = ("Router", "RouterConfig")
_FLEET_CTORS = ("FleetController", "AutoscaleConfig")
_SIM_NAMES = ("CostModelEngine", "sim_engine_factory")


def _call_name(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _const_int(node) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _kw_int(call: ast.Call, name: str) -> int | None:
    for kw in call.keywords:
        if kw.arg == name:
            return _const_int(kw.value)
    return None


def _raises_nodes(fn) -> set[int]:
    """ids of every node inside a ``with pytest.raises(...)`` block —
    requests built there are rejected, not served."""
    skip: set[int] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.With):
            continue
        if any(
            isinstance(item.context_expr, ast.Call)
            and _call_name(item.context_expr) == "raises"
            for item in node.items
        ):
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    skip.add(id(sub))
    return skip


def has_slow_marker(fn) -> bool:
    for dec in fn.decorator_list:
        node = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(node, ast.Attribute) and node.attr == "slow":
            return True
    return False


def sim_only(fn) -> bool:
    """True when every engine this test can construct is the cost-model
    twin (ISSUE 18): a sim name appears outside ``pytest.raises``, no
    ``InferenceEngine`` does, and every ``RouterConfig`` /
    ``router_config`` call site passes ``engine_factory=`` (a router
    site without one builds real engines). Sound for plain code —
    one real-engine path anywhere disqualifies."""
    skip = _raises_nodes(fn)
    saw_sim = False
    for node in ast.walk(fn):
        if id(node) in skip:
            continue
        if isinstance(node, ast.Name):
            if node.id in _SIM_NAMES:
                saw_sim = True
            elif node.id == "InferenceEngine":
                return False
        elif isinstance(node, ast.Attribute) and node.attr in _SIM_NAMES:
            saw_sim = True
        elif isinstance(node, ast.Call):
            if _call_name(node) in ("RouterConfig", "router_config") \
                    and not any(kw.arg == "engine_factory"
                                for kw in node.keywords):
                return False
    return saw_sim


def estimate(fn) -> tuple[bool, int, int]:
    """``(uses_scheduler, est_tokens_per_run, est_topologies)`` for one
    test function's AST (see module docstring for the metric)."""
    skip = _raises_nodes(fn)
    uses_scheduler = False
    prompt_set = 0
    request_sites = 0
    max_new = 0
    spec_k = 0
    topologies = 1
    router_replicas = 0
    fleet_caps = 0
    trainer_sites = 0
    precisions: set = set()
    kv_dtypes: set = set()
    for node in ast.walk(fn):
        if id(node) in skip:
            continue
        if isinstance(node, ast.Name) and node.id in (
            "Scheduler", "Router", "SloMonitor", "AnomalyDetector",
            "GoodputTracker", "FleetController", "Autoscaler",
            "publish_program_ledger", "program_text",
        ):
            # SloMonitor (ISSUE 10) / AnomalyDetector + GoodputTracker
            # (ISSUE 11) / FleetController + Autoscaler (ISSUE 13): the
            # SLO/anomaly/goodput/fleet tests drive schedulers and
            # routers through those surfaces — any of these names alone
            # marks the test as scheduler-driving, so the observability
            # and fleet tests count into the same budgets. The comms
            # ledger surfaces (ISSUE 20) mark too: a test recounting
            # through program_text / publish_program_ledger is
            # AOT-compiling real multi-device programs.
            uses_scheduler = True
        if isinstance(node, ast.For) and isinstance(
            node.iter, (ast.Tuple, ast.List)
        ):
            sweeps_engine = any(
                isinstance(sub, ast.Call)
                and _call_name(sub) in _ENGINE_CTORS + _TRAIN_CTORS
                for stmt in node.body
                for sub in ast.walk(stmt)
            )
            if sweeps_engine:
                topologies *= max(1, len(node.iter.elts))
        if not isinstance(node, ast.Call):
            continue
        # ISSUE 15 extension: roles= marks scheduler-driving wherever
        # it appears; speculate_k= literals feed the token budget.
        for kw in node.keywords:
            if kw.arg == "roles":
                uses_scheduler = True
            elif kw.arg == "speculate_k":
                v = _const_int(kw.value)
                if v is not None:
                    spec_k = max(spec_k, v)
            elif kw.arg in ("precision", "kv_dtype") and isinstance(
                kw.value, ast.Constant
            ) and isinstance(kw.value.value, (str, type(None))):
                # ISSUE 19 extension: every DISTINCT literal precision
                # policy / KV dtype compiles its own program ladder —
                # an fp32-vs-int8 A/B is two engines even without a
                # ``for`` sweep, so distinct values per axis multiply
                # into the variant ledger below (None counts: it is
                # the fp32/full-precision arm of such an A/B).
                (precisions if kw.arg == "precision"
                 else kv_dtypes).add(kw.value.value)
        name = _call_name(node)
        if name in ("Request", "dict"):
            # dict() covers the mixed-traffic class specs — their
            # max_new_tokens bounds every generated request's budget.
            if name == "Request":
                request_sites += 1
            v = _kw_int(node, "max_new_tokens")
            if v is not None:
                max_new = max(max_new, v)
        elif name in _ROUTER_CTORS + _FLEET_CTORS:
            v = _kw_int(node, "replicas")
            if v is not None:
                router_replicas += v
            # ISSUE 13: an autoscaled fleet can grow to max_replicas
            # engines — the cap ledger sums across sites and the final
            # bound takes the LARGEST ledger (the cap subsumes the
            # seed replicas of the router it governs).
            v = _kw_int(node, "max_replicas")
            if v is not None:
                fleet_caps += v
        elif name in _TRAIN_CTORS:
            # ISSUE 20: every constructed trainer compiles its own
            # span/eval program pair — sites SUM like replicas.
            trainer_sites += 1
        elif name == "synthesize_prompts":
            v = _kw_int(node, "num")
            if v is not None:
                prompt_set = max(prompt_set, v)
        elif name == "synthesize_shared_prefix_prompts":
            fam = _kw_int(node, "n_families") or 1
            per = _kw_int(node, "per_family") or 1
            prompt_set = max(prompt_set, fam * per)
        elif name == "synthesize_longtail_prompts":
            ns = _kw_int(node, "num_short") or 0
            nl = _kw_int(node, "num_long") or 0
            prompt_set = max(prompt_set, ns + nl)
        elif name == "synthesize_mixed_traffic":
            v = _kw_int(node, "max_requests")
            if v is not None:
                prompt_set = max(prompt_set, v)
    tokens = max(prompt_set, request_sites) * (max_new + spec_k)
    variants = max(1, len(precisions)) * max(1, len(kv_dtypes))
    return uses_scheduler, tokens, max(topologies, router_replicas,
                                       fleet_caps, variants,
                                       trainer_sites)


def _audit(tree) -> list[tuple[str, int, int]]:
    """Violations ``(test_name, tokens, topologies)`` in one module."""
    out = []
    for fn in tree.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not fn.name.startswith("test"):
            continue
        uses, tokens, topo = estimate(fn)
        if not uses or has_slow_marker(fn) or sim_only(fn):
            continue
        if tokens > MAX_FAST_TOKENS or topo > MAX_FAST_TOPOLOGIES:
            out.append((fn.name, tokens, topo))
    return out


def test_serve_scheduler_tests_carry_slow_marker():
    """THE audit: every unmarked tier-1 test in this suite that drives
    the serve Scheduler stays within 64 generated tokens per run and
    2 swept topologies; anything bigger must be @pytest.mark.slow."""
    violations = []
    for path in sorted(pathlib.Path(__file__).parent.glob("test_*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        violations += [(path.name, *v) for v in _audit(tree)]
    assert not violations, (
        "serve-scheduler tests exceeding the tier-1 budget without "
        "@pytest.mark.slow (file, test, est_tokens, est_topologies): "
        f"{violations} — mark them slow or shrink the run "
        f"(<= {MAX_FAST_TOKENS} tokens, <= {MAX_FAST_TOPOLOGIES} "
        "topologies)"
    )


def test_router_audit_estimator_flags_and_permits():
    """ISSUE 8 self-pin: Router tests count into the audit — replicas
    literals SUM into the topology bound (two 3-replica routers = six
    engines), synthesize_mixed_traffic's max_requests is the request
    bound, class-spec dict(max_new_tokens=...) literals bound the token
    budget, and a Router name alone marks the test as
    scheduler-driving."""
    src = textwrap.dedent("""
        import pytest

        def test_replica_overrun():
            cfg = RouterConfig(serve=ServeConfig(), replicas=3)
            t = synthesize_mixed_traffic(
                classes={"c": dict(rate=1.0, max_new_tokens=2)},
                max_requests=4)
            Router(cfg).run(t)

        def test_two_router_sites_overrun():
            a = Router(RouterConfig(serve=ServeConfig(), replicas=2))
            b = Router(RouterConfig(serve=ServeConfig(), replicas=2))
            t = synthesize_mixed_traffic(
                classes={"c": dict(rate=1.0, max_new_tokens=1)},
                max_requests=4)
            a.run(t); b.run(t)

        def test_mixed_token_overrun():
            t = synthesize_mixed_traffic(
                classes={"c": dict(rate=1.0, max_new_tokens=4)},
                max_requests=40)
            Router(RouterConfig(serve=ServeConfig(), replicas=2)).run(t)

        def test_in_budget_router():
            t = synthesize_mixed_traffic(
                classes={"c": dict(rate=1.0, max_new_tokens=2)},
                max_requests=10)
            Router(RouterConfig(serve=ServeConfig(), replicas=2)).run(t)

        def test_rejected_router_exempt():
            with pytest.raises(ValueError):
                Router(RouterConfig(serve=ServeConfig(), replicas=9))
    """)
    tree = ast.parse(src)
    names = {v[0] for v in _audit(tree)}
    assert names == {"test_replica_overrun", "test_two_router_sites_overrun",
                     "test_mixed_token_overrun"}
    fns = {f.name: f for f in tree.body if isinstance(f, ast.FunctionDef)}
    uses, tokens, topo = estimate(fns["test_replica_overrun"])
    assert uses and tokens == 8 and topo == 3
    uses, tokens, topo = estimate(fns["test_two_router_sites_overrun"])
    assert uses and tokens == 4 and topo == 4  # replicas SUM across sites
    uses, tokens, topo = estimate(fns["test_mixed_token_overrun"])
    assert uses and tokens == 160 and topo == 2
    uses, tokens, topo = estimate(fns["test_in_budget_router"])
    assert uses and tokens == 20 and topo == 2
    # A Router referenced ONLY inside pytest.raises never runs: the
    # whole test is exempt, same as the Request/fault conventions.
    uses, tokens, topo = estimate(fns["test_rejected_router_exempt"])
    assert not uses and tokens == 0 and topo == 1


# -- fault-injection trainer audit (ISSUE 6 satellite) ------------------------
#
# Resilience tests run WHOLE trainer loops (often several per test: a
# golden run, a faulted run, a resume run), which dwarfs the serve
# scheduler's per-token cost. Same mechanical discipline as above: any
# unmarked test that references the fault-injection surface and either
# trains more than MAX_FAST_TRAIN_STEPS estimated optimizer steps per
# test or re-runs more than MAX_FAST_RESUME_CYCLES resume cycles must
# carry @pytest.mark.slow. The step estimate is a documented LOWER
# bound: sites * max(epochs) * (max(num_train|synthetic_train) //
# max(batch_size)), with unresolvable values contributing 1/0 — plain
# code can never false-positive.

MAX_FAST_TRAIN_STEPS = 64
MAX_FAST_RESUME_CYCLES = 2
_FAULT_NAMES = ("FaultSpec", "FaultInjector", "parse_fault",
                "corrupt_checkpoint", "truncate_checkpoint")


def estimate_fault(fn) -> tuple[bool, int, int]:
    """``(uses_faults, est_train_steps, resume_cycles)`` for one test
    function's AST. ``uses_faults``: any fault-injection name appears
    outside pytest.raises blocks. ``est_train_steps``: `.train(` call
    sites times the largest literal epochs times the largest literal
    dataset-size // batch-size. ``resume_cycles``: `.train(` calls
    passing a truthy literal ``resume``."""
    skip = _raises_nodes(fn)
    uses = False
    train_sites = 0
    resume_cycles = 0
    epochs = 1
    ntrain = 0
    batch = 0
    for node in ast.walk(fn):
        if id(node) in skip:
            continue
        if isinstance(node, ast.Name) and node.id in _FAULT_NAMES:
            uses = True
        if isinstance(node, ast.Attribute) and node.attr in _FAULT_NAMES:
            uses = True
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) and node.func.attr == "train":
            train_sites += 1
            for kw in node.keywords:
                if kw.arg == "resume" and isinstance(kw.value, ast.Constant) \
                        and bool(kw.value.value):
                    resume_cycles += 1
        for kw in node.keywords:
            v = _const_int(kw.value)
            if v is None:
                continue
            if kw.arg == "epochs":
                epochs = max(epochs, v)
            elif kw.arg in ("num_train", "synthetic_train"):
                ntrain = max(ntrain, v)
            elif kw.arg == "batch_size":
                batch = max(batch, v)
    per_run = epochs * (ntrain // batch if ntrain and batch else 1)
    return uses, train_sites * per_run, resume_cycles


def _audit_faults(tree) -> list[tuple[str, int, int]]:
    """Violations ``(test_name, est_steps, resume_cycles)``."""
    out = []
    for fn in tree.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not fn.name.startswith("test"):
            continue
        uses, steps, cycles = estimate_fault(fn)
        if not uses or has_slow_marker(fn):
            continue
        if steps > MAX_FAST_TRAIN_STEPS or cycles > MAX_FAST_RESUME_CYCLES:
            out.append((fn.name, steps, cycles))
    return out


def test_slo_audit_estimator_extension():
    """ISSUE 10 self-pin: an ``SloMonitor`` name alone marks a test as
    scheduler-driving (the SLO tests drive serving through the monitor
    surface), so token/topology overruns in the new SLO/export tests
    flag exactly like direct Scheduler/Router tests; a monitor-only
    test within budget stays exempt-by-budget."""
    src = textwrap.dedent("""
        def test_slo_token_overrun():
            mon = SloMonitor([rule], reg)
            t = synthesize_mixed_traffic(
                classes={"c": dict(rate=1.0, max_new_tokens=8)},
                max_requests=20)
            drive(mon, t)

        def test_slo_in_budget():
            mon = SloMonitor([rule], reg)
            t = synthesize_mixed_traffic(
                classes={"c": dict(rate=1.0, max_new_tokens=2)},
                max_requests=10)
            drive(mon, t)
    """)
    tree = ast.parse(src)
    names = {v[0] for v in _audit(tree)}
    assert names == {"test_slo_token_overrun"}
    fns = {f.name: f for f in tree.body if isinstance(f, ast.FunctionDef)}
    uses, tokens, topo = estimate(fns["test_slo_token_overrun"])
    assert uses and tokens == 160 and topo == 1
    uses, tokens, _ = estimate(fns["test_slo_in_budget"])
    assert uses and tokens == 20


def test_anomaly_goodput_audit_estimator_extension():
    """ISSUE 11 self-pin: an ``AnomalyDetector`` or ``GoodputTracker``
    name alone marks a test as scheduler-driving (the goodput/anomaly
    tests drive serving through those surfaces), so token overruns in
    the new observability tests flag exactly like direct
    Scheduler/Router tests; in-budget ones stay exempt-by-budget."""
    src = textwrap.dedent("""
        def test_anomaly_token_overrun():
            det = AnomalyDetector([rule], reg)
            prompts = synthesize_prompts(num=10, min_len=4, max_len=8)
            reqs = [Request(id=i, prompt=p, max_new_tokens=20)
                    for i, p in enumerate(prompts)]
            drive(det, reqs)

        def test_goodput_in_budget():
            gp = GoodputTracker(reg, "serve")
            prompts = synthesize_prompts(num=4, min_len=4, max_len=8)
            reqs = [Request(id=i, prompt=p, max_new_tokens=4)
                    for i, p in enumerate(prompts)]
            drive(gp, reqs)
    """)
    tree = ast.parse(src)
    names = {v[0] for v in _audit(tree)}
    assert names == {"test_anomaly_token_overrun"}
    fns = {f.name: f for f in tree.body if isinstance(f, ast.FunctionDef)}
    uses, tokens, topo = estimate(fns["test_anomaly_token_overrun"])
    assert uses and tokens == 200 and topo == 1
    uses, tokens, _ = estimate(fns["test_goodput_in_budget"])
    assert uses and tokens == 16


def test_fleet_audit_estimator_extension():
    """ISSUE 13 self-pin: a ``FleetController``/``Autoscaler`` name
    alone marks a test as scheduler-driving, and ``max_replicas=``
    literals SUM into the topology budget (the fleet can grow to its
    cap; the cap ledger subsumes the seed replicas, so the bound is the
    largest of the three ledgers — a 1-replica router under a
    max_replicas=4 controller counts 4 engines, while replicas=2 with
    max_replicas=2 stays in budget)."""
    src = textwrap.dedent("""
        def test_fleet_cap_overrun():
            ctrl = FleetController(AutoscaleConfig(max_replicas=4))
            r = Router(RouterConfig(serve=ServeConfig(), replicas=1),
                       controller=ctrl)
            t = synthesize_mixed_traffic(
                classes={"c": dict(rate=1.0, max_new_tokens=2)},
                max_requests=4)
            r.run(t)

        def test_fleet_in_budget():
            ctrl = FleetController(AutoscaleConfig(max_replicas=2,
                                                   min_replicas=2))
            r = Router(RouterConfig(serve=ServeConfig(), replicas=2),
                       controller=ctrl)
            t = synthesize_mixed_traffic(
                classes={"c": dict(rate=1.0, max_new_tokens=2)},
                max_requests=4)
            r.run(t)

        def test_autoscaler_name_marks():
            sim = Autoscaler()
            sim.step()
    """)
    tree = ast.parse(src)
    names = {v[0] for v in _audit(tree)}
    assert names == {"test_fleet_cap_overrun"}
    fns = {f.name: f for f in tree.body if isinstance(f, ast.FunctionDef)}
    uses, tokens, topo = estimate(fns["test_fleet_cap_overrun"])
    assert uses and tokens == 8 and topo == 4
    uses, tokens, topo = estimate(fns["test_fleet_in_budget"])
    assert uses and tokens == 8 and topo == 2
    uses, tokens, topo = estimate(fns["test_autoscaler_name_marks"])
    assert uses and tokens == 0 and topo == 1


def test_speculate_roles_audit_estimator_extension():
    """ISSUE 15 self-pin: ``speculate_k=`` literals ADD to the
    generated-token budget (each verify step computes up to k draft-
    lane rows beyond the token it emits), and a ``roles=`` keyword
    alone marks a test scheduler-driving — so disagg/speculation tests
    flag exactly like direct Scheduler/Router tests, while an in-budget
    speculative test stays exempt-by-budget."""
    src = textwrap.dedent("""
        def test_speculate_token_overrun():
            cfg = ServeConfig(page_size=8, speculate_k=4)
            prompts = synthesize_prompts(num=10, min_len=4, max_len=8)
            reqs = [Request(id=i, prompt=p, max_new_tokens=8)
                    for i, p in enumerate(prompts)]
            Scheduler(InferenceEngine(cfg)).run(reqs)

        def test_speculate_in_budget():
            cfg = ServeConfig(page_size=8, speculate_k=2)
            prompts = synthesize_prompts(num=4, min_len=4, max_len=8)
            reqs = [Request(id=i, prompt=p, max_new_tokens=6)
                    for i, p in enumerate(prompts)]
            Scheduler(InferenceEngine(cfg)).run(reqs)

        def test_roles_marks_scheduler_driving():
            rcfg = RouterConfig(serve=ServeConfig(page_size=8),
                                replicas=3,
                                roles=("prefill", "decode", "decode"))
            drive(rcfg)
    """)
    tree = ast.parse(src)
    names = {v[0] for v in _audit(tree)}
    assert names == {"test_speculate_token_overrun",
                     "test_roles_marks_scheduler_driving"}
    fns = {f.name: f for f in tree.body if isinstance(f, ast.FunctionDef)}
    uses, tokens, topo = estimate(fns["test_speculate_token_overrun"])
    assert uses and tokens == 120 and topo == 1  # 10 * (8 + 4)
    uses, tokens, _ = estimate(fns["test_speculate_in_budget"])
    assert uses and tokens == 32  # 4 * (6 + 2)
    # roles= alone marks the test, and the replicas literal still sums
    # into the topology ledger — the 3-replica role fleet flags.
    uses, tokens, topo = estimate(fns["test_roles_marks_scheduler_driving"])
    assert uses and tokens == 0 and topo == 3


def test_twin_audit_estimator_extension():
    """ISSUE 18 self-pin: a sim-only test — cost-model engines behind
    every router site — is exempt from the scheduler budgets even at
    MILLION-request scale (no compiles, hashed tokens, virtual clock),
    while one real engine anywhere, or one router site without an
    ``engine_factory=``, keeps the full teeth: the twin exemption can
    never leak real-engine cost into tier-1."""
    src = textwrap.dedent("""
        def test_million_request_twin():
            t = synthesize_mixed_traffic(
                classes={"c": dict(rate=1.0, max_new_tokens=4)},
                max_requests=1000000)
            r = Router(RouterConfig(serve=ServeConfig(), replicas=128,
                                    engine_factory=sim_engine_factory()))
            r.run(t)

        def test_real_engine_keeps_teeth():
            CostModelEngine(ServeConfig())
            eng = InferenceEngine(ServeConfig())
            t = synthesize_mixed_traffic(
                classes={"c": dict(rate=1.0, max_new_tokens=4)},
                max_requests=100)
            Scheduler(eng).run(t)

        def test_unfactored_router_keeps_teeth():
            sim = CostModelEngine(ServeConfig())
            Router(RouterConfig(serve=ServeConfig(), replicas=3)).run(
                synthesize_mixed_traffic(
                    classes={"c": dict(rate=1.0, max_new_tokens=4)},
                    max_requests=100))
    """)
    tree = ast.parse(src)
    names = {v[0] for v in _audit(tree)}
    assert names == {"test_real_engine_keeps_teeth",
                     "test_unfactored_router_keeps_teeth"}
    fns = {f.name: f for f in tree.body if isinstance(f, ast.FunctionDef)}
    # The million-request twin test IS over every budget — and exempt.
    uses, tokens, topo = estimate(fns["test_million_request_twin"])
    assert uses and tokens == 4_000_000 and topo == 128
    assert sim_only(fns["test_million_request_twin"])
    assert not sim_only(fns["test_real_engine_keeps_teeth"])
    assert not sim_only(fns["test_unfactored_router_keeps_teeth"])


def test_precision_kv_audit_estimator_extension():
    """ISSUE 19 self-pin: distinct literal ``precision=`` values times
    distinct literal ``kv_dtype=`` values form the variant ledger —
    every precision policy / KV dtype compiles its own program ladder,
    so a 2x2 precision-by-dtype matrix flags (4 engines) while a plain
    fp32-vs-int8 A/B stays in budget (2), ``None`` literals count as
    the full-precision arm, and non-literal values contribute nothing
    (the documented lower-bound discipline)."""
    src = textwrap.dedent("""
        def test_precision_kv_matrix_overrun():
            engines = [
                make_engine(precision="fp32", kv_dtype=None),
                make_engine(precision="fp32", kv_dtype="int8"),
                make_engine(precision="bf16", kv_dtype=None),
                make_engine(precision="bf16", kv_dtype="int8"),
            ]
            sched = Scheduler(engines)
            sched.run([Request(id=0, prompt=p, max_new_tokens=4)])

        def test_kv_dtype_ab_in_budget():
            base = InferenceEngine(ServeConfig(page_size=8,
                                               kv_dtype=None))
            quant = InferenceEngine(ServeConfig(page_size=8,
                                                kv_dtype="int8"))
            reqs = [Request(id=0, prompt=p, max_new_tokens=8),
                    Request(id=1, prompt=p, max_new_tokens=8)]
            Scheduler(base).run(reqs)
            Scheduler(quant).run(reqs)

        def test_nonliteral_kv_exempt():
            for kd in dtypes:
                eng = InferenceEngine(ServeConfig(page_size=8,
                                                  kv_dtype=kd))
                Scheduler(eng).run([Request(id=0, prompt=p,
                                            max_new_tokens=2)])
    """)
    tree = ast.parse(src)
    names = {v[0] for v in _audit(tree)}
    assert names == {"test_precision_kv_matrix_overrun"}
    fns = {f.name: f for f in tree.body if isinstance(f, ast.FunctionDef)}
    uses, tokens, topo = estimate(fns["test_precision_kv_matrix_overrun"])
    assert uses and tokens == 4 and topo == 4  # 2 precisions x 2 dtypes
    uses, tokens, topo = estimate(fns["test_kv_dtype_ab_in_budget"])
    assert uses and tokens == 16 and topo == 2  # None + "int8" arms
    # kv_dtype bound to a variable resolves to nothing: the estimate is
    # a lower bound, never a false positive on plain code — and the
    # non-literal ``for`` iterable doesn't sweep the topology ledger.
    uses, tokens, topo = estimate(fns["test_nonliteral_kv_exempt"])
    assert uses and tokens == 2 and topo == 1


def test_comms_audit_estimator_extension():
    """ISSUE 20 self-pin: a ``program_text`` /
    ``publish_program_ledger`` name marks the test compile-driving,
    ``SeqTrainer`` constructor sites SUM into the topology ledger and a
    literal-tuple ``for`` sweep constructing one MULTIPLIES — so a
    3-config ledger recount flags while the 1-trainer recount stays in
    budget, and ``collective_ops`` alone (pure text parsing, no
    compile) never marks even over a 4-way trainer sweep."""
    src = textwrap.dedent("""
        def test_ledger_sweep_overrun():
            reg = MetricRegistry()
            for cfg in (cfg_a, cfg_b, cfg_c):
                tr = SeqTrainer(cfg, ds)
                tr.train(log=nolog, metrics=reg)
                publish_program_ledger(
                    reg, program_text(span(tr)),
                    program="train_span[1]")

        def test_trainer_sites_overrun():
            a = SeqTrainer(cfg_a, ds)
            b = SeqTrainer(cfg_b, ds)
            c = SeqTrainer(cfg_c, ds)
            for tr in (a, b, c):
                ops = collective_ops(program_text(span(tr)))

        def test_recount_in_budget():
            tr = SeqTrainer(cfg, ds)
            tr.train(log=nolog, metrics=reg)
            ops = collective_ops(program_text(span(tr)))

        def test_parser_only_exempt():
            for cfg in (cfg_a, cfg_b, cfg_c, cfg_d):
                tr = SeqTrainer(cfg, ds)
                ops = collective_ops(HLO)
    """)
    tree = ast.parse(src)
    names = {v[0] for v in _audit(tree)}
    assert names == {"test_ledger_sweep_overrun",
                     "test_trainer_sites_overrun"}
    fns = {f.name: f for f in tree.body if isinstance(f, ast.FunctionDef)}
    uses, tokens, topo = estimate(fns["test_ledger_sweep_overrun"])
    assert uses and tokens == 0 and topo == 3  # sweep multiplies
    uses, tokens, topo = estimate(fns["test_trainer_sites_overrun"])
    assert uses and topo == 3  # sites sum; the name-only loop doesn't
    uses, tokens, topo = estimate(fns["test_recount_in_budget"])
    assert uses and topo == 1
    # collective_ops without program_text/publish_program_ledger is
    # parsing, not compiling: no gate, however wide the trainer sweep.
    uses, tokens, topo = estimate(fns["test_parser_only_exempt"])
    assert not uses and topo == 4


def test_fault_injection_tests_carry_slow_marker():
    """THE fault audit: every unmarked tier-1 test touching the fault
    injection surface stays within 64 estimated trainer steps and 2
    resume cycles; anything bigger must be @pytest.mark.slow."""
    violations = []
    for path in sorted(pathlib.Path(__file__).parent.glob("test_*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        violations += [(path.name, *v) for v in _audit_faults(tree)]
    assert not violations, (
        "fault-injection tests exceeding the tier-1 budget without "
        "@pytest.mark.slow (file, test, est_steps, resume_cycles): "
        f"{violations} — mark them slow or shrink the run "
        f"(<= {MAX_FAST_TRAIN_STEPS} steps, <= {MAX_FAST_RESUME_CYCLES} "
        "resume cycles)"
    )


def test_fault_audit_estimator_flags_and_permits():
    """Self-pin for the fault estimator: step overruns flag, resume-
    cycle overruns flag, slow-marked / in-budget / non-fault tests are
    exempt, pytest.raises bodies don't count as fault usage."""
    src = textwrap.dedent("""
        import pytest

        def test_step_overrun():
            inj = FaultInjector(FaultSpec(kind="nan_grads", step=1))
            ds = synthesize_copy(num_train=640, seq_len=32)
            cfg = SeqConfig(epochs=4, batch_size=16)
            SeqTrainer(cfg, ds).train(fault_injector=inj)

        def test_resume_cycle_overrun():
            inj = FaultInjector(FaultSpec(kind="sigterm", step=1))
            t = SeqTrainer(SeqConfig(epochs=1, batch_size=16),
                           synthesize_copy(num_train=16))
            t.train(fault_injector=inj)
            t.train(resume=True)
            t.train(resume="auto")
            t.train(resume="auto")

        @pytest.mark.slow
        def test_marked_overrun():
            corrupt_checkpoint("x")
            cfg = SeqConfig(epochs=100, batch_size=1)
            SeqTrainer(cfg, synthesize_copy(num_train=100)).train()

        def test_in_budget():
            truncate_checkpoint("x")
            cfg = SeqConfig(epochs=2, batch_size=16)
            ds = synthesize_copy(num_train=64)
            SeqTrainer(cfg, ds).train()
            SeqTrainer(cfg, ds).train(resume="auto")

        def test_raises_only_exempt():
            with pytest.raises(ValueError):
                parse_fault("bogus")

        def test_no_faults_big_train():
            cfg = SeqConfig(epochs=100, batch_size=1)
            SeqTrainer(cfg, synthesize_copy(num_train=1000)).train()
    """)
    tree = ast.parse(src)
    names = {v[0] for v in _audit_faults(tree)}
    assert names == {"test_step_overrun", "test_resume_cycle_overrun"}
    fns = {f.name: f for f in tree.body if isinstance(f, ast.FunctionDef)}
    uses, steps, cycles = estimate_fault(fns["test_step_overrun"])
    assert uses and steps == 160 and cycles == 0
    uses, steps, cycles = estimate_fault(fns["test_resume_cycle_overrun"])
    assert uses and cycles == 3
    uses, steps, cycles = estimate_fault(fns["test_in_budget"])
    assert uses and steps == 16 and cycles == 1
    uses, _, _ = estimate_fault(fns["test_raises_only_exempt"])
    assert not uses
    uses, _, _ = estimate_fault(fns["test_no_faults_big_train"])
    assert not uses


def test_audit_estimator_flags_and_permits():
    """Pin the estimator itself on synthetic sources, so the audit's
    teeth cannot rot silently: token overruns flag, topology sweeps
    flag, slow-marked and in-budget tests pass, pytest.raises bodies
    and non-Scheduler tests are exempt."""
    src = textwrap.dedent("""
        import pytest

        def test_token_overrun():
            prompts = synthesize_prompts(num=10, min_len=4, max_len=8)
            reqs = [Request(id=i, prompt=p, max_new_tokens=20)
                    for i, p in enumerate(prompts)]
            Scheduler(InferenceEngine(ServeConfig())).run(reqs)

        def test_topology_sweep():
            for slots in (1, 2, 4):
                eng = InferenceEngine(ServeConfig(slots=slots))
                Scheduler(eng).run([Request(id=0, prompt=p,
                                            max_new_tokens=1)])

        @pytest.mark.slow
        def test_marked_overrun():
            prompts = synthesize_prompts(num=100, min_len=4, max_len=8)
            reqs = [Request(id=i, prompt=p, max_new_tokens=64)
                    for i, p in enumerate(prompts)]
            Scheduler(InferenceEngine(ServeConfig())).run(reqs)

        def test_in_budget():
            ps = synthesize_shared_prefix_prompts(n_families=2,
                                                  per_family=3)
            reqs = [Request(id=i, prompt=p, max_new_tokens=6)
                    for i, p in enumerate(ps)]
            Scheduler(InferenceEngine(ServeConfig())).run(reqs)

        def test_longtail_overrun():
            ps = synthesize_longtail_prompts(num_short=10, num_long=2,
                                             long_len=96)
            reqs = [Request(id=i, prompt=p, max_new_tokens=8)
                    for i, p in enumerate(ps)]
            Scheduler(InferenceEngine(ServeConfig(page_size=8))).run(reqs)

        def test_rejected_requests_exempt():
            sched = Scheduler(InferenceEngine(ServeConfig()))
            with pytest.raises(ValueError):
                sched.run([Request(id=0, prompt=p,
                                   max_new_tokens=9999)])

        def test_no_scheduler():
            prompts = synthesize_prompts(num=500, min_len=4, max_len=8)
            assert len(prompts) == 500
    """)
    tree = ast.parse(src)
    names = {v[0] for v in _audit(tree)}
    assert names == {"test_token_overrun", "test_topology_sweep",
                     "test_longtail_overrun"}
    fns = {f.name: f for f in tree.body
           if isinstance(f, ast.FunctionDef)}
    assert has_slow_marker(fns["test_marked_overrun"])
    uses, tokens, topo = estimate(fns["test_token_overrun"])
    assert uses and tokens == 200 and topo == 1
    # The paged-serve long-tail generator counts num_short + num_long —
    # the ISSUE 7 audit extension, pinned so it cannot rot.
    uses, tokens, topo = estimate(fns["test_longtail_overrun"])
    assert uses and tokens == 96 and topo == 1
    _, tokens, topo = estimate(fns["test_topology_sweep"])
    assert tokens == 1 and topo == 3
    _, tokens, _ = estimate(fns["test_in_budget"])
    assert tokens == 36
    uses, tokens, _ = estimate(fns["test_rejected_requests_exempt"])
    assert uses and tokens == 0
