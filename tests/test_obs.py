"""Unified telemetry tests (ISSUE 5): registry label-set semantics,
histogram/StepStats percentile parity, span nesting + JSONL round-trip,
the derived-TTFT/ITL ≡ ServeStats pin at tp=1 AND tp=2, and the
in-graph health signals against a single-device ``jax.grad`` oracle on
the dp2 x tp2 (and zero1 / hybrid / pipeline) meshes."""

import functools
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl_tpu.data.lm import synthesize_copy, synthesize_prompts
from ddl_tpu.models import transformer
from ddl_tpu.models.transformer import TINY_SPEC
from ddl_tpu.obs import MetricRegistry, MetricsWriter, Tracer, run_manifest
from ddl_tpu.obs import health as hlt
from ddl_tpu.obs.trace import NULL_TRACER, chrome_trace_events, read_jsonl
from ddl_tpu.parallel import ring
from ddl_tpu.utils.metrics import StepStats

SPEC = TINY_SPEC
T = 32
B = 4


# -- registry ---------------------------------------------------------------


def test_registry_label_set_semantics():
    """Each distinct label set is an independent series; the same set
    (any key order) accumulates; kind conflicts and counter decreases
    are errors."""
    reg = MetricRegistry()
    c = reg.counter("req_total")
    c.inc(2, tp=1, slots=4)
    c.inc(3, slots=4, tp=1)  # same set, different order
    c.inc(1, tp=2, slots=4)
    c.inc()  # the unlabelled series is its own series
    assert c.value(tp=1, slots=4) == 5
    assert c.value(tp=2, slots=4) == 1
    assert c.value() == 1
    assert c.value(tp=3, slots=4) == 0  # untouched series reads 0
    assert len(c.label_sets()) == 3
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)
    g = reg.gauge("depth")
    g.set(7, queue="a")
    g.set(9, queue="a")  # last write wins
    assert g.value(queue="a") == 9
    assert g.value(queue="b") is None
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("req_total")
    # Same name re-requested with the same kind returns the instance.
    assert reg.counter("req_total") is c


def test_histogram_percentiles_match_stepstats_from_times():
    """The registry histogram and ``StepStats.from_times`` are ONE
    percentile definition: stats() is field-for-field equal, and
    ``percentile`` matches np.percentile's linear interpolation on the
    raw samples (including the n=1/n=2 edges test_utils pins for
    StepStats)."""
    for samples in ([0.010, 0.020, 0.030, 0.040], [0.012], [0.010, 0.030]):
        reg = MetricRegistry()
        h = reg.histogram("lat")
        h.observe_many(samples)
        assert h.stats() == StepStats.from_times(samples)
        assert h.percentile(95) == pytest.approx(
            float(np.percentile(samples, 95))
        )
    assert MetricRegistry().histogram("empty").stats() == \
        StepStats.from_times([])


def test_histogram_percentile_raises_named_error_on_missing_series():
    """ISSUE 10 satellite: a percentile of nothing is a question error
    — the empty registry AND a wrong/unknown label set both raise the
    named ``NoSamplesError`` (a ``LookupError``), never silently 0.0;
    ``stats()`` keeps its zero-filled StepStats contract."""
    from ddl_tpu.obs import NoSamplesError

    reg = MetricRegistry()
    h = reg.histogram("lat")
    with pytest.raises(NoSamplesError, match="no samples"):
        h.percentile(50)  # empty registry: never observed at all
    h.observe(0.5, tp=1)
    with pytest.raises(NoSamplesError, match="lat"):
        h.percentile(50, tp=2)  # wrong label set
    with pytest.raises(NoSamplesError):
        h.percentile(50)  # unlabelled series still never observed
    assert isinstance(NoSamplesError("x"), LookupError)
    assert h.percentile(50, tp=1) == 0.5
    assert reg.histogram("other").stats() == StepStats.from_times([])


def test_prometheus_text_escapes_label_values():
    """ISSUE 10 satellite: backslash, double-quote and newline in a
    label VALUE are escaped per the Prometheus exposition format — all
    three characters in one value, pinned byte-for-byte."""
    reg = MetricRegistry()
    reg.counter("c").inc(1, path='a\\b"c\nd')
    text = reg.prometheus_text()
    assert 'c{path="a\\\\b\\"c\\nd"} 1' in text.splitlines()
    # The escaped body is the ONLY backslash/newline inside the braces:
    # the line count is unchanged (a raw newline would split the line).
    assert sum(1 for line in text.splitlines()
               if line.startswith("c{")) == 1


def test_prometheus_text_and_snapshot():
    reg = MetricRegistry()
    reg.counter("c", "help line").inc(5, tp=1)
    reg.gauge("g").set(2.5)
    reg.histogram("h").observe_many([0.010, 0.020, 0.030, 0.040])
    text = reg.prometheus_text()
    assert "# HELP c help line" in text
    assert '# TYPE c counter' in text and 'c{tp="1"} 5' in text
    assert "g 2.5" in text
    assert 'h{quantile="0.95"} 0.0385' in text
    assert "h_count 4" in text
    snap = reg.snapshot()
    by = {(r["name"], tuple(sorted(r["labels"].items()))): r for r in snap}
    assert by[("c", (("tp", "1"),))]["value"] == 5
    h = by[("h", ())]
    assert h["count"] == 4 and h["p50"] == pytest.approx(0.025)


# -- tracer -----------------------------------------------------------------


def test_tracer_span_nesting_and_ordering():
    tr = Tracer()  # in-memory
    with tr.span("outer", a=1):
        tr.event("mid")
        with tr.span("inner"):
            pass
    # Spans emit at END: child before parent; depth is the span's own
    # nesting level; t0/t1 of the child nest inside the parent's.
    assert [r["name"] for r in tr.records] == ["mid", "inner", "outer"]
    mid, inner, outer = tr.records
    assert outer["depth"] == 0 and inner["depth"] == 1 and mid["depth"] == 1
    assert outer["t0"] <= inner["t0"] <= inner["t"] <= outer["t"]
    assert outer["attrs"] == {"a": 1}
    assert [r["seq"] for r in tr.records] == [0, 1, 2]
    # The null tracer is falsy (call sites gate clock reads on it) and
    # records nothing; a real tracer is truthy.
    assert not NULL_TRACER and tr
    with NULL_TRACER.span("x"):
        NULL_TRACER.event("y")
    assert NULL_TRACER.records == ()


def test_trace_jsonl_roundtrip_and_chrome_conversion(tmp_path):
    path = tmp_path / "host_trace_p0.jsonl"
    tr = Tracer(path)
    with tr.span("outer"):
        tr.event("tick", t=1.5, req=3)
    tr.close()
    recs = read_jsonl(path)
    assert [r["name"] for r in recs] == ["tick", "outer"]
    assert recs[0]["attrs"] == {"req": 3} and recs[0]["t"] == 1.5
    assert all("pid" in r and "process_index" in r and "t_wall" in r
               for r in recs)
    evs = chrome_trace_events(recs)
    # Sorted by timestamp: the instant at t=1.5 precedes nothing that
    # started earlier; span -> "X" with µs ts/dur, event -> "i".
    kinds = {e["name"]: e["ph"] for e in evs}
    assert kinds == {"tick": "i", "outer": "X"}
    span = next(e for e in evs if e["ph"] == "X")
    assert span["dur"] >= 0 and span["ts"] > 0


def test_chrome_conversion_incident_flow_roundtrip(tmp_path):
    """ISSUE 11 satellite: incident instants (anomaly, guard_skip,
    shed, ...) render as GLOBAL-scope instants under cat="incident",
    chained by flow (s/t/f) events along their identity — a request's
    shed flows to its completion record, guard skips to the rollback
    that resolves them, consecutive anomalies of one signal to each
    other — and the file converter round-trips all of it."""
    import json

    from ddl_tpu.obs.trace import INCIDENT_EVENTS, convert

    path = tmp_path / "host_trace_p0.jsonl"
    tr = Tracer(path, keep=True)
    tr.event("eligible", t=1.0, req=7)          # plain lifecycle: no flow
    tr.event("deadline_exceeded", t=2.0, req=7)  # incident opens req chain
    tr.event("complete", t=3.0, req=7, status="deadline_exceeded")
    tr.event("complete", t=3.5, req=8, status="ok")  # no incident: no chain
    tr.event("anomaly", t=4.0, signal="itl", tick=4, z=9.0)
    tr.event("anomaly", t=5.0, signal="itl", tick=5, z=7.0)
    tr.event("guard_skip", t=6.0, gstep=3, consecutive=1)
    tr.event("guard_skip", t=6.5, gstep=4, consecutive=2)
    tr.event("guard_rollback", t=7.0, to_step=2, rollbacks=1)
    tr.event("shed", t=8.0, req=9, step=8)      # 1-length chain: no flow
    tr.close()
    evs = chrome_trace_events(tr.records)
    instants = {e["name"]: e for e in evs if e["ph"] == "i"}
    for name in ("deadline_exceeded", "anomaly", "guard_skip", "shed"):
        assert name in INCIDENT_EVENTS
        assert instants[name]["s"] == "g"
        assert instants[name]["cat"] == "incident"
    # Plain events keep thread scope and no category.
    assert instants["eligible"]["s"] == "t"
    assert "cat" not in instants["eligible"]
    flows = [e for e in evs if e.get("cat") == "incident_flow"]
    by_chain: dict = {}
    for f in flows:
        by_chain.setdefault(f["name"], []).append(f)
    # Three chains: req=7 (incident -> complete), signal=itl (two
    # anomalies), guard (2 skips -> rollback). req=8's complete and the
    # lone shed open no chain.
    assert set(by_chain) == {"incident:req=7", "incident:signal=itl",
                             "incident:guard=train"}
    for name, chain in by_chain.items():
        chain.sort(key=lambda e: e["ts"])
        phs = [e["ph"] for e in chain]
        assert phs[0] == "s" and phs[-1] == "f"
        assert set(phs[1:-1]) <= {"t"}
        assert len({e["id"] for e in chain}) == 1  # one flow id per chain
        assert chain[-1]["bp"] == "e"
    assert [e["ph"] for e in by_chain["incident:guard=train"]] == \
        ["s", "t", "f"]
    # Flow ids are distinct across chains and deterministic.
    ids = {chain[0]["id"] for chain in by_chain.values()}
    assert len(ids) == 3
    assert chrome_trace_events(tr.records) == evs  # deterministic
    # File round-trip: convert() writes a loadable trace_event JSON
    # carrying every instant AND every flow event.
    dst = tmp_path / "chrome.json"
    n = convert(path, dst)
    doc = json.loads(dst.read_text())
    assert len(doc["traceEvents"]) == n == len(evs)
    assert doc["traceEvents"] == evs


def test_metrics_writer_manifest_first_and_snapshot_roundtrip(tmp_path):
    from ddl_tpu.strategies.seq import SeqConfig

    reg = MetricRegistry()
    reg.counter("c").inc(4)
    path = tmp_path / "metrics.jsonl"
    man = run_manifest(config=SeqConfig(spec=SPEC), extra={"variant": "lm"})
    with MetricsWriter(path, reg, man, interval_s=0.0) as w:
        w.maybe_flush()
        reg.counter("c").inc(1)
    lines = [json.loads(line) for line in open(path)]
    # Manifest FIRST (ISSUE 5 satellite): versions + config dump present.
    assert lines[0]["record"] == "manifest"
    assert lines[0]["jax_version"] == jax.__version__
    assert lines[0]["config"]["spec"]["d_model"] == SPEC.d_model
    assert lines[0]["variant"] == "lm"
    snaps = [l for l in lines[1:] if l["record"] == "snapshot"]
    assert snaps, "close() must force a final snapshot"
    final = snaps[-1]["metrics"]
    assert final == [{"name": "c", "kind": "counter", "labels": {},
                     "value": 5}]


def test_metrics_writer_interval_rate_limits(tmp_path):
    reg = MetricRegistry()
    w = MetricsWriter(tmp_path / "m.jsonl", reg, {}, interval_s=3600.0)
    assert w.maybe_flush()  # first flush always lands
    assert not w.maybe_flush()  # inside the interval: suppressed
    assert w.maybe_flush(force=True)
    w.close()


# -- serve lifecycle trace ---------------------------------------------------


def test_derived_ttft_itl_equal_servestats_tp1_tp2():
    """THE serve pin: TTFT/ITL derived purely from the request
    lifecycle trace are EXACTLY (same floats) the ``ServeStats``
    numbers, for tp=1 AND tp=2; warmup emits nothing; the registry
    histograms/counters agree with ServeStats too."""
    from ddl_tpu.serve import (
        InferenceEngine,
        Request,
        Scheduler,
        ServeConfig,
        derive_request_slo,
    )

    prompts = synthesize_prompts(num=3, min_len=4, max_len=10,
                                 vocab=SPEC.vocab, seed=0)
    for tp in (1, 2):
        eng = InferenceEngine(ServeConfig(
            spec=SPEC, slots=2, capacity=64, tensor_parallel=tp,
            prefix_slots=2,
        ))
        reqs = [Request(id=i, prompt=p, max_new_tokens=4, arrival=i)
                for i, p in enumerate(prompts)]
        tracer, reg = Tracer(), MetricRegistry()
        sched = Scheduler(eng, tracer=tracer, registry=reg)
        sched.warmup(reqs)
        assert not tracer.records, "warmup telemetry must be suppressed"
        done, stats = sched.run(reqs)
        ttft, itl = derive_request_slo(tracer.records)
        assert ttft == stats.ttft  # exact — same floats, not approx
        assert itl == stats.itl
        assert reg.histogram("serve_ttft_seconds").stats() == stats.ttft
        assert reg.histogram("serve_itl_seconds").stats() == stats.itl
        assert reg.counter("serve_prefill_tokens_total").value() \
            == stats.prefill_tokens
        assert reg.counter("serve_decode_tokens_total").value() \
            == stats.decode_tokens
        assert reg.counter("serve_requests_completed_total").value() == 3
        names = {r["name"] for r in tracer.records}
        assert {"submit", "eligible", "admit", "prefill_chunk",
                "first_token", "decode_tick", "complete"} <= names
        # Per-request lifecycle ordering: eligible <= admit <=
        # first_token <= complete for every request id.
        for rid in (0, 1, 2):
            ts = {
                name: next(r["t"] if "t" in r else r["t0"]
                           for r in tracer.records
                           if r["name"] == name
                           and r["attrs"].get("req") == rid)
                for name in ("eligible", "admit", "first_token", "complete")
            }
            assert ts["eligible"] <= ts["admit"] <= ts["first_token"] \
                <= ts["complete"]


def test_derive_request_slo_group_by_grouped_equals_filtered():
    """ISSUE 8 satellite: ``derive_request_slo(records, group_by=...)``
    pools PER-REQUEST samples by group with the single
    ``StepStats.from_times`` percentile definition, and a group's
    result is IDENTICAL to filtering the records to that group first
    and deriving then. The ungrouped path stays the exact-ServeStats
    derivation (pinned above)."""
    from ddl_tpu.serve import (
        InferenceEngine,
        Request,
        Scheduler,
        ServeConfig,
        derive_request_slo,
        request_slo_samples,
    )

    prompts = synthesize_prompts(num=6, min_len=4, max_len=8,
                                 vocab=SPEC.vocab, seed=31)
    reqs = [Request(id=i, prompt=p, max_new_tokens=4, arrival=i % 2)
            for i, p in enumerate(prompts)]
    eng = InferenceEngine(ServeConfig(spec=SPEC, slots=2, capacity=32))
    tracer = Tracer()
    sched = Scheduler(eng, tracer=tracer)
    done, stats = sched.run(reqs)
    cls_of = {i: ("chat" if i % 2 == 0 else "bulk") for i in range(6)}
    grouped = derive_request_slo(tracer.records, group_by=cls_of)
    assert set(grouped) == {"chat", "bulk"}
    # Every request contributes exactly one TTFT sample to its group.
    assert grouped["chat"][0].steps == 3
    assert grouped["bulk"][0].steps == 3
    # Per-request ITL exists (multi-token requests decode repeatedly).
    assert grouped["chat"][1].steps > 0

    # THE pin: grouped ≡ filtered-then-derived. Filtering keeps the
    # group's request-scoped events and intersects decode_tick `reqs`
    # with the group — deriving the filtered stream under a constant
    # group_by must reproduce the grouped entry field for field.
    for cls in ("chat", "bulk"):
        members = {i for i, c in cls_of.items() if c == cls}
        filtered = []
        for rec in tracer.records:
            attrs = rec.get("attrs", {})
            if rec.get("name") == "decode_tick":
                filtered.append({**rec, "attrs": {
                    **attrs,
                    "reqs": [r for r in attrs.get("reqs", ())
                             if r in members],
                }})
            elif "req" in attrs:
                if attrs["req"] in members:
                    filtered.append(rec)
            else:
                filtered.append(rec)
        alone = derive_request_slo(filtered, group_by=lambda rid: cls)
        assert alone[cls] == grouped[cls], cls

    # The shared substrate: per-request sample map covers every served
    # request, TTFT totals match the global derivation.
    samples = request_slo_samples(tracer.records)
    assert sorted(samples) == list(range(6))
    ttft, itl = derive_request_slo(tracer.records)
    assert ttft == stats.ttft and itl == stats.itl  # ungrouped unchanged
    # Callable group_by; None drops a request from every group.
    partial = derive_request_slo(tracer.records,
                                 group_by=lambda rid: "x" if rid < 2
                                 else None)
    assert set(partial) == {"x"} and partial["x"][0].steps == 2


def test_derive_request_slo_degenerate_inputs():
    """ISSUE 10 satellite: the documented SKIP semantics on degenerate
    inputs — empty record list, a group with zero completions (absent,
    not zero-filled), and a callable group_by returning None — without
    ever raising (the derivation is a read-only reporting surface)."""
    from ddl_tpu.serve import derive_request_slo
    from ddl_tpu.serve.scheduler import request_slo_samples

    # Empty record list: zero-filled StepStats ungrouped, {} grouped,
    # {} samples.
    ttft, itl = derive_request_slo([])
    assert ttft == StepStats.from_times([]) and itl == StepStats.from_times([])
    assert derive_request_slo([], group_by={}) == {}
    assert request_slo_samples([]) == {}

    # A synthetic trace: request 0 served (eligible -> first_token ->
    # one chained decode), request 1 shed (eligible only — no first
    # token ever).
    records = [
        {"type": "event", "name": "eligible", "t": 1.0,
         "attrs": {"req": 0}},
        {"type": "event", "name": "eligible", "t": 1.0,
         "attrs": {"req": 1}},
        {"type": "event", "name": "shed", "t": 1.5, "attrs": {"req": 1}},
        {"type": "event", "name": "first_token", "t": 2.0,
         "attrs": {"req": 0}},
        {"type": "span", "name": "decode_tick", "t0": 2.0, "t": 2.5,
         "attrs": {"chained": True, "reqs": [0]}},
    ]
    # Group with zero completions: "shed_group" holds only request 1,
    # which never reached a first token -> the group is ABSENT (skip,
    # not a zero-filled entry — no latency evidence is not zero
    # latency; the router's ClassReport counts the miss separately).
    grouped = derive_request_slo(
        records, group_by={0: "served", 1: "shed_group"}
    )
    assert set(grouped) == {"served"}
    assert grouped["served"][0].steps == 1
    assert grouped["served"][0].p50_ms == pytest.approx(1000.0)
    assert grouped["served"][1].steps == 1  # the one chained gap
    # Callable group_by returning None drops the request everywhere.
    assert derive_request_slo(records, group_by=lambda rid: None) == {}
    only0 = derive_request_slo(
        records, group_by=lambda rid: "g" if rid == 0 else None
    )
    assert set(only0) == {"g"} and only0["g"][0].steps == 1


# -- in-graph health vs jax.grad oracle -------------------------------------


def _oracle(host_params, ds):
    """Single-device global weighted-mean-loss gradient — the oracle
    every distributed health grad_norm must reproduce."""
    attn = functools.partial(ring.full_attention, causal=True)

    def loss(p):
        num, den = transformer.lm_loss_sums(
            p, jnp.asarray(ds.tokens), jnp.asarray(ds.targets),
            jnp.asarray(ds.weights), SPEC, attn_fn=attn,
            positions=jnp.arange(T),
        )
        return num / den

    g = jax.grad(loss)(host_params)
    norm = jnp.sqrt(sum(
        jnp.sum(jnp.square(a.astype(jnp.float32)))
        for a in jax.tree.leaves(g)
    ))
    return float(norm)


@pytest.fixture(scope="module")
def health_ds():
    return synthesize_copy(num_train=B, num_test=B, seq_len=T,
                           vocab=SPEC.vocab, seed=0)


@pytest.fixture(scope="module")
def oracle_grad_norm(health_ds):
    host = transformer.init_lm_params(jax.random.PRNGKey(0), SPEC)
    return _oracle(host, health_ds)


def _one_health_step(cfg, ds):
    from ddl_tpu.strategies.seq import SeqTrainer

    tr = SeqTrainer(cfg, ds)
    xs = tr.stage_batches(ds.tokens, 1, B)
    ys = tr.stage_batches(ds.targets, 1, B)
    ws = tr.stage_batches(ds.weights, 1, B)
    p, o, l, h = tr.span_program(1, health=True)(
        tr.params, tr.opt_state, xs, ys, ws, jnp.int32(0)
    )
    # The health-off program returns the plain triple with the same loss
    # (the aux is an output, never a numerics change).
    _, _, l_off = tr.span_program(1)(
        jax.tree.map(jnp.copy, tr.params),
        jax.tree.map(jnp.copy, tr.opt_state), xs, ys, ws, jnp.int32(0)
    )
    assert float(l) == float(l_off)
    return {k: np.asarray(v)[0] for k, v in h.items()}


def test_health_grad_norm_oracle_dp2_tp2(health_ds, oracle_grad_norm):
    """The acceptance pin: replicated-step health on the dp2 x tp2 mesh
    reproduces the single-device jax.grad oracle's global grad norm
    (tp-sharded leaves' squared sums reduce over tp — a wrong/missing
    psum would be off by ~sqrt(2) on the sharded subtree)."""
    from ddl_tpu.strategies.seq import SeqConfig

    h = _one_health_step(
        SeqConfig(num_workers=1, data_parallel=2, tensor_parallel=2,
                  scheme="full", batch_size=B, spec=SPEC),
        health_ds,
    )
    assert float(h["grad_norm"]) == pytest.approx(oracle_grad_norm,
                                                  rel=1e-4)
    assert int(h["nonfinite_grads"]) == 0
    host = transformer.init_lm_params(jax.random.PRNGKey(0), SPEC)
    pn = float(jnp.sqrt(sum(
        jnp.sum(jnp.square(a)) for a in jax.tree.leaves(host)
    )))
    assert float(h["param_norm"]) == pytest.approx(pn, rel=1e-4)
    # Subtree norms compose to the global norm.
    subs = [float(v) for k, v in h.items() if k.startswith("param_norm/")]
    assert np.sqrt(np.sum(np.square(subs))) == pytest.approx(pn, rel=1e-4)
    assert set(h) == set(hlt.health_keys(host))


def test_health_grad_norm_oracle_zero1_and_hybrid(health_ds,
                                                  oracle_grad_norm):
    """The flat-chunk paths: zero1 (dp2 x sp2) and the hybrid
    zero1 x tp (dp2 x sp2... tp2 on 8 devices) reproduce the same
    oracle grad norm from their reduce-scattered chunks, with the SAME
    health key set as the replicated mode."""
    from ddl_tpu.strategies.seq import SeqConfig

    h_z = _one_health_step(
        SeqConfig(num_workers=2, data_parallel=2, scheme="ring",
                  batch_size=B, zero1=True, spec=SPEC),
        health_ds,
    )
    h_h = _one_health_step(
        SeqConfig(num_workers=2, data_parallel=2, tensor_parallel=2,
                  scheme="ring", batch_size=B, zero1=True, spec=SPEC),
        health_ds,
    )
    for h in (h_z, h_h):
        assert float(h["grad_norm"]) == pytest.approx(oracle_grad_norm,
                                                      rel=1e-4)
        assert int(h["nonfinite_grads"]) == 0
    assert set(h_z) == set(h_h)


def test_health_grad_norm_oracle_pipeline(health_ds, oracle_grad_norm):
    """Pipeline pp=2: stage-resident block grads' squared sums reduce
    over pp (spec-driven), shared leaves are already fully reduced —
    the stacked-tree health matches the same oracle."""
    from ddl_tpu.pipeline.trainer import make_pipeline_program
    from ddl_tpu.strategies.seq import SeqConfig

    cfg = SeqConfig(num_workers=1, scheme="full", batch_size=B, spec=SPEC,
                    pipeline_parallel=2, microbatches=2)
    fn, state = make_pipeline_program(
        cfg, health_ds.tokens, health_ds.targets, health_ds.weights,
        health=True,
    )
    _, _, _, h = fn(*state)
    assert float(np.asarray(h["grad_norm"])) == pytest.approx(
        oracle_grad_norm, rel=1e-4
    )
    assert int(np.asarray(h["nonfinite_grads"])) == 0


def test_record_health_into_registry():
    """record_health: last-step gauges (subtree-labelled), span-summed
    non-finite counter."""
    reg = MetricRegistry()
    hlt.record_health(reg, {
        "grad_norm": np.array([1.0, 2.0]),
        "nonfinite_grads": np.array([1, 3], np.int32),
        "param_norm": np.array([5.0, 6.0]),
        "update_norm": np.array([0.5, 0.25]),
        "param_norm/blocks": np.array([4.0, 4.5]),
        "update_norm/blocks": np.array([0.4, 0.2]),
    })
    assert reg.gauge("train_grad_norm").value() == 2.0
    assert reg.gauge("train_param_norm").value(subtree="blocks") == 4.5
    assert reg.gauge("train_update_norm").value() == 0.25
    assert reg.counter("train_nonfinite_grads_total").value() == 4
    # The trainers' split: the tripwire counter moves on EVERY span
    # (record_nonfinite), the gauges only on interval-crossing spans
    # (record_health with include_nonfinite=False — no double count).
    hlt.record_nonfinite(reg, np.array([2, 0], np.int32))
    assert reg.counter("train_nonfinite_grads_total").value() == 6
    hlt.record_health(reg, {
        "grad_norm": np.array([3.0]),
        "nonfinite_grads": np.array([9], np.int32),
        "param_norm": np.array([5.0]),
        "update_norm": np.array([0.5]),
    }, include_nonfinite=False)
    assert reg.counter("train_nonfinite_grads_total").value() == 6
    assert reg.gauge("train_grad_norm").value() == 3.0


def test_health_keys_static_and_spec_tree_safe():
    """health_keys works on value trees, shapes-only templates AND
    PartitionSpec trees (P is a tuple subclass — must be a leaf)."""
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.models.partition import lm_param_specs, pipeline_param_specs

    host = transformer.init_lm_params(jax.random.PRNGKey(0), SPEC)
    keys = hlt.health_keys(host)
    assert hlt.health_keys(jax.eval_shape(lambda: host)) == keys
    assert hlt.health_keys(lm_param_specs(SPEC, 2)) == keys
    assert hlt.health_keys(pipeline_param_specs(SPEC, 2, 1)) == keys
    assert hlt.health_out_specs(host) == {k: P() for k in keys}
